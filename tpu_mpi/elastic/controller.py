"""The broker-side elastic autoscaler + resize orchestrator.

One controller thread per broker. Every ``TPU_MPI_ELASTIC_INTERVAL_MS`` it
reads four signals — fair-queue depth, busy-rejection backlog, the infer
scheduler's SLO hit rate, and the failure detector — and decides between
three moves:

- **restore** (immediately, no cooldown): a rank was declared dead, or the
  pool is below target — run the full resize: shrink out the dead ranks,
  GROW replacements back to target, rebind the affected leases.
- **pressure grow** (hysteresis + cooldown): sustained queue pressure with
  headroom under ``TPU_MPI_ELASTIC_MAX_RANKS`` — raise the target by one
  and resize.
- **idle retire** (hysteresis + cooldown): a spare rank — healthy, leased
  by nobody, outside the infer engine — has been idle for
  ``TPU_MPI_ELASTIC_IDLE_TICKS`` ticks and the pool is above
  ``TPU_MPI_ELASTIC_MIN_RANKS`` — drain-and-retire it through the same
  shrink path a failure takes (deliberately: one code path, one set of
  invariants).

The resize itself is the two-phase rebind protocol (docs/fault-tolerance.md
"Elastic recovery"): pause the fair queue, drain in-flight ops, park the
infer scheduler at a step boundary, gate attaches; **quiesce** barrier over
the survivors; ``Comm_shrink`` + ``Comm_spawn``/``Intercomm_merge`` GROW;
remap dead->replacement in every affected lease (same cids — ledger books
and cid-range ownership survive untouched) and in the infer engine;
**resume** barrier over the full new pool; reopen the gates. Queued ops
never leave the fair queue during the window and in-flight ops are drained
before it opens, so no op is dropped or duplicated.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from .. import config
from .. import locksmith
from ..analyze import events as _ev


class ElasticController:
    def __init__(self, broker, cfg=None):
        cfg = cfg or config.load()
        self.broker = broker
        self.interval = max(0.01, cfg.elastic_interval_ms / 1000.0)
        self.cooldown = max(0.0, cfg.elastic_cooldown_ms / 1000.0)
        self.hysteresis = max(1, int(cfg.elastic_hysteresis))
        self.depth_high = max(1, int(cfg.elastic_depth_high))
        self.idle_ticks_limit = max(0, int(cfg.elastic_idle_ticks))
        self.min_ranks = max(1, int(cfg.elastic_min_ranks))
        self.max_ranks = int(cfg.elastic_max_ranks) or broker.pool.nranks
        self.target = len(broker.pool.healthy())   # restore point
        self.drain_timeout = 10.0
        self._seq = 0
        self._pressure_ticks = 0
        self._idle_ticks = 0
        self._last_busy = 0
        self._last_resize_mono = 0.0
        self._resize_lock = locksmith.make_lock("elastic.resize")
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop,
                                        name="elastic-controller",
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        self._kick.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def kick(self) -> None:
        """Wake the loop now (failure detector verdict just landed)."""
        self._kick.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._kick.wait(self.interval)
            self._kick.clear()
            if self._stop.is_set():
                return
            try:
                self._tick()
            except Exception as e:          # noqa: BLE001 - controller must live
                with self.broker._elastic_lock:
                    self.broker.elastic_state["last_error"] = repr(e)

    # -- decision loop -------------------------------------------------------
    def _tick(self) -> None:
        b = self.broker
        pool = b.pool
        # availability first: dead ranks (or a pool under target) restore
        # without hysteresis or cooldown — degraded minutes are SLO minutes
        if pool.failed - pool.retired or len(pool.healthy()) < self.target:
            self.resize("rank failure")
            return
        qs = b.fq.stats()
        depth = sum(t["queued"] for t in qs["tenants"].values())
        busy_delta = qs["rejected_busy"] - self._last_busy
        self._last_busy = qs["rejected_busy"]
        # ledger slack: bytes admitted but not yet measured on the pool — a
        # coarse how-far-behind signal that keeps working when queues are
        # bounded (rejections) rather than deep
        rep = b.ledger.report()
        admitted = sum(e["admitted_bytes"] for e in rep["tenants"].values())
        measured = sum(int((e.get("measured") or {}).get("bytes_sent", 0))
                       for e in rep["tenants"].values())
        slack = max(0, admitted - measured)
        slo_bad = False
        if b._infer_sched is not None:
            ss = b._infer_sched.stats()
            hr = ss.get("slo_hit_rate")
            fin = ss.get("slo_hits", 0) + ss.get("slo_misses", 0)
            slo_bad = hr is not None and fin >= 4 and hr < 0.9
        # latency-derived grow signal (docs/observability.md "SLO
        # burn-rate"): the worst tenant's measured miss fraction over its
        # error budget — above 1.0 the tenant is burning budget even if the
        # queue looks shallow (slow ranks, not deep queues)
        slo_burn = b.ledger.max_burn_rate()
        burn_bad = slo_burn is not None and slo_burn > 1.0
        pressured = (depth >= self.depth_high or busy_delta > 0 or slo_bad
                     or burn_bad)
        if pressured:
            self._pressure_ticks += 1
            self._idle_ticks = 0
        elif depth == 0 and busy_delta == 0:
            self._idle_ticks += 1
            self._pressure_ticks = 0
        else:
            self._pressure_ticks = 0
            self._idle_ticks = 0
        with b._elastic_lock:
            b.elastic_state["signals"] = {
                "depth": depth, "busy_delta": busy_delta,
                "ledger_slack_bytes": slack, "slo_bad": slo_bad,
                "slo_burn": slo_burn,
                "pressure_ticks": self._pressure_ticks,
                "idle_ticks": self._idle_ticks}
        if (self._last_resize_mono
                and time.monotonic() - self._last_resize_mono < self.cooldown):
            return
        cap = len(pool.healthy())
        if self._pressure_ticks >= self.hysteresis and cap < self.max_ranks:
            self.target = cap + 1
            self._pressure_ticks = 0
            self.resize("slo burn" if burn_bad and depth < self.depth_high
                        and busy_delta <= 0 else "queue pressure")
        elif (self.idle_ticks_limit
              and self._idle_ticks >= self.idle_ticks_limit
              and cap > self.min_ranks):
            spare = self._spare_rank()
            if spare is not None:
                self.target = cap - 1
                self._idle_ticks = 0
                pool.mark_failed(spare)     # drain-and-retire: failure path
                if b.sidecars is not None:
                    b.sidecars.retire(spare)
                self.resize("idle retire")

    def _spare_rank(self) -> Optional[int]:
        """A healthy rank no lease spans and the infer engine doesn't
        occupy — the only kind the idle path may retire."""
        b = self.broker
        used: set = set()
        with b._lease_lock:
            for lease in b._leases.values():
                used.update(lease.group)
        if b.infer_engine is not None:
            used.update(b.infer_engine.ranks)
        for r in reversed(b.pool.healthy()):
            if r not in used:
                return r
        return None

    # -- resize orchestration -------------------------------------------------
    def resize(self, reason: str) -> dict:
        """Run one full two-phase resize (see the module docstring for the
        protocol). Returns the ``last_resize`` record."""
        b = self.broker
        pool = b.pool
        with self._resize_lock:
            t0 = time.monotonic()
            self._seq += 1
            epoch = self._seq
            grew = shrunk = rebinds = 0
            b._resize_gate.clear()
            try:
                # ---- quiesce: stop dispatch, drain the pool -----------------
                b.fq.pause()
                deadline = time.monotonic() + self.drain_timeout
                while b.fq.inflight_total() and time.monotonic() < deadline:
                    time.sleep(0.005)
                if b._infer_sched is not None:
                    b._infer_sched.pause(timeout=30.0)
                dead: tuple = ()
                if pool.failed - pool.retired:
                    _, dead = pool.shrink_base()
                    shrunk = len(dead)
                self._round("quiesce", epoch)
                # ---- grow back to target ------------------------------------
                new_ranks: tuple = ()
                n_new = max(0, self.target - len(pool.healthy()))
                if n_new:
                    _, new_ranks = pool.grow_base(n_new)
                    grew = len(new_ranks)
                    if b.sidecars is not None:
                        for r in new_ranks:
                            b.sidecars.spawn_for(r)
                # ---- remap: dead -> replacement, same cids ------------------
                mapping = dict(zip(sorted(dead), new_ranks))
                if mapping:
                    rebinds = self._rebind_leases(mapping)
                    if b.infer_engine is not None and \
                            set(b.infer_engine.ranks) & mapping.keys():
                        b.infer_engine.rebind(mapping)
                self._round("resume", epoch)
            finally:
                if b._infer_sched is not None:
                    b._infer_sched.resume()
                b.fq.resume()
                b._resize_gate.set()
            self._last_resize_mono = time.monotonic()
            dur_ms = (self._last_resize_mono - t0) * 1e3
            record = {"reason": reason, "epoch": epoch,
                      "duration_ms": round(dur_ms, 3), "grew": grew,
                      "shrunk": shrunk, "rebinds": rebinds,
                      "at": time.time()}
            with b._elastic_lock:
                b.elastic_state["resizes"] += 1
                b.elastic_state["rebinds"] += rebinds
                b.elastic_state["last_resize"] = record
            from .. import perfvars
            if perfvars.enabled():
                perfvars.note_elastic(resizes=1, rebinds=rebinds, grown=grew,
                                      shrunk=shrunk)
                perfvars.set_elastic_gauges(
                    pool_size=len(pool.healthy()), target_size=self.target,
                    degraded=int(bool(pool.failed - pool.retired)))
            _ev.record_serve(pool.ctx, "resize", reason=reason, epoch=epoch,
                             grew=grew, shrunk=shrunk, rebinds=rebinds,
                             group=tuple(pool.base_comm.group))
            return record

    def _round(self, op: str, epoch: int) -> None:
        """One rebind round on every rank of the pool-wide comm (the ranks
        themselves rendezvous — a REAL Barrier, so explore models it and
        T214 audits the participant set). Delegated to the pool because the
        two backends reach their ranks differently: thread workers take a
        closure, procs workers take a framed 'round' op — the protocol
        (record + Barrier, elastic.protocol.rebind_round) is the same."""
        self.broker.pool.elastic_round(op, epoch)

    def _rebind_leases(self, mapping: dict) -> int:
        """Move every lease that spans a dead rank onto its replacement:
        position-wise group substitution, SAME cids (books and cid-range
        ownership survive), fresh channels via rebind_comm. A lease revoked
        while we iterate is skipped — revocation settled its state first."""
        b = self.broker
        n = 0
        with b._lease_lock:
            leases = list(b._leases.values())
        for lease in leases:
            if not set(lease.group) & mapping.keys():
                continue
            with b._lease_lock:
                if b._leases.get(lease.tenant) is not lease or lease.revoked:
                    continue            # revocation raced the rebind
                lease.group = tuple(mapping.get(r, r) for r in lease.group)
                group = lease.group
                cids = sorted(lease.comms, key=str)
            for cid in cids:
                b.pool.rebind_comm(cid, group, lease.tenant)
            b.ledger.note_rebind(lease.tenant)
            _ev.record_serve(b.pool.ctx, "lease_rebind", tenant=lease.tenant,
                             group=tuple(group),
                             mapping=sorted(map(list, mapping.items())))
            n += 1
        return n
