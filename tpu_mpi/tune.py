"""Collective algorithm portfolio + measurement-driven autotuner.

The reference outsources algorithm choice to libmpi's ``coll_tuned`` module
(``/root/reference/src/collective.jl:691-738``): MPICH/OpenMPI pick ring vs
recursive-doubling vs binomial per (collective, communicator size, message
size) from a *measured* decision table. This module is that layer for the
multi-process tier:

- :data:`PORTFOLIO` names every algorithm the proc-tier engine
  (``backend.ProcChannel``) implements per collective, and
  :func:`eligible` is the rank-uniform eligibility rule for each (the same
  deterministic-function-of-shared-values contract every tier gate obeys,
  so ranks can never pick different protocols for one round).
- :func:`select` is the ONE decision function — it replaces the scattered
  threshold constants. Resolution order: force-override
  (``TPU_MPI_COLL_ALGO`` / ``config.coll_algo``, for debugging and CI) →
  measured tuning table (``TPU_MPI_TUNE_TABLE`` / ``config.tune_table``,
  written by ``tpurun --tune``) → built-in heuristic. Every layer is
  clamped by :func:`eligible`, so a stale table or an aggressive override
  degrades to a correct algorithm instead of a protocol error.
  ``tpu_mpi.collective`` calls it at plan-build time, so the chosen
  algorithm is cached inside the :class:`~tpu_mpi.overlap.CollectivePlan`
  and invalidated with it (``config.GENERATION`` bumps on any reload,
  including a tuning-table change).
- :func:`autotune` / ``python -m tpu_mpi.tune`` / ``tpurun --tune`` sweep
  algorithm × size ladder × nranks *on the actual substrate* (real child
  processes over the real transport), assert every algorithm's result is
  bitwise-equal to the star reference, and persist the measured crossovers
  as a TOML table :func:`select` loads.

The built-in heuristic intentionally reproduces the engine's historical
behavior (star below ``TPU_MPI_RING_MIN_BYTES``, ring above for commutative
ops, dissemination Barrier, binomial Bcast) plus the same-host shm fold for
the small-message band — theory-guided guesses. The measured table exists
precisely because such guesses are wrong per substrate: on a single-core
TCP-loopback box, message *count* dominates and log-P algorithms lose to
the star, while the shm fold (no transport hop at all) wins by an order of
magnitude; on a real multi-host network the table flips the other way.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import config

__all__ = ["PORTFOLIO", "eligible", "candidates", "select", "heuristic",
           "parse_override", "load_table", "load_db_table", "write_table",
           "topology_key", "autotune", "merge_db", "main"]


# Every algorithm the proc-tier engine implements, per collective. "star"
# is the generic root-serialized rendezvous (always eligible; the chunked
# "starc" pipeline is a transparent refinement of it, not a separate
# selection). The rest map to ProcChannel runners in tpu_mpi/backend.py.
PORTFOLIO: Dict[str, Tuple[str, ...]] = {
    "allreduce":  ("star", "shm", "rdouble", "rabenseifner", "ring", "hier"),
    "barrier":    ("star", "shm", "dissemination"),
    "bcast":      ("star", "binomial"),
    "reduce":     ("star", "binomial"),
    "gather":     ("star", "binomial"),
    "scatter":    ("star", "binomial"),
    "allgather":  ("star", "ring", "hier"),
    "allgatherv": ("star", "ring"),
    "alltoall":   ("star", "pairwise", "hier"),
    "alltoallv":  ("star", "pairwise"),
}


def eligible(coll: str, algo: str, nranks: int, nbytes: Optional[int], *,
             commutative: bool = False, elementwise: bool = False,
             shm: bool = False, numeric: bool = True,
             domains: int = 0) -> bool:
    """Whether ``algo`` may run ``coll`` for this signature.

    Must stay a deterministic function of rank-uniform values: collective
    name, communicator size, payload bytes (uniform by the MPI count/dtype
    contract), op properties, config, and same-host topology (every rank of
    a single-host communicator agrees it is single-host). ``nbytes`` None
    means "payload size unknown" (object payloads) and disqualifies every
    size-gated algorithm. ``numeric`` means the payload is a fixed-dtype
    array (not dtype=object / arbitrary pickled objects). ``domains`` is
    the hierarchy-usable domain count from ``topology.domain_count`` (0 =
    flat world) — rank-uniform because the domain map is a function of
    the member list plus replicated inputs.
    """
    if algo == "star":
        return True
    if nranks < 2 or algo not in PORTFOLIO.get(coll, ()):
        return False
    if algo == "hier":
        # two-level composite: needs >= 2 contiguous equal domains with
        # >= 2 ranks each, raw array payloads of known size; the
        # allreduce variant chains per-segment rank-order left folds, so
        # it additionally needs an elementwise (segment-separable) op.
        if domains < 2 or nranks % domains or nranks // domains < 2:
            return False
        if not numeric or nbytes is None:
            return False
        return elementwise if coll == "allreduce" else True
    if algo == "shm":
        # the one-segment fold spans the whole communicator; a world split
        # into >= 2 domains (real hosts, or the TPU_MPI_DOMAINS emulation)
        # has no single shared segment — the comm layer's coll_shm_ok
        # already reports shm=False there, and this clamp keeps callers
        # that pass a stale flag (or probe eligibility off-comm) honest
        if not shm or domains >= 2:
            return False
        cap = config.load().coll_shm_max_bytes
        if cap <= 0:
            return False
        if coll == "barrier":
            return True
        # allreduce through the shm slots: fixed-size raw array payloads
        # folded flat at the owner — needs an elementwise op (flattening
        # must not change semantics) and a slot-sized payload.
        return (numeric and elementwise
                and nbytes is not None and nbytes < cap)
    if algo == "rdouble":
        # concatenation-allgather of raw contributions + the star's own
        # rank-order fold at every rank: any op, any picklable payload.
        return True
    if algo == "rabenseifner":
        # per-segment rank-order folds: elementwise (segment-separable),
        # raw array payloads only.
        return numeric and elementwise and nbytes is not None
    if algo == "ring":
        if coll == "allreduce":
            # ring order != rank order: commutativity required.
            return commutative and numeric and nbytes is not None
        return numeric                      # allgather / allgatherv
    if algo == "pairwise":
        return numeric                      # alltoall / alltoallv
    if algo in ("dissemination", "binomial"):
        return True
    return False


def candidates(coll: str, nranks: int, nbytes: Optional[int], *,
               commutative: bool = False, elementwise: bool = False,
               shm: bool = False, numeric: bool = True,
               domains: int = 0) -> List[str]:
    """Eligible algorithms for a signature, portfolio order."""
    return [a for a in PORTFOLIO.get(coll, ("star",))
            if eligible(coll, a, nranks, nbytes, commutative=commutative,
                        elementwise=elementwise, shm=shm, numeric=numeric,
                        domains=domains)]


# ---------------------------------------------------------------------------
# Force-override parsing ("allreduce=rdouble,barrier=star")
# ---------------------------------------------------------------------------

_override_cache: Tuple[str, Dict[str, str]] = ("", {})


def parse_override(spec: str) -> Dict[str, str]:
    """Parse ``config.coll_algo``: a comma list of ``collective=algorithm``
    pins. Unknown collectives/algorithms are ignored with a one-time
    warning rather than erroring — a typo'd debug knob must not take the
    job down."""
    global _override_cache
    if spec == _override_cache[0]:
        return _override_cache[1]
    out: Dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        coll, _, algo = part.partition("=")
        coll, algo = coll.strip().lower(), algo.strip().lower()
        if coll in PORTFOLIO and algo in PORTFOLIO[coll]:
            out[coll] = algo
        else:
            print(f"tpu_mpi: ignoring unknown algorithm override "
                  f"{part!r} (known: "
                  f"{ {c: list(a) for c, a in PORTFOLIO.items()} })",
                  file=sys.stderr)
    _override_cache = (spec, out)
    return out


# ---------------------------------------------------------------------------
# Tuning-table persistence (TOML): {(coll, nranks): [(min_bytes, algo)...]}
# ---------------------------------------------------------------------------

# Table shape on disk:
#
#   schema = 1
#   [allreduce.n8]
#   "0" = "shm"
#   "65536" = "ring"
#
# [<coll>.n<ranks>] sections map a byte threshold (TOML keys are strings)
# to the algorithm that wins from that size up. Thresholds are the measured
# crossover points, so at every measured (size, nranks) the table selects
# the argmin algorithm exactly.

# per-path (mtime, table) cache — a dict, not a single slot, because the
# table layer and the fleet database (config.tune_db) are consulted on the
# same select() call and a one-slot cache would thrash between them
_table_cache: Dict[str, Tuple[Any, Dict]] = {}
_TABLE_CACHE_CAP = 8
_table_warned: set = set()


def _parse_table_text(text: str) -> dict:
    """Tiny TOML-subset parser for the tuning table (sections + quoted
    string pairs), used when ``tomllib``/``tomli`` is unavailable
    (Python 3.10 without the vendored fallback's table support)."""
    root: dict = {}
    cur = root
    for ln, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("[") and line.endswith("]"):
            cur = root
            for part in line[1:-1].strip().split("."):
                part = part.strip().strip('"').strip("'")
                cur = cur.setdefault(part, {})
            continue
        key, eq, val = line.partition("=")
        if not eq:
            raise ValueError(f"tuning table line {ln}: not key = value")
        key = key.strip().strip('"').strip("'")
        val = val.split("#", 1)[0].strip()
        if val.startswith(("'", '"')):
            val = val[1:-1]
        elif val.isdigit():
            val = int(val)  # type: ignore[assignment]
        cur[key] = val
    return root


def _read_table_toml(path: str) -> dict:
    with open(path, "rb") as f:
        data = f.read()
    try:
        import tomllib
        return tomllib.loads(data.decode())
    except ImportError:
        pass
    try:
        import tomli  # type: ignore
        return tomli.loads(data.decode())
    except ImportError:
        return _parse_table_text(data.decode())


def _ladders_from_raw(raw: dict) -> Dict[Tuple[str, int],
                                         List[Tuple[int, str]]]:
    """Crossover ladders from one parsed TOML tree level: every
    ``[<coll>.n<ranks>]`` section whose collective/algorithms the
    portfolio knows. Unknown sections (meta, provenance, samples, topo)
    fall through silently — forward compatibility."""
    table: Dict[Tuple[str, int], List[Tuple[int, str]]] = {}
    for coll, per_n in raw.items():
        if coll not in PORTFOLIO or not isinstance(per_n, dict):
            continue
        for nkey, ladder in per_n.items():
            if not (isinstance(ladder, dict) and nkey.startswith("n")):
                continue
            n = int(nkey[1:])
            ent = sorted(((int(th), str(algo))
                          for th, algo in ladder.items()
                          if str(algo) in PORTFOLIO[coll]),
                         reverse=True)
            if ent:
                table[(coll, n)] = ent
    return table


def _cached_table(cache_key: str, path: str, build) -> Dict:
    """mtime-cached table load with the shared unreadable/unusable
    warn-once behavior; ``build(raw)`` turns the parsed TOML into the
    table for this view."""
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        if path not in _table_warned:
            _table_warned.add(path)
            print(f"tpu_mpi: tuning table {path!r} not readable; "
                  f"using the built-in heuristic", file=sys.stderr)
        return {}
    hit = _table_cache.get(cache_key)
    if hit is not None and hit[0] == mtime:
        return hit[1]
    try:
        table = build(_read_table_toml(path))
    except Exception as e:
        if path not in _table_warned:
            _table_warned.add(path)
            print(f"tpu_mpi: tuning table {path!r} unusable ({e}); "
                  f"using the built-in heuristic", file=sys.stderr)
        table = {}
    while len(_table_cache) >= _TABLE_CACHE_CAP:
        _table_cache.pop(next(iter(_table_cache)))
    _table_cache[cache_key] = (mtime, table)
    return table


def load_table(path: str) -> Dict[Tuple[str, int], List[Tuple[int, str]]]:
    """Load (and cache on mtime) a tuning table. A missing or malformed
    file disables the table layer with a one-time warning — the heuristic
    still serves, a bad table never takes the job down."""
    path = os.path.expanduser(path)
    return _cached_table(path, path, _ladders_from_raw)


def load_db_table(path: str, topology: str) -> Dict[Tuple[str, int],
                                                    List[Tuple[int, str]]]:
    """Per-topology view of a fleet database: the top-level ladders
    belong to the fabric named by ``[meta] topology`` (missing/empty
    meta = a plain v1 table, applied everywhere); every other fabric's
    ladders live under ``[topo."<key>".<coll>.n<n>]``. A query only ever
    sees its own topology's ladders, so ``_nearest_nranks``
    interpolation cannot leak a foreign fabric's crossovers."""
    path = os.path.expanduser(path)

    def build(raw: dict) -> Dict:
        meta = raw.get("meta")
        meta_topo = str(meta.get("topology", "") if isinstance(meta, dict)
                        else "")
        if not meta_topo or meta_topo == topology:
            return _ladders_from_raw(raw)
        topo = raw.get("topo")
        sub = topo.get(topology) if isinstance(topo, dict) else None
        return _ladders_from_raw(sub) if isinstance(sub, dict) else {}

    return _cached_table(f"{path}\x00{topology}", path, build)


def write_table(path: str,
                table: Dict[Tuple[str, int], List[Tuple[int, str]]],
                header: str = "") -> None:
    """Persist a tuning table as TOML (atomic rename)."""
    path = os.path.expanduser(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    lines = ["# tpu_mpi collective tuning table (tpurun --tune)"]
    if header:
        lines += [f"# {h}" for h in header.splitlines()]
    lines.append("schema = 1")
    for (coll, n) in sorted(table):
        lines.append(f"\n[{coll}.n{n}]")
        for th, algo in sorted(table[(coll, n)]):
            lines.append(f'"{th}" = "{algo}"')
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write("\n".join(lines) + "\n")
    os.replace(tmp, path)


def _nearest_nranks(ns: Sequence[int], nranks: int) -> int:
    """The measured communicator size a query interpolates to: exact match,
    else the nearest measured size below (libmpi decision tables
    interpolate the same way), CLAMPED at the table edges — queries below
    the smallest measured size use the smallest, queries above the largest
    use the largest. No extrapolation: an n=3 query against a table
    measured at {4, 8} must not invent an unmeasured regime, and an n=16
    query against the same table pins to n=8."""
    if nranks in ns:
        return nranks
    below = [n for n in ns if n < nranks]
    return below[-1] if below else min(ns)


def _table_lookup(table: Dict[Tuple[str, int], List[Tuple[int, str]]],
                  coll: str, nranks: int,
                  nbytes: Optional[int]) -> Optional[str]:
    """The table's pick for one (coll, nranks, nbytes) query, via
    :func:`_nearest_nranks` interpolation over the measured sizes."""
    ns = sorted(n for (c, n) in table if c == coll)
    if not ns:
        return None
    n = _nearest_nranks(ns, nranks)
    size = 0 if nbytes is None else int(nbytes)
    # order-independent walk: loaded tables arrive descending-sorted, but
    # the in-memory table from _crossovers is built ascending
    for th, algo in sorted(table[(coll, n)], reverse=True):
        if size >= th:
            return algo
    return None


# ---------------------------------------------------------------------------
# Heuristic table + the one decision function
# ---------------------------------------------------------------------------

def heuristic(coll: str, nranks: int, nbytes: Optional[int], *,
              commutative: bool = False, elementwise: bool = False,
              shm: bool = False, numeric: bool = True,
              domains: int = 0) -> str:
    """Built-in crossovers (used when no measured table applies). The bulk
    threshold is ``backend._RING_MIN_BYTES`` — read live, because tests and
    users monkeypatch it / set ``TPU_MPI_RING_MIN_BYTES`` (the historical
    knob this table absorbed). Bulk algorithms take precedence over the shm
    fold so a forced-low ring threshold behaves exactly as it always has.
    On multi-domain worlds the two-level composite wins once the payload
    clears ``config.hier_min_bytes`` — inter-domain messages are the
    expensive resource there, and hierarchy sends D-1 of them per segment
    instead of n-1."""
    from . import backend as B

    def ok(algo: str) -> bool:
        return eligible(coll, algo, nranks, nbytes, commutative=commutative,
                        elementwise=elementwise, shm=shm, numeric=numeric,
                        domains=domains)

    ring_min = B._RING_MIN_BYTES
    bulky = numeric and nbytes is not None and nbytes >= ring_min
    hier_ok = (domains >= 2 and numeric and nbytes is not None
               and nbytes >= config.load().hier_min_bytes and ok("hier"))
    if coll == "allreduce":
        if hier_ok:
            return "hier"
        if bulky and ok("ring"):
            return "ring"
        if ok("shm"):
            return "shm"
        return "star"
    if coll == "barrier":
        return "shm" if ok("shm") else "dissemination"
    if coll == "bcast":
        return "binomial"
    if coll in ("allgather", "allgatherv"):
        if coll == "allgather" and hier_ok:
            return "hier"
        return "ring" if bulky and ok("ring") else "star"
    if coll == "alltoall":
        if hier_ok:
            return "hier"
        return "pairwise" if bulky and ok("pairwise") else "star"
    if coll == "alltoallv":
        # counts differ per rank: dtype-only gate (uniform by contract),
        # a size gate would let ranks disagree on the tier.
        return "pairwise" if ok("pairwise") else "star"
    return "star"           # reduce / gather / scatter default to the star


def topology_key(domains: int = 0, nranks: int = 0,
                 arch: Optional[str] = None) -> str:
    """Shared fleet-DB topology key — delegates to
    :func:`tpu_mpi.topology.topology_key` so the runtime, sweeps and
    ``tune merge`` can never disagree on the spelling."""
    from . import topology as _topo
    return _topo.topology_key(domains, nranks, arch)


def select(coll: str, nranks: int, nbytes: Optional[int] = None, *,
           commutative: bool = False, elementwise: bool = False,
           shm: bool = False, numeric: bool = True,
           domains: int = 0) -> str:
    """THE algorithm decision for one collective signature.

    Resolution: force-override → online hot-swap table (the in-memory
    table the bandit loop recomputes from live arm stats,
    :mod:`tpu_mpi.tune_online`) → measured table → fleet database
    (``config.tune_db``, written by ``tune merge``) → heuristic, each
    clamped by :func:`eligible`. Called once per plan signature (the
    result is cached inside the CollectivePlan); must stay deterministic
    across ranks for fixed rank-uniform inputs + uniform config — the
    online table satisfies this because every rank derives it from the
    SAME merged cross-rank stats in a lockstep swap round. The fleet DB
    layer resolves per-topology: only rows recorded under THIS world's
    ``topology_key`` are consulted, so a foreign fabric's crossovers are
    never applied here.
    """
    if nranks < 2:
        return "star"

    def ok(algo: str) -> bool:
        return eligible(coll, algo, nranks, nbytes, commutative=commutative,
                        elementwise=elementwise, shm=shm, numeric=numeric,
                        domains=domains)

    cfg = config.load()
    forced = parse_override(cfg.coll_algo).get(coll)
    if forced is not None and ok(forced):
        return forced
    if cfg.tune_explore > 0.0:
        from . import tune_online
        online = tune_online.table()
        if online:
            algo = _table_lookup(online, coll, nranks, nbytes)
            if algo is not None and ok(algo):
                return algo
    if cfg.tune_table:
        algo = _table_lookup(load_table(cfg.tune_table), coll, nranks, nbytes)
        if algo is not None and ok(algo):
            return algo
    if cfg.tune_db:
        algo = _table_lookup(
            load_db_table(cfg.tune_db, topology_key(domains, nranks)),
            coll, nranks, nbytes)
        if algo is not None and ok(algo):
            return algo
    return heuristic(coll, nranks, nbytes, commutative=commutative,
                     elementwise=elementwise, shm=shm, numeric=numeric,
                     domains=domains)


# ---------------------------------------------------------------------------
# The autotuner: measure every algorithm on the actual substrate
# ---------------------------------------------------------------------------

LADDER = (8, 64, 512, 4096, 32768, 262144, 2097152)
ROOTED_LADDER = (64, 4096, 262144)
SWEEP_COLLS = ("allreduce", "barrier", "bcast", "reduce", "gather", "scatter")


def _iters_for(nbytes: int, scale: float = 1.0) -> Tuple[int, int]:
    """(warmup, iters) per point; fewer repeats for bulk sizes."""
    if nbytes >= 1 << 20:
        w, it = 1, 3
    elif nbytes >= 1 << 18:
        w, it = 1, 5
    elif nbytes >= 1 << 15:
        w, it = 2, 10
    else:
        w, it = 3, 20
    return w, max(1, int(it * scale))


# The in-job bench worker. Runs as an SPMD script under launch_processes:
# every rank walks the identical (coll, algo, size) schedule in lockstep,
# flipping the algorithm via the force-override env + config reload (which
# also exercises the override path end to end), and rank 0 writes the
# measured rows. Results are asserted bitwise-equal to the star reference
# per point, on every rank, and AND-reduced.
_WORKER = r'''
import json, os, sys, time
import numpy as np
import tpu_mpi as MPI
from tpu_mpi import config as _cfg
from tpu_mpi import tune as _tune

MPI.Init()
comm = MPI.COMM_WORLD
rank, size = MPI.Comm_rank(comm), MPI.Comm_size(comm)
spec = json.load(open(sys.argv[1]))
scale = spec["scale"]

def set_algo(coll, algo):
    os.environ["TPU_MPI_COLL_ALGO"] = f"{coll}={algo}"
    _cfg.load(refresh=True)

def payload(nbytes):
    n = max(1, nbytes // 8)
    # integer-valued float64: SUM folds are exact, so bitwise equality is a
    # meaningful assertion rather than vacuous float luck
    return (np.arange(n, dtype=np.float64) % 97) + rank + 1.0

def once(coll, nbytes):
    if coll == "barrier":
        MPI.Barrier(comm); return None
    if coll == "allreduce":
        return np.asarray(MPI.Allreduce(payload(nbytes), MPI.SUM, comm))
    if coll == "bcast":
        buf = payload(nbytes) if rank == 0 else np.zeros(max(1, nbytes // 8))
        return np.asarray(MPI.Bcast(buf, 0, comm))
    if coll == "reduce":
        out = MPI.Reduce(payload(nbytes), MPI.SUM, 0, comm)
        return None if out is None else np.asarray(out)
    if coll == "gather":
        out = MPI.Gather(payload(nbytes), 0, comm)
        return None if out is None else np.asarray(out)
    if coll == "scatter":
        send = np.tile(payload(nbytes), size) if rank == 0 else None
        out = MPI.Scatter(send, max(1, nbytes // 8), 0, comm)
        return None if out is None else np.asarray(out)
    raise AssertionError(coll)

rows = []
for coll, nbytes, algos in spec["points"]:
    set_algo(coll, "star")
    ref = once(coll, nbytes)
    refb = b"" if ref is None else ref.tobytes()
    for algo in algos:
        set_algo(coll, algo)
        out = once(coll, nbytes)                     # correctness probe
        same = (b"" if out is None else out.tobytes()) == refb
        warm, iters = _tune._iters_for(nbytes, scale)
        for _ in range(warm):
            once(coll, nbytes)
        t0 = time.perf_counter()
        for _ in range(iters):
            once(coll, nbytes)
        dt = (time.perf_counter() - t0) / iters
        # slowest rank defines the collective's latency; bitwise flag is
        # the AND over ranks (MIN on {0,1})
        stats = np.asarray(MPI.Allreduce(
            np.array([dt, float(same)]), MPI.MAX, comm))
        ok = np.asarray(MPI.Allreduce(
            np.array([float(same)]), MPI.MIN, comm))
        if rank == 0:
            rows.append({"coll": coll, "nranks": size, "bytes": int(nbytes),
                         "algo": algo,
                         "lat_us": round(float(stats[0]) * 1e6, 2),
                         "bitwise_equal_to_star": bool(ok[0] >= 1.0)})
            print(f"  {coll:<10} n{size} {nbytes:>9d}B {algo:<13} "
                  f"{float(stats[0])*1e6:>10.1f} us  "
                  f"bitwise={bool(ok[0] >= 1.0)}", file=sys.stderr)
set_algo("allreduce", "star")
if rank == 0:
    with open(sys.argv[2], "w") as f:
        json.dump(rows, f)
MPI.Finalize()
'''


def _active_domains(nranks: int) -> int:
    """The hierarchy domain count ``TPU_MPI_DOMAINS`` implies for a world
    of ``nranks`` (0 when unset or the world doesn't split evenly) — the
    sweep-side mirror of ``topology.domain_count``, which needs a live
    communicator the tune CLI doesn't have."""
    k = int(config.load().domains)
    return k if (k >= 2 and nranks % k == 0 and nranks // k >= 2) else 0


def _sweep_spec(nranks: int, sizes: Sequence[int],
                colls: Sequence[str]) -> list:
    """The lockstep (coll, nbytes, algos) schedule for one world size.
    Algorithms are the deployment-eligible set per point (shm capped by the
    configured slot size etc., hier only on a multi-domain world), so the
    emitted table never selects something the runtime would clamp away."""
    points = []
    shm_ok = os.path.isdir("/dev/shm")   # single-host sweep by construction
    dom = _active_domains(nranks)
    for coll in colls:
        ladder: Sequence[int] = ((0,) if coll == "barrier"
                                 else sizes if coll == "allreduce"
                                 else [s for s in ROOTED_LADDER
                                       if s <= max(sizes)])
        for nbytes in ladder:
            algos = candidates(coll, nranks, nbytes, commutative=True,
                               elementwise=True, shm=shm_ok, numeric=True,
                               domains=dom)
            points.append((coll, int(nbytes), algos))
    return points


def _crossovers(rows: List[dict]) -> Dict[Tuple[str, int],
                                          List[Tuple[int, str]]]:
    """Reduce measured rows to threshold->algorithm crossover entries: at
    each measured size the winner is the argmin latency; thresholds sit at
    the measured sizes where the winner changes (so the table reproduces
    the argmin at every measured point exactly)."""
    best: Dict[Tuple[str, int], List[Tuple[int, str]]] = {}
    by_point: Dict[Tuple[str, int, int], Tuple[float, str]] = {}
    for r in rows:
        key = (r["coll"], r["nranks"], r["bytes"])
        if key not in by_point or r["lat_us"] < by_point[key][0]:
            by_point[key] = (r["lat_us"], r["algo"])
    for (coll, n, nbytes) in sorted(by_point):
        _, algo = by_point[(coll, n, nbytes)]
        ent = best.setdefault((coll, n), [])
        if not ent:
            ent.append((0, algo))            # below-ladder sizes inherit
        elif ent[-1][1] != algo:
            ent.append((nbytes, algo))
    return best


def rows_from_pvars(records: Sequence[dict],
                    min_samples: Optional[int] = None,
                    skipped: Optional[List[Tuple]] = None) -> List[dict]:
    """Measured rows (the autotune sweep's row schema) from pvar dump
    records (``perfvars.snapshot``): mean latency per (collective, world
    size, payload bytes, algorithm) aggregated across ranks and comms. The
    production workload's own counters become tuning input — the table is
    fed by the same measurements it will later be judged against.

    Cells with fewer than ``min_samples`` observations (default
    ``config.tune_min_samples``) are dropped — a single cold-start outlier
    must not set a crossover. Pass a list as ``skipped`` to collect the
    dropped (coll, nranks, nbytes, algo) keys."""
    if min_samples is None:
        min_samples = max(1, int(config.load().tune_min_samples))
    acc: Dict[Tuple[str, int, int, str], List[float]] = {}
    for rec in records:
        for comm in rec.get("comms", ()):
            n = int(comm.get("size") or 0)
            if n < 2:
                continue
            for t in comm.get("times", ()):
                # non-portfolio names (internal rendezvous like the online
                # tuner's own TuneSwap round) are not tunable cells
                if t["coll"] not in PORTFOLIO:
                    continue
                nbytes = int(t["nbytes"])
                key = (t["coll"], n, max(0, nbytes), t["algo"])
                ent = acc.setdefault(key, [0.0, 0.0])
                ent[0] += t["count"]
                ent[1] += t["total_s"]
    rows = []
    for (c, n, b, a), (cnt, tot) in sorted(acc.items()):
        if not cnt:
            continue
        if cnt < min_samples:
            if skipped is not None:
                skipped.append((c, n, b, a, int(cnt)))
            continue
        rows.append({"coll": c, "nranks": n, "bytes": b, "algo": a,
                     "count": int(cnt),
                     "lat_us": round(tot / cnt * 1e6, 3)})
    return rows


def table_from_pvars(paths: Sequence[str],
                     out_table: Optional[str] = None) -> dict:
    """Crossover table from pvar dumps: load, reduce to rows, argmin per
    measured point (``_crossovers``), optionally persist. A point measured
    under only ONE algorithm still pins that algorithm as its threshold
    entry — production counters rarely cover the full portfolio, so this
    table refines, not replaces, a sweep-built one."""
    from . import perfvars
    records = perfvars.load_dumps(paths)
    skipped: List[Tuple] = []
    rows = rows_from_pvars(records, skipped=skipped)
    table = _crossovers(rows)
    rec = {"bench": "coll_algos_from_pvars", "rows": rows,
           "table": {f"{c}.n{n}": {str(th): algo for th, algo in ent}
                     for (c, n), ent in table.items()},
           "min_samples": max(1, int(config.load().tune_min_samples)),
           "skipped_cells": len(skipped),
           "skipped": [{"coll": c, "nranks": n, "bytes": b, "algo": a,
                        "count": cnt} for c, n, b, a, cnt in skipped],
           "sources": [r["_path"] for r in records]}
    if out_table:
        write_table(out_table, table,
                    header=f"from pvar dumps: {len(records)} ranks")
        rec["table_path"] = os.path.expanduser(out_table)
    return rec


# ---------------------------------------------------------------------------
# Fleet database (schema 2): shared crossover ladders + the samples behind
# them
# ---------------------------------------------------------------------------

# DB shape on disk — a schema-1 table every existing consumer can load
# as-is (the ladder sections are byte-identical and load_table skips
# unknown top-level keys), plus the evidence behind the ladders:
#
#   schema = 2
#   [allreduce.n4]
#   "0" = "shm"
#   [meta]
#   topology = "single-host/x86_64"
#   [provenance.s0]
#   source = "pvars-rank0.json"
#   kind = "pvars"
#   [samples.allreduce.n4.shm]
#   "1024" = "32:41.5"              # observation count : mean latency (us)
#   [topo."2d4r/x86_64".allreduce.n8]
#   "0" = "hier"
#   [topo."2d4r/x86_64".samples.allreduce.n8.hier]
#   "65536" = "32:120.5"
#
# Keeping raw (count, mean) cells makes re-merges sample-count-weighted by
# construction: a node contributing 1000 observations of a cell outweighs
# one contributing 10, and folding the same DB again is idempotent on the
# ladders. The [meta] topology string is the database's DEFAULT fleet key:
# its ladders and samples sit at the top level (byte-compatible with the
# pre-topology schema), while every other fabric's rows live under
# [topo."<key>"...] — so one DB can hold the whole fleet's evidence and
# ``load_db_table`` serves each world only its own fabric's crossovers.


def _db_read(path: str) -> Tuple[Dict[Tuple[str, str, int, int, str],
                                      List[float]],
                                 List[dict], Dict]:
    """(samples, provenance, meta) from an existing fleet DB, for
    incremental re-merges; all-empty when the file is absent or predates
    schema 2 (plain tables contribute ladders via the overlay path, not
    samples). Sample keys are ``(topology, coll, nranks, bytes, algo)``;
    top-level sample sections belong to the DB's meta topology (``""``
    when the DB predates the field — the caller re-keys that to its
    default)."""
    samples: Dict[Tuple[str, str, int, int, str], List[float]] = {}
    prov: List[dict] = []
    meta: Dict = {}
    try:
        raw = _read_table_toml(os.path.expanduser(path))
    except Exception:
        return samples, prov, meta
    meta = dict(raw.get("meta") or {})
    pv = raw.get("provenance") or {}
    for skey in sorted(pv, key=str):
        if isinstance(pv[skey], dict):
            prov.append(dict(pv[skey]))

    def read_samples(tree: dict, topo: str) -> None:
        for coll, per_n in (tree.get("samples") or {}).items():
            if coll not in PORTFOLIO or not isinstance(per_n, dict):
                continue
            for nkey, per_algo in per_n.items():
                if not (isinstance(per_algo, dict)
                        and str(nkey).startswith("n")):
                    continue
                n = int(str(nkey)[1:])
                for algo, cells in per_algo.items():
                    if (algo not in PORTFOLIO[coll]
                            or not isinstance(cells, dict)):
                        continue
                    for bkey, val in cells.items():
                        cnt_s, _, mean_s = str(val).partition(":")
                        try:
                            cnt, mean = int(cnt_s), float(mean_s)
                        except ValueError:
                            continue
                        ent = samples.setdefault(
                            (topo, coll, n, int(bkey), algo), [0, 0.0])
                        ent[0] += cnt
                        ent[1] += cnt * mean

    read_samples(raw, str(meta.get("topology") or ""))
    topo_tree = raw.get("topo")
    if isinstance(topo_tree, dict):
        for tkey, sub in topo_tree.items():
            if isinstance(sub, dict):
                read_samples(sub, str(tkey))
    return samples, prov, meta


def _write_db(path: str,
              samples: Dict[Tuple[str, str, int, int, str], List[float]],
              overlay: Dict[Tuple[str, int], List[Tuple[int, str]]],
              provenance: List[dict], meta: Dict,
              min_samples: int) -> dict:
    """Derive per-topology ladders from the merged samples (min-samples
    guard applied per cell), overlay sample-less measured-table ladders
    for default-topology (coll, nranks) keys the samples don't cover, and
    persist the schema-2 DB atomically. The meta topology's ladders and
    samples keep the legacy top-level layout; every other topology's go
    under ``[topo."<key>"...]``. Returns the merge record."""
    default_topo = str(meta.get("topology") or "")
    rows: List[dict] = []
    skipped: List[Tuple] = []
    by_topo_rows: Dict[str, List[dict]] = {}
    for (topo, c, n, b, a), (cnt, tot_us) in sorted(samples.items()):
        if cnt < min_samples:
            skipped.append((c, n, b, a, int(cnt)))
            continue
        row = {"topology": topo, "coll": c, "nranks": n, "bytes": b,
               "algo": a, "count": int(cnt),
               "lat_us": round(tot_us / cnt, 3)}
        rows.append(row)
        by_topo_rows.setdefault(topo, []).append(row)

    tables: Dict[str, Dict[Tuple[str, int], List[Tuple[int, str]]]] = {
        topo: _crossovers(trows) for topo, trows in by_topo_rows.items()}
    table = tables.setdefault(default_topo, {})
    overlaid = []
    for k, ent in sorted(overlay.items()):
        if k not in table:
            table[k] = list(ent)
            overlaid.append(f"{k[0]}.n{k[1]}")

    path = os.path.expanduser(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    lines = ["# tpu_mpi fleet tuning database (python -m tpu_mpi.tune merge)",
             "schema = 2"]

    def emit_ladders(tab: Dict, prefix: str) -> None:
        for (coll, n) in sorted(tab):
            lines.append(f"\n[{prefix}{coll}.n{n}]")
            for th, algo in sorted(tab[(coll, n)]):
                lines.append(f'"{th}" = "{algo}"')

    def emit_samples(topo: str, prefix: str) -> None:
        by_sec: Dict[Tuple[str, int, str],
                     List[Tuple[int, int, float]]] = {}
        for (t, c, n, b, a), (cnt, tot_us) in samples.items():
            if t == topo:
                by_sec.setdefault((c, n, a), []).append(
                    (b, int(cnt), tot_us / cnt))
        for (c, n, a) in sorted(by_sec):
            lines.append(f"\n[{prefix}samples.{c}.n{n}.{a}]")
            for b, cnt, mean in sorted(by_sec[(c, n, a)]):
                lines.append(f'"{b}" = "{cnt}:{round(mean, 3)}"')

    emit_ladders(table, "")
    lines.append("\n[meta]")
    for k in sorted(meta):
        v = meta[k]
        lines.append(f"{k} = {v}" if isinstance(v, int)
                     else f'{k} = "{v}"')
    for i, ent in enumerate(provenance):
        lines.append(f"\n[provenance.s{i}]")
        for k in sorted(ent):
            v = ent[k]
            lines.append(f"{k} = {v}" if isinstance(v, int)
                         else f'{k} = "{v}"')
    emit_samples(default_topo, "")
    for topo in sorted(tables):
        if topo == default_topo:
            continue
        emit_ladders(tables[topo], f'topo."{topo}".')
        emit_samples(topo, f'topo."{topo}".')
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write("\n".join(lines) + "\n")
    os.replace(tmp, path)

    all_topos = sorted({t for (t, *_rest) in samples} | {default_topo})
    return {"bench": "tune_merge", "db_path": path,
            "schema": 2, "meta": dict(meta),
            "min_samples": min_samples,
            "cells": len(samples), "rows": rows,
            "skipped_cells": len(skipped),
            "skipped": [{"coll": c, "nranks": n, "bytes": b, "algo": a,
                         "count": cnt} for c, n, b, a, cnt in skipped],
            "overlaid": overlaid,
            "topologies": all_topos,
            "table": {f"{c}.n{n}": {str(th): algo for th, algo in ent}
                      for (c, n), ent in table.items()},
            "tables": {topo: {f"{c}.n{n}": {str(th): algo
                                            for th, algo in ent}
                              for (c, n), ent in tab.items()}
                       for topo, tab in tables.items()},
            "provenance": provenance}


def merge_db(out_path: str, pvar_paths: Sequence[str] = (),
             table_paths: Sequence[str] = (),
             min_samples: Optional[int] = None,
             topology: Optional[str] = None) -> dict:
    """Fold per-rank pvar dumps and measured tuning tables into one shared
    fleet database at ``out_path`` (``select()`` loads it through
    ``config.tune_db`` with the same nearest-nranks interpolation as the
    per-job table). An existing DB at the path is folded back in first, so
    repeated merges accumulate fleet evidence instead of overwriting it;
    measured v1 tables carry no samples and contribute their ladders only
    where the samples are silent."""
    from . import perfvars
    if min_samples is None:
        min_samples = max(1, int(config.load().tune_min_samples))
    out_path = os.path.expanduser(out_path)
    samples, prov, meta = (_db_read(out_path) if os.path.exists(out_path)
                           else ({}, [], {}))
    if topology is not None:
        meta["topology"] = topology
    elif not meta.get("topology"):
        # the shared key helper — the same spelling the runtime stamps
        # into pvar dump records, so merge and runtime can never disagree
        meta["topology"] = topology_key()
    meta["merged_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    default_topo = str(meta["topology"])
    # pre-topology DBs carry "" sample keys: they are the DB's own rows
    for key in [k for k in samples if k[0] == ""]:
        ent = samples.setdefault((default_topo,) + key[1:], [0, 0.0])
        old = samples.pop(key)
        ent[0] += old[0]
        ent[1] += old[1]

    records = perfvars.load_dumps(pvar_paths) if pvar_paths else []
    for rec in records:
        # dump records are stamped with the topology key of the world
        # that produced them (perfvars.snapshot); unstamped legacy dumps
        # fold into the DB's default fabric
        rtopo = str(rec.get("topology") or "") or default_topo
        ncomms = 0
        for comm in rec.get("comms", ()):
            n = int(comm.get("size") or 0)
            if n < 2:
                continue
            ncomms += 1
            for t in comm.get("times", ()):
                coll, algo = t["coll"], t["algo"]
                if coll not in PORTFOLIO or algo not in PORTFOLIO[coll]:
                    continue
                key = (rtopo, coll, n, max(0, int(t["nbytes"])), algo)
                ent = samples.setdefault(key, [0, 0.0])
                ent[0] += int(t["count"])
                ent[1] += float(t["total_s"]) * 1e6
        prov.append({"source": os.path.basename(rec["_path"]),
                     "kind": "pvars", "comms": ncomms, "topology": rtopo})
    overlay: Dict[Tuple[str, int], List[Tuple[int, str]]] = {}
    for tp in table_paths:
        t = load_table(tp)
        for k, ent in t.items():
            overlay.setdefault(k, list(ent))
        prov.append({"source": os.path.basename(os.path.expanduser(tp)),
                     "kind": "table", "entries": len(t)})
    return _write_db(out_path, samples, overlay, prov, meta, min_samples)


def merge_main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: ``python -m tpu_mpi.tune merge`` / ``tpurun --tune merge``."""
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m tpu_mpi.tune merge",
        description="Fold per-rank pvar dumps and measured tuning tables "
                    "into one shared fleet database (schema 2, "
                    "sample-count-weighted), loaded by select() via "
                    "TPU_MPI_TUNE_DB.")
    p.add_argument("sources", nargs="*", metavar="PVAR_DUMP",
                   help="pvar dump files/dirs (TPU_MPI_PVARS_DUMP output)")
    p.add_argument("--table", action="append", default=[], metavar="TOML",
                   help="measured tuning table to fold in (ladder overlay "
                        "for (coll, nranks) keys without samples); repeat "
                        "for several")
    p.add_argument("-o", "--out", default=None,
                   help="fleet DB path (default: $TPU_MPI_TUNE_DB or "
                        "~/.config/tpu_mpi/tune-db.toml)")
    p.add_argument("--min-samples", type=int, default=None,
                   help="noise guard: drop cells with fewer observations "
                        "(default $TPU_MPI_TUNE_MIN_SAMPLES)")
    p.add_argument("--topology", default=None,
                   help="fleet key stamped into [meta] (default: keep the "
                        "existing DB's, else single-host/<machine>)")
    p.add_argument("--json", default=None,
                   help="also write the merge record as JSON")
    args = p.parse_args(argv)
    if not args.sources and not args.table:
        p.error("nothing to merge: give pvar dumps and/or --table files")
    out = (args.out or config.load().tune_db
           or os.path.join("~", ".config", "tpu_mpi", "tune-db.toml"))
    rec = merge_db(out, args.sources, args.table,
                   min_samples=args.min_samples, topology=args.topology)
    if args.json:
        with open(os.path.expanduser(args.json), "w") as f:
            json.dump(rec, f, indent=1)
    print(f"tune merge: {rec['db_path']} <- {len(rec['provenance'])} "
          f"sources, {rec['cells']} sample cells "
          f"({rec['skipped_cells']} below min_samples="
          f"{rec['min_samples']}), topology {rec['meta']['topology']}")
    for sect, ladder in sorted(rec["table"].items()):
        tag = " (overlay)" if sect in rec["overlaid"] else ""
        print(f"  [{sect}]{tag} " + "  ".join(
            f"{th}B->{algo}" for th, algo in sorted(
                ladder.items(), key=lambda kv: int(kv[0]))))
    return 0


# ---------------------------------------------------------------------------
# Regression sentinel: does the committed table still win here?
# ---------------------------------------------------------------------------

def sentinel_main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: ``python -m tpu_mpi.tune sentinel`` — re-measure the committed
    tuning artifacts' points on the current runner (best-of-N repeats to
    suppress scheduler noise) and fail when the committed table's selection
    loses to an eligible alternate by more than the threshold, printing the
    offending cells. CI runs this against the committed cpusim artifacts so
    a substrate drift that invalidates them fails loudly instead of
    silently serving a stale table."""
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m tpu_mpi.tune sentinel",
        description="Replay the committed tuning artifacts and fail when a "
                    "committed selection loses to an eligible alternate on "
                    "this runner.")
    p.add_argument("--table", default="benchmarks/results/tune-cpusim.toml",
                   help="committed tuning table to judge")
    p.add_argument("--record", default="benchmarks/results/"
                                       "coll-algos-cpusim.json",
                   help="committed sweep record naming the measured points")
    p.add_argument("--threshold", type=float, default=1.10,
                   help="fail ratio, committed selection vs best measured "
                        "(default 1.10 = loses by >10%%)")
    p.add_argument("--repeat", type=int, default=3,
                   help="best-of-N sweep repeats per world size (noise "
                        "suppression; default 3)")
    p.add_argument("--nranks", default=None,
                   help="restrict to these world sizes (comma list; "
                        "default: every size in the record)")
    p.add_argument("--max-points", type=int, default=0,
                   help="cap (coll, size) points per world size (0 = all)")
    p.add_argument("--scale", type=float, default=0.5,
                   help="iteration-count multiplier per point (default 0.5)")
    p.add_argument("--json", default=None,
                   help="also write the sentinel record as JSON")
    args = p.parse_args(argv)

    committed = load_table(args.table)
    if not committed:
        print(f"tune sentinel: no committed table at {args.table!r}",
              file=sys.stderr)
        return 2
    with open(os.path.expanduser(args.record)) as f:
        rec = json.load(f)
    want_n = ([int(x) for x in args.nranks.split(",") if x]
              if args.nranks else None)
    pts: Dict[int, Dict[Tuple[str, int], List[str]]] = {}
    for r in rec.get("rows", []):
        n = int(r["nranks"])
        if (want_n and n not in want_n) or r["coll"] not in SWEEP_COLLS:
            continue
        # topology-keyed records: a row measured on a foreign fabric (a
        # different domain shape than this runner reproduces) is not
        # replayable here and must not be judged here
        rtopo = r.get("topology")
        if rtopo and rtopo != topology_key(_active_domains(n), n):
            continue
        algos = pts.setdefault(n, {}).setdefault(
            (r["coll"], int(r["bytes"])), [])
        if r["algo"] not in algos:
            algos.append(r["algo"])
    if not pts:
        print("tune sentinel: record names no replayable points",
              file=sys.stderr)
        return 2

    best_lat: Dict[Tuple[str, int, int, str], float] = {}
    for n, cells in sorted(pts.items()):
        points = [[coll, b, algos]
                  for (coll, b), algos in sorted(cells.items())]
        if args.max_points:
            points = points[:args.max_points]
        for rep in range(max(1, args.repeat)):
            print(f"tune sentinel: n{n} pass {rep + 1}/{args.repeat} "
                  f"({len(points)} points) ...", file=sys.stderr)
            for r in _run_sweep(n, points, args.scale):
                k = (r["coll"], int(r["nranks"]), int(r["bytes"]), r["algo"])
                lat = float(r["lat_us"])
                if k not in best_lat or lat < best_lat[k]:
                    best_lat[k] = lat

    by_point: Dict[Tuple[str, int, int], Dict[str, float]] = {}
    for (coll, n, b, a), lat in best_lat.items():
        by_point.setdefault((coll, n, b), {})[a] = lat
    offending, checked = [], 0
    for (coll, n, b), algs in sorted(by_point.items()):
        picked = _table_lookup(committed, coll, n, b)
        if picked is None or picked not in algs:
            continue            # heuristic-governed or unmeasurable here
        checked += 1
        best_algo = min(algs, key=algs.get)
        ratio = algs[picked] / max(algs[best_algo], 1e-9)
        if ratio > args.threshold:
            offending.append({"coll": coll, "nranks": n, "bytes": b,
                              "committed": picked,
                              "committed_lat_us": round(algs[picked], 2),
                              "best": best_algo,
                              "best_lat_us": round(algs[best_algo], 2),
                              "ratio": round(ratio, 3)})
    out_rec = {"bench": "tune_sentinel", "table": args.table,
               "record": args.record, "threshold": args.threshold,
               "repeat": args.repeat, "checked_cells": checked,
               "offending": offending}
    if args.json:
        with open(os.path.expanduser(args.json), "w") as f:
            json.dump(out_rec, f, indent=1)
    if offending:
        print(f"tune sentinel: FAIL — {len(offending)}/{checked} committed "
              f"selections lose by >{(args.threshold - 1) * 100:.0f}% on "
              f"this runner:")
        for c in offending:
            print(f"  {c['coll']:<10} n{c['nranks']} {c['bytes']:>9d}B "
                  f"committed {c['committed']:<13} "
                  f"{c['committed_lat_us']:>10.1f}us vs best {c['best']} "
                  f"{c['best_lat_us']:.1f}us (x{c['ratio']})")
        print("  -> re-run `python -m tpu_mpi.tune` on this runner and "
              "commit the refreshed artifacts")
        return 1
    print(f"tune sentinel: OK — {checked} committed selections hold within "
          f"{(args.threshold - 1) * 100:.0f}% on this runner")
    return 0


# ---------------------------------------------------------------------------
# Online-exploration report (tpurun --tune --online)
# ---------------------------------------------------------------------------

def _online_report(paths: Sequence[str], json_out: Optional[str] = None,
                   ) -> int:
    """What the in-process bandit did, reconstructed from pvar dumps:
    explored-call fraction through the decision point, per-arm sample
    counts, table swaps and the last swap's config generation, plus the
    crossover table the accumulated arms imply (what the next lockstep
    swap would install)."""
    from . import perfvars
    records = perfvars.load_dumps(paths)
    calls = explored = swaps = 0
    last_gen = 0
    for rec in records:
        for comm in rec.get("comms", ()):
            ex = comm.get("explore") or {}
            calls += int(ex.get("calls") or 0)
            explored += int(ex.get("explored") or 0)
            swaps = max(swaps, int(ex.get("table_swaps") or 0))
            last_gen = max(last_gen, int(ex.get("last_swap_gen") or 0))
    rows = rows_from_pvars(records, min_samples=1)
    implied = _crossovers(rows_from_pvars(records))
    rec_out = {"bench": "tune_online_report", "ranks": len(records),
               "explore": {"calls": calls, "explored": explored,
                           "fraction": (round(explored / calls, 4)
                                        if calls else None),
                           "table_swaps": swaps, "last_swap_gen": last_gen},
               "arms": rows,
               "implied_table": {
                   f"{c}.n{n}": {str(th): algo for th, algo in ent}
                   for (c, n), ent in implied.items()}}
    if json_out:
        with open(os.path.expanduser(json_out), "w") as f:
            json.dump(rec_out, f, indent=1)
    frac = f"{explored / calls:.1%}" if calls else "n/a"
    print(f"online: {len(records)} ranks, {calls} decision-point calls, "
          f"{explored} explored ({frac}), {swaps} table swaps "
          f"(last at config generation {last_gen})")
    if rows:
        print("arms (count-weighted mean latency):")
        for r in rows:
            print(f"  {r['coll']:<10} n{r['nranks']} {r['bytes']:>9d}B "
                  f"{r['algo']:<13} count={r['count']:<6d} "
                  f"{r['lat_us']:>10.1f} us")
    if implied:
        print("implied table (what the next lockstep swap would install):")
        for (c, n), ent in sorted(implied.items()):
            print(f"  [{c}.n{n}] " + "  ".join(
                f"{th}B->{algo}" for th, algo in sorted(ent)))
    return 0


def _run_sweep(nranks: int, points: list, scale: float) -> List[dict]:
    """Run the lockstep ``_WORKER`` bench over ``points`` on ``nranks``
    real child processes and return the measured rows (shared by the
    autotune sweep and the regression sentinel)."""
    import tempfile
    from .launcher import launch_processes
    with tempfile.TemporaryDirectory(prefix="tpu_mpi_tune_") as td:
        worker = os.path.join(td, "tune_worker.py")
        pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(worker, "w") as f:
            f.write(f"import sys; sys.path.insert(0, {pkg_parent!r})\n"
                    + _WORKER)
        spec_path = os.path.join(td, f"spec{nranks}.json")
        out_path = os.path.join(td, f"rows{nranks}.json")
        with open(spec_path, "w") as f:
            json.dump({"scale": scale, "points": points}, f)
        rc = launch_processes(worker, nranks,
                              script_args=[spec_path, out_path], sim=1)
        if rc != 0:
            raise RuntimeError(f"tune sweep on {nranks} ranks exited {rc}")
        with open(out_path) as f:
            return json.load(f)


def autotune(nranks_list: Sequence[int] = (2, 4, 8),
             sizes: Sequence[int] = LADDER,
             colls: Sequence[str] = SWEEP_COLLS,
             scale: float = 1.0,
             out_table: Optional[str] = None,
             out_json: Optional[str] = None,
             verbose: bool = True) -> dict:
    """Run the sweep, write the tuning table, return the full record."""
    t_start = time.time()
    rows: List[dict] = []
    for n in nranks_list:
        points = _sweep_spec(n, sizes, colls)
        if verbose:
            npts = sum(len(p[2]) for p in points)
            print(f"tune: sweeping {npts} (coll, size, algo) points "
                  f"on {n} ranks ...", file=sys.stderr)
        tkey = topology_key(_active_domains(n), n)
        for r in _run_sweep(n, points, scale):
            r.setdefault("topology", tkey)
            rows.append(r)

    table = _crossovers(rows)
    # selection audit: what the freshly-written table picks at every
    # measured point, vs the best measured algorithm there
    by_point: Dict[Tuple[str, int, int], List[dict]] = {}
    for r in rows:
        by_point.setdefault((r["coll"], r["nranks"], r["bytes"]), []).append(r)
    selection = []
    for (coll, n, nbytes), prs in sorted(by_point.items()):
        best = min(prs, key=lambda r: r["lat_us"])
        picked = _table_lookup(table, coll, n, nbytes) or heuristic(
            coll, n, nbytes, commutative=True, elementwise=True,
            shm=os.path.isdir("/dev/shm"))
        sel = next((r for r in prs if r["algo"] == picked), best)
        selection.append({
            "coll": coll, "nranks": n, "bytes": nbytes,
            "tuner_selected": sel["algo"], "selected_lat_us": sel["lat_us"],
            "best_algo": best["algo"], "best_lat_us": best["lat_us"],
            "ratio_vs_best": round(sel["lat_us"] / max(best["lat_us"], 1e-9),
                                   4),
        })

    record = {
        "bench": "coll_algos",
        "rows": rows,
        "selection": selection,
        "table": {f"{c}.n{n}": dict(
            (str(th), algo) for th, algo in ent)
            for (c, n), ent in table.items()},
        "elapsed_s": round(time.time() - t_start, 1),
    }
    if out_table:
        write_table(out_table, table,
                    header=f"measured on {os.uname().nodename} "
                           f"nranks={list(nranks_list)}")
        record["table_path"] = os.path.expanduser(out_table)
    if out_json:
        with open(os.path.expanduser(out_json), "w") as f:
            json.dump(record, f, indent=1)
    return record


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: ``python -m tpu_mpi.tune`` / ``tpurun --tune``. Subcommands:
    ``merge`` (fleet database), ``sentinel`` (committed-artifact regression
    check); default is the measurement sweep."""
    import argparse
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv[:1] == ["merge"]:
        return merge_main(argv[1:])
    if argv[:1] == ["sentinel"]:
        return sentinel_main(argv[1:])
    p = argparse.ArgumentParser(
        prog="tpurun --tune",
        description="Measure every collective algorithm on this substrate "
                    "and persist the crossover table select() loads.")
    p.add_argument("--nranks", default="2,4,8",
                   help="comma list of world sizes to sweep (default 2,4,8)")
    p.add_argument("--sizes", default=None,
                   help="comma list of payload bytes "
                        f"(default {','.join(map(str, LADDER))})")
    p.add_argument("--colls", default=",".join(SWEEP_COLLS),
                   help="comma list of collectives to sweep")
    p.add_argument("--scale", type=float, default=1.0,
                   help="iteration-count multiplier (e.g. 0.3 for a quick "
                        "pass)")
    p.add_argument("--quick", action="store_true",
                   help="tiny sweep: 2 ranks, 3 sizes, allreduce+barrier")
    p.add_argument("-o", "--out", default=None,
                   help="tuning-table path (default: $TPU_MPI_TUNE_TABLE "
                        "or ~/.config/tpu_mpi/tune.toml)")
    p.add_argument("--json", default=None,
                   help="also write the full sweep record as JSON")
    p.add_argument("--from-pvars", nargs="+", default=None, metavar="PATH",
                   help="build the table from pvar dump files/dirs "
                        "(TPU_MPI_PVARS_DUMP output) instead of sweeping")
    p.add_argument("--online", nargs="+", default=None, metavar="PATH",
                   help="report the online autotuner's exploration from "
                        "pvar dumps (explored fraction, per-arm samples, "
                        "table swaps) instead of sweeping")
    args = p.parse_args(argv)

    if args.online:
        return _online_report(args.online, json_out=args.json)

    if args.from_pvars:
        out_table = (args.out or config.load().tune_table
                     or os.path.join("~", ".config", "tpu_mpi", "tune.toml"))
        rec = table_from_pvars(args.from_pvars, out_table=out_table)
        if args.json:
            with open(os.path.expanduser(args.json), "w") as f:
                json.dump(rec, f, indent=1)
        print(f"tune: wrote {rec['table_path']} from {len(rec['sources'])} "
              f"pvar dumps ({len(rec['rows'])} measured points)")
        for (sect, ladder) in sorted(rec["table"].items()):
            print(f"  [{sect}] " + "  ".join(
                f"{th}B->{algo}" for th, algo in sorted(
                    ladder.items(), key=lambda kv: int(kv[0]))))
        return 0

    nranks = [int(x) for x in args.nranks.split(",") if x]
    sizes = ([int(x) for x in args.sizes.split(",") if x]
             if args.sizes else list(LADDER))
    colls = [c.strip() for c in args.colls.split(",") if c.strip()]
    if args.quick:
        nranks, sizes = [2], [64, 4096, 65536]
        colls = ["allreduce", "barrier"]
    out_table = (args.out or config.load().tune_table
                 or os.path.join("~", ".config", "tpu_mpi", "tune.toml"))
    rec = autotune(nranks, sizes, colls, scale=args.scale,
                   out_table=out_table, out_json=args.json)
    print(f"tune: wrote {rec['table_path']} "
          f"({len(rec['rows'])} measured points, {rec['elapsed_s']}s)")
    for (sect, ladder) in sorted(rec["table"].items()):
        print(f"  [{sect}] " + "  ".join(
            f"{th}B->{algo}" for th, algo in sorted(
                ladder.items(), key=lambda kv: int(kv[0]))))
    worst = max((s["ratio_vs_best"] for s in rec["selection"]), default=1.0)
    print(f"tune: selected-vs-best worst ratio {worst:.3f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
