"""Collective algorithm portfolio + measurement-driven autotuner.

The reference outsources algorithm choice to libmpi's ``coll_tuned`` module
(``/root/reference/src/collective.jl:691-738``): MPICH/OpenMPI pick ring vs
recursive-doubling vs binomial per (collective, communicator size, message
size) from a *measured* decision table. This module is that layer for the
multi-process tier:

- :data:`PORTFOLIO` names every algorithm the proc-tier engine
  (``backend.ProcChannel``) implements per collective, and
  :func:`eligible` is the rank-uniform eligibility rule for each (the same
  deterministic-function-of-shared-values contract every tier gate obeys,
  so ranks can never pick different protocols for one round).
- :func:`select` is the ONE decision function — it replaces the scattered
  threshold constants. Resolution order: force-override
  (``TPU_MPI_COLL_ALGO`` / ``config.coll_algo``, for debugging and CI) →
  measured tuning table (``TPU_MPI_TUNE_TABLE`` / ``config.tune_table``,
  written by ``tpurun --tune``) → built-in heuristic. Every layer is
  clamped by :func:`eligible`, so a stale table or an aggressive override
  degrades to a correct algorithm instead of a protocol error.
  ``tpu_mpi.collective`` calls it at plan-build time, so the chosen
  algorithm is cached inside the :class:`~tpu_mpi.overlap.CollectivePlan`
  and invalidated with it (``config.GENERATION`` bumps on any reload,
  including a tuning-table change).
- :func:`autotune` / ``python -m tpu_mpi.tune`` / ``tpurun --tune`` sweep
  algorithm × size ladder × nranks *on the actual substrate* (real child
  processes over the real transport), assert every algorithm's result is
  bitwise-equal to the star reference, and persist the measured crossovers
  as a TOML table :func:`select` loads.

The built-in heuristic intentionally reproduces the engine's historical
behavior (star below ``TPU_MPI_RING_MIN_BYTES``, ring above for commutative
ops, dissemination Barrier, binomial Bcast) plus the same-host shm fold for
the small-message band — theory-guided guesses. The measured table exists
precisely because such guesses are wrong per substrate: on a single-core
TCP-loopback box, message *count* dominates and log-P algorithms lose to
the star, while the shm fold (no transport hop at all) wins by an order of
magnitude; on a real multi-host network the table flips the other way.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import config

__all__ = ["PORTFOLIO", "eligible", "candidates", "select", "heuristic",
           "parse_override", "load_table", "write_table", "autotune", "main"]


# Every algorithm the proc-tier engine implements, per collective. "star"
# is the generic root-serialized rendezvous (always eligible; the chunked
# "starc" pipeline is a transparent refinement of it, not a separate
# selection). The rest map to ProcChannel runners in tpu_mpi/backend.py.
PORTFOLIO: Dict[str, Tuple[str, ...]] = {
    "allreduce":  ("star", "shm", "rdouble", "rabenseifner", "ring"),
    "barrier":    ("star", "shm", "dissemination"),
    "bcast":      ("star", "binomial"),
    "reduce":     ("star", "binomial"),
    "gather":     ("star", "binomial"),
    "scatter":    ("star", "binomial"),
    "allgather":  ("star", "ring"),
    "allgatherv": ("star", "ring"),
    "alltoall":   ("star", "pairwise"),
    "alltoallv":  ("star", "pairwise"),
}


def eligible(coll: str, algo: str, nranks: int, nbytes: Optional[int], *,
             commutative: bool = False, elementwise: bool = False,
             shm: bool = False, numeric: bool = True) -> bool:
    """Whether ``algo`` may run ``coll`` for this signature.

    Must stay a deterministic function of rank-uniform values: collective
    name, communicator size, payload bytes (uniform by the MPI count/dtype
    contract), op properties, config, and same-host topology (every rank of
    a single-host communicator agrees it is single-host). ``nbytes`` None
    means "payload size unknown" (object payloads) and disqualifies every
    size-gated algorithm. ``numeric`` means the payload is a fixed-dtype
    array (not dtype=object / arbitrary pickled objects).
    """
    if algo == "star":
        return True
    if nranks < 2 or algo not in PORTFOLIO.get(coll, ()):
        return False
    if algo == "shm":
        if not shm:
            return False
        cap = config.load().coll_shm_max_bytes
        if cap <= 0:
            return False
        if coll == "barrier":
            return True
        # allreduce through the shm slots: fixed-size raw array payloads
        # folded flat at the owner — needs an elementwise op (flattening
        # must not change semantics) and a slot-sized payload.
        return (numeric and elementwise
                and nbytes is not None and nbytes < cap)
    if algo == "rdouble":
        # concatenation-allgather of raw contributions + the star's own
        # rank-order fold at every rank: any op, any picklable payload.
        return True
    if algo == "rabenseifner":
        # per-segment rank-order folds: elementwise (segment-separable),
        # raw array payloads only.
        return numeric and elementwise and nbytes is not None
    if algo == "ring":
        if coll == "allreduce":
            # ring order != rank order: commutativity required.
            return commutative and numeric and nbytes is not None
        return numeric                      # allgather / allgatherv
    if algo == "pairwise":
        return numeric                      # alltoall / alltoallv
    if algo in ("dissemination", "binomial"):
        return True
    return False


def candidates(coll: str, nranks: int, nbytes: Optional[int], *,
               commutative: bool = False, elementwise: bool = False,
               shm: bool = False, numeric: bool = True) -> List[str]:
    """Eligible algorithms for a signature, portfolio order."""
    return [a for a in PORTFOLIO.get(coll, ("star",))
            if eligible(coll, a, nranks, nbytes, commutative=commutative,
                        elementwise=elementwise, shm=shm, numeric=numeric)]


# ---------------------------------------------------------------------------
# Force-override parsing ("allreduce=rdouble,barrier=star")
# ---------------------------------------------------------------------------

_override_cache: Tuple[str, Dict[str, str]] = ("", {})


def parse_override(spec: str) -> Dict[str, str]:
    """Parse ``config.coll_algo``: a comma list of ``collective=algorithm``
    pins. Unknown collectives/algorithms are ignored with a one-time
    warning rather than erroring — a typo'd debug knob must not take the
    job down."""
    global _override_cache
    if spec == _override_cache[0]:
        return _override_cache[1]
    out: Dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        coll, _, algo = part.partition("=")
        coll, algo = coll.strip().lower(), algo.strip().lower()
        if coll in PORTFOLIO and algo in PORTFOLIO[coll]:
            out[coll] = algo
        else:
            print(f"tpu_mpi: ignoring unknown algorithm override "
                  f"{part!r} (known: "
                  f"{ {c: list(a) for c, a in PORTFOLIO.items()} })",
                  file=sys.stderr)
    _override_cache = (spec, out)
    return out


# ---------------------------------------------------------------------------
# Tuning-table persistence (TOML): {(coll, nranks): [(min_bytes, algo)...]}
# ---------------------------------------------------------------------------

# Table shape on disk:
#
#   schema = 1
#   [allreduce.n8]
#   "0" = "shm"
#   "65536" = "ring"
#
# [<coll>.n<ranks>] sections map a byte threshold (TOML keys are strings)
# to the algorithm that wins from that size up. Thresholds are the measured
# crossover points, so at every measured (size, nranks) the table selects
# the argmin algorithm exactly.

_table_cache: Tuple[Any, Any, Dict] = (None, None, {})
_table_warned: set = set()


def _parse_table_text(text: str) -> dict:
    """Tiny TOML-subset parser for the tuning table (sections + quoted
    string pairs), used when ``tomllib``/``tomli`` is unavailable
    (Python 3.10 without the vendored fallback's table support)."""
    root: dict = {}
    cur = root
    for ln, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("[") and line.endswith("]"):
            cur = root
            for part in line[1:-1].strip().split("."):
                part = part.strip().strip('"').strip("'")
                cur = cur.setdefault(part, {})
            continue
        key, eq, val = line.partition("=")
        if not eq:
            raise ValueError(f"tuning table line {ln}: not key = value")
        key = key.strip().strip('"').strip("'")
        val = val.split("#", 1)[0].strip()
        if val.startswith(("'", '"')):
            val = val[1:-1]
        elif val.isdigit():
            val = int(val)  # type: ignore[assignment]
        cur[key] = val
    return root


def _read_table_toml(path: str) -> dict:
    with open(path, "rb") as f:
        data = f.read()
    try:
        import tomllib
        return tomllib.loads(data.decode())
    except ImportError:
        pass
    try:
        import tomli  # type: ignore
        return tomli.loads(data.decode())
    except ImportError:
        return _parse_table_text(data.decode())


def load_table(path: str) -> Dict[Tuple[str, int], List[Tuple[int, str]]]:
    """Load (and cache on mtime) a tuning table. A missing or malformed
    file disables the table layer with a one-time warning — the heuristic
    still serves, a bad table never takes the job down."""
    global _table_cache
    path = os.path.expanduser(path)
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        if path not in _table_warned:
            _table_warned.add(path)
            print(f"tpu_mpi: tuning table {path!r} not readable; "
                  f"using the built-in heuristic", file=sys.stderr)
        return {}
    if _table_cache[0] == path and _table_cache[1] == mtime:
        return _table_cache[2]
    table: Dict[Tuple[str, int], List[Tuple[int, str]]] = {}
    try:
        raw = _read_table_toml(path)
        for coll, per_n in raw.items():
            if coll not in PORTFOLIO or not isinstance(per_n, dict):
                continue
            for nkey, ladder in per_n.items():
                if not (isinstance(ladder, dict) and nkey.startswith("n")):
                    continue
                n = int(nkey[1:])
                ent = sorted(((int(th), str(algo))
                              for th, algo in ladder.items()
                              if str(algo) in PORTFOLIO[coll]),
                             reverse=True)
                if ent:
                    table[(coll, n)] = ent
    except Exception as e:
        if path not in _table_warned:
            _table_warned.add(path)
            print(f"tpu_mpi: tuning table {path!r} unusable ({e}); "
                  f"using the built-in heuristic", file=sys.stderr)
        table = {}
    _table_cache = (path, mtime, table)
    return table


def write_table(path: str,
                table: Dict[Tuple[str, int], List[Tuple[int, str]]],
                header: str = "") -> None:
    """Persist a tuning table as TOML (atomic rename)."""
    path = os.path.expanduser(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    lines = ["# tpu_mpi collective tuning table (tpurun --tune)"]
    if header:
        lines += [f"# {h}" for h in header.splitlines()]
    lines.append("schema = 1")
    for (coll, n) in sorted(table):
        lines.append(f"\n[{coll}.n{n}]")
        for th, algo in sorted(table[(coll, n)]):
            lines.append(f'"{th}" = "{algo}"')
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write("\n".join(lines) + "\n")
    os.replace(tmp, path)


def _table_lookup(table: Dict[Tuple[str, int], List[Tuple[int, str]]],
                  coll: str, nranks: int,
                  nbytes: Optional[int]) -> Optional[str]:
    """The table's pick: exact nranks entry, else the nearest measured
    communicator size below (libmpi decision tables interpolate the same
    way), else the smallest above."""
    ns = sorted(n for (c, n) in table if c == coll)
    if not ns:
        return None
    if nranks in ns:
        n = nranks
    else:
        below = [n for n in ns if n < nranks]
        n = below[-1] if below else ns[0]
    size = 0 if nbytes is None else int(nbytes)
    # order-independent walk: loaded tables arrive descending-sorted, but
    # the in-memory table from _crossovers is built ascending
    for th, algo in sorted(table[(coll, n)], reverse=True):
        if size >= th:
            return algo
    return None


# ---------------------------------------------------------------------------
# Heuristic table + the one decision function
# ---------------------------------------------------------------------------

def heuristic(coll: str, nranks: int, nbytes: Optional[int], *,
              commutative: bool = False, elementwise: bool = False,
              shm: bool = False, numeric: bool = True) -> str:
    """Built-in crossovers (used when no measured table applies). The bulk
    threshold is ``backend._RING_MIN_BYTES`` — read live, because tests and
    users monkeypatch it / set ``TPU_MPI_RING_MIN_BYTES`` (the historical
    knob this table absorbed). Bulk algorithms take precedence over the shm
    fold so a forced-low ring threshold behaves exactly as it always has."""
    from . import backend as B

    def ok(algo: str) -> bool:
        return eligible(coll, algo, nranks, nbytes, commutative=commutative,
                        elementwise=elementwise, shm=shm, numeric=numeric)

    ring_min = B._RING_MIN_BYTES
    bulky = numeric and nbytes is not None and nbytes >= ring_min
    if coll == "allreduce":
        if bulky and ok("ring"):
            return "ring"
        if ok("shm"):
            return "shm"
        return "star"
    if coll == "barrier":
        return "shm" if ok("shm") else "dissemination"
    if coll == "bcast":
        return "binomial"
    if coll in ("allgather", "allgatherv"):
        return "ring" if bulky and ok("ring") else "star"
    if coll == "alltoall":
        return "pairwise" if bulky and ok("pairwise") else "star"
    if coll == "alltoallv":
        # counts differ per rank: dtype-only gate (uniform by contract),
        # a size gate would let ranks disagree on the tier.
        return "pairwise" if ok("pairwise") else "star"
    return "star"           # reduce / gather / scatter default to the star


def select(coll: str, nranks: int, nbytes: Optional[int] = None, *,
           commutative: bool = False, elementwise: bool = False,
           shm: bool = False, numeric: bool = True) -> str:
    """THE algorithm decision for one collective signature.

    Resolution: force-override → measured table → heuristic, each clamped
    by :func:`eligible`. Called once per plan signature (the result is
    cached inside the CollectivePlan); must stay deterministic across
    ranks for fixed rank-uniform inputs + uniform config.
    """
    if nranks < 2:
        return "star"

    def ok(algo: str) -> bool:
        return eligible(coll, algo, nranks, nbytes, commutative=commutative,
                        elementwise=elementwise, shm=shm, numeric=numeric)

    cfg = config.load()
    forced = parse_override(cfg.coll_algo).get(coll)
    if forced is not None and ok(forced):
        return forced
    if cfg.tune_table:
        algo = _table_lookup(load_table(cfg.tune_table), coll, nranks, nbytes)
        if algo is not None and ok(algo):
            return algo
    return heuristic(coll, nranks, nbytes, commutative=commutative,
                     elementwise=elementwise, shm=shm, numeric=numeric)


# ---------------------------------------------------------------------------
# The autotuner: measure every algorithm on the actual substrate
# ---------------------------------------------------------------------------

LADDER = (8, 64, 512, 4096, 32768, 262144, 2097152)
ROOTED_LADDER = (64, 4096, 262144)
SWEEP_COLLS = ("allreduce", "barrier", "bcast", "reduce", "gather", "scatter")


def _iters_for(nbytes: int, scale: float = 1.0) -> Tuple[int, int]:
    """(warmup, iters) per point; fewer repeats for bulk sizes."""
    if nbytes >= 1 << 20:
        w, it = 1, 3
    elif nbytes >= 1 << 18:
        w, it = 1, 5
    elif nbytes >= 1 << 15:
        w, it = 2, 10
    else:
        w, it = 3, 20
    return w, max(1, int(it * scale))


# The in-job bench worker. Runs as an SPMD script under launch_processes:
# every rank walks the identical (coll, algo, size) schedule in lockstep,
# flipping the algorithm via the force-override env + config reload (which
# also exercises the override path end to end), and rank 0 writes the
# measured rows. Results are asserted bitwise-equal to the star reference
# per point, on every rank, and AND-reduced.
_WORKER = r'''
import json, os, sys, time
import numpy as np
import tpu_mpi as MPI
from tpu_mpi import config as _cfg
from tpu_mpi import tune as _tune

MPI.Init()
comm = MPI.COMM_WORLD
rank, size = MPI.Comm_rank(comm), MPI.Comm_size(comm)
spec = json.load(open(sys.argv[1]))
scale = spec["scale"]

def set_algo(coll, algo):
    os.environ["TPU_MPI_COLL_ALGO"] = f"{coll}={algo}"
    _cfg.load(refresh=True)

def payload(nbytes):
    n = max(1, nbytes // 8)
    # integer-valued float64: SUM folds are exact, so bitwise equality is a
    # meaningful assertion rather than vacuous float luck
    return (np.arange(n, dtype=np.float64) % 97) + rank + 1.0

def once(coll, nbytes):
    if coll == "barrier":
        MPI.Barrier(comm); return None
    if coll == "allreduce":
        return np.asarray(MPI.Allreduce(payload(nbytes), MPI.SUM, comm))
    if coll == "bcast":
        buf = payload(nbytes) if rank == 0 else np.zeros(max(1, nbytes // 8))
        return np.asarray(MPI.Bcast(buf, 0, comm))
    if coll == "reduce":
        out = MPI.Reduce(payload(nbytes), MPI.SUM, 0, comm)
        return None if out is None else np.asarray(out)
    if coll == "gather":
        out = MPI.Gather(payload(nbytes), 0, comm)
        return None if out is None else np.asarray(out)
    if coll == "scatter":
        send = np.tile(payload(nbytes), size) if rank == 0 else None
        out = MPI.Scatter(send, max(1, nbytes // 8), 0, comm)
        return None if out is None else np.asarray(out)
    raise AssertionError(coll)

rows = []
for coll, nbytes, algos in spec["points"]:
    set_algo(coll, "star")
    ref = once(coll, nbytes)
    refb = b"" if ref is None else ref.tobytes()
    for algo in algos:
        set_algo(coll, algo)
        out = once(coll, nbytes)                     # correctness probe
        same = (b"" if out is None else out.tobytes()) == refb
        warm, iters = _tune._iters_for(nbytes, scale)
        for _ in range(warm):
            once(coll, nbytes)
        t0 = time.perf_counter()
        for _ in range(iters):
            once(coll, nbytes)
        dt = (time.perf_counter() - t0) / iters
        # slowest rank defines the collective's latency; bitwise flag is
        # the AND over ranks (MIN on {0,1})
        stats = np.asarray(MPI.Allreduce(
            np.array([dt, float(same)]), MPI.MAX, comm))
        ok = np.asarray(MPI.Allreduce(
            np.array([float(same)]), MPI.MIN, comm))
        if rank == 0:
            rows.append({"coll": coll, "nranks": size, "bytes": int(nbytes),
                         "algo": algo,
                         "lat_us": round(float(stats[0]) * 1e6, 2),
                         "bitwise_equal_to_star": bool(ok[0] >= 1.0)})
            print(f"  {coll:<10} n{size} {nbytes:>9d}B {algo:<13} "
                  f"{float(stats[0])*1e6:>10.1f} us  "
                  f"bitwise={bool(ok[0] >= 1.0)}", file=sys.stderr)
set_algo("allreduce", "star")
if rank == 0:
    with open(sys.argv[2], "w") as f:
        json.dump(rows, f)
MPI.Finalize()
'''


def _sweep_spec(nranks: int, sizes: Sequence[int],
                colls: Sequence[str]) -> list:
    """The lockstep (coll, nbytes, algos) schedule for one world size.
    Algorithms are the deployment-eligible set per point (shm capped by the
    configured slot size etc.), so the emitted table never selects
    something the runtime would clamp away."""
    points = []
    shm_ok = os.path.isdir("/dev/shm")   # single-host sweep by construction
    for coll in colls:
        ladder: Sequence[int] = ((0,) if coll == "barrier"
                                 else sizes if coll == "allreduce"
                                 else [s for s in ROOTED_LADDER
                                       if s <= max(sizes)])
        for nbytes in ladder:
            algos = candidates(coll, nranks, nbytes, commutative=True,
                               elementwise=True, shm=shm_ok, numeric=True)
            points.append((coll, int(nbytes), algos))
    return points


def _crossovers(rows: List[dict]) -> Dict[Tuple[str, int],
                                          List[Tuple[int, str]]]:
    """Reduce measured rows to threshold->algorithm crossover entries: at
    each measured size the winner is the argmin latency; thresholds sit at
    the measured sizes where the winner changes (so the table reproduces
    the argmin at every measured point exactly)."""
    best: Dict[Tuple[str, int], List[Tuple[int, str]]] = {}
    by_point: Dict[Tuple[str, int, int], Tuple[float, str]] = {}
    for r in rows:
        key = (r["coll"], r["nranks"], r["bytes"])
        if key not in by_point or r["lat_us"] < by_point[key][0]:
            by_point[key] = (r["lat_us"], r["algo"])
    for (coll, n, nbytes) in sorted(by_point):
        _, algo = by_point[(coll, n, nbytes)]
        ent = best.setdefault((coll, n), [])
        if not ent:
            ent.append((0, algo))            # below-ladder sizes inherit
        elif ent[-1][1] != algo:
            ent.append((nbytes, algo))
    return best


def rows_from_pvars(records: Sequence[dict]) -> List[dict]:
    """Measured rows (the autotune sweep's row schema) from pvar dump
    records (``perfvars.snapshot``): mean latency per (collective, world
    size, payload bytes, algorithm) aggregated across ranks and comms. The
    production workload's own counters become tuning input — the table is
    fed by the same measurements it will later be judged against."""
    acc: Dict[Tuple[str, int, int, str], List[float]] = {}
    for rec in records:
        for comm in rec.get("comms", ()):
            n = int(comm.get("size") or 0)
            if n < 2:
                continue
            for t in comm.get("times", ()):
                nbytes = int(t["nbytes"])
                key = (t["coll"], n, max(0, nbytes), t["algo"])
                ent = acc.setdefault(key, [0.0, 0.0])
                ent[0] += t["count"]
                ent[1] += t["total_s"]
    return [{"coll": c, "nranks": n, "bytes": b, "algo": a,
             "lat_us": round(tot / cnt * 1e6, 3)}
            for (c, n, b, a), (cnt, tot) in sorted(acc.items()) if cnt]


def table_from_pvars(paths: Sequence[str],
                     out_table: Optional[str] = None) -> dict:
    """Crossover table from pvar dumps: load, reduce to rows, argmin per
    measured point (``_crossovers``), optionally persist. A point measured
    under only ONE algorithm still pins that algorithm as its threshold
    entry — production counters rarely cover the full portfolio, so this
    table refines, not replaces, a sweep-built one."""
    from . import perfvars
    records = perfvars.load_dumps(paths)
    rows = rows_from_pvars(records)
    table = _crossovers(rows)
    rec = {"bench": "coll_algos_from_pvars", "rows": rows,
           "table": {f"{c}.n{n}": {str(th): algo for th, algo in ent}
                     for (c, n), ent in table.items()},
           "sources": [r["_path"] for r in records]}
    if out_table:
        write_table(out_table, table,
                    header=f"from pvar dumps: {len(records)} ranks")
        rec["table_path"] = os.path.expanduser(out_table)
    return rec


def autotune(nranks_list: Sequence[int] = (2, 4, 8),
             sizes: Sequence[int] = LADDER,
             colls: Sequence[str] = SWEEP_COLLS,
             scale: float = 1.0,
             out_table: Optional[str] = None,
             out_json: Optional[str] = None,
             verbose: bool = True) -> dict:
    """Run the sweep, write the tuning table, return the full record."""
    import tempfile
    from .launcher import launch_processes

    t_start = time.time()
    rows: List[dict] = []
    with tempfile.TemporaryDirectory(prefix="tpu_mpi_tune_") as td:
        worker = os.path.join(td, "tune_worker.py")
        pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(worker, "w") as f:
            f.write(f"import sys; sys.path.insert(0, {pkg_parent!r})\n"
                    + _WORKER)
        for n in nranks_list:
            spec = {"scale": scale, "points": _sweep_spec(n, sizes, colls)}
            spec_path = os.path.join(td, f"spec{n}.json")
            out_path = os.path.join(td, f"rows{n}.json")
            with open(spec_path, "w") as f:
                json.dump(spec, f)
            if verbose:
                npts = sum(len(p[2]) for p in spec["points"])
                print(f"tune: sweeping {npts} (coll, size, algo) points "
                      f"on {n} ranks ...", file=sys.stderr)
            rc = launch_processes(worker, n, script_args=[spec_path, out_path],
                                  sim=1)
            if rc != 0:
                raise RuntimeError(f"tune sweep on {n} ranks exited {rc}")
            with open(out_path) as f:
                rows.extend(json.load(f))

    table = _crossovers(rows)
    # selection audit: what the freshly-written table picks at every
    # measured point, vs the best measured algorithm there
    by_point: Dict[Tuple[str, int, int], List[dict]] = {}
    for r in rows:
        by_point.setdefault((r["coll"], r["nranks"], r["bytes"]), []).append(r)
    selection = []
    for (coll, n, nbytes), prs in sorted(by_point.items()):
        best = min(prs, key=lambda r: r["lat_us"])
        picked = _table_lookup(table, coll, n, nbytes) or heuristic(
            coll, n, nbytes, commutative=True, elementwise=True,
            shm=os.path.isdir("/dev/shm"))
        sel = next((r for r in prs if r["algo"] == picked), best)
        selection.append({
            "coll": coll, "nranks": n, "bytes": nbytes,
            "tuner_selected": sel["algo"], "selected_lat_us": sel["lat_us"],
            "best_algo": best["algo"], "best_lat_us": best["lat_us"],
            "ratio_vs_best": round(sel["lat_us"] / max(best["lat_us"], 1e-9),
                                   4),
        })

    record = {
        "bench": "coll_algos",
        "rows": rows,
        "selection": selection,
        "table": {f"{c}.n{n}": dict(
            (str(th), algo) for th, algo in ent)
            for (c, n), ent in table.items()},
        "elapsed_s": round(time.time() - t_start, 1),
    }
    if out_table:
        write_table(out_table, table,
                    header=f"measured on {os.uname().nodename} "
                           f"nranks={list(nranks_list)}")
        record["table_path"] = os.path.expanduser(out_table)
    if out_json:
        with open(os.path.expanduser(out_json), "w") as f:
            json.dump(record, f, indent=1)
    return record


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: ``python -m tpu_mpi.tune`` / ``tpurun --tune``."""
    import argparse
    p = argparse.ArgumentParser(
        prog="tpurun --tune",
        description="Measure every collective algorithm on this substrate "
                    "and persist the crossover table select() loads.")
    p.add_argument("--nranks", default="2,4,8",
                   help="comma list of world sizes to sweep (default 2,4,8)")
    p.add_argument("--sizes", default=None,
                   help="comma list of payload bytes "
                        f"(default {','.join(map(str, LADDER))})")
    p.add_argument("--colls", default=",".join(SWEEP_COLLS),
                   help="comma list of collectives to sweep")
    p.add_argument("--scale", type=float, default=1.0,
                   help="iteration-count multiplier (e.g. 0.3 for a quick "
                        "pass)")
    p.add_argument("--quick", action="store_true",
                   help="tiny sweep: 2 ranks, 3 sizes, allreduce+barrier")
    p.add_argument("-o", "--out", default=None,
                   help="tuning-table path (default: $TPU_MPI_TUNE_TABLE "
                        "or ~/.config/tpu_mpi/tune.toml)")
    p.add_argument("--json", default=None,
                   help="also write the full sweep record as JSON")
    p.add_argument("--from-pvars", nargs="+", default=None, metavar="PATH",
                   help="build the table from pvar dump files/dirs "
                        "(TPU_MPI_PVARS_DUMP output) instead of sweeping")
    args = p.parse_args(argv)

    if args.from_pvars:
        out_table = (args.out or config.load().tune_table
                     or os.path.join("~", ".config", "tpu_mpi", "tune.toml"))
        rec = table_from_pvars(args.from_pvars, out_table=out_table)
        if args.json:
            with open(os.path.expanduser(args.json), "w") as f:
                json.dump(rec, f, indent=1)
        print(f"tune: wrote {rec['table_path']} from {len(rec['sources'])} "
              f"pvar dumps ({len(rec['rows'])} measured points)")
        for (sect, ladder) in sorted(rec["table"].items()):
            print(f"  [{sect}] " + "  ".join(
                f"{th}B->{algo}" for th, algo in sorted(
                    ladder.items(), key=lambda kv: int(kv[0]))))
        return 0

    nranks = [int(x) for x in args.nranks.split(",") if x]
    sizes = ([int(x) for x in args.sizes.split(",") if x]
             if args.sizes else list(LADDER))
    colls = [c.strip() for c in args.colls.split(",") if c.strip()]
    if args.quick:
        nranks, sizes = [2], [64, 4096, 65536]
        colls = ["allreduce", "barrier"]
    out_table = (args.out or config.load().tune_table
                 or os.path.join("~", ".config", "tpu_mpi", "tune.toml"))
    rec = autotune(nranks, sizes, colls, scale=args.scale,
                   out_table=out_table, out_json=args.json)
    print(f"tune: wrote {rec['table_path']} "
          f"({len(rec['rows'])} measured points, {rec['elapsed_s']}s)")
    for (sect, ladder) in sorted(rec["table"].items()):
        print(f"  [{sect}] " + "  ".join(
            f"{th}B->{algo}" for th, algo in sorted(
                ladder.items(), key=lambda kv: int(kv[0]))))
    worst = max((s["ratio_vs_best"] for s in rec["selection"]), default=1.0)
    print(f"tune: selected-vs-best worst ratio {worst:.3f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
