"""Sharded checkpoint/resume built on the collective File layer.

The reference ships no checkpoint subsystem — `MPI.File` collective I/O is
the substrate applications build one from (SURVEY.md §5 "Checkpoint /
resume"; /root/reference/src/io.jl is the whole surface). This module is
that application layer, provided in-tree: every rank contributes its LOCAL
pytree of arrays (a dp-sharded optimizer state, a pipeline stage's
parameters, …) and the world collectively writes ONE coherent file:

    [magic u64][header_len u64][pickled header][rank 0 data][rank 1 data]…

The header (written by rank 0) records every rank's tree structure, dtypes,
shapes and byte offsets, so a restarted job — or an offline reader — can
locate any shard. Shard data moves with independent `File.write_at` /
`read_at` at header-computed offsets (leaf counts may differ per rank, so
the collective `_all` variants don't fit); a closing `Barrier` is the
completion point.

    from tpu_mpi import checkpoint
    checkpoint.save_sharded(path, {"w": w, "step": step}, comm)
    state = checkpoint.load_sharded(path, comm)

Arrays come back as numpy (device placement is the caller's policy —
`DeviceBuffer(state["w"])` / `jax.device_put` to return to HBM).
"""

from __future__ import annotations

import pickle
from typing import Any

import numpy as np

from . import io as File
from .buffers import extract_array
from .collective import Barrier
from .comm import Comm
from . import error as _ec
from .error import MPIError

_MAGIC = 0x7D5AC4B7_00000001


def _esc(key: str) -> str:
    """Escape the path separator in dict keys: a key containing '/' must
    not collide with nested structure ("a/b" vs {"a": {"b": ...}})."""
    return str(key).replace("\\", "\\\\").replace("/", "\\/")


def _flatten(tree: Any, prefix: str = "") -> list[tuple[str, np.ndarray]]:
    """Deterministic (key, array) leaves of a nested dict/list/tuple tree."""
    out: list[tuple[str, np.ndarray]] = []
    if isinstance(tree, dict):
        for k in sorted(tree):
            out.extend(_flatten(tree[k], f"{prefix}{_esc(k)}/"))
        return out
    if isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.extend(_flatten(v, f"{prefix}{i}/"))
        return out
    arr = extract_array(tree)
    if arr is None:
        raise MPIError(f"checkpoint leaf {prefix[:-1]!r} is not an array "
                       f"({type(tree).__name__})", code=_ec.ERR_ARG)
    arr = np.asarray(arr)
    if arr.dtype.hasobject:
        # must fail HERE, before any collective: a raw ValueError later in
        # the write loop would strand the other ranks mid-rendezvous
        raise MPIError(f"checkpoint leaf {prefix[:-1]!r} has object dtype "
                       f"{arr.dtype} (not storable as raw bytes)",
                       code=_ec.ERR_ARG)
    return [(prefix[:-1], arr)]


def _unflatten(spec: Any, leaves: dict[str, np.ndarray], prefix: str = ""):
    if isinstance(spec, dict):
        return {k: _unflatten(v, leaves, f"{prefix}{_esc(k)}/")
                for k, v in spec.items()}
    if isinstance(spec, (list, tuple)):
        seq = [_unflatten(v, leaves, f"{prefix}{i}/")
               for i, v in enumerate(spec)]
        return type(spec)(seq) if isinstance(spec, tuple) else seq
    return leaves[prefix[:-1]]


def _tree_spec(tree: Any):
    """Structure with leaves replaced by None (pickled into the header)."""
    if isinstance(tree, dict):
        return {k: _tree_spec(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        seq = [_tree_spec(v) for v in tree]
        return tuple(seq) if isinstance(tree, tuple) else seq
    return None


def save_sharded(path: str, tree: Any, comm: Comm) -> None:
    """Collectively write every rank's local ``tree`` into one file."""
    rank, size = comm.rank(), comm.size()
    leaves = _flatten(tree)
    my_meta = (_tree_spec(tree),
               # structured dtypes keep their field layout via descr
               [(k, a.dtype.str if a.dtype.names is None else a.dtype.descr,
                 a.shape, int(a.nbytes)) for k, a in leaves])
    # allgather of python meta objects (dynamic sizes) via the rendezvous
    from .collective import _run
    all_metas = _run(comm, my_meta, lambda cs: [list(cs)] * len(cs),
                     f"ckpt_meta@{comm.cid}")

    header = {"magic": _MAGIC, "ranks": [
        {"spec": spec, "leaves": leafmeta, "offset": 0}
        for (spec, leafmeta) in all_metas]}
    # offsets depend on the header length which depends on the offsets'
    # pickled width — break the cycle by padding the header to a stable
    # capacity (every rank computes the identical value)
    hdr_cap = len(pickle.dumps(header)) + 16 * size + 64
    off = 16 + hdr_cap
    for r, (spec, leafmeta) in enumerate(all_metas):
        header["ranks"][r]["offset"] = off
        off += sum(m[3] for m in leafmeta)
    hdr = pickle.dumps(header)
    if len(hdr) > hdr_cap:
        raise MPIError("checkpoint header overflow (internal)",
                       code=_ec.ERR_INTERN)
    hdr = hdr + b"\x00" * (hdr_cap - len(hdr))

    fh = File.open(comm, path, write=True, create=True)
    if rank == 0:
        head = np.frombuffer(
            _MAGIC.to_bytes(8, "little") + hdr_cap.to_bytes(8, "little")
            + hdr, np.uint8)
        File.write_at(fh, 0, head)
    my_off = header["ranks"][rank]["offset"]
    # independent (non-collective) writes: leaf COUNTS may differ per rank,
    # and write_at_all requires matched call sequences; the closing Barrier
    # is the completion point
    for k, a in leaves:
        flat = np.ascontiguousarray(a).reshape(-1).view(np.uint8)
        File.write_at(fh, my_off, flat)
        my_off += a.nbytes
    File.sync(fh)
    File.close(fh)
    Barrier(comm)


def load_sharded(path: str, comm: Comm) -> Any:
    """Collectively restore this rank's tree from a save_sharded file.

    Trust model: the header is a pickle — loading executes code, exactly
    like ``np.load(allow_pickle=True)`` or a torch checkpoint. Only load
    checkpoints your own job (or another trusted writer) produced.
    """
    rank, size = comm.rank(), comm.size()
    fh = File.open(comm, path, read=True)
    head = np.zeros(16, np.uint8)
    File.read_at(fh, 0, head)
    magic = int.from_bytes(head[:8].tobytes(), "little")
    if magic != _MAGIC:
        File.close(fh)
        raise MPIError(f"{path!r} is not a tpu_mpi sharded checkpoint",
                       code=_ec.ERR_FILE)
    hdr_cap = int.from_bytes(head[8:].tobytes(), "little")
    # bound the header-capacity field by the actual file size before
    # allocating: a truncated/corrupt file with valid magic must fail
    # cleanly, not trigger an arbitrary-size allocation
    fsize = File.get_size(fh)
    if hdr_cap <= 0 or 16 + hdr_cap > fsize:
        File.close(fh)
        raise MPIError(
            f"corrupt checkpoint header: capacity {hdr_cap} exceeds file "
            f"size {fsize}", code=_ec.ERR_FILE)
    raw = np.zeros(hdr_cap, np.uint8)
    File.read_at(fh, 16, raw)
    header = pickle.loads(raw.tobytes())
    if len(header["ranks"]) != size:
        File.close(fh)
        raise MPIError(
            f"checkpoint has {len(header['ranks'])} shards, comm has "
            f"{size} ranks (elastic resharding is not supported)",
            code=_ec.ERR_SIZE)
    entry = header["ranks"][rank]
    off = entry["offset"]
    leaves: dict[str, np.ndarray] = {}
    for k, dt, shape, nbytes in entry["leaves"]:
        buf = np.zeros(nbytes, np.uint8)
        File.read_at(fh, off, buf)          # independent: counts differ
        leaves[k] = buf.view(np.dtype(dt)).reshape(shape)
        off += nbytes
    File.close(fh)
    Barrier(comm)
    return _unflatten(entry["spec"], leaves)
