"""Sharded checkpoint/resume built on the collective File layer.

The reference ships no checkpoint subsystem — `MPI.File` collective I/O is
the substrate applications build one from (SURVEY.md §5 "Checkpoint /
resume"; /root/reference/src/io.jl is the whole surface). This module is
that application layer, provided in-tree: every rank contributes its LOCAL
pytree of arrays (a dp-sharded optimizer state, a pipeline stage's
parameters, …) and the world collectively writes ONE coherent file:

    [magic u64][header_len u64][pickled header][rank 0 data][rank 1 data]…

The header (written by rank 0) records every rank's tree structure, dtypes,
shapes and byte offsets, so a restarted job — or an offline reader — can
locate any shard. Shard data moves with independent `File.write_at` /
`read_at` at header-computed offsets (leaf counts may differ per rank, so
the collective `_all` variants don't fit); a closing `Barrier` is the
completion point.

    from tpu_mpi import checkpoint
    checkpoint.save_sharded(path, {"w": w, "step": step}, comm)
    state = checkpoint.load_sharded(path, comm)

Arrays come back as numpy (device placement is the caller's policy —
`DeviceBuffer(state["w"])` / `jax.device_put` to return to HBM).
"""

from __future__ import annotations

import os
import pickle
import zlib
from typing import Any

import numpy as np

from . import io as File
from .buffers import extract_array
from .collective import Barrier
from .comm import Comm
from . import error as _ec
from .error import MPIError

# v2: 32-byte fixed head [magic u64][hdr_cap u64][hdr_len u64][hdr_crc u32]
# [pad u32], CRC32 over the unpadded pickled header, per-leaf payload CRCs
# in the header, and writes go to a temp file atomically renamed into place
# — a torn write (killed rank, full disk) can never masquerade as a valid
# checkpoint (docs/fault-tolerance.md: the shrink→restore→continue recipe
# leans on this).
_MAGIC = 0x7D5AC4B7_00000002
_MAGIC_V1 = 0x7D5AC4B7_00000001
_HEAD = 32


def _esc(key: str) -> str:
    """Escape the path separator in dict keys: a key containing '/' must
    not collide with nested structure ("a/b" vs {"a": {"b": ...}})."""
    return str(key).replace("\\", "\\\\").replace("/", "\\/")


def _flatten(tree: Any, prefix: str = "") -> list[tuple[str, np.ndarray]]:
    """Deterministic (key, array) leaves of a nested dict/list/tuple tree."""
    out: list[tuple[str, np.ndarray]] = []
    if isinstance(tree, dict):
        for k in sorted(tree):
            out.extend(_flatten(tree[k], f"{prefix}{_esc(k)}/"))
        return out
    if isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.extend(_flatten(v, f"{prefix}{i}/"))
        return out
    arr = extract_array(tree)
    if arr is None:
        raise MPIError(f"checkpoint leaf {prefix[:-1]!r} is not an array "
                       f"({type(tree).__name__})", code=_ec.ERR_ARG)
    arr = np.asarray(arr)
    if arr.dtype.hasobject:
        # must fail HERE, before any collective: a raw ValueError later in
        # the write loop would strand the other ranks mid-rendezvous
        raise MPIError(f"checkpoint leaf {prefix[:-1]!r} has object dtype "
                       f"{arr.dtype} (not storable as raw bytes)",
                       code=_ec.ERR_ARG)
    return [(prefix[:-1], arr)]


def _unflatten(spec: Any, leaves: dict[str, np.ndarray], prefix: str = ""):
    if isinstance(spec, dict):
        return {k: _unflatten(v, leaves, f"{prefix}{_esc(k)}/")
                for k, v in spec.items()}
    if isinstance(spec, (list, tuple)):
        seq = [_unflatten(v, leaves, f"{prefix}{i}/")
               for i, v in enumerate(spec)]
        return type(spec)(seq) if isinstance(spec, tuple) else seq
    return leaves[prefix[:-1]]


def _tree_spec(tree: Any):
    """Structure with leaves replaced by None (pickled into the header)."""
    if isinstance(tree, dict):
        return {k: _tree_spec(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        seq = [_tree_spec(v) for v in tree]
        return tuple(seq) if isinstance(tree, tuple) else seq
    return None


def save_sharded(path: str, tree: Any, comm: Comm) -> None:
    """Collectively write every rank's local ``tree`` into one file.

    Torn-write hardening: all ranks write a temp file next to ``path``;
    after every shard is synced, rank 0 atomically renames it into place.
    A reader never observes a half-written checkpoint — either the old
    file or the complete new one. Header and every leaf payload carry
    CRC32s that ``load_sharded`` verifies."""
    rank, size = comm.rank(), comm.size()
    leaves = _flatten(tree)
    flats = [np.ascontiguousarray(a).reshape(-1).view(np.uint8)
             for _, a in leaves]
    my_meta = (_tree_spec(tree),
               # structured dtypes keep their field layout via descr;
               # trailing field: CRC32 of the leaf's raw bytes
               [(k, a.dtype.str if a.dtype.names is None else a.dtype.descr,
                 a.shape, int(a.nbytes), zlib.crc32(f))
                for (k, a), f in zip(leaves, flats)])
    # allgather of python meta objects (dynamic sizes) via the rendezvous
    from .collective import _run
    all_metas = _run(comm, my_meta, lambda cs: [list(cs)] * len(cs),
                     f"ckpt_meta@{comm.cid}")

    header = {"magic": _MAGIC, "ranks": [
        {"spec": spec, "leaves": leafmeta, "offset": 0}
        for (spec, leafmeta) in all_metas]}
    # offsets depend on the header length which depends on the offsets'
    # pickled width — break the cycle by padding the header to a stable
    # capacity (every rank computes the identical value)
    hdr_cap = len(pickle.dumps(header)) + 16 * size + 64
    off = _HEAD + hdr_cap
    for r, (spec, leafmeta) in enumerate(all_metas):
        header["ranks"][r]["offset"] = off
        off += sum(m[3] for m in leafmeta)
    hdr = pickle.dumps(header)
    if len(hdr) > hdr_cap:
        raise MPIError("checkpoint header overflow (internal)",
                       code=_ec.ERR_INTERN)
    hdr_len, hdr_crc = len(hdr), zlib.crc32(hdr)
    hdr = hdr + b"\x00" * (hdr_cap - hdr_len)

    tmp = path + ".tmp"
    if rank == 0 and os.path.exists(tmp):
        os.unlink(tmp)      # a stale temp from a killed job must not linger
    Barrier(comm)
    fh = File.open(comm, tmp, write=True, create=True)
    if rank == 0:
        head = np.frombuffer(
            _MAGIC.to_bytes(8, "little") + hdr_cap.to_bytes(8, "little")
            + hdr_len.to_bytes(8, "little") + hdr_crc.to_bytes(4, "little")
            + b"\x00" * 4 + hdr, np.uint8)
        File.write_at(fh, 0, head)
    my_off = header["ranks"][rank]["offset"]
    # independent (non-collective) writes: leaf COUNTS may differ per rank,
    # and write_at_all requires matched call sequences; the closing Barrier
    # is the completion point
    for flat in flats:
        File.write_at(fh, my_off, flat)
        my_off += flat.nbytes
    File.sync(fh)
    File.close(fh)
    Barrier(comm)
    if rank == 0:
        os.replace(tmp, path)   # the atomic publication point
    Barrier(comm)


def shard_count(path: str, comm: Comm) -> int:
    """Number of rank shards in a save_sharded file (collective over
    ``comm`` only in that every caller may open the file; no rendezvous).
    The fault-tolerance restore path uses this to re-partition a checkpoint
    written by a LARGER (pre-shrink) communicator."""
    fh = File.open(comm, path, read=True)
    try:
        fsize = File.get_size(fh)
        head = np.zeros(_HEAD, np.uint8)
        if fsize >= _HEAD:
            File.read_at(fh, 0, head)
        magic = int.from_bytes(head[:8].tobytes(), "little")
        hdr_cap = int.from_bytes(head[8:16].tobytes(), "little")
        hdr_len = int.from_bytes(head[16:24].tobytes(), "little")
        if (fsize < _HEAD or magic != _MAGIC or hdr_cap <= 0
                or _HEAD + hdr_cap > fsize or not (0 < hdr_len <= hdr_cap)):
            raise MPIError(f"{path!r} is not a readable tpu_mpi sharded "
                           f"checkpoint", code=_ec.ERR_FILE)
        raw = np.zeros(hdr_cap, np.uint8)
        File.read_at(fh, _HEAD, raw)
        try:
            return len(pickle.loads(raw[:hdr_len].tobytes())["ranks"])
        except Exception as e:
            raise MPIError(
                f"undecodable checkpoint header in {path!r}: "
                f"{type(e).__name__}: {e}", code=_ec.ERR_FILE) from None
    finally:
        File.close(fh)


def load_sharded(path: str, comm: Comm, *, shard: int | None = None) -> Any:
    """Collectively restore this rank's tree from a save_sharded file.

    ``shard`` overrides which rank shard this caller reads (default: its
    own comm rank, requiring the comm size to match the writer's). The
    override exists for fault-tolerant restore: after Comm_shrink, the
    survivor communicator is SMALLER than the one that wrote the
    checkpoint, and each survivor re-reads whichever shards its new
    partition covers (docs/fault-tolerance.md).

    Trust model: the header is a pickle — loading executes code, exactly
    like ``np.load(allow_pickle=True)`` or a torch checkpoint. Only load
    checkpoints your own job (or another trusted writer) produced.
    """
    rank, size = comm.rank(), comm.size()
    fh = File.open(comm, path, read=True)
    try:
        fsize = File.get_size(fh)
        if fsize < _HEAD:
            raise MPIError(
                f"{path!r} is truncated ({fsize} bytes; no checkpoint head)",
                code=_ec.ERR_FILE)
        head = np.zeros(_HEAD, np.uint8)
        File.read_at(fh, 0, head)
        magic = int.from_bytes(head[:8].tobytes(), "little")
        if magic == _MAGIC_V1:
            raise MPIError(
                f"{path!r} is a v1 sharded checkpoint (no integrity "
                f"metadata); re-save it with this version",
                code=_ec.ERR_FILE)
        if magic != _MAGIC:
            raise MPIError(f"{path!r} is not a tpu_mpi sharded checkpoint",
                           code=_ec.ERR_FILE)
        hdr_cap = int.from_bytes(head[8:16].tobytes(), "little")
        hdr_len = int.from_bytes(head[16:24].tobytes(), "little")
        hdr_crc = int.from_bytes(head[24:28].tobytes(), "little")
        # bound the header-capacity field by the actual file size before
        # allocating: a truncated/corrupt file with valid magic must fail
        # cleanly, not trigger an arbitrary-size allocation
        if (hdr_cap <= 0 or _HEAD + hdr_cap > fsize
                or not (0 < hdr_len <= hdr_cap)):
            raise MPIError(
                f"corrupt checkpoint header: capacity {hdr_cap} / length "
                f"{hdr_len} inconsistent with file size {fsize}",
                code=_ec.ERR_FILE)
        raw = np.zeros(hdr_cap, np.uint8)
        File.read_at(fh, _HEAD, raw)
        hdr_bytes = raw[:hdr_len].tobytes()
        if zlib.crc32(hdr_bytes) != hdr_crc:
            raise MPIError(
                f"checkpoint header CRC mismatch in {path!r} — torn or "
                f"corrupted write", code=_ec.ERR_FILE)
        try:
            header = pickle.loads(hdr_bytes)
            ranks_meta = header["ranks"]
            if rank < len(ranks_meta):
                _ = (ranks_meta[rank]["spec"], ranks_meta[rank]["offset"],
                     ranks_meta[rank]["leaves"])
        except MPIError:
            raise
        except Exception as e:
            raise MPIError(
                f"undecodable checkpoint header in {path!r}: "
                f"{type(e).__name__}: {e}", code=_ec.ERR_FILE) from None
        if shard is None and len(ranks_meta) != size:
            raise MPIError(
                f"checkpoint has {len(ranks_meta)} shards, comm has "
                f"{size} ranks (elastic resharding is not supported; pass "
                f"shard= to read a specific one)", code=_ec.ERR_SIZE)
        want = rank if shard is None else int(shard)
        if not (0 <= want < len(ranks_meta)):
            raise MPIError(
                f"checkpoint has {len(ranks_meta)} shards; shard {want} "
                f"does not exist", code=_ec.ERR_ARG)
        entry = ranks_meta[want]
        off = entry["offset"]
        leaves: dict[str, np.ndarray] = {}
        for k, dt, shape, nbytes, crc in entry["leaves"]:
            if off + nbytes > fsize:
                raise MPIError(
                    f"checkpoint shard for rank {rank} is truncated: leaf "
                    f"{k!r} needs bytes [{off}, {off + nbytes}) but "
                    f"{path!r} is {fsize} bytes", code=_ec.ERR_FILE)
            buf = np.zeros(nbytes, np.uint8)
            File.read_at(fh, off, buf)      # independent: counts differ
            if zlib.crc32(buf) != crc:
                raise MPIError(
                    f"checkpoint payload CRC mismatch for leaf {k!r} "
                    f"(rank {rank}) in {path!r} — torn or corrupted write",
                    code=_ec.ERR_FILE)
            leaves[k] = buf.view(np.dtype(dt)).reshape(shape)
            off += nbytes
    finally:
        File.close(fh)
    Barrier(comm)
    return _unflatten(entry["spec"], leaves)


def load_all_shards(path: str, comm: Comm) -> list:
    """Resharding helper: read EVERY rank shard of a ``save_sharded``
    file, in writer-rank order, regardless of the reader comm's size.

    This is the restore half of elastic resharding (docs/training.md
    "Resize and resume"): a world that shrank, grew, or replaced ranks
    since the checkpoint was written reassembles the writers' global
    state from all N shards and re-partitions it for its own size. Each
    caller reads the whole file; callers that only need a slice should
    use ``load_sharded(..., shard=s)`` directly."""
    return [load_sharded(path, comm, shard=s)
            for s in range(shard_count(path, comm))]
