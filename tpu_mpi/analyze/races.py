"""RMA race detection: vector-clock happens-before over window epochs.

Every origin-side Put/Get/Accumulate is stamped with its rank's vector clock
(:func:`tpu_mpi.analyze.events.rma_access`); ``Win_fence`` joins all ranks'
clocks (accesses of epoch N happen-before every access of epoch N+1, on every
rank) and ``Win_lock``/``Win_unlock`` publish/acquire clocks per
(window, target) — exclusive locks serialize, shared locks only order against
prior exclusive releases. Two accesses to the same target window RACE when

- they come from different origin ranks,
- their element ranges ``[lo, hi)`` overlap,
- at least one writes (any kind-pair except Get/Get; Accumulate/Accumulate
  is ordered element-wise by MPI semantics, so it is exempt too), and
- neither happens-before the other under the recorded clocks (R301).

This is the MPI-RMA analog of the FastTrack-style VC race detectors; one
epoch's same-target concurrent accesses are exactly what MPI-4 §12.7 leaves
undefined.

:func:`detect_donation_races` (R302) covers the registered-buffer fast path
of persistent collectives: in production mode round ``k``'s result lives in
a donated registered slot that the round ``k+2`` ``Start`` re-donates.
Under tracing the fast path is disabled (every round hands back a fresh
array), so the trace alone shows no corruption — but the ``start`` events
carry the ``invalidates=<round-k result id>`` edge, and any later traced
operation that READS that result object after its invalidating Start is a
use that corrupts silently in production. The result objects of the last
few rounds are kept alive by the request (``PersistentCollRequest._results``),
so within the modeled window an id names exactly one array.
"""

from __future__ import annotations

from typing import List

from .diagnostics import Diagnostic


def _hb(a, b) -> bool:
    """a happened-before (or same-op-as) b under the recorded clocks."""
    return b.vc.get(a.origin, 0) >= a.vc.get(a.origin, 0)


def _kind_class(op: str) -> str:
    """"read" (Get), "acc" (Accumulate family — element-wise ordered by MPI
    semantics), or "write" (Put)."""
    op = op.lower()
    if "accumulate" in op or "fetch" in op:
        return "acc"
    if op.startswith("get"):
        return "read"
    return "write"


def _conflict(a, b) -> bool:
    ca, cb = _kind_class(a.op), _kind_class(b.op)
    if ca == "read" and cb == "read":
        return False
    if ca == "acc" and cb == "acc":
        return False
    return True


def _overlap(a, b) -> bool:
    return not (a.hi <= b.lo or b.hi <= a.lo)


def detect_races(tr) -> List[Diagnostic]:
    """All R301 races in the tracer's RMA event log."""
    out: List[Diagnostic] = []
    seen = set()
    with tr.lock:
        events = list(tr.rma_events)
    # group by (window, target rank): only same-target accesses share memory
    groups: dict = {}
    for ev in events:
        groups.setdefault((ev.win, ev.peer), []).append(ev)
    for evs in groups.values():
        for i in range(len(evs)):
            a = evs[i]
            for j in range(i + 1, len(evs)):
                b = evs[j]
                if a.origin == b.origin:
                    continue        # program order on one rank is ordered
                if not _conflict(a, b) or not _overlap(a, b):
                    continue
                if _hb(a, b) or _hb(b, a):
                    continue
                # anchor at the later event, point back at the earlier one
                first, second = (a, b) if a.t <= b.t else (b, a)
                key = (a.win, frozenset((a.origin, b.origin)),
                       first.file, first.line, second.file, second.line)
                if key in seen:
                    continue
                seen.add(key)
                out.append(Diagnostic(
                    "R301",
                    f"concurrent overlapping RMA accesses: "
                    f"{first.op} by world rank {first.origin} and "
                    f"{second.op} by world rank {second.origin} both touch "
                    f"[{max(a.lo, b.lo)}, {min(a.hi, b.hi)}) of world rank "
                    f"{a.peer}'s window in one exposure epoch",
                    file=second.file, line=second.line, rank=second.origin,
                    context="no happens-before edge between the accesses",
                    related=((first.file, first.line,
                              f"the other access ({first.op} by world rank "
                              f"{first.origin})"),)))
    out.sort(key=lambda d: (d.file, d.line, d.code))
    return out


def detect_donation_races(tr) -> List[Diagnostic]:
    """All R302 uses of a donated persistent-fold result after the Start
    that re-donates its registered slot (see module docstring)."""
    out: List[Diagnostic] = []
    by_rank: dict = {}
    for ev in tr.events():
        by_rank.setdefault(ev.rank, []).append(ev)
    for rank, evs in sorted(by_rank.items()):
        evs.sort(key=lambda e: e.t or 0.0)
        produced: dict = {}      # bufid -> the wait event that returned it
        invalidated: dict = {}   # bufid -> (invalidating start, round)
        for ev in evs:
            if ev.kind == "wait" and ev.bufid is not None:
                # a NEW result now owns this id: any stale invalidation
                # entry refers to a dead object, not to this one
                produced[ev.bufid] = ev
                invalidated.pop(ev.bufid, None)
            elif ev.kind == "start":
                if ev.bufid is not None and ev.bufid in produced:
                    invalidated[ev.bufid] = (ev, ev.round)
                # results older than the request's keep-alive window may be
                # garbage-collected, after which CPython can reuse the id —
                # retire their invalidation entries instead of guessing
                if ev.round is not None:
                    for bid, (sev, rnd) in list(invalidated.items()):
                        if sev.handle == ev.handle and rnd is not None \
                                and rnd <= ev.round - 4:
                            del invalidated[bid]
            elif ev.kind in ("send", "coll") and ev.bufid is not None \
                    and ev.bufid in invalidated:
                sev, _rnd = invalidated.pop(ev.bufid)
                wev = produced.get(ev.bufid)
                rel = [(sev.file, sev.line,
                        f"the Start (round {sev.round}) that re-donates the "
                        f"result's registered slot")]
                if wev is not None:
                    rel.append((wev.file, wev.line,
                                f"the Wait (round {wev.round}) that handed "
                                f"the result to the user"))
                out.append(Diagnostic(
                    "R302",
                    f"{ev.op} reads the round-{wev.round if wev else '?'} "
                    f"result of a persistent {sev.op} after the round-"
                    f"{sev.round} Start invalidated its donated buffer — "
                    f"under the registered fast path this reads data the "
                    f"in-flight round is overwriting",
                    file=ev.file, line=ev.line, rank=rank,
                    context="trace ran the safe legacy lane; the hazard is "
                            "the production registered path",
                    related=tuple(rel)))
    out.sort(key=lambda d: (d.file, d.line, d.code))
    return out
