"""Cross-rank Chrome-trace / Perfetto export of the event IR.

The pvar subsystem (:mod:`tpu_mpi.perfvars`) stamps traced events with
``t_start``/``t_end`` and the phase spans the channels observed
(rendezvous / fold / copy). This module turns those into the Chrome
trace-event JSON format (load in Perfetto UI or ``chrome://tracing``):
one process row per rank (``pid`` = world rank), the whole op as a
complete-event slice, its phases as nested slices, and point events
(sends, receives, RMA accesses) as instants.

Ranks on the multi-process tier each run their own monotonic clock, so a
naive merge skews rows by process start time. :func:`clock_offsets` fixes
that with the classic Barrier-exchange estimate: every rank samples its
clock immediately after leaving a Barrier (all ranks exit within one
rendezvous wakeup of each other), Allgathers the samples, and the median
per-rank delta over several rounds becomes the rank's offset to rank 0's
clock. Subtracting a constant per rank keeps per-rank timestamp order
monotone by construction.

Typical use (every rank calls; rank 0 writes)::

    MPI.analyze.timeline.merge_trace(comm, "trace.json")
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence

# Chrome-trace envelope version stamped in otherData. 2 added thread_name
# metadata rows, named the synthetic broker lane, and the request-span
# renderer (spans_to_chrome).
SCHEMA = 2


def clock_offsets(comm: Any, rounds: int = 5) -> List[float]:
    """Per-comm-rank clock offsets to rank 0 (collective: all ranks call).

    ``aligned_t = t - offsets[rank]`` puts every rank's ``time.monotonic``
    readings on rank 0's clock, up to the Barrier-exit skew (microseconds
    on one host). The median over ``rounds`` rounds rejects stragglers
    (a rank descheduled between Barrier exit and its clock sample)."""
    import numpy as np

    from ..collective import Allgather, Barrier
    size = comm.size()
    samples = np.empty((rounds, size), dtype=np.float64)
    mine = np.empty(1, dtype=np.float64)
    for i in range(rounds):
        Barrier(comm)
        mine[0] = time.monotonic()
        samples[i] = np.asarray(Allgather(mine, comm)).reshape(-1)
    deltas = samples - samples[:, :1]          # per-round offset to rank 0
    return [float(x) for x in np.median(deltas, axis=0)]


def _event_dicts(events: Sequence[Any]) -> List[dict]:
    """Plain-dict projection of Event records (what travels over the wire
    in merge_trace, and what to_chrome consumes)."""
    out = []
    for ev in events:
        out.append({
            "kind": ev.kind, "rank": ev.rank, "op": ev.op, "cid": ev.cid,
            "seq": ev.seq, "peer": ev.peer, "tag": ev.tag,
            "count": ev.count, "dtype": ev.dtype, "algo": ev.algo,
            "t": ev.t, "t_start": getattr(ev, "t_start", None),
            "t_end": getattr(ev, "t_end", None),
            "phases": getattr(ev, "phases", None),
        })
    return out


def local_events(ctx: Any = None) -> List[dict]:
    """This process's recorded events as plain dicts (proc tier: only the
    local rank; thread tier: every rank shares one tracer)."""
    from . import events as _ev
    if ctx is None:
        from .._runtime import current_env
        env = current_env()
        tr = _ev.tracer_for(env[0]) if env is not None else _ev.last_trace()
    else:
        tr = _ev.tracer_for(ctx)
    if tr is None:
        return []
    return _event_dicts(tr.events())


def to_chrome(event_dicts: Sequence[dict],
              offsets: Optional[Dict[int, float]] = None) -> dict:
    """Chrome trace-event JSON object from event dicts.

    ``offsets`` maps world rank -> clock offset (seconds, subtracted from
    that rank's timestamps). Spanned events (t_start/t_end) become ph="X"
    complete slices with their phases nested inside; point events become
    ph="i" instants at ``t``. Timestamps are microseconds from the
    earliest aligned instant in the batch."""
    offsets = offsets or {}
    base = None
    for d in event_dicts:
        off = offsets.get(d["rank"], 0.0)
        t0 = d["t_start"] if d["t_start"] is not None else d["t"]
        if t0 is not None:
            t0 -= off
            if base is None or t0 < base:
                base = t0
    base = base or 0.0

    def us(t: float, rank: int) -> float:
        return round((t - offsets.get(rank, 0.0) - base) * 1e6, 3)

    trace: List[dict] = []
    pids = sorted({d["rank"] for d in event_dicts})
    for pid in pids:
        # negative pids are synthetic lanes (events.BROKER_RANK) — name
        # them for what they are so Perfetto rows read "broker", not
        # "rank -1"
        lane = f"rank {pid}" if pid >= 0 else "broker"
        trace.append({"ph": "M", "pid": pid, "tid": 0,
                      "name": "process_name",
                      "args": {"name": lane}})
        trace.append({"ph": "M", "pid": pid, "tid": 0,
                      "name": "process_sort_index",
                      "args": {"sort_index": pid}})
        trace.append({"ph": "M", "pid": pid, "tid": 0,
                      "name": "thread_name",
                      "args": {"name": lane}})
    for d in event_dicts:
        rank = d["rank"]
        args = {k: d[k] for k in ("cid", "seq", "peer", "tag", "count",
                                  "dtype", "algo") if d.get(k) is not None}
        if d["t_start"] is not None and d["t_end"] is not None:
            ts = us(d["t_start"], rank)
            trace.append({
                "ph": "X", "pid": rank, "tid": 0, "name": d["op"],
                "cat": d["kind"], "ts": ts,
                "dur": max(0.001, round((d["t_end"] - d["t_start"]) * 1e6, 3)),
                "args": args,
            })
            for name, p0, p1 in d.get("phases") or ():
                # clip to the parent slice so Perfetto nests cleanly
                p0 = max(p0, d["t_start"])
                p1 = min(p1, d["t_end"])
                if p1 <= p0:
                    continue
                trace.append({
                    "ph": "X", "pid": rank, "tid": 0, "name": name,
                    "cat": "phase", "ts": us(p0, rank),
                    "dur": max(0.001, round((p1 - p0) * 1e6, 3)),
                })
        elif d["t"] is not None:
            trace.append({
                "ph": "i", "pid": rank, "tid": 0, "name": d["op"],
                "cat": d["kind"], "ts": us(d["t"], rank), "s": "t",
                "args": args,
            })
    return {"traceEvents": trace, "displayTimeUnit": "ms",
            "otherData": {"tool": "tpu_mpi.analyze.timeline",
                          "schema": SCHEMA}}


def write_chrome(path: str, event_dicts: Sequence[dict],
                 offsets: Optional[Dict[int, float]] = None) -> str:
    """Write :func:`to_chrome` output as JSON; returns the path."""
    rec = to_chrome(event_dicts, offsets)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(rec, f)
        f.write("\n")
    os.replace(tmp, path)
    return path


def _span_lane(who: str) -> Optional[int]:
    """Rank whos ("rank 3") map onto the same pid as the event rows so a
    request trace and an event trace merge into one timeline; other actors
    get synthetic pids assigned by spans_to_chrome."""
    if who.startswith("rank "):
        try:
            return int(who.split(None, 1)[1])
        except ValueError:
            return None
    return None


def spans_to_chrome(spans: Sequence[dict]) -> dict:
    """Chrome trace-event JSON from request-span dicts
    (:func:`tpu_mpi.tracectx.drain`).

    One process row per actor: rank spans land on ``pid`` = world rank
    (merging cleanly with :func:`to_chrome` rows); non-rank actors
    (client, router, broker, serve workers) get deterministic pids from
    1000 up in sorted-name order. Every span becomes a ph="X" complete
    slice carrying its trace/span/parent ids and status in ``args``, so
    Perfetto's flow queries (and the CI trace gate) can walk the request
    tree across lanes. Open spans (t1 is None) render with their reason
    visible: status "open" and a 1µs sliver at t0."""
    spans = [s for s in spans if s.get("t0") is not None]
    base = min((s["t0"] for s in spans), default=0.0)
    whos = sorted({s["who"] for s in spans})
    pid_of: Dict[str, int] = {}
    synth = 1000
    for who in whos:
        lane = _span_lane(who)
        if lane is None:
            lane, synth = synth, synth + 1
        pid_of[who] = lane
    trace: List[dict] = []
    for who in whos:
        pid = pid_of[who]
        trace.append({"ph": "M", "pid": pid, "tid": 0,
                      "name": "process_name", "args": {"name": who}})
        trace.append({"ph": "M", "pid": pid, "tid": 0,
                      "name": "process_sort_index",
                      "args": {"sort_index": pid}})
        trace.append({"ph": "M", "pid": pid, "tid": 0,
                      "name": "thread_name", "args": {"name": who}})
    core = ("trace", "span", "parent", "name", "who", "t0", "t1")
    for s in spans:
        t1 = s.get("t1")
        args = {"trace": s["trace"], "span": s["span"],
                "parent": s.get("parent"),
                "status": s.get("status", "ok") if t1 is not None else "open"}
        args.update({k: v for k, v in s.items()
                     if k not in core and k != "status" and v is not None})
        dur = (t1 - s["t0"]) * 1e6 if t1 is not None else 1.0
        trace.append({
            "ph": "X", "pid": pid_of[s["who"]], "tid": 0,
            "name": s["name"], "cat": "span",
            "ts": round((s["t0"] - base) * 1e6, 3),
            "dur": max(0.001, round(dur, 3)), "args": args,
        })
    return {"traceEvents": trace, "displayTimeUnit": "ms",
            "otherData": {"tool": "tpu_mpi.analyze.timeline",
                          "schema": SCHEMA, "content": "spans"}}


def write_spans(path: str, spans: Sequence[dict]) -> str:
    """Write :func:`spans_to_chrome` output as JSON; returns the path."""
    rec = spans_to_chrome(spans)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(rec, f)
        f.write("\n")
    os.replace(tmp, path)
    return path


def merge_trace(comm: Any, path: Optional[str] = None,
                rounds: int = 5) -> Optional[dict]:
    """Cross-rank merged Chrome trace (collective: every rank calls).

    Aligns clocks via :func:`clock_offsets`, gathers every rank's local
    events to comm rank 0, and returns the merged trace object there
    (writing ``path`` when given); other ranks return None. On the thread
    tier all ranks share one tracer, so rank 0 sends nothing and
    duplicates are dropped by (rank, kind, cid, seq) identity."""
    from ..pointtopoint import recv, send
    offs = clock_offsets(comm, rounds=rounds)
    mine = local_events()
    rank, size = comm.rank(), comm.size()
    tag = 271_828     # private-ish tag lane for the gather
    if rank != 0:
        send(mine, 0, tag, comm)
        return None
    seen = set()
    merged: List[dict] = []
    world_of = comm.world_rank_of
    offsets = {world_of(r): offs[r] for r in range(size)}
    for batch in [mine] + [recv(r, tag, comm)[0] for r in range(1, size)]:
        for d in batch:
            key = (d["rank"], d["kind"], d["cid"], d["seq"])
            if key in seen:
                continue
            seen.add(key)
            merged.append(d)
    rec = to_chrome(merged, offsets)
    if path:
        write_chrome(path, merged, offsets)
    return rec
