"""Cross-rank trace verification + the DeadlockError wait-for dump.

:func:`verify_trace` consumes one :class:`tpu_mpi.analyze.events.Tracer` and
checks what no single rank can check alone:

- **T201** — ranks of one communicator called *different* collectives in the
  same round (aligned by absolute per-communicator round ordinals, so ring
  eviction cannot misalign the comparison);
- **T202** — same collective, disagreeing signature: root ranks, or
  dtype/count where the caller supplied a precise signature (reductions,
  Bcast — per-rank-varying Gatherv counts are deliberately not compared),
  plus per-peer count agreement for the ``*v`` family: Alltoallv events
  carry ``scounts``/``rcounts`` in ``extra``, and rank i's ``scounts[j]``
  must equal rank j's ``rcounts[i]``;
- **T203** — a sent message that was never received (suppressed when the
  receiver's ring overflowed: absence of evidence is not evidence);
- **T207** — ULFM protocol divergence: ranks of one communicator disagree on
  the agreement epoch, the agreed flag value, or the shrink survivor set in
  the same protocol round;
- **T208** — serve-tier accounting: a broker ``book`` event whose per-tenant
  measured rows fail to partition the pool totals;
- **T214** — elastic rebind participation: a rank the quiesce/resume round
  declares, and which appears in the trace, never recorded the round (it
  skipped the rebind barrier and can race the remap);
- plus any online findings the hooks queued (T206 Isend buffer mutation),
  the RMA race pass (:func:`tpu_mpi.analyze.races.detect_races`), and the
  donated-buffer invalidation pass
  (:func:`tpu_mpi.analyze.races.detect_donation_races`, R302).

:func:`deadlock_report` renders the per-rank pending operations and the
wait-for cycle appended to DeadlockError messages by the runtime watchdog
(``_runtime.raise_deadlock``).
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Any, Dict, List, Optional

from .diagnostics import Diagnostic


def _tracer_of(obj: Any) -> Optional[Any]:
    from .events import Tracer, last_trace
    if obj is None:
        return last_trace()
    if isinstance(obj, Tracer):
        return obj
    return getattr(obj, "_tracer", None)       # an SpmdContext


def verify_trace(obj: Any = None) -> List[Diagnostic]:
    """All trace-verifier diagnostics for ``obj`` (a Tracer, a context, or
    None for the most recent traced run)."""
    tr = _tracer_of(obj)
    if tr is None:
        return []
    with tr.lock:
        out = list(tr.diagnostics)
    out += _check_collectives(tr)
    out += _check_p2p(tr)
    out += _check_ft(tr)
    out += _check_serve(tr)
    out += _check_elastic(tr)
    out += _check_lock_serialization(tr)
    from .races import detect_donation_races, detect_races
    out += detect_races(tr)
    out += detect_donation_races(tr)
    out.sort(key=lambda d: (d.file, d.line, d.code))
    return out


# ---------------------------------------------------------------------------
# Collective order + signature agreement (T201 / T202)
# ---------------------------------------------------------------------------

def _check_collectives(tr) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    # (cid, group, round ordinal) names one rendezvous across ranks; the
    # group tuple keeps same-cid-different-group comms (COMM_SELF is cid 1
    # on every rank) from being cross-checked.
    rounds: Dict[tuple, list] = defaultdict(list)
    for ev in tr.events():
        if ev.kind == "coll":
            rounds[(ev.cid, ev.grp, ev.seq)].append(ev)
    # cids mix ints with recovery tuples (("shrink", cid, epoch)): str-keyed
    for (cid, grp, seq), evs in sorted(rounds.items(),
                                       key=lambda kv: (str(kv[0][0]),
                                                       kv[0][2])):
        if len(evs) < 2:
            continue                 # size-1 groups have nothing to agree on
        ops = {ev.op for ev in evs}
        if len(ops) > 1:
            by_op: Dict[str, list] = defaultdict(list)
            for ev in evs:
                by_op[ev.op].append(ev)
            majority = max(by_op, key=lambda op: len(by_op[op]))
            minority = [ev for ev in evs if ev.op != majority]
            anchor = min(minority, key=lambda ev: ev.rank)
            out.append(Diagnostic(
                "T201",
                f"world rank {anchor.rank} called {anchor.op!r} while "
                f"rank(s) {sorted(ev.rank for ev in by_op[majority])} called "
                f"{majority!r} in collective round {seq} of comm {cid}",
                file=anchor.file, line=anchor.line, rank=anchor.rank,
                context=f"group {list(grp)}"))
            continue                 # signature checks presume one op
        roots = {ev.root for ev in evs if ev.root is not None}
        if len(roots) > 1:
            anchor = min((ev for ev in evs if ev.root is not None),
                         key=lambda ev: ev.rank)
            out.append(Diagnostic(
                "T202",
                f"root argument disagrees across ranks in {anchor.op}: "
                f"{sorted(roots)} (collective round {seq} of comm {cid})",
                file=anchor.file, line=anchor.line, rank=anchor.rank,
                context=f"group {list(grp)}"))
        # dtype/count agreement is only meaningful for events carrying a
        # precise signature (reductions and Bcast set one; Gatherv-family
        # counts legitimately differ per rank and carry none).
        sigged = [ev for ev in evs if ev.dtype is not None]
        if len(sigged) > 1 and len({ev.dtype for ev in sigged}) > 1:
            anchor = min(sigged, key=lambda ev: ev.rank)
            out.append(Diagnostic(
                "T202",
                f"dtype disagrees across ranks in {anchor.op}: "
                f"{sorted({ev.dtype for ev in sigged})} "
                f"(collective round {seq} of comm {cid})",
                file=anchor.file, line=anchor.line, rank=anchor.rank))
        counted = [ev for ev in evs if ev.count is not None]
        if len(counted) > 1 and len({ev.count for ev in counted}) > 1:
            anchor = min(counted, key=lambda ev: ev.rank)
            out.append(Diagnostic(
                "T202",
                f"element count disagrees across ranks in {anchor.op}: "
                f"{sorted({ev.count for ev in counted})} "
                f"(collective round {seq} of comm {cid})",
                file=anchor.file, line=anchor.line, rank=anchor.rank))
        # T213: algorithm-selection divergence. The selection is required
        # to be a deterministic function of rank-uniform inputs (see
        # tune.select), so one rank recording a different algorithm for
        # the same round means the run mixed tiers — at the proc tier
        # that is a CollectiveMismatchError in flight; at the thread tier
        # it documents a selection-determinism bug. A hierarchical run is
        # ONE logical round here (its sub-collectives are internal
        # alg-tier frames, never separate coll events), so composites
        # stay clean by construction.
        algod = [ev for ev in evs if ev.algo is not None]
        if len(algod) > 1 and len({ev.algo for ev in algod}) > 1:
            by_algo: Dict[str, list] = defaultdict(list)
            for ev in algod:
                by_algo[ev.algo].append(ev)
            majority = max(by_algo, key=lambda a: len(by_algo[a]))
            minority = [ev for ev in algod if ev.algo != majority]
            anchor = min(minority, key=lambda ev: ev.rank)
            out.append(Diagnostic(
                "T213",
                f"algorithm selection disagrees across ranks in "
                f"{anchor.op}: world rank {anchor.rank} selected "
                f"{anchor.algo!r} while rank(s) "
                f"{sorted(ev.rank for ev in by_algo[majority])} selected "
                f"{majority!r} (collective round {seq} of comm {cid})",
                file=anchor.file, line=anchor.line, rank=anchor.rank,
                context=f"group {list(grp)}"))
        out += _check_vector_counts(cid, grp, seq, evs)
    return out


def _check_vector_counts(cid, grp, seq, evs) -> List[Diagnostic]:
    """Per-peer count agreement for ``*v`` collectives: events carrying
    ``scounts``/``rcounts`` in ``extra`` (Alltoallv records both) must
    satisfy ``rank_i.scounts[j] == rank_j.rcounts[i]`` — what rank i ships
    toward peer slot j is exactly what rank j budgeted for peer slot i.
    Position in the count vectors is the rank's index within the group.
    One diagnostic per round (the first disagreeing pair), anchored at the
    lower-rank participant."""
    vevs = [ev for ev in evs
            if isinstance(ev.extra, dict) and "scounts" in ev.extra
            and "rcounts" in ev.extra]
    if len(vevs) < 2:
        return []
    pos = {ev.rank: grp.index(ev.rank) for ev in vevs if ev.rank in grp}
    for a in sorted(vevs, key=lambda ev: ev.rank):
        for b in sorted(vevs, key=lambda ev: ev.rank):
            i, j = pos.get(a.rank), pos.get(b.rank)
            if i is None or j is None:
                continue
            sc, rc = list(a.extra["scounts"]), list(b.extra["rcounts"])
            if len(sc) != len(grp) or len(rc) != len(grp):
                continue       # malformed vectors already fail at runtime
            if sc[j] != rc[i]:
                anchor = a if a.rank <= b.rank else b
                return [Diagnostic(
                    "T202",
                    f"per-peer count disagrees in {anchor.op}: world rank "
                    f"{a.rank} sends {sc[j]} element(s) to world rank "
                    f"{b.rank}, which expects {rc[i]} (collective round "
                    f"{seq} of comm {cid})",
                    file=anchor.file, line=anchor.line, rank=anchor.rank,
                    context=f"group {list(grp)}: scounts[{a.rank}]={sc}, "
                            f"rcounts[{b.rank}]={rc}")]
    return []


# ---------------------------------------------------------------------------
# Send/recv pairing (T203)
# ---------------------------------------------------------------------------

def _check_p2p(tr) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    # (cid, sender world rank, receiver world rank, delivered tag) ->
    # per-direction counts. Recv events record the *delivered* message's
    # concrete tag, so wildcard receives still land in the right bucket.
    sends: Dict[tuple, list] = defaultdict(list)
    recvs: Dict[tuple, int] = defaultdict(int)
    dropped = dict(tr.dropped)
    for ev in tr.events():
        if ev.kind == "send":
            sends[(ev.cid, ev.rank, ev.peer, ev.tag)].append(ev)
        elif ev.kind == "recv" and ev.peer is not None:
            recvs[(ev.cid, ev.peer, ev.rank, ev.tag)] += 1
    for key, evs in sorted(sends.items(),
                           key=lambda kv: (str(kv[0][0]), kv[0][1])):
        cid, src, dst, tag = key
        unmatched = len(evs) - recvs.get(key, 0)
        if unmatched <= 0:
            continue
        if dropped.get(dst):
            continue        # receiver's ring overflowed: recv may be evicted
        for ev in evs[-unmatched:]:
            out.append(Diagnostic(
                "T203",
                f"message sent by world rank {src} to world rank {dst} "
                f"(tag={tag}, comm {cid}) was never received",
                file=ev.file, line=ev.line, rank=src,
                context=f"{len(evs)} send(s), {recvs.get(key, 0)} receive(s) "
                        f"for this (source, destination, tag)"))
    return out


# ---------------------------------------------------------------------------
# ULFM protocol agreement (T207)
# ---------------------------------------------------------------------------

def _canon(v):
    """Hashable form of an ``extra`` field — JSON round-trips the recorded
    survivor tuples back as lists."""
    return tuple(v) if isinstance(v, list) else v


def _check_ft(tr) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    # Alignment can NOT use ev.seq: the ft ordinal mixes Comm_revoke (which
    # only the revoking rank records) with the collective agree/shrink
    # steps. Re-derive a per-(rank, cid, op) ordinal from ring order instead.
    rounds: Dict[tuple, list] = defaultdict(list)
    ordinal: Dict[tuple, int] = defaultdict(int)
    for ev in tr.events():
        if ev.kind != "ft" or ev.op == "Comm_revoke":
            continue
        k = (ev.rank, ev.cid, ev.op)
        rounds[(ev.cid, ev.op, ordinal[k])].append(ev)
        ordinal[k] += 1
    for (cid, op, rnd), evs in sorted(rounds.items(),
                                      key=lambda kv: (str(kv[0][0]),
                                                      str(kv[0][1]),
                                                      kv[0][2])):
        if len(evs) < 2:
            continue        # dead or evicted peers: nothing to compare
        for field, label in (("epoch", "agreement epoch"),
                             ("value", "agreed value"),
                             ("survivors", "survivor set")):
            vals = {ev.rank: _canon((ev.extra or {}).get(field))
                    for ev in evs}
            distinct = {v for v in vals.values() if v is not None}
            if len(distinct) > 1:
                anchor = min(evs, key=lambda ev: ev.rank)
                out.append(Diagnostic(
                    "T207",
                    f"{label} of {op} round {rnd} on comm {cid} diverges "
                    f"across ranks: "
                    + ", ".join(f"rank {r} -> {v}"
                                for r, v in sorted(vals.items())
                                if v is not None),
                    file=anchor.file, line=anchor.line, rank=anchor.rank,
                    context=f"ranks {sorted(vals)}"))
                break       # one diagnostic per divergent round
    return out


# ---------------------------------------------------------------------------
# Elastic rebind quiesce/resume participation (T214)
# ---------------------------------------------------------------------------

def _check_elastic(tr) -> List[Diagnostic]:
    """Every rank an elastic quiesce/resume round *declares* must have
    recorded the round — a declared rank that shows up elsewhere in the
    trace but skipped the rebind barrier would race the remap (the defect
    the two-phase protocol exists to exclude). Ranks wholly absent from
    the trace are not held to it (dead, or ring-evicted)."""
    out: List[Diagnostic] = []
    present = {r for r in tr.rings if r >= 0 and tr.rings[r]}
    rounds: Dict[tuple, list] = defaultdict(list)
    for ev in tr.events():
        if ev.kind != "elastic":
            continue
        declared = _canon((ev.extra or {}).get("declared")) or ()
        rounds[(ev.op, (ev.extra or {}).get("epoch"), declared)].append(ev)
    for (op, epoch, declared), evs in sorted(
            rounds.items(), key=lambda kv: (str(kv[0][1]), kv[0][0])):
        seen = {ev.rank for ev in evs}
        missing = [r for r in declared if r in present and r not in seen]
        if missing:
            anchor = min(evs, key=lambda ev: ev.rank)
            out.append(Diagnostic(
                "T214",
                f"elastic {op} round (epoch {epoch}) declares ranks "
                f"{list(declared)} but rank(s) {missing} never recorded "
                f"it — a rank skipped the rebind barrier and can race "
                f"the remap",
                file=anchor.file, line=anchor.line, rank=anchor.rank,
                context=f"participants {sorted(seen)}"))
    return out


# ---------------------------------------------------------------------------
# Serve-tier book partition (T208)
# ---------------------------------------------------------------------------

def _check_serve(tr) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    flush_no = 0
    for ev in tr.events():
        if ev.kind != "serve" or ev.op != "book" or not ev.extra:
            continue
        flush_no += 1
        totals = ev.extra.get("totals") or {}
        measured = ev.extra.get("measured") or {}
        for field, total in sorted(totals.items()):
            attributed = sum(int((row or {}).get(field, 0) or 0)
                             for row in measured.values())
            if attributed != int(total or 0):
                out.append(Diagnostic(
                    "T208",
                    f"ledger flush {flush_no}: per-tenant measured "
                    f"{field!r} rows sum to {attributed} but the pool "
                    f"total is {total} — cid-ownership attribution lost "
                    f"{int(total or 0) - attributed} unit(s)",
                    file=ev.file, line=ev.line, rank=ev.rank,
                    context=f"tenants {sorted(measured)}"))
    return out


# ---------------------------------------------------------------------------
# Dispatch-lock serialization (T215): the broker's dispatcher records each
# pop under BROKER_RANK; if the dispatch-lock critical sections serialize,
# every rank initiates its first collective per comm in the same relative
# order the dispatcher released them.
# ---------------------------------------------------------------------------

def _check_lock_serialization(tr) -> List[Diagnostic]:
    from .events import BROKER_RANK
    out: List[Diagnostic] = []
    dispatch_order: List[Any] = []          # cids by first dispatch event
    seen: set = set()
    for ev in tr.events(BROKER_RANK):
        if ev.kind == "serve" and ev.op == "dispatch" and ev.cid is not None:
            if ev.cid not in seen:
                seen.add(ev.cid)
                dispatch_order.append(ev.cid)
    if len(dispatch_order) < 2:
        return out
    pos = {cid: i for i, cid in enumerate(dispatch_order)}
    with tr.lock:
        ranks = sorted(r for r in tr.rings if r != BROKER_RANK)
        dropped = dict(tr.dropped)
    for rank in ranks:
        if dropped.get(rank):
            # the ring evicted this rank's early events: its observed first
            # occurrences are not the real first occurrences — stay silent
            continue
        firsts: List[Any] = []
        by_cid: Dict[Any, Any] = {}
        for ev in tr.events(rank):
            if ev.kind == "coll" and ev.cid in pos and ev.cid not in by_cid:
                by_cid[ev.cid] = ev
                firsts.append(ev.cid)
        for a, b in zip(firsts, firsts[1:]):
            if pos[a] > pos[b]:
                ev = by_cid[b]
                out.append(Diagnostic(
                    "T215",
                    f"rank {rank} initiated comm {b}'s first collective "
                    f"before comm {a}'s, but the dispatcher released "
                    f"{a} before {b} — dispatch-lock critical sections "
                    f"did not serialize op initiation",
                    file=ev.file, line=ev.line, rank=rank,
                    context=f"dispatch order {dispatch_order}, "
                            f"rank order {firsts}"))
                break
    return out


# ---------------------------------------------------------------------------
# DeadlockError dump: per-rank pending operations + the wait-for cycle
# ---------------------------------------------------------------------------

def _waits_for(ctx, ev, blocked: Dict[int, Any]) -> List[int]:
    """World ranks ``ev``'s blocked operation is waiting on."""
    if ev.kind in ("send", "recv", "lock"):
        if ev.peer is None:      # ANY_SOURCE: anyone blocked could unblock it
            return [r for r in blocked if r != ev.rank]
        return [ev.peer]
    if ev.kind == "coll" and ev.grp:
        # missing contributors of this round, read off the live channel
        try:
            from .._runtime import _EMPTY
            ch = ctx._channels.get(ev.cid)
            if ch is not None and len(ch.contribs) == len(ev.grp):
                return [wr for i, wr in enumerate(ev.grp)
                        if wr != ev.rank and ch.contribs[i] is _EMPTY]
        except Exception:
            pass
        return [wr for wr in ev.grp if wr != ev.rank]
    return []


def _find_cycle(edges: Dict[int, List[int]]) -> Optional[List[int]]:
    """One directed cycle in the wait-for graph, as a rank list."""
    WHITE, GREY, BLACK = 0, 1, 2
    color = {r: WHITE for r in edges}
    path: List[int] = []

    def dfs(r: int) -> Optional[List[int]]:
        color[r] = GREY
        path.append(r)
        for nxt in edges.get(r, ()):
            if nxt not in edges:
                continue
            if color[nxt] == GREY:
                return path[path.index(nxt):]
            if color[nxt] == WHITE:
                cyc = dfs(nxt)
                if cyc is not None:
                    return cyc
        path.pop()
        color[r] = BLACK
        return None

    for r in sorted(edges):
        if color[r] == WHITE:
            cyc = dfs(r)
            if cyc is not None:
                return cyc
    return None


def deadlock_report(ctx: Any) -> str:
    """Multi-line dump of per-rank pending operations and the wait-for
    cycle, appended to DeadlockError messages when tracing is on; armed
    witness runs (TPU_MPI_LOCKCHECK=1) additionally get every thread's
    held-lock set with acquisition sites. Returns "" when there is nothing
    useful to say — never raises (this runs while the job is already
    failing)."""
    lines: List[str] = []
    try:
        tr = getattr(ctx, "_tracer", None)
        blocked = {}
        if tr is not None:
            with tr.lock:
                blocked = dict(tr.blocked)
        if blocked:
            now = time.monotonic()
            lines.append("per-rank pending operations:")
            edges: Dict[int, List[int]] = {}
            for r in sorted(blocked):
                ev = blocked[r]
                lines.append(f"  world rank {r}: blocked {now - ev.t:.1f}s "
                             f"in {ev.describe()} at {ev.file}:{ev.line}")
                edges[r] = _waits_for(ctx, ev, blocked)
            idle = [r for r in range(getattr(ctx, "size", 0))
                    if r not in blocked]
            if idle:
                lines.append(f"  rank(s) {idle} not blocked in any traced "
                             f"operation")
            cyc = _find_cycle(edges)
            if cyc:
                lines.append("wait-for cycle: " + " -> ".join(
                    f"rank {r}" for r in cyc + [cyc[0]]))
    except Exception:
        pass
    try:
        # witness-armed runs know which locks every thread holds and where
        # it acquired them — the missing half of a deadlock dump
        from .. import locksmith
        witness = locksmith.witness_report()
        if witness:
            lines.append(witness)
    except Exception:
        pass
    return "\n".join(lines)
