"""``python -m tpu_mpi.analyze <command> …`` — the analyzer CLI.

Commands:

- ``lint file.py dir/ …`` — static communication lint (also available as
  ``python -m tpu_mpi.lint``);
- ``locks file.py dir/ …`` — static concurrency lint: builds the
  lock-acquisition graph and flags lock-order cycles (L112), blocking
  calls under a dispatch lock (L113), unguarded shared fields (L114) and
  missed releases on exception edges (L115)
  (:mod:`tpu_mpi.analyze.concurrency`);
- ``explore <trace prefix or files> [--max-schedules N] [--max-states N]``
  — DPOR-style schedule-space verification over a recorded trace
  (:mod:`tpu_mpi.analyze.explore`); record one with ``TPU_MPI_TRACE=1
  TPU_MPI_TRACE_DUMP=<prefix>`` and pass the prefix here;
- ``verify <trace prefix or files>`` — the cross-rank trace verifier
  (:func:`tpu_mpi.analyze.matcher.verify_trace`) over dumped traces;
- ``flight <dump.json>`` — CRC-verify and render a crash flight-recorder
  dump (:mod:`tpu_mpi.flight`): the timeline of spans, lifecycle notes and
  typed errors recorded in the seconds before the process died.

Every command prints diagnostics and exits 1 if any were found.
"""

from __future__ import annotations

import sys
from typing import List, Optional

_USAGE = __doc__


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(_USAGE)
        return 0
    cmd, rest = argv[0], argv[1:]
    if cmd == "lint":
        from .lint import main as lint_main
        return lint_main(rest)
    if cmd == "locks":
        from .concurrency import main as locks_main
        return locks_main(rest)
    if cmd == "explore":
        from .explore import main as explore_main
        return explore_main(rest)
    if cmd == "verify":
        if not rest:
            print("usage: python -m tpu_mpi.analyze verify <trace...>")
            return 2
        from .events import load_trace
        from .matcher import verify_trace
        tr = load_trace(rest if len(rest) > 1 else rest[0])
        diags = verify_trace(tr)
        for d in diags:
            print(d)
        if diags:
            print(f"{len(diags)} diagnostic(s)")
            return 1
        print("trace verifies clean")
        return 0
    if cmd == "flight":
        if not rest:
            print("usage: python -m tpu_mpi.analyze flight <dump.json>")
            return 2
        from .. import flight
        status = 0
        for path in rest:
            try:
                payload = flight.read_dump(path)
            except (OSError, ValueError, KeyError) as e:
                print(f"{path}: {e}")
                status = 1
                continue
            print(flight.render(payload))
        return status
    print(f"unknown command {cmd!r}\n{_USAGE}")
    return 2


if __name__ == "__main__":
    sys.exit(main())
