"""The shared diagnostic record every analyzer pass emits.

One code space across the three passes (docs/analysis.md):

- ``Lxxx`` — static lint (:mod:`tpu_mpi.analyze.lint`)
- ``Txxx`` — cross-rank trace verifier (:mod:`tpu_mpi.analyze.matcher`)
- ``Rxxx`` — RMA race detector (:mod:`tpu_mpi.analyze.races`)
- ``Cxxx`` — runtime lock witness (:mod:`tpu_mpi.locksmith`); the static
  concurrency lint (:mod:`tpu_mpi.analyze.concurrency`) shares the
  ``Lxxx`` space (L112–L115)

Each diagnostic projects onto an MPI error class
(:data:`tpu_mpi.error.DIAGNOSTIC_CODES`), so ``Error_string`` /
``MPIError.Get_error_string`` cover analyzer findings exactly like
runtime-raised errors.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

# code -> one-line description of the defect class.
CODES = {
    "L100": "source file could not be parsed",
    "L101": "rank-divergent collective call sequence",
    "L102": "collective root argument differs across rank branches",
    "L103": "collective op/dtype argument differs across rank branches",
    "L104": "receive count smaller than the matching send (truncation)",
    "L105": "send with no matching receive",
    "L106": "Isend buffer mutated before its Wait",
    "L107": "blocking send/recv cycle pattern (deadlock)",
    "L108": "overlapping RMA accesses in one exposure epoch",
    "L109": "persistent-request misuse (double Start / buffer mutation "
            "between Start and Wait / Start after free)",
    "L110": "operation on a revoked or shrunk communicator",
    "L111": "serve-session misuse (cross-tenant comm / op after detach)",
    "L112": "lock-order cycle across acquisition paths (potential deadlock)",
    "L113": "blocking call while holding a dispatch/pool lock",
    "L114": "shared mutable field written on multiple threads with no "
            "common guard",
    "L115": "lock released on a different path than it was acquired "
            "(missed release on an exception edge)",
    "L116": "gradient-bucket handle misuse (Start twice without Wait / "
            "Wait on an unstarted bucket)",
    "T201": "ranks called different collectives in the same round",
    "T202": "collective signature (root/dtype/count) disagrees across ranks",
    "T203": "sent message was never received",
    "T206": "Isend buffer was modified before its Wait completed",
    "T207": "agree/shrink protocol divergence across ranks",
    "T208": "per-tenant measured books fail to partition the pool totals",
    "T210": "alternate schedule deadlocks (found by analyze.explore)",
    "T211": "alternate schedule orphans a sent message",
    "T212": "wildcard receive observes schedule-dependent values",
    "T213": "algorithm selection disagrees across ranks in a collective "
            "round",
    "T214": "a rank skipped an elastic rebind quiesce/resume barrier",
    "T215": "dispatch-lock critical sections failed to serialize "
            "(op-initiation order diverges from cross-rank collective "
            "order)",
    "C401": "blocking call while holding another witnessed lock "
            "(runtime lock witness)",
    "R301": "concurrent overlapping RMA accesses (vector-clock race)",
    "R302": "donated persistent-fold result used after a later Start "
            "invalidated it",
}

# Codes deliberately absent from CODES. T204/T205 were allotted to
# receive-side pairing checks in the PR-2 design; both folded into T203's
# send/recv accounting (one keyed table covers "never received" and
# "received with nobody sending"), and the numbers stay reserved so old
# suppression lists keep meaning the same thing. T209 is reserved for the
# serve dispatcher's cross-cid initiation-order invariant, which the
# explorer currently reports through T210 (a divergent initiation order IS
# an alternate-schedule deadlock).
RESERVED_CODES = ("T204", "T205", "T209")


@dataclass
class Diagnostic:
    """One analyzer finding, printable as ``file:line: CODE message``."""

    code: str
    message: str
    file: str = "<unknown>"
    line: int = 0
    rank: Optional[int] = None
    # rank-condition context (lint) or op detail (trace), human-readable.
    context: str = ""
    # related sites: (file, line, note) triples (e.g. the other racing access).
    related: Tuple[tuple, ...] = field(default=())

    @property
    def mpi_code(self) -> int:
        """The MPI error class this diagnostic projects onto."""
        from ..error import diagnostic_error_code
        return diagnostic_error_code(self.code)

    def error(self):
        """This diagnostic as a raisable :class:`tpu_mpi.error.AnalyzerError`."""
        from ..error import AnalyzerError
        return AnalyzerError(str(self), diag_code=self.code)

    def __str__(self) -> str:
        ctx = f" [{self.context}]" if self.context else ""
        rel = "".join(f"\n    related: {f}:{ln}: {note}"
                      for f, ln, note in self.related)
        return f"{self.file}:{self.line}: {self.code} {self.message}{ctx}{rel}"
