"""tpu_mpi.analyze: communication-correctness tooling (docs/analysis.md).

Three cooperating passes over one shared event IR:

- **static lint** (:mod:`.lint`, CLI ``python -m tpu_mpi.lint file.py …``):
  a CPython-``ast`` pass over SPMD programs flagging rank-divergent
  collective sequences, root/op/dtype mismatches, recv truncation, unmatched
  sends, Isend-buffer reuse before Wait, blocking cycles and static RMA
  races — no runtime needed;
- **trace verifier** (:mod:`.events` + :mod:`.matcher`): a low-overhead
  tracing hook (config knob ``trace`` / env ``TPU_MPI_TRACE``) records
  per-rank events from ``comm``/``collective``/``pointtopoint``/``onesided``
  into ring buffers; the cross-rank matcher checks collective order and
  signature agreement, pairs sends with receives, and renders the
  DeadlockError dump of per-rank pending operations + the wait-for cycle;
- **RMA race detector** (:mod:`.races`): vector-clock happens-before over
  window epochs (Win_fence / Win_lock), flagging concurrent overlapping
  Put/Put and Put/Get ranges inside one exposure epoch;
- **schedule explorer** (:mod:`.explore`, CLI ``python -m tpu_mpi.analyze
  explore <trace>``): DPOR-style enumeration of the alternate schedules a
  recorded run could have taken — wildcard matchings, persistent
  Start/Wait reorderings, dispatcher interleavings — checking each for
  deadlock (T210), orphaned messages (T211) and value divergence (T212).

This package stays import-light (stdlib + numpy): the lint CLI must start
without touching jax, and the runtime hooks only pay for what they call.
"""

from __future__ import annotations

from .diagnostics import CODES, Diagnostic

__all__ = ["CODES", "Diagnostic", "lint_paths", "lint_source",
           "lock_lint_paths", "lock_lint_source", "verify_trace",
           "detect_races", "deadlock_report", "last_trace", "timeline",
           "merge_trace", "write_chrome", "clock_offsets", "explore",
           "ExploreResult", "load_trace", "dump_trace"]


def __getattr__(name):
    # lazy re-exports: keep `import tpu_mpi` from paying for the ast pass
    # and keep hot modules' `from .analyze import events` cheap.
    if name in ("lint_paths", "lint_source"):
        from . import lint as _lint
        return getattr(_lint, name)
    if name in ("lock_lint_paths", "lock_lint_source"):
        from . import concurrency as _concurrency
        return getattr(_concurrency, name)
    if name in ("verify_trace", "deadlock_report"):
        from . import matcher as _matcher
        return getattr(_matcher, name)
    if name == "detect_races":
        from .races import detect_races
        return detect_races
    if name == "last_trace":
        from .events import last_trace
        return last_trace
    if name in ("load_trace", "dump_trace"):
        from . import events as _events
        return getattr(_events, name)
    if name in ("explore", "ExploreResult"):
        # "explore" resolves to the MODULE (like .timeline): the import
        # machinery pins the submodule as the package attribute anyway, so
        # returning the function here would only hold until first import.
        import importlib
        _explore = importlib.import_module(".explore", __name__)
        return _explore if name == "explore" else getattr(_explore, name)
    if name in ("timeline", "merge_trace", "write_chrome", "clock_offsets"):
        # importlib, not `from . import timeline`: the fromlist machinery
        # resolves missing attributes through THIS __getattr__ and recurses
        import importlib
        _timeline = importlib.import_module(".timeline", __name__)
        return _timeline if name == "timeline" else getattr(_timeline, name)
    raise AttributeError(f"module 'tpu_mpi.analyze' has no attribute {name!r}")
