"""Schedule-space verification: DPOR-style exploration over one trace.

One recorded run (a :class:`tpu_mpi.analyze.events.Tracer`, live or loaded
back from ``TPU_MPI_TRACE_DUMP`` files) fixes each rank's *program*: the
sequence of communication operations that rank performed. The runtime chose
ONE schedule for that program's nondeterministic choice points; this module
re-executes the per-rank programs over an abstract machine and enumerates
the others:

- **wildcard receive matchings** — a receive posted with ``ANY_SOURCE``
  (the ``want`` slot is None) may match any in-flight message; per source,
  MPI's non-overtaking rule pins the first tag-match, so the branch set is
  "one candidate per sender";
- **persistent Start/Wait reorderings** — ``start`` events mark a round
  begun, the matching ``wait`` blocks until every participant started it;
  the interleavings of different ranks' Start/Wait pairs are explored like
  any other transitions;
- **dispatcher/collective interleavings** — collectives (including the
  ULFM ``Comm_agree``/``Comm_shrink`` steps and the serve pool's rounds)
  are synchronizing transitions over the participant set *observed in the
  trace*, so a world already shrunk does not dead-wait on its dead ranks.

Each maximal schedule is checked for

- **T210** deadlock: a non-terminal state with no enabled transition — the
  diagnostic carries the per-rank executed-event listing of the schedule
  that got there plus each rank's pending operation;
- **T211** orphaned messages: a terminal schedule that leaves sent
  messages unreceived;
- **T212** value divergence: a wildcard receive that observes messages
  with *different payload signatures* (tag/count/dtype — deliberately not
  the source itself, or every explored matching would count) depending on
  the schedule.

Reduction. Deterministic transitions (sends, collectives whose
participants all arrived, exact-source receives — FIFO per sender makes
their match unique — starts, and waits) are executed eagerly without
branching: they are persistent in the DPOR sense, since no other rank's
transition can change what they do. Branching happens only at quiescence
(no deterministic transition enabled) and only over wildcard-receive
candidates; converging interleavings are pruned by a visited-state sleep
set keyed on (program counters, channel contents, started rounds). Small
worlds — up to ~8 ranks and a few hundred events — verify in well under a
second; ``max_schedules``/``max_states`` bound the walk and set
``truncated`` when they bite (never silently).

The model is an *eager-buffered* MPI: sends never block, receives block
until a match is in flight, collectives block until every observed
participant arrives. Branch sets are formed at quiescence, so a match that
only becomes available after ANOTHER rank's later wildcard choice can be
missed (the classic POE approximation) — exploration is sound (a reported
deadlock is reachable under the model) but not exhaustive.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

from .diagnostics import Diagnostic

# ---------------------------------------------------------------------------
# Per-rank programs: trace events -> transitions
# ---------------------------------------------------------------------------


class _Tx:
    """One transition of one rank's re-executable program."""

    __slots__ = ("kind", "rank", "op", "cid", "dst", "want", "wtag", "tag",
                 "count", "dtype", "key", "file", "line", "idx")

    def __init__(self, kind, rank, op, **kw):
        # "send" | "recv" | "coll" (rendezvous) | "start" | "pwait" | "local"
        self.kind = kind
        self.rank = rank
        self.op = op
        for name in self.__slots__[3:]:
            setattr(self, name, kw.get(name))

    def describe(self) -> str:
        if self.kind == "send":
            return f"{self.op}(dst=rank {self.dst}, tag={self.tag})"
        if self.kind == "recv":
            src = "ANY_SOURCE" if self.want is None else f"rank {self.want}"
            tag = "ANY_TAG" if self.wtag is None else self.wtag
            return f"{self.op}(src={src}, tag={tag})"
        if self.kind in ("coll", "start", "pwait"):
            return f"{self.op} on comm {self.cid}"
        return f"{self.op}"


def _build_programs(tr) -> Tuple[Dict[int, List[_Tx]], Dict[Any, frozenset]]:
    """(rank -> transition list, rendezvous key -> observed participants)."""
    progs: Dict[int, List[_Tx]] = {}
    ft_ord: Dict[tuple, int] = defaultdict(int)
    for rank in sorted(r for r in tr.rings if r >= 0):
        prog: List[_Tx] = []
        for ev in tr.events(rank):
            tx = None
            if ev.kind == "send":
                tx = _Tx("send", rank, ev.op, cid=ev.cid, dst=ev.peer,
                         tag=ev.tag, count=ev.count, dtype=ev.dtype,
                         file=ev.file, line=ev.line)
            elif ev.kind == "recv":
                tx = _Tx("recv", rank, ev.op, cid=ev.cid, want=ev.want,
                         wtag=ev.wtag, file=ev.file, line=ev.line)
            elif ev.kind == "coll":
                if ev.handle is not None:
                    continue    # persistent round: modeled by start/wait
                key = ("coll", ev.cid, ev.grp, ev.seq)
                tx = _Tx("coll", rank, ev.op, cid=ev.cid, key=key,
                         file=ev.file, line=ev.line)
            elif ev.kind == "start":
                key = ("round", ev.cid, ev.op, ev.round)
                tx = _Tx("start", rank, ev.op, cid=ev.cid, key=key,
                         file=ev.file, line=ev.line)
            elif ev.kind == "wait":
                key = ("round", ev.cid, ev.op, ev.round)
                tx = _Tx("pwait", rank, f"Wait[{ev.op}]", cid=ev.cid,
                         key=key, file=ev.file, line=ev.line)
            elif ev.kind == "ft":
                if ev.op == "Comm_revoke":
                    tx = _Tx("local", rank, ev.op, cid=ev.cid,
                             file=ev.file, line=ev.line)
                else:
                    k = (rank, ev.cid, ev.op)
                    key = ("ft", ev.cid, ev.op, ft_ord[k])
                    ft_ord[k] += 1
                    tx = _Tx("coll", rank, ev.op, cid=ev.cid, key=key,
                             file=ev.file, line=ev.line)
            # "rma"/"sync"/"serve" events carry no matching nondeterminism
            if tx is not None:
                tx.idx = len(prog)
                prog.append(tx)
        progs[rank] = prog
    participants: Dict[Any, set] = defaultdict(set)
    for rank, prog in progs.items():
        for tx in prog:
            if tx.kind in ("coll", "start", "pwait"):
                participants[tx.key].add(rank)
    parts = {k: frozenset(v) for k, v in participants.items()}
    # a rendezvous only one rank ever recorded synchronizes nothing
    for prog in progs.values():
        for tx in prog:
            if tx.kind == "coll" and len(parts[tx.key]) < 2:
                tx.kind = "local"
    return progs, parts


# ---------------------------------------------------------------------------
# Abstract machine state
# ---------------------------------------------------------------------------


class _State:
    __slots__ = ("pcs", "chans", "started", "hist")

    def __init__(self, pcs, chans, started, hist):
        self.pcs: Dict[int, int] = pcs
        # (cid, dst rank) -> {src rank: [ (tag, count, dtype), ... ] FIFO}
        self.chans: Dict[tuple, Dict[int, list]] = chans
        self.started: set = started      # rendezvous round keys begun, per rank
        self.hist: List[tuple] = hist    # (rank, description, file, line)

    def clone(self) -> "_State":
        chans = {k: {s: list(q) for s, q in by_src.items()}
                 for k, by_src in self.chans.items()}
        return _State(dict(self.pcs), chans, set(self.started),
                      list(self.hist))

    def fingerprint(self) -> tuple:
        chans = tuple(sorted(
            (k, tuple(sorted((s, tuple(q)) for s, q in by_src.items() if q)))
            for k, by_src in self.chans.items()
            if any(by_src.values())))
        return (tuple(sorted(self.pcs.items())), chans,
                frozenset(self.started))


def _tag_match(wtag, tag) -> bool:
    return wtag is None or wtag == tag


def _candidates(st: _State, tx: _Tx) -> List[tuple]:
    """(src, tag, count, dtype) matches for a receive — per source, the
    first tag-match in that sender's FIFO (MPI non-overtaking)."""
    by_src = st.chans.get((tx.cid, tx.rank), {})
    srcs = [tx.want] if tx.want is not None else sorted(by_src)
    out = []
    for src in srcs:
        for msg in by_src.get(src, ()):
            if _tag_match(tx.wtag, msg[0]):
                out.append((src,) + msg)
                break
    return out


class _Machine:
    def __init__(self, progs, parts):
        self.progs = progs
        self.parts = parts

    def cur(self, st: _State, rank: int) -> Optional[_Tx]:
        pc = st.pcs[rank]
        prog = self.progs[rank]
        return prog[pc] if pc < len(prog) else None

    def coll_ready(self, st: _State, tx: _Tx) -> bool:
        for r in self.parts[tx.key]:
            other = self.cur(st, r)
            if other is None or other.kind != "coll" or other.key != tx.key:
                return False
        return True

    def wait_ready(self, st: _State, tx: _Tx) -> bool:
        return all((tx.key, r) in st.started for r in self.parts[tx.key])

    def step(self, st: _State, tx: _Tx, chosen: Optional[tuple] = None):
        """Execute ``tx`` (with ``chosen`` = the (src, tag, count, dtype)
        match for a receive), mutating ``st``."""
        rank = tx.rank
        if tx.kind == "coll":
            for r in self.parts[tx.key]:
                st.pcs[r] += 1
            st.hist.append((rank, f"{tx.describe()} "
                                  f"[ranks {sorted(self.parts[tx.key])}]",
                            tx.file, tx.line))
            return
        if tx.kind == "send":
            by_src = st.chans.setdefault((tx.cid, tx.dst), {})
            by_src.setdefault(rank, []).append((tx.tag, tx.count, tx.dtype))
        elif tx.kind == "recv":
            src, tag = chosen[0], chosen[1]
            q = st.chans[(tx.cid, rank)][src]
            for i, msg in enumerate(q):
                if _tag_match(tx.wtag, msg[0]) and msg[0] == tag:
                    del q[i]
                    break
        elif tx.kind == "start":
            st.started.add((tx.key, rank))
        st.pcs[rank] += 1
        detail = tx.describe()
        if tx.kind == "recv" and chosen is not None:
            detail += f" <- matched rank {chosen[0]}, tag {chosen[1]}"
        st.hist.append((rank, detail, tx.file, tx.line))


# ---------------------------------------------------------------------------
# The exploration driver
# ---------------------------------------------------------------------------


class ExploreResult:
    """Outcome of one :func:`explore` run."""

    def __init__(self):
        self.schedules = 0          # maximal schedules reached
        self.deadlocks = 0
        self.states = 0             # quiescent states expanded
        self.truncated = False
        self.diagnostics: List[Diagnostic] = []
        self.ranks: List[int] = []
        self.transitions = 0

    def __repr__(self):
        return (f"<ExploreResult schedules={self.schedules} "
                f"deadlocks={self.deadlocks} states={self.states} "
                f"diagnostics={len(self.diagnostics)}"
                f"{' TRUNCATED' if self.truncated else ''}>")


def _schedule_listing(m: _Machine, st: _State, tail: int = 12) -> str:
    """The deadlocking schedule as a per-rank event listing."""
    lines = []
    for rank in sorted(m.progs):
        mine = [d for r, d, _f, _l in st.hist if r == rank]
        shown = mine[-tail:]
        pre = f"  rank {rank}: "
        body = " ; ".join(shown) if shown else "(no executed events)"
        if len(mine) > len(shown):
            body = f"... {body}"
        tx = m.cur(st, rank)
        if tx is not None:
            body += f" ; BLOCKED at {tx.describe()} ({tx.file}:{tx.line})"
        else:
            body += " ; (finished)"
        lines.append(pre + body)
    return "\n".join(lines)


def explore(obj: Any = None, max_schedules: int = 1000,
            max_states: int = 100000) -> ExploreResult:
    """Enumerate alternate schedules of the traced run ``obj`` (a Tracer, a
    context, a trace-dump path/prefix, or None for the most recent traced
    run) and verify each one. Returns an :class:`ExploreResult` whose
    ``diagnostics`` carry T210/T211/T212 findings."""
    from .matcher import _tracer_of
    if isinstance(obj, str):
        from .events import load_trace
        tr = load_trace(obj)
    else:
        tr = _tracer_of(obj)
    res = ExploreResult()
    if tr is None:
        return res
    progs, parts = _build_programs(tr)
    res.ranks = sorted(progs)
    res.transitions = sum(len(p) for p in progs.values())
    if not progs:
        return res
    m = _Machine(progs, parts)
    init = _State({r: 0 for r in progs}, {}, set(), [])
    stack: List[_State] = [init]
    visited: set = set()
    # (rank, recv index) -> delivered payload signatures across schedules
    recv_sigs: Dict[tuple, set] = defaultdict(set)
    recv_site: Dict[tuple, tuple] = {}
    deadlock_keys: set = set()
    orphan_keys: set = set()

    def eager_step(st: _State) -> bool:
        for rank in sorted(progs):
            tx = m.cur(st, rank)
            if tx is None:
                continue
            if tx.kind in ("local", "send", "start"):
                m.step(st, tx)
                return True
            if tx.kind == "pwait" and m.wait_ready(st, tx):
                m.step(st, tx)
                return True
            if tx.kind == "coll" and m.coll_ready(st, tx):
                m.step(st, tx)
                return True
            if tx.kind == "recv" and tx.want is not None:
                cands = _candidates(st, tx)
                if cands:
                    recv_sigs[(rank, tx.idx)].add(cands[0][1:])
                    recv_site[(rank, tx.idx)] = (tx.file, tx.line, tx.op)
                    m.step(st, tx, cands[0])
                    return True
        return False

    while stack:
        if res.schedules >= max_schedules or res.states >= max_states:
            res.truncated = True
            break
        st = stack.pop()
        while eager_step(st):
            pass
        fp = st.fingerprint()
        if fp in visited:
            # sleep-set hit: a distinct interleaving that converged with an
            # already-expanded state — count the schedule, skip the re-walk
            res.schedules += 1
            continue
        visited.add(fp)
        res.states += 1
        done = all(m.cur(st, r) is None for r in progs)
        if done:
            res.schedules += 1
            for (cid, dst), by_src in st.chans.items():
                for src, q in by_src.items():
                    for tag, count, dtype in q:
                        key = (cid, src, dst, tag)
                        if key in orphan_keys:
                            continue
                        orphan_keys.add(key)
                        res.diagnostics.append(Diagnostic(
                            "T211",
                            f"an explored schedule terminates with the "
                            f"message rank {src} -> rank {dst} (tag={tag}, "
                            f"comm {cid}) still in flight — no receive "
                            f"consumes it on that schedule",
                            rank=src,
                            context=f"{res.schedules} schedule(s) explored "
                                    f"so far"))
            continue
        branches: List[tuple] = []
        for rank in sorted(progs):
            tx = m.cur(st, rank)
            if tx is not None and tx.kind == "recv" and tx.want is None:
                for cand in _candidates(st, tx):
                    branches.append((tx, cand))
        if not branches:
            res.schedules += 1
            res.deadlocks += 1
            key = tuple(sorted(st.pcs.items()))
            if key in deadlock_keys:
                continue
            deadlock_keys.add(key)
            pend = [(r, m.cur(st, r)) for r in sorted(progs)
                    if m.cur(st, r) is not None]
            anchor = pend[0][1]
            res.diagnostics.append(Diagnostic(
                "T210",
                f"an alternate schedule deadlocks: rank(s) "
                f"{[r for r, _ in pend]} block with no enabled transition "
                f"(the recorded run chose a different wildcard matching). "
                f"Schedule:\n{_schedule_listing(m, st)}",
                file=anchor.file, line=anchor.line, rank=anchor.rank,
                context="per-rank listing shows the executed prefix and "
                        "each blocked operation",
                related=tuple((tx.file, tx.line,
                               f"rank {r} blocked in {tx.describe()}")
                              for r, tx in pend)))
            continue
        for tx, cand in branches:
            nxt = st.clone()
            recv_sigs[(tx.rank, tx.idx)].add(cand[1:])
            recv_site[(tx.rank, tx.idx)] = (tx.file, tx.line, tx.op)
            m.step(nxt, tx, cand)
            stack.append(nxt)

    for (rank, idx), sigs in sorted(recv_sigs.items()):
        if len(sigs) > 1:
            f, ln, op = recv_site[(rank, idx)]
            res.diagnostics.append(Diagnostic(
                "T212",
                f"wildcard {op} on rank {rank} observes schedule-dependent "
                f"payloads: {sorted(sigs)} (tag, count, dtype) depending on "
                f"which message the matching picks",
                file=f, line=ln, rank=rank,
                context="the received VALUE depends on the schedule, not "
                        "just the source"))
    res.diagnostics.sort(key=lambda d: (d.code, d.file, d.line))
    return res


# ---------------------------------------------------------------------------
# CLI driver (python -m tpu_mpi.analyze explore <trace prefix or files>)
# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m tpu_mpi.analyze explore",
        description="Enumerate and verify alternate schedules of a recorded "
                    "trace (record one with TPU_MPI_TRACE=1 "
                    "TPU_MPI_TRACE_DUMP=<prefix>).")
    p.add_argument("trace", nargs="+",
                   help="trace-dump file(s) or the prefix passed to "
                        "TPU_MPI_TRACE_DUMP")
    p.add_argument("--max-schedules", type=int, default=1000)
    p.add_argument("--max-states", type=int, default=100000)
    args = p.parse_args(argv)
    from .events import load_trace
    tr = load_trace(args.trace if len(args.trace) > 1 else args.trace[0])
    res = explore(tr, max_schedules=args.max_schedules,
                  max_states=args.max_states)
    print(f"explored {res.schedules} schedule(s) over ranks {res.ranks} "
          f"({res.transitions} transitions, {res.states} states"
          f"{', TRUNCATED by budget' if res.truncated else ''})")
    for d in res.diagnostics:
        print(d)
    if res.diagnostics:
        print(f"{len(res.diagnostics)} finding(s)")
        return 1
    print("no schedule-dependent defects found")
    return 0
