"""Per-rank communication event recording (the trace verifier's front end).

When tracing is on (config knob ``trace`` / env ``TPU_MPI_TRACE``), the hot
paths in ``comm``/``collective``/``pointtopoint``/``onesided`` call the
``record_*`` hooks below, which append :class:`Event` records into per-rank
ring buffers on one :class:`Tracer` attached to the :class:`SpmdContext`.
The rings are consumed by :func:`tpu_mpi.analyze.matcher.verify_trace` (cross-
rank order/signature checks + send/recv pairing), by
:func:`tpu_mpi.analyze.races.detect_races` (vector-clock happens-before over
window epochs), and by the DeadlockError dump
(:func:`tpu_mpi.analyze.matcher.deadlock_report`).

Overhead discipline: every hook front-loads :func:`enabled` — one tuple
compare against ``config.GENERATION`` — so an untraced run pays a single
predictable branch per operation. All heavier imports (numpy, config) stay
inside the traced branch.

Vector clocks are plain ``{origin_rank: counter}`` dicts rather than fixed
arrays so a world grown by ``Comm_spawn`` keeps working without resizing.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import zlib

from .. import config
from collections import deque
from typing import Any, Dict, Optional, Tuple

# first source directory outside this package wins as the "call site"
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_STDLIB_DIR = os.path.dirname(threading.__file__)

_mod_lock = threading.Lock()

# the most recently created Tracer: testing.run_spmd tears the ctx down
# before returning, so post-run verification reaches the trace through here.
last_tracer: Optional["Tracer"] = None


def last_trace() -> Optional["Tracer"]:
    """The Tracer of the most recent traced run (or None)."""
    return last_tracer


# Sentinel initial generation: None can never equal config.GENERATION (an
# int), so the very first call populates the cache and every later call is
# one tuple compare — including at GENERATION == 0, where the old `gen != 0`
# guard forced a config.load() per call until the first refresh bump.
_UNSET = object()
_enabled_cache: Tuple[Any, bool] = (_UNSET, False)


def enabled() -> bool:
    """Whether event tracing is on — cached on ``config.GENERATION`` so the
    per-operation cost of an untraced run is one tuple compare."""
    global _enabled_cache
    cached_gen, val = _enabled_cache
    if cached_gen == config.GENERATION:
        return val
    val = bool(config.load().trace)
    _enabled_cache = (config.GENERATION, val)
    return val


def call_site(skip: int = 2) -> Tuple[str, int]:
    """(file, line) of the first frame outside tpu_mpi — the user's call.

    Returns ``("<unknown>", 0)`` when every frame is internal (e.g. the
    nonblocking-collective worker threads, whose stacks bottom out in
    ``threading``)."""
    try:
        f = sys._getframe(skip)
    except ValueError:
        return ("<unknown>", 0)
    depth = 0
    while f is not None and depth < 50:
        fn = f.f_code.co_filename
        if not fn.startswith(_PKG_DIR):
            if fn.startswith(_STDLIB_DIR) or fn.startswith("<"):
                return ("<unknown>", 0)
            return (fn, f.f_lineno)
        f = f.f_back
        depth += 1
    return ("<unknown>", 0)


class Event:
    """One recorded communication operation (the shared IR of all passes)."""

    __slots__ = ("kind", "rank", "op", "cid", "seq", "peer", "root", "tag",
                 "count", "dtype", "win", "lo", "hi", "vc", "origin", "grp",
                 "algo", "file", "line", "t",
                 # pvar span fields (perfvars.op_end stamps them): wall-clock
                 # bracket of the whole op plus the phase spans the channels
                 # observed inside it, as (name, t0, t1) monotonic tuples —
                 # analyze.timeline renders these as nested Perfetto slices.
                 "t_start", "t_end", "phases",
                 # schedule-exploration fields: the POSTED source/tag of a
                 # receive (None = wildcard; `peer`/`tag` hold the delivered
                 # values), the persistent-request handle + round a
                 # start/wait/coll event belongs to, the identity of the
                 # user buffer an op read (R302), and a grab-bag dict for
                 # ft/serve records (epoch, survivors, books, ...).
                 "want", "wtag", "handle", "round", "bufid", "extra")

    def __init__(self, kind: str, rank: int, **kw: Any):
        # "coll" | "send" | "recv" | "rma" | "sync" | "start" | "wait"
        # | "ft" | "serve" | "elastic"
        self.kind = kind
        self.rank = rank          # world rank of the recording rank
        for name in self.__slots__[2:]:
            setattr(self, name, kw.get(name))
        if self.t is None:
            self.t = time.monotonic()

    def describe(self) -> str:
        """Human-readable one-liner (used by the deadlock dump)."""
        if self.kind == "coll":
            return f"{self.op} on comm {self.cid}"
        if self.kind in ("send", "recv"):
            peer = "ANY_SOURCE" if self.peer is None else self.peer
            return (f"{self.op}(peer=world rank {peer}, tag={self.tag}) "
                    f"on comm {self.cid}")
        if self.kind == "rma":
            return (f"{self.op}(target=world rank {self.peer}, "
                    f"range=[{self.lo}, {self.hi}))")
        if self.kind in ("start", "wait"):
            return f"{self.op} [{self.kind} round {self.round}] on comm {self.cid}"
        if self.kind in ("ft", "elastic"):
            return f"{self.op} on comm {self.cid} ({self.extra})"
        return f"{self.op}"

    def __repr__(self) -> str:
        return (f"<Event {self.kind} r{self.rank} {self.describe()} "
                f"seq={self.seq} at {self.file}:{self.line}>")


class Tracer:
    """Per-context event store: one ring buffer per world rank, plus the
    cross-rank synchronization state the RMA vector-clock pass needs."""

    def __init__(self, nprocs: int, cap: int):
        self.nprocs = nprocs
        self.cap = max(16, int(cap))
        self.lock = threading.RLock()
        self.rings: Dict[int, deque] = {}          # rank -> deque[Event]
        # absolute per-(rank, kind, cid) ordinals: matcher alignment stays
        # correct even after the ring evicted early events.
        self.counts: Dict[tuple, int] = {}
        self.dropped: Dict[int, int] = {}          # rank -> evicted events
        self.blocked: Dict[int, Event] = {}        # rank -> current block
        self.diagnostics: list = []                # online findings (T206)
        # RMA pass state — rma_events is global-ordered (append order is the
        # real interleaving on the thread tier) and larger than the rings:
        # races need the full epoch, not a window.
        self.rma_events: deque = deque(maxlen=65536)
        self._vc: Dict[int, Dict[int, int]] = {}   # rank -> vector clock
        self._fence_round: Dict[tuple, int] = {}   # (rank, win) -> round no.
        self._fence_acc: Dict[tuple, dict] = {}    # (win, round) -> joined vc
        self._excl_release: Dict[tuple, dict] = {}  # (win, target) -> vc
        self._shared_accum: Dict[tuple, dict] = {}  # (win, target) -> vc

    def record(self, ev: Event) -> Event:
        with self.lock:
            ring = self.rings.get(ev.rank)
            if ring is None:
                ring = self.rings[ev.rank] = deque(maxlen=self.cap)
            key = (ev.rank, ev.kind, ev.cid)
            ev.seq = self.counts.get(key, 0)
            self.counts[key] = ev.seq + 1
            if len(ring) == ring.maxlen:
                self.dropped[ev.rank] = self.dropped.get(ev.rank, 0) + 1
            ring.append(ev)
        return ev

    def events(self, rank: Optional[int] = None):
        """Snapshot of recorded events (one rank, or all ranks merged)."""
        with self.lock:
            if rank is not None:
                return list(self.rings.get(rank, ()))
            out = []
            for r in sorted(self.rings):
                out.extend(self.rings[r])
            return out


def tracer_for(ctx: Any, create: bool = False) -> Optional[Tracer]:
    """The context's Tracer, lazily attached on first recorded event."""
    tr = getattr(ctx, "_tracer", None)
    if tr is None and create:
        global last_tracer
        with _mod_lock:
            tr = getattr(ctx, "_tracer", None)
            if tr is None:
                cfg = config.load()
                tr = Tracer(getattr(ctx, "size", 0), cfg.trace_buffer)
                ctx._tracer = tr
            last_tracer = tr
    return tr


def _env() -> Optional[tuple]:
    from .._runtime import current_env
    return current_env()


# ---------------------------------------------------------------------------
# Recording hooks (called from comm/collective/pointtopoint/onesided)
# ---------------------------------------------------------------------------

# persistent-request round tagging: while a traced persistent round runs its
# legacy collective lane, the inner record_collective event is stamped with
# the owning handle so analyze.explore models the round's timing from the
# start/wait pair instead of double-counting the inner event.
_tls = threading.local()


class persistent_scope:
    """Context manager marking collective events recorded inside it as
    belonging to one persistent handle's round."""

    def __init__(self, handle: int, rnd: int):
        self._tag = (handle, rnd)

    def __enter__(self):
        _tls.phandle = self._tag
        return self

    def __exit__(self, *exc):
        _tls.phandle = None
        return False


def record_collective(comm: Any, opname: str,
                      sig: Optional[dict] = None) -> Optional[Event]:
    """One collective entry on this rank; ``sig`` carries the cross-rank-
    checkable signature fields (root/dtype/count, plus per-peer
    scounts/rcounts for the ``*v`` family) when the caller knows them
    precisely (reductions, Bcast, Alltoallv)."""
    env = _env()
    if env is None:
        return None
    ctx, wrank = env
    tr = tracer_for(ctx, create=True)
    sig = sig or {}
    f, ln = call_site()
    ptag = getattr(_tls, "phandle", None)
    extra = {k: list(sig[k]) for k in ("scounts", "rcounts") if k in sig}
    ev = Event("coll", wrank, op=str(opname), cid=comm.cid,
               grp=tuple(comm.group), root=sig.get("root"),
               dtype=sig.get("dtype"), count=sig.get("count"),
               algo=sig.get("algo"), bufid=sig.get("bufid"),
               handle=ptag[0] if ptag else None,
               round=ptag[1] if ptag else None,
               file=f, line=ln, extra=extra or None)
    return tr.record(ev)


def record_send(comm: Any, dest: int, tag: Any, count: Any, dtype: Any,
                op: str = "Send", buf: Any = None) -> Optional[Event]:
    env = _env()
    if env is None:
        return None
    ctx, wrank = env
    try:
        peer = comm.world_rank_of(int(dest))
    except Exception:
        return None
    tr = tracer_for(ctx, create=True)
    f, ln = call_site()
    ev = Event("send", wrank, op=op, cid=comm.cid, peer=peer,
               tag=tag if isinstance(tag, tuple) else int(tag),
               count=count, dtype=str(dtype) if dtype is not None else None,
               bufid=buf_id(buf), file=f, line=ln)
    return tr.record(ev)


_POSTED_UNKNOWN = object()


def record_recv(comm: Any, msg: Any, op: str = "Recv",
                want: Any = _POSTED_UNKNOWN,
                wtag: Any = _POSTED_UNKNOWN) -> Optional[Event]:
    """One completed receive; ``msg`` is the delivered runtime Message
    (``msg.src`` is the sender's comm rank). ``want``/``wtag`` are the
    POSTED source/tag — ``None`` meaning ANY_SOURCE/ANY_TAG — which the
    schedule explorer re-enumerates; callers that don't know them (old
    call sites) leave the defaults and the posted values degrade to the
    delivered ones."""
    env = _env()
    if env is None:
        return None
    ctx, wrank = env
    try:
        peer = comm.world_rank_of(int(msg.src))
    except Exception:
        peer = None
    if want is _POSTED_UNKNOWN:
        posted_src = peer
    elif want is None:
        posted_src = None
    else:
        try:
            posted_src = comm.world_rank_of(int(want))
        except Exception:
            posted_src = peer
    posted_tag = msg.tag if wtag is _POSTED_UNKNOWN else wtag
    tr = tracer_for(ctx, create=True)
    f, ln = call_site()
    ev = Event("recv", wrank, op=op, cid=comm.cid, peer=peer, tag=msg.tag,
               count=msg.count, want=posted_src, wtag=posted_tag,
               file=f, line=ln)
    return tr.record(ev)


# ---------------------------------------------------------------------------
# Blocked-operation tracking (feeds the DeadlockError dump)
# ---------------------------------------------------------------------------

def blocked_event(comm: Any, kind: str, op: str, peer: Optional[int] = None,
                  tag: Any = None) -> Optional[Event]:
    """An Event describing an operation about to block — NOT recorded into
    the ring (it has not completed); pass to :func:`set_blocked`."""
    env = _env()
    if env is None:
        return None
    _, wrank = env
    world_peer = None
    if peer is not None:
        try:
            world_peer = comm.world_rank_of(int(peer))
        except Exception:
            world_peer = None
    f, ln = call_site()
    return Event(kind, wrank, op=op, cid=getattr(comm, "cid", None),
                 grp=tuple(getattr(comm, "group", ())) or None,
                 peer=world_peer, tag=tag, file=f, line=ln)


def set_blocked(ctx: Any, ev: Optional[Event]) -> None:
    if ev is None:
        return
    tr = tracer_for(ctx, create=True)
    with tr.lock:
        tr.blocked[ev.rank] = ev


def clear_blocked(ctx: Any, ev: Optional[Event]) -> None:
    if ev is None:
        return
    tr = tracer_for(ctx)
    if tr is None:
        return
    with tr.lock:
        if tr.blocked.get(ev.rank) is ev:
            del tr.blocked[ev.rank]


# ---------------------------------------------------------------------------
# Isend buffer-reuse check (T206)
# ---------------------------------------------------------------------------

def _buf_crc(buf: Any) -> Optional[int]:
    try:
        import numpy as np
        arr = np.ascontiguousarray(np.asarray(buf))
        return zlib.crc32(arr.tobytes())
    except Exception:
        return None


def note_isend(req: Any, comm: Any, buf: Any) -> None:
    """Checksum an Isend's user buffer at post time; :func:`check_isend`
    re-checksums at Wait and reports T206 on mutation."""
    crc = _buf_crc(buf)
    if crc is None:
        return
    try:
        req._trace_isend = (call_site(), crc, buf)
        req._trace_comm = comm
    except Exception:
        pass


def check_isend(ctx: Any, req: Any) -> None:
    noted = getattr(req, "_trace_isend", None)
    if noted is None:
        return
    req._trace_isend = None
    (f, ln), crc, buf = noted
    now = _buf_crc(buf)
    if now is None or now == crc:
        return
    tr = tracer_for(ctx, create=True)
    from .diagnostics import Diagnostic
    env = _env()
    with tr.lock:
        tr.diagnostics.append(Diagnostic(
            "T206", "Isend buffer was modified before its Wait completed",
            file=f, line=ln, rank=env[1] if env else None,
            context="checksum at post != checksum at Wait"))


# ---------------------------------------------------------------------------
# RMA: vector-clock bookkeeping over window epochs (R301 front end)
# ---------------------------------------------------------------------------

def _win_key(win: Any) -> int:
    # _WinState is the one object all ranks of the window share on the
    # thread tier, so its id names the window across ranks.
    return id(getattr(win, "_state", win))


def _join_into(dst: Dict[int, int], src: Optional[Dict[int, int]]) -> None:
    if not src:
        return
    for k, v in src.items():
        if dst.get(k, 0) < v:
            dst[k] = v


def rma_access(win: Any, kind: str, target_world: int, lo: int,
               hi: int) -> None:
    """One origin-side Put/Get/Accumulate touching ``[lo, hi)`` elements of
    ``target_world``'s window — stamped with the origin's vector clock."""
    env = _env()
    if env is None:
        return
    ctx, wrank = env
    tr = tracer_for(ctx, create=True)
    f, ln = call_site()
    with tr.lock:
        vc = tr._vc.setdefault(wrank, {})
        vc[wrank] = vc.get(wrank, 0) + 1
        ev = Event("rma", wrank, op=kind, win=_win_key(win),
                   peer=int(target_world), lo=int(lo), hi=int(hi),
                   vc=dict(vc), origin=wrank, file=f, line=ln)
        tr.rma_events.append(ev)
        tr.record(ev)    # also in the per-rank ring (deadlock-dump context)


def fence_begin(win: Any) -> None:
    """Entering Win_fence: contribute this rank's clock to the fence's
    accumulator. Sound because the fence's barrier orders every begin
    before any end."""
    env = _env()
    if env is None:
        return
    ctx, wrank = env
    tr = tracer_for(ctx, create=True)
    wk = _win_key(win)
    with tr.lock:
        rnd = tr._fence_round.get((wrank, wk), 0)
        acc = tr._fence_acc.setdefault((wk, rnd), {})
        _join_into(acc, tr._vc.setdefault(wrank, {}))


def fence_end(win: Any) -> None:
    """Leaving Win_fence: join the accumulated clock of ALL ranks' pre-fence
    work into this rank's clock; later accesses happen-after every access of
    the previous epoch, on every rank."""
    env = _env()
    if env is None:
        return
    ctx, wrank = env
    tr = tracer_for(ctx, create=True)
    wk = _win_key(win)
    with tr.lock:
        rnd = tr._fence_round.get((wrank, wk), 0)
        _join_into(tr._vc.setdefault(wrank, {}), tr._fence_acc.get((wk, rnd)))
        tr._fence_round[(wrank, wk)] = rnd + 1


def lock_acquired(win: Any, target_world: int, excl: bool) -> None:
    """After a Win_lock acquires: an exclusive lock happens-after every prior
    release of this (window, target); a shared lock happens-after prior
    EXCLUSIVE releases only — concurrent shared holders stay concurrent, so
    racing accesses under shared locks are still flagged."""
    env = _env()
    if env is None:
        return
    ctx, wrank = env
    tr = tracer_for(ctx, create=True)
    key = (_win_key(win), int(target_world))
    with tr.lock:
        vc = tr._vc.setdefault(wrank, {})
        _join_into(vc, tr._excl_release.get(key))
        if excl:
            _join_into(vc, tr._shared_accum.get(key))
        vc[wrank] = vc.get(wrank, 0) + 1


def lock_released(win: Any, target_world: int, excl: bool) -> None:
    """Before Win_unlock releases: publish this rank's clock to later
    acquirers of the same (window, target)."""
    env = _env()
    if env is None:
        return
    ctx, wrank = env
    tr = tracer_for(ctx, create=True)
    key = (_win_key(win), int(target_world))
    with tr.lock:
        vc = tr._vc.setdefault(wrank, {})
        vc[wrank] = vc.get(wrank, 0) + 1
        if excl:
            tr._excl_release[key] = dict(vc)
        else:
            _join_into(tr._shared_accum.setdefault(key, {}), vc)


def record_sync(win: Any, op: str) -> None:
    """A window synchronization call (fence/flush/lock) as a ring event —
    context for dumps; no happens-before effect of its own."""
    env = _env()
    if env is None:
        return
    ctx, wrank = env
    tr = tracer_for(ctx, create=True)
    f, ln = call_site()
    tr.record(Event("sync", wrank, op=op, win=_win_key(win), file=f, line=ln))


# ---------------------------------------------------------------------------
# Persistent-request records (Start/Wait reordering + R302 front end)
# ---------------------------------------------------------------------------

def buf_id(buf: Any) -> Optional[int]:
    """Stable identity of the array object backing ``buf`` (R302 keys the
    donated-result invalidation window on it)."""
    if buf is None:
        return None
    try:
        from ..buffers import extract_array
        return id(extract_array(buf))
    except Exception:
        return id(buf)


def record_start(comm: Any, op: str, handle: int, rnd: int,
                 invalidates: Optional[int] = None) -> Optional[Event]:
    """A persistent request's Start on this rank. ``invalidates`` names the
    buffer id whose donated-fast-path slot this Start re-donates (the round
    ``rnd - 2`` result) — R302's invalidation edge."""
    env = _env()
    if env is None:
        return None
    ctx, wrank = env
    tr = tracer_for(ctx, create=True)
    f, ln = call_site()
    ev = Event("start", wrank, op=op, cid=comm.cid, grp=tuple(comm.group),
               handle=handle, round=rnd, bufid=invalidates, file=f, line=ln)
    return tr.record(ev)


def record_wait(comm: Any, op: str, handle: int, rnd: int,
                result: Any = None) -> Optional[Event]:
    """A persistent request's Wait completing round ``rnd``; ``result`` is
    the object handed back to the user (identity tracked for R302)."""
    env = _env()
    if env is None:
        return None
    ctx, wrank = env
    tr = tracer_for(ctx, create=True)
    f, ln = call_site()
    ev = Event("wait", wrank, op=op, cid=comm.cid, grp=tuple(comm.group),
               handle=handle, round=rnd, bufid=buf_id(result),
               file=f, line=ln)
    return tr.record(ev)


# ---------------------------------------------------------------------------
# Fault-tolerance protocol records (T207 front end)
# ---------------------------------------------------------------------------

def record_ft(comm: Any, op: str, epoch: Optional[int] = None,
              survivors: Any = None, dead: Any = None,
              value: Any = None) -> Optional[Event]:
    """One ULFM protocol step (revoke/agree/shrink) with the cross-rank-
    comparable outcome: the agreement epoch, the agreed flag value, and —
    for shrink — the survivor set every rank must derive identically."""
    env = _env()
    if env is None:
        return None
    ctx, wrank = env
    tr = tracer_for(ctx, create=True)
    f, ln = call_site()
    extra = {"epoch": epoch}
    if survivors is not None:
        extra["survivors"] = tuple(sorted(survivors))
    if dead is not None:
        extra["dead"] = tuple(sorted(dead))
    if value is not None:
        extra["value"] = value
    ev = Event("ft", wrank, op=op, cid=comm.cid, grp=tuple(comm.group),
               extra=extra, file=f, line=ln)
    return tr.record(ev)


# ---------------------------------------------------------------------------
# Elastic-rebind protocol records (T214 front end)
# ---------------------------------------------------------------------------

def record_elastic(comm: Any, op: str, epoch: Optional[int] = None,
                   declared: Any = None) -> Optional[Event]:
    """One elastic rebind step (``quiesce``/``resume``) as seen from a rank
    thread. ``declared`` is the set of ranks the protocol *intends* to
    rendezvous (normally the comm's group): the T214 check holds every
    declared rank that appears in the trace to having recorded this round.
    The barrier itself is a real traced collective — this event only carries
    the protocol metadata (the explorer models the barrier, not this)."""
    env = _env()
    if env is None:
        return None
    ctx, wrank = env
    tr = tracer_for(ctx, create=True)
    f, ln = call_site()
    extra = {"epoch": epoch,
             "declared": tuple(sorted(declared if declared is not None
                                      else comm.group))}
    ev = Event("elastic", wrank, op=op, cid=comm.cid, grp=tuple(comm.group),
               extra=extra, file=f, line=ln)
    return tr.record(ev)


# ---------------------------------------------------------------------------
# Serve-tier records (T208 front end + dispatcher-interleaving context).
# The broker's handler/dispatcher threads run without a rank env, so these
# take the pool ctx explicitly and record under the synthetic rank -1.
# ---------------------------------------------------------------------------

BROKER_RANK = -1


def record_serve(ctx: Any, op: str, **extra: Any) -> Optional[Event]:
    """One broker-side event (lease grant/revoke, op dispatch, ledger
    flush) in the pool context's trace, under the synthetic BROKER_RANK."""
    if not enabled() or ctx is None:
        return None
    tr = tracer_for(ctx, create=True)
    f, ln = call_site()
    ev = Event("serve", BROKER_RANK, op=op, cid=extra.pop("cid", None),
               extra=extra or None, file=f, line=ln)
    return tr.record(ev)


# ---------------------------------------------------------------------------
# Trace persistence: one JSON file per rank (the multi-process tier has one
# Tracer per process), merged back by load_trace for offline exploration.
# ---------------------------------------------------------------------------

_DUMP_FIELDS = ("kind", "rank", "op", "cid", "seq", "peer", "root", "tag",
                "count", "dtype", "grp", "algo", "file", "line", "t",
                "want", "wtag", "handle", "round", "bufid", "extra")


def _jsonable(v: Any) -> Any:
    if isinstance(v, tuple):
        return list(v)
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return repr(v)


def dump_trace(tr: "Tracer", path: str, rank: Optional[int] = None) -> str:
    """Write ``tr``'s events (one rank, or every ring this process holds)
    as JSON to ``path``. Returns the path written."""
    import json
    with tr.lock:
        ranks = [rank] if rank is not None else sorted(tr.rings)
        recs = []
        for r in ranks:
            for ev in tr.rings.get(r, ()):
                recs.append({k: _jsonable(getattr(ev, k, None))
                             for k in _DUMP_FIELDS})
        payload = {
            "version": 1,
            "nprocs": tr.nprocs,
            "dropped": {str(k): v for k, v in tr.dropped.items()},
            "events": recs,
        }
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


def finalize_dump() -> None:
    """Called from MPI.Finalize: when ``trace_dump`` names a path prefix,
    write this rank's trace to ``<prefix>.rank<N>.trace.json``."""
    if not enabled():
        return
    cfg = config.load()
    prefix = getattr(cfg, "trace_dump", "")
    if not prefix:
        return
    env = _env()
    if env is None:
        return
    ctx, wrank = env
    tr = tracer_for(ctx)
    if tr is None:
        return
    dump_trace(tr, f"{prefix}.rank{wrank}.trace.json", rank=wrank)


def load_trace(paths: Any) -> Tracer:
    """Merge one or more trace-dump JSON files (or a prefix produced by
    ``finalize_dump``) back into an offline :class:`Tracer`."""
    import glob
    import json
    if isinstance(paths, str):
        paths = [paths]
    files: list = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
        else:
            files.extend(sorted(glob.glob(f"{p}.rank*.trace.json")))
    if not files:
        raise FileNotFoundError(f"no trace dumps found for {paths!r}")
    events = []
    nprocs = 0
    dropped: Dict[int, int] = {}
    for fn in files:
        with open(fn) as f:
            payload = json.load(f)
        nprocs = max(nprocs, int(payload.get("nprocs", 0)))
        for r, n in payload.get("dropped", {}).items():
            dropped[int(r)] = dropped.get(int(r), 0) + int(n)
        events.extend(payload.get("events", ()))
    tr = Tracer(nprocs, max(len(events), 16))
    tr.dropped = dropped
    for rec in sorted(events, key=lambda e: (e.get("t") or 0.0)):
        kw = {k: rec.get(k) for k in _DUMP_FIELDS if k not in ("kind", "rank")}
        if isinstance(kw.get("grp"), list):
            kw["grp"] = tuple(kw["grp"])
        if isinstance(kw.get("tag"), list):
            kw["tag"] = tuple(kw["tag"])
        seq = kw.pop("seq", None)
        ev = Event(rec["kind"], int(rec["rank"]), **kw)
        tr.record(ev)
        if seq is not None:
            ev.seq = seq     # preserve the recorder's absolute ordinal
    return tr
