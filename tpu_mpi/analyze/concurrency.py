"""Static concurrency lint: the lock-acquisition graph of a thread fabric.

``python -m tpu_mpi.analyze locks file.py dir/ …`` builds the
lock-acquisition graph of the analyzed tree — which locks are taken while
which are held, from ``with self._lock:`` blocks, ``lock.acquire()`` /
``lock.release()`` statements, and intra-class / intra-module call
propagation — and flags the defect classes that are cheap to prove from
source alone:

- **L112** lock-order cycle: two acquisition paths establish inverted
  order (potential deadlock); both paths are reported as file:line
  chains.
- **L113** blocking call — socket ``accept``/``recv``, ``queue.get``,
  ``Condition.wait`` on a *different* lock's condition, ``Event.wait``,
  or a collective entry (``MPI.X`` / ``collective.X``) — while holding a
  dispatch/pool lock (a lock whose field name contains ``dispatch`` or
  ends in ``_pool_lock``, or one annotated ``# lock: dispatch``).
- **L114** a shared mutable field assigned on two or more threads
  (threads mapped from ``Thread(target=self.method)`` roots and their
  intra-class call closures) with no common lock guarding every write.
- **L115** a lock acquired via ``.acquire()`` whose matching
  ``.release()`` is not protected by a ``try/finally`` — an exception
  between the two leaks the lock (release on a different path than the
  acquire).

A small ``# lock:`` annotation grammar covers what the AST cannot see
(docs/analysis.md):

- ``# lock: acquires NAME`` / ``# lock: releases NAME`` — the statement
  on this line takes/drops lock ``NAME`` dynamically.
- ``# lock: blocking`` — the call on this line may block.
- ``# lock: guard NAME`` — the field write on this line is guarded by
  ``NAME`` at runtime (suppresses L114 for that write).
- ``# lock: dispatch`` — the lock constructed on this line is a
  dispatch/pool lock for L113 purposes.
- ``# lock: ignore`` — suppress concurrency diagnostics on this line.

Like the communication lint, this pass is deliberately conservative: it
only trusts receivers it can resolve (``self.X`` fields constructed as
``threading.Lock/RLock/Condition``, ``queue.Queue``, ``threading.Event``,
or the :mod:`tpu_mpi.locksmith` factories; locals assigned the same) and
stays silent otherwise. Zero diagnostics on the whole ``tpu_mpi`` tree is
part of the CI contract.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Any, Dict, List, Optional, Set, Tuple

from .diagnostics import Diagnostic

Site = Tuple[str, int]                       # (file, line)

_LOCK_CTORS = {"Lock": "lock", "RLock": "lock", "make_lock": "lock",
               "make_rlock": "lock"}
_COND_CTORS = {"Condition", "make_condition"}
_QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}
_EVENT_CTORS = {"Event"}

# receivers we cannot type still block on these names (sockets / wire)
_BLOCKING_ATTRS = {"accept", "recv_into"}
_BLOCKING_FUNCS = {"recv_frame"}
# blocking collective entries, matched only as attributes of these bases
_COLL_BASES = {"MPI", "mpi", "tpu_mpi", "collective", "coll"}
_COLL_NAMES = {"Barrier", "Bcast", "Reduce", "Allreduce", "Allgather",
               "Allgatherv", "Alltoall", "Alltoallv", "Gather", "Gatherv",
               "Scatter", "Scatterv", "Scan", "Exscan", "Reduce_scatter",
               "Send", "Ssend", "Recv", "Sendrecv", "Wait", "Waitall",
               "Comm_agree", "Comm_shrink", "Comm_spawn", "Intercomm_merge"}

_ANN_RE = re.compile(
    r"#\s*lock:\s*(acquires|releases|blocking|guard|dispatch|ignore)"
    r"(?:\s+([A-Za-z_][\w.]*))?")


def _fmt(site: Site) -> str:
    return f"{site[0]}:{site[1]}"


def _ctor_of(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


class _Scope:
    """Lock/queue/event fields of one class (or of the module itself)."""

    def __init__(self, name: str):
        self.name = name                     # "Cls" or module basename
        self.locks: Dict[str, str] = {}      # attr -> lock id
        self.cond_lock: Dict[str, str] = {}  # cond attr -> underlying lock id
        self.queues: Set[str] = set()
        self.events: Set[str] = set()
        self.dispatch: Set[str] = set()      # lock ids that gate L113
        self.methods: Dict[str, ast.AST] = {}
        self.thread_roots: Set[str] = set()  # Thread(target=self.X) methods

    def lock_id(self, attr: str) -> Optional[str]:
        if attr in self.locks:
            return self.locks[attr]
        return self.cond_lock.get(attr)


class _Summary:
    """What one function/method does, lock-wise."""

    def __init__(self, qual: str):
        self.qual = qual
        self.acquired: Dict[str, Site] = {}        # lock id -> first site
        self.blocking: List[Tuple[Site, str]] = []  # (site, description)
        self.calls: List[Tuple[tuple, str, Site]] = []  # (held, callee, site)
        self.writes: List[Tuple[str, Site, tuple]] = []  # (field, site, held)


class _Analysis:
    """Per-file facts: scopes, summaries, edges, and file-local diags."""

    def __init__(self, path: str, tree: ast.Module, src: str):
        self.path = path
        self.tree = tree
        self.mod = os.path.splitext(os.path.basename(path))[0]
        self.ann: Dict[int, List[Tuple[str, Optional[str]]]] = {}
        for lineno, text in enumerate(src.splitlines(), 1):
            for m in _ANN_RE.finditer(text):
                self.ann.setdefault(lineno, []).append((m.group(1),
                                                        m.group(2)))
        self.scopes: Dict[str, _Scope] = {}
        self.summaries: Dict[str, _Summary] = {}
        # edges[(outer, inner)] = (outer site, inner site), first observation
        self.edges: Dict[Tuple[str, str], Tuple[Site, Site]] = {}
        self.diags: List[Diagnostic] = []
        self.ignored: Set[int] = {ln for ln, anns in self.ann.items()
                                  if any(k == "ignore" for k, _ in anns)}

    # -- helpers -------------------------------------------------------------
    def diag(self, code: str, message: str, line: int, context: str = "",
             related: tuple = ()) -> None:
        if line in self.ignored:
            return
        self.diags.append(Diagnostic(code, message, file=self.path,
                                     line=line, context=context,
                                     related=related))

    def edge(self, outer: str, outer_site: Site, inner: str,
             inner_site: Site) -> None:
        if outer == inner:
            return
        self.edges.setdefault((outer, inner), (outer_site, inner_site))

    # -- pass 1: scopes ------------------------------------------------------
    def collect(self) -> None:
        mod_scope = _Scope(self.mod)
        self.scopes[""] = mod_scope
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                self.scopes[node.name] = self._collect_class(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mod_scope.methods[node.name] = node
            elif isinstance(node, ast.Assign):
                self._field_ctor(mod_scope, None, node)
        # module-level thread roots: Thread(target=fn) over module functions
        for call in ast.walk(self.tree):
            if isinstance(call, ast.Call) and _ctor_of(call) == "Thread":
                for kw in call.keywords:
                    if kw.arg == "target" and isinstance(kw.value, ast.Name) \
                            and kw.value.id in mod_scope.methods:
                        mod_scope.thread_roots.add(kw.value.id)

    def _collect_class(self, cls: ast.ClassDef) -> _Scope:
        scope = _Scope(cls.name)
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope.methods[node.name] = node
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                self._field_ctor(scope, "self", node)
            elif isinstance(node, ast.Call) and _ctor_of(node) == "Thread":
                for kw in node.keywords:
                    if (kw.arg == "target"
                            and isinstance(kw.value, ast.Attribute)
                            and isinstance(kw.value.value, ast.Name)
                            and kw.value.value.id == "self"
                            and kw.value.attr in scope.methods):
                        scope.thread_roots.add(kw.value.attr)
        return scope

    def _field_ctor(self, scope: _Scope, base: Optional[str],
                    node: ast.Assign) -> None:
        """Record ``self.X = threading.Lock()``-style constructions (or the
        module-level ``X = …`` form when ``base`` is None)."""
        if not isinstance(node.value, ast.Call):
            return
        name = None
        for tgt in node.targets:
            if base is None and isinstance(tgt, ast.Name):
                name = tgt.id
            elif (base is not None and isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == base):
                name = tgt.attr
        if name is None:
            return
        ctor = _ctor_of(node.value)
        lid = f"{scope.name}.{name}"
        anns = [k for k, _ in self.ann.get(node.lineno, ())]
        if ctor in _LOCK_CTORS:
            scope.locks[name] = lid
            if ("dispatch" in name or name.endswith("_pool_lock")
                    or "dispatch" in anns):
                scope.dispatch.add(lid)
        elif ctor in _COND_CTORS:
            args = node.value.args
            tied_ix = 1 if ctor == "make_condition" else 0
            tied = None
            if len(args) > tied_ix:
                a = args[tied_ix]
                if (isinstance(a, ast.Attribute)
                        and isinstance(a.value, ast.Name)
                        and a.value.id == "self"):
                    tied = scope.locks.get(a.attr)
            scope.cond_lock[name] = tied if tied is not None else lid
        elif ctor in _QUEUE_CTORS:
            scope.queues.add(name)
        elif ctor in _EVENT_CTORS:
            scope.events.add(name)

    # -- pass 2: summaries ---------------------------------------------------
    def summarize(self) -> None:
        for sname, scope in self.scopes.items():
            for mname, fn in scope.methods.items():
                qual = f"{scope.name}.{mname}" if sname else mname
                summ = _Summary(qual)
                self.summaries[qual] = summ
                _FuncWalker(self, scope, summ).run(fn)
        # module-level statements run on the importing thread
        mod = self.scopes[""]
        summ = _Summary("<module>")
        self.summaries["<module>"] = summ
        walker = _FuncWalker(self, mod, summ)
        walker.walk_body([st for st in self.tree.body
                          if not isinstance(st, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef,
                                                 ast.ClassDef))])
        walker.finish()

    # -- pass 3: call propagation + per-file rules ---------------------------
    def propagate(self) -> None:
        # transitive may-acquire / may-block sets per function (fixpoint)
        acq: Dict[str, Dict[str, Site]] = {
            q: dict(s.acquired) for q, s in self.summaries.items()}
        blk: Dict[str, List[Tuple[Site, str]]] = {
            q: list(s.blocking) for q, s in self.summaries.items()}
        changed = True
        while changed:
            changed = False
            for q, s in self.summaries.items():
                for _held, callee, _site in s.calls:
                    if callee not in self.summaries:
                        continue
                    for lid, site in acq[callee].items():
                        if lid not in acq[q]:
                            acq[q][lid] = site
                            changed = True
                    for b in blk[callee]:
                        if b not in blk[q]:
                            blk[q].append(b)
                            changed = True
        # cross-method edges and held-while-blocking through calls
        for q, s in self.summaries.items():
            for held, callee, site in s.calls:
                if callee not in self.summaries or not held:
                    continue
                for lid, asite in acq[callee].items():
                    for hid, hsite in held:
                        self.edge(hid, hsite, lid, asite)
                for bsite, desc in blk[callee]:
                    self._blocking_held(held, bsite, desc,
                                        via=(self.path, site[1]))

    def _blocking_held(self, held: tuple, site: Site, desc: str,
                       via: Optional[Site] = None,
                       exempt: Optional[str] = None) -> None:
        """L113 when any held lock is a dispatch/pool lock."""
        dispatch = set()
        for scope in self.scopes.values():
            dispatch |= scope.dispatch
        for hid, hsite in held:
            if hid not in dispatch or hid == exempt:
                continue
            rel = [(hsite[0], hsite[1], f"{hid!r} acquired here")]
            if via is not None:
                rel.append((via[0], via[1], "reached via this call"))
            self.diag("L113",
                      f"{desc} while holding dispatch lock {hid!r}",
                      site[1], related=tuple(rel))
            return

    def check_l114(self) -> None:
        for scope in self.scopes.values():
            # one in-class thread + the external caller thread would also
            # make two writers, but resolving the external side is
            # guesswork — require two explicit roots (conservative)
            if len(scope.thread_roots) < 2:
                continue
            prefix = f"{scope.name}."
            # intra-scope call graph closure per thread root
            callees: Dict[str, Set[str]] = {}
            for q, s in self.summaries.items():
                if not q.startswith(prefix):
                    continue
                m = q[len(prefix):]
                callees[m] = {c[len(prefix):] for _h, c, _s in s.calls
                              if c.startswith(prefix)}
            closures: Dict[str, Set[str]] = {}
            for root in scope.thread_roots:
                seen = {root}
                frontier = [root]
                while frontier:
                    m = frontier.pop()
                    for c in callees.get(m, ()):
                        if c not in seen:
                            seen.add(c)
                            frontier.append(c)
                closures[root] = seen
            # field -> write records grouped by root
            writes: Dict[str, Dict[str, List[Tuple[Site, tuple]]]] = {}
            for q, s in self.summaries.items():
                if not q.startswith(prefix):
                    continue
                m = q[len(prefix):]
                if m in ("__init__", "__new__"):
                    continue
                for field, site, held in s.writes:
                    for root, members in closures.items():
                        if m in members:
                            writes.setdefault(field, {}).setdefault(
                                root, []).append((site, held))
            for field, by_root in sorted(writes.items()):
                if len(by_root) < 2:
                    continue
                if field in scope.locks or field in scope.cond_lock \
                        or field in scope.queues or field in scope.events:
                    continue
                all_recs = [r for recs in by_root.values() for r in recs]
                guard_sets = [{hid for hid, _hs in rec[1]}
                              for rec in all_recs]
                if guard_sets and set.intersection(*guard_sets):
                    continue
                sites = sorted({rec[0] for rec in all_recs},
                               key=lambda s: (s[0], s[1]))
                first = sites[0]
                related = tuple(
                    (s[0], s[1], "another unguarded write") for s in sites[1:])
                roots = ", ".join(sorted(by_root))
                qual = f"{scope.name}.{field}"
                self.diag("L114",
                          f"field {qual!r} is written on threads rooted at "
                          f"{roots} with no common lock",
                          first[1], related=related)

    def run(self) -> List[Diagnostic]:
        self.collect()
        self.summarize()
        self.propagate()
        self.check_l114()
        return self.diags


class _FuncWalker:
    """Walks one function body tracking the held-lock stack."""

    def __init__(self, an: _Analysis, scope: _Scope, summ: _Summary):
        self.an = an
        self.scope = scope
        self.summ = summ
        self.held: List[Tuple[str, Site]] = []
        self.locals: Dict[str, str] = {}       # local var -> lock id
        self.local_queues: Set[str] = set()
        self.local_events: Set[str] = set()
        self.finally_releases: List[Set[str]] = []
        self.in_finally = 0
        self.nested: List[ast.AST] = []

    def run(self, fn: ast.AST) -> None:
        self.walk_body(fn.body)
        self.finish()

    def finish(self) -> None:
        while self.nested:
            sub = self.nested.pop()
            inner = _FuncWalker(self.an, self.scope, self.summ)
            inner.locals = dict(self.locals)
            inner.local_queues = set(self.local_queues)
            inner.local_events = set(self.local_events)
            inner.walk_body(sub.body)
            inner.finish()

    # -- resolution ----------------------------------------------------------
    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return self.scope.lock_id(expr.attr)
        if isinstance(expr, ast.Name):
            if expr.id in self.locals:
                return self.locals[expr.id]
            mod = self.an.scopes.get("")
            if mod is not None:
                return mod.lock_id(expr.id)
        return None

    def _cond_underlying(self, expr: ast.AST) -> Optional[str]:
        """The lock under a condition receiver, or None if not a cond."""
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and expr.attr in self.scope.cond_lock):
            return self.scope.cond_lock[expr.attr]
        return None

    def _is_queue(self, expr: ast.AST) -> bool:
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return expr.attr in self.scope.queues
        return isinstance(expr, ast.Name) and expr.id in self.local_queues

    def _is_event(self, expr: ast.AST) -> bool:
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return expr.attr in self.scope.events
        return isinstance(expr, ast.Name) and expr.id in self.local_events

    # -- held stack ----------------------------------------------------------
    def _push(self, lid: str, line: int) -> None:
        site = (self.an.path, line)
        for hid, hsite in self.held:
            self.an.edge(hid, hsite, lid, site)
        if lid not in self.summ.acquired:
            self.summ.acquired[lid] = site
        self.held.append((lid, site))

    def _pop(self, lid: str) -> None:
        for i in range(len(self.held) - 1, -1, -1):
            if self.held[i][0] == lid:
                del self.held[i]
                return

    # -- statement walk ------------------------------------------------------
    def walk_body(self, body: List[ast.stmt]) -> None:
        pushed_here: List[str] = []
        for i, st in enumerate(body):
            for kind, arg in self.an.ann.get(st.lineno, ()):
                if kind == "acquires" and arg:
                    lid = self.scope.lock_id(arg) or arg
                    self._push(lid, st.lineno)
                    pushed_here.append(lid)
                elif kind == "releases" and arg:
                    lid = self.scope.lock_id(arg) or arg
                    self._pop(lid)
                    if lid in pushed_here:
                        pushed_here.remove(lid)
            self._stmt(st, body, i, pushed_here)
        for lid in pushed_here:
            self._pop(lid)

    def _stmt(self, st: ast.stmt, body: List[ast.stmt], i: int,
              pushed_here: List[str]) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.nested.append(st)
            return
        if isinstance(st, ast.ClassDef):
            return
        if isinstance(st, ast.With):
            pushed = []
            for item in st.items:
                self._scan_calls(item.context_expr, st.lineno)
                lid = self._lock_of(item.context_expr)
                if lid is not None:
                    self._push(lid, item.context_expr.lineno)
                    pushed.append(lid)
            self.walk_body(st.body)
            for lid in reversed(pushed):
                self._pop(lid)
            return
        if isinstance(st, ast.Try):
            released = self._releases_in(st.finalbody)
            self.finally_releases.append(released)
            self.walk_body(st.body)
            for h in st.handlers:
                self.walk_body(h.body)
            self.walk_body(st.orelse)
            self.finally_releases.pop()
            self.in_finally += 1
            self.walk_body(st.finalbody)
            self.in_finally -= 1
            return
        if isinstance(st, ast.If):
            self._scan_calls(st.test, st.lineno)
            self.walk_body(st.body)
            self.walk_body(st.orelse)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._scan_calls(st.iter, st.lineno)
            self.walk_body(st.body)
            self.walk_body(st.orelse)
            return
        if isinstance(st, ast.While):
            self._scan_calls(st.test, st.lineno)
            self.walk_body(st.body)
            self.walk_body(st.orelse)
            return
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
            call = st.value
            f = call.func
            if isinstance(f, ast.Attribute) and f.attr in ("acquire",
                                                           "release"):
                lid = self._lock_of(f.value) or self._cond_underlying(f.value)
                if lid is not None:
                    if f.attr == "acquire":
                        self._l115(lid, st, body, i)
                        self._push(lid, st.lineno)
                        pushed_here.append(lid)
                    else:
                        self._pop(lid)
                        if lid in pushed_here:
                            pushed_here.remove(lid)
                    return
            self._scan_calls(st.value, st.lineno)
            return
        if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._assign(st)
            return
        if isinstance(st, (ast.Return, ast.Raise)):
            val = getattr(st, "value", None) or getattr(st, "exc", None)
            if val is not None:
                self._scan_calls(val, st.lineno)
            return
        if isinstance(st, ast.Assert):
            self._scan_calls(st.test, st.lineno)
            return

    def _assign(self, st: ast.stmt) -> None:
        value = st.value
        if value is not None:
            # local lock/queue/event constructions
            if isinstance(value, ast.Call):
                ctor = _ctor_of(value)
                targets = st.targets if isinstance(st, ast.Assign) \
                    else [st.target]
                tgt = targets[0] if targets else None
                if isinstance(tgt, ast.Name):
                    if ctor in _LOCK_CTORS:
                        self.locals[tgt.id] = \
                            f"{self.scope.name}.{self.summ.qual}.{tgt.id}"
                    elif ctor in _QUEUE_CTORS:
                        self.local_queues.add(tgt.id)
                    elif ctor in _EVENT_CTORS:
                        self.local_events.add(tgt.id)
            self._scan_calls(value, st.lineno)
        targets = st.targets if isinstance(st, ast.Assign) else [st.target]
        for tgt in targets:
            self._record_write(tgt, st.lineno)

    def _record_write(self, tgt: ast.AST, line: int) -> None:
        if isinstance(tgt, ast.Tuple):
            for elt in tgt.elts:
                self._record_write(elt, line)
            return
        if (isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"):
            held = tuple(self.held)
            for kind, arg in self.an.ann.get(line, ()):
                if kind == "guard" and arg:
                    lid = self.scope.lock_id(arg) or arg
                    held = held + ((lid, (self.an.path, line)),)
            self.summ.writes.append((tgt.attr, (self.an.path, line), held))

    def _releases_in(self, stmts: List[ast.stmt]) -> Set[str]:
        out: Set[str] = set()
        for node in stmts:
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "release"):
                    lid = self._lock_of(sub.func.value) \
                        or self._cond_underlying(sub.func.value)
                    if lid is not None:
                        out.add(lid)
        return out

    def _l115(self, lid: str, st: ast.stmt, body: List[ast.stmt],
              i: int) -> None:
        """Flag acquire() whose release is not on every exception edge."""
        if self.in_finally:
            return                       # re-acquire in a finally
        for released in self.finally_releases:
            if lid in released:
                return
        if i + 1 < len(body) and isinstance(body[i + 1], ast.Try) \
                and lid in self._releases_in(body[i + 1].finalbody):
            return
        release_line = None
        risky = False
        for j in range(i + 1, len(body)):
            nxt = body[j]
            if (isinstance(nxt, ast.Expr) and isinstance(nxt.value, ast.Call)
                    and isinstance(nxt.value.func, ast.Attribute)
                    and nxt.value.func.attr == "release"):
                rid = self._lock_of(nxt.value.func.value) \
                    or self._cond_underlying(nxt.value.func.value)
                if rid == lid:
                    release_line = nxt.lineno
                    break
            for sub in ast.walk(nxt):
                if isinstance(sub, (ast.Call, ast.Raise)):
                    risky = True
                    break
        if release_line is not None and risky:
            self.an.diag(
                "L115",
                f"{lid!r} acquired here but released at line {release_line} "
                f"with no try/finally — an exception in between leaks the "
                f"lock",
                st.lineno,
                related=((self.an.path, release_line, "the release"),))

    # -- call scanning -------------------------------------------------------
    def _scan_calls(self, expr: ast.AST, line: int) -> None:
        ann_blocking = any(k == "blocking"
                           for k, _ in self.an.ann.get(line, ()))
        for node in self._walk_no_lambda(expr):
            if not isinstance(node, ast.Call):
                continue
            self._classify_call(node, ann_blocking)

    @staticmethod
    def _walk_no_lambda(expr: ast.AST):
        stack = [expr]
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, ast.Lambda):
                continue                 # body runs later, elsewhere
            stack.extend(ast.iter_child_nodes(node))

    def _classify_call(self, call: ast.Call, ann_blocking: bool) -> None:
        site = (self.an.path, call.lineno)
        held = tuple(self.held)
        f = call.func
        desc = None
        if ann_blocking:
            desc = "annotated-blocking call"
        elif isinstance(f, ast.Attribute):
            recv, attr = f.value, f.attr
            if attr == "get" and self._is_queue(recv) \
                    and not self._nonblocking_get(call):
                desc = "queue.get()"
            elif attr == "wait":
                under = self._cond_underlying(recv)
                if under is not None:
                    if any(h != under for h, _s in held):
                        self._blocking_held(
                            held, site, f"Condition.wait on {under!r}",
                            exempt=under)
                    return
                if self._is_event(recv):
                    desc = "Event.wait()"
            elif attr in _BLOCKING_ATTRS:
                desc = f".{attr}()"
            elif attr == "recv" and not isinstance(recv, ast.Attribute):
                # sock.recv(...) — bare-name receivers only, so dict-like
                # helper methods named recv on self/fields never match
                desc = ".recv()"
            elif (attr in _COLL_NAMES and isinstance(recv, ast.Name)
                    and recv.id in _COLL_BASES):
                desc = f"collective entry {recv.id}.{attr}"
        elif isinstance(f, ast.Name) and f.id in _BLOCKING_FUNCS:
            desc = f"{f.id}()"
        if desc is not None:
            self._blocking_held(held, site, desc)
            if (self.an.path, call.lineno) not in [s for s, _d
                                                   in self.summ.blocking]:
                self.summ.blocking.append((site, desc))
            return
        # self.method() / module_fn() calls: record for propagation
        callee = None
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "self" and f.attr in self.scope.methods):
            callee = f"{self.scope.name}.{f.attr}"
        elif isinstance(f, ast.Name):
            mod = self.an.scopes.get("")
            if mod is not None and f.id in mod.methods:
                callee = f.id
        if callee is not None:
            self.summ.calls.append((held, callee, site))

    def _blocking_held(self, held: tuple, site: Site, desc: str,
                       exempt: Optional[str] = None) -> None:
        self.an._blocking_held(held, site, desc, exempt=exempt)

    @staticmethod
    def _nonblocking_get(call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return True
        if call.args and isinstance(call.args[0], ast.Constant) \
                and call.args[0].value is False:
            return True
        return False


# ---------------------------------------------------------------------------
# Cycle detection over the aggregated (possibly multi-file) edge set
# ---------------------------------------------------------------------------

def _find_path(edges: Dict[Tuple[str, str], Tuple[Site, Site]],
               src: str, dst: str) -> Optional[List[str]]:
    succ: Dict[str, List[str]] = {}
    for a, b in edges:
        succ.setdefault(a, []).append(b)
    seen = {src}
    parent: Dict[str, str] = {}
    frontier = [src]
    while frontier:
        nxt = []
        for a in frontier:
            for b in sorted(succ.get(a, ())):
                if b in seen:
                    continue
                seen.add(b)
                parent[b] = a
                if b == dst:
                    path = [b]
                    while path[-1] != src:
                        path.append(parent[path[-1]])
                    path.reverse()
                    return path
                nxt.append(b)
        frontier = nxt
    return None


def _cycle_diags(edges: Dict[Tuple[str, str], Tuple[Site, Site]],
                 ignored: Dict[str, Set[int]]) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    reported: Set[frozenset] = set()
    for (a, b) in sorted(edges):
        back = _find_path(edges, b, a)
        if back is None:
            continue
        nodes = frozenset([a] + back)
        if nodes in reported:
            continue
        reported.add(nodes)
        cycle = [(a, b)] + list(zip(back, back[1:]))
        # anchor at the lexically last inner acquisition — where the
        # inversion completes
        anchor = max(cycle, key=lambda e: edges[e][1])
        afile, aline = edges[anchor][1]
        if aline in ignored.get(afile, set()):
            continue
        related = []
        for (x, y) in cycle:
            osite, isite = edges[(x, y)]
            related.append((isite[0], isite[1],
                            f"{y!r} acquired while holding {x!r} "
                            f"(held since {_fmt(osite)})"))
        names = " -> ".join([a, b] if len(nodes) == 2
                            else [a] + back)
        out.append(Diagnostic(
            "L112",
            f"lock-order cycle: {names} — two acquisition paths establish "
            f"inverted order (potential deadlock)",
            file=afile, line=aline, related=tuple(related)))
    return out


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

def _analyze_source(src: str, path: str) -> Tuple[Optional[_Analysis],
                                                  List[Diagnostic]]:
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return None, [Diagnostic("L100", f"could not parse: {e.msg}",
                                 file=path, line=e.lineno or 0)]
    an = _Analysis(path, tree, src)
    return an, an.run()


def lock_lint_source(src: str, path: str = "<string>") -> List[Diagnostic]:
    """Analyze one source string (edges resolve within the file)."""
    an, diags = _analyze_source(src, path)
    if an is not None:
        diags = diags + _cycle_diags(an.edges, {an.path: an.ignored})
    diags.sort(key=lambda d: (d.file, d.line, d.code))
    return diags


def _expand(paths) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, _dirs, files in os.walk(p):
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(files) if f.endswith(".py"))
        else:
            out.append(p)
    return out


def lock_lint_paths(paths) -> List[Diagnostic]:
    """Analyze files/directories; the lock graph aggregates across all of
    them, so cross-file inverted acquisition orders are still cycles."""
    diags: List[Diagnostic] = []
    edges: Dict[Tuple[str, str], Tuple[Site, Site]] = {}
    ignored: Dict[str, Set[int]] = {}
    for path in _expand(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
        except OSError as e:
            diags.append(Diagnostic("L100", f"could not read: {e}",
                                    file=path))
            continue
        an, file_diags = _analyze_source(src, path)
        diags.extend(file_diags)
        if an is not None:
            for k, v in an.edges.items():
                edges.setdefault(k, v)
            ignored[an.path] = an.ignored
    diags.extend(_cycle_diags(edges, ignored))
    diags.sort(key=lambda d: (d.file, d.line, d.code))
    return diags


def main(argv: Optional[List[str]] = None) -> int:
    """CLI: ``python -m tpu_mpi.analyze locks file.py dir/ …`` — prints
    diagnostics, exits 1 if any were found."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    diags = lock_lint_paths(argv)
    for d in diags:
        print(d)
    if diags:
        print(f"{len(diags)} diagnostic(s) in {len(_expand(argv))} file(s)")
        return 1
    return 0
