"""Static communication lint: a CPython-``ast`` pass over SPMD programs.

``python -m tpu_mpi.lint file.py dir/ …`` flags the defect classes that are
cheap to prove from source alone — before any rank runs:

- **L101** rank-divergent collective sequences: a collective inside
  ``if rank == …`` with no matching call on the other branch(es);
- **L102** root argument mismatch across rank branches;
- **L103** reduction op / buffer dtype mismatch across rank branches;
- **L104** receive posted into a buffer smaller than the matching send;
- **L105** a send whose (literal) tag no receive in the unit matches;
- **L106** an Isend buffer mutated before its Wait;
- **L107** blocking send/recv cycle patterns (every rank receives first);
- **L108** overlapping RMA accesses to one target inside one fence epoch;
- **L109** persistent-request misuse: ``Start`` called twice without an
  intervening ``Wait``, the plan's buffer mutated between ``Start`` and
  ``Wait``, ``Start`` on a freed plan / freed communicator, or — when the
  unit literally sets ``TPU_MPI_AUTO_ARM_DONATE=1`` — in-place mutation
  of an allocating ``Allreduce`` result (the auto-armed donated plan may
  re-donate that buffer on a later round);
- **L110** an operation on a communicator after ``Comm_revoke`` (with no
  intervening ``Comm_agree``) or on the parent after ``Comm_shrink``;
- **L111** serve-session misuse: an RPC on a detached session, or a
  ``SessionComm`` passed to a *different* session's operation;
- **L116** gradient-bucket handle misuse (training tier): a handle
  produced by ``arm_bucket`` ``Start``ed twice with no intervening
  ``Wait`` (the second round's reduction is lost), or ``Wait``ed while
  not started (blocks forever on the legacy lane).

The linter is deliberately conservative: it only trusts what it can resolve
(literal tags/counts/roots, ``np.zeros``-style buffer shapes, rank variables
seeded from ``Comm_rank``) and stays silent otherwise — zero diagnostics on
``examples/`` and ``tpu_mpi/parallel`` is part of the CI contract
(docs/analysis.md). Calls count as MPI calls only as bare names or as
attributes of ``MPI`` / ``mpi`` / ``tpu_mpi``, so unrelated APIs with
colliding method names (e.g. ``queue.get``) are never matched.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from .diagnostics import Diagnostic

_MPI_BASES = {"MPI", "mpi", "tpu_mpi"}

COLLECTIVES = {
    "Barrier", "Bcast", "bcast", "Scatter", "scatter", "Scatterv",
    "Gather", "gather", "Gatherv", "Allgather", "allgather", "Allgatherv",
    "Alltoall", "alltoall", "Alltoallv", "Reduce", "reduce", "Allreduce",
    "allreduce", "Scan", "scan", "Exscan", "exscan", "Reduce_scatter",
    "Reduce_scatter_block", "Comm_dup", "Comm_split", "Comm_split_type",
    "Comm_spawn", "Intercomm_merge", "Win_create", "Win_create_dynamic",
    "Win_allocate_shared", "Win_fence", "Ibarrier", "Ibcast", "Iallreduce",
    "Ireduce", "Igather", "Iallgather", "Iscatter", "Ialltoall", "Iscan",
    "Iexscan",
    # post-PR-2 surface: ULFM recovery steps and MPI-4 persistent inits are
    # collective too — L101's arm-sequence comparison must not skip them.
    # (Comm_revoke is non-collective per ULFM, but a revoke reached on only
    # SOME arms of a rank-If still leaves the others publishing to a comm
    # the group is abandoning — flag the divergence; symmetric revoke or
    # module-level recovery code stays silent.)
    "Comm_shrink", "Comm_agree", "Comm_revoke",
    "Allreduce_init", "Bcast_init", "Barrier_init",
}
# root rank = keyword "root", else the second-to-last positional argument
# (every rooted signature here ends (..., root, comm)).
ROOTED = {"Bcast", "bcast", "Ibcast", "Reduce", "Ireduce", "Gather",
          "Igather", "Gatherv", "Scatter", "Iscatter", "Scatterv",
          "Bcast_init"}
# reduction-op position from the end of the positional argument list
REDUCE_OP_POS = {"Reduce": -3, "Ireduce": -3, "Allreduce": -2,
                 "Iallreduce": -2, "Scan": -2, "Iscan": -2, "Exscan": -2,
                 "Iexscan": -2, "Reduce_scatter": -2,
                 "Reduce_scatter_block": -2, "Allreduce_init": -2}

# send name -> tag argument position (buffer/object is argument 0)
SEND_TAG_POS = {"Send": 2, "Isend": 2, "send": 2, "isend": 2, "Send_init": 2,
                "Psend_init": 3}
# receive name -> (tag position, buffer position or None)
RECV_TAG_POS = {"Recv": (2, 0), "Irecv": (2, 0), "recv": (1, None),
                "irecv": (1, None), "Recv_init": (2, 0),
                "Precv_init": (3, 0)}
# blocking operations for the deadlock-cycle flow analysis
BLOCKING_RECV = {"Recv", "recv", "Probe"}
BLOCKING_SEND = {"Send", "send"}
RMA_ACCESS = {"Put", "Get", "Accumulate"}

WAIT_NAMES = {"Wait", "Waitall", "Waitany", "Waitsome", "Test", "Testall",
              "Testany", "Testsome"}

# MPI-4 persistent plans whose Start/Wait lifecycle L109 tracks
PERSISTENT_INITS = {"Allreduce_init", "Bcast_init", "Barrier_init",
                    "Send_init", "Recv_init", "Psend_init", "Precv_init"}
# the ULFM recovery verbs — the only calls L110 permits on a marked comm
FT_VERBS = {"Comm_revoke", "Comm_shrink", "Comm_agree", "free", "Comm_free"}
# communication ops whose comm argument L110 inspects (queries like
# Comm_rank stay legal on a revoked comm, so they are not in here)
COMM_OPS = (COLLECTIVES | set(SEND_TAG_POS) | set(RECV_TAG_POS)
            | {"Sendrecv", "Probe", "Iprobe"}) - FT_VERBS
# the serve-tier ClientSession RPC surface (L111)
SESSION_OPS = {"allreduce", "bcast", "barrier", "comm_dup", "comm_free",
               "pcontrol", "stats", "ping"}

_RANK_SEEDS = {"rank", "my_rank", "myrank"}
_BUF_MAKERS = {"zeros", "ones", "empty", "full", "arange", "array"}


def _unparse(node: Optional[ast.AST]) -> str:
    if node is None:
        return "<none>"
    try:
        return ast.unparse(node)
    except Exception:
        return "<expr>"


def _call_name(call: ast.Call) -> Optional[str]:
    """The MPI operation a call names, or None if it isn't one."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
            and f.value.id in _MPI_BASES):
        return f.attr
    return None


class _Op:
    """One recognized MPI call in program order."""

    __slots__ = ("name", "call", "line", "arm", "cond", "epoch", "locked")

    def __init__(self, name, call, arm, cond, epoch, locked):
        self.name = name
        self.call = call
        self.line = call.lineno
        self.arm = arm          # innermost rank-branch id, () = unconditional
        self.cond = cond        # under any non-rank conditional / loop
        self.epoch = epoch      # fence-epoch ordinal (L108)
        self.locked = locked    # inside an exclusive Win_lock section


class _Unit:
    """One analysis scope: the module's top level, or one function body."""

    def __init__(self, name: str, stmts: List[ast.stmt], linter: "_Linter"):
        self.name = name
        self.L = linter
        self.ops: List[_Op] = []
        # rank-If descriptors: (if-node, [per-arm collective op lists],
        # has_else, test-source)
        self.rank_ifs: List[tuple] = []
        self._armed: Dict[str, tuple] = {}      # req var -> (buf var, line)
        # L109: plan var -> {kind, buf, comm, started, freed, init_line}
        self._pers: Dict[str, dict] = {}
        # L116: gradient-bucket handle var (arm_bucket result) ->
        # {started: Optional[line], init_line}
        self._bucket: Dict[str, dict] = {}
        self._freed: set = set()                # comm vars already freed
        # L110: comm var -> ("revoked" | "shrunk", line)
        self._ft: Dict[str, tuple] = {}
        # L111: session var -> detach line (None while live);
        # SessionComm var -> owning session var
        self._sessions: Dict[str, Optional[int]] = {}
        self._sess_comms: Dict[str, str] = {}
        # L109 auto-arm lane: only armed by a literal
        # os.environ["TPU_MPI_AUTO_ARM_DONATE"] = "1" in this unit;
        # name -> line of the allocating Allreduce that produced it
        self._auto_donate = False
        self._auto_live: Dict[str, int] = {}
        self._epoch = 0
        self._lock_depth = 0
        self._scan(stmts, arm=(), cond=False)

    # -- ordered traversal --------------------------------------------------

    def _scan(self, stmts, arm, cond):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue                      # separate units
            if isinstance(st, ast.If) and self.L.is_rank_test(st.test):
                self._scan_rank_if(st, arm, cond)
            elif isinstance(st, ast.If):
                self._scan(st.body, arm, True)
                self._scan(st.orelse, arm, True)
            elif isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
                self._scan(st.body, arm, True)
                self._scan(st.orelse, arm, True)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                self._scan(st.body, arm, cond)
            elif isinstance(st, ast.Try):
                for blk in (st.body, st.orelse, st.finalbody):
                    self._scan(blk, arm, True if blk is not st.body else cond)
                for h in st.handlers:
                    self._scan(h.body, arm, True)
            else:
                self._leaf(st, arm, cond)

    def _scan_rank_if(self, node: ast.If, arm, cond):
        """Flatten an ``if rank…/elif/else`` chain into arms and record the
        per-arm collective sequences for L101/102/103."""
        arms: List[List[_Op]] = []
        test_src = _unparse(node.test)
        ifid = id(node)
        cur: Any = node
        has_else = False
        idx = 0
        while True:
            start = len(self.ops)
            self._scan(cur.body, arm + ((ifid, idx),), cond)
            arms.append([op for op in self.ops[start:]
                         if op.name in COLLECTIVES])
            idx += 1
            orelse = cur.orelse
            if len(orelse) == 1 and isinstance(orelse[0], ast.If):
                cur = orelse[0]
                continue
            if orelse:
                has_else = True
                start = len(self.ops)
                self._scan(orelse, arm + ((ifid, idx),), cond)
                arms.append([op for op in self.ops[start:]
                             if op.name in COLLECTIVES])
            break
        self.rank_ifs.append((node, arms, has_else, test_src))

    def _leaf(self, st: ast.stmt, arm, cond):
        calls = [n for n in ast.walk(st) if isinstance(n, ast.Call)]
        calls.sort(key=lambda c: (c.lineno, c.col_offset))
        for call in calls:
            name = _call_name(call)
            if name is None:
                # a `<mod>.arm_bucket(...)` from a non-MPI base still
                # mints a tracked bucket handle (L116)
                self._bucket_effects(st, call, None)
                self._method_effects(st, call)
                continue
            if name == "Win_fence":
                self._epoch += 1
            elif name == "Win_lock":
                if call.args and "EXCLUSIVE" in _unparse(call.args[0]):
                    self._lock_depth += 1
            elif name == "Win_unlock":
                self._lock_depth = max(0, self._lock_depth - 1)
            self.ops.append(_Op(name, call, arm, cond, self._epoch,
                                self._lock_depth > 0))
            self._isend_effects(st, call, name)
            self._persistent_effects(st, call, name)
            self._bucket_effects(st, call, name)
            self._ft_effects(st, call, name)
        self._auto_arm_effects(st)
        self._mutation_effects(st)
        self._assign_clears(st)

    # -- L109 auto-arm bookkeeping: donated armed-result lifetime -----------

    @staticmethod
    def _is_environ(node) -> bool:
        if isinstance(node, ast.Name):
            return node.id == "environ"
        return (isinstance(node, ast.Attribute) and node.attr == "environ"
                and isinstance(node.value, ast.Name)
                and node.value.id == "os")

    def _auto_arm_effects(self, st):
        """Track the donate-knob gate and live donated-result names. The
        gate only opens on a *literal* env assignment, so the rule is
        structurally silent on the shipped tree (zero-FP contract)."""
        if isinstance(st, ast.Delete):
            for t in st.targets:
                if (isinstance(t, ast.Subscript) and self._is_environ(t.value)
                        and isinstance(t.slice, ast.Constant)
                        and t.slice.value == "TPU_MPI_AUTO_ARM_DONATE"):
                    self._auto_donate = False
            return
        for call in ast.walk(st):
            # os.environ.pop("TPU_MPI_AUTO_ARM_DONATE", ...) closes the gate
            if (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr == "pop"
                    and self._is_environ(call.func.value)
                    and call.args and isinstance(call.args[0], ast.Constant)
                    and call.args[0].value == "TPU_MPI_AUTO_ARM_DONATE"):
                self._auto_donate = False
        if not isinstance(st, ast.Assign) or len(st.targets) != 1:
            return
        t = st.targets[0]
        if (isinstance(t, ast.Subscript) and self._is_environ(t.value)
                and isinstance(t.slice, ast.Constant)
                and t.slice.value == "TPU_MPI_AUTO_ARM_DONATE"
                and isinstance(st.value, ast.Constant)):
            self._auto_donate = str(st.value.value).strip().lower() \
                not in ("", "0", "false", "no", "off")
            return
        target = self._assign_target(st)
        if target is None:
            return
        v = st.value
        if isinstance(v, ast.Call) and _call_name(v) == "Allreduce":
            # allocating form: a result binding while the donate knob is
            # set may alias the armed plan's donated ring slot
            if self._auto_donate:
                self._auto_live[target] = st.lineno
            else:
                self._auto_live.pop(target, None)
        elif isinstance(v, ast.Name) and v.id in self._auto_live:
            self._auto_live[target] = self._auto_live[v.id]
        else:
            self._auto_live.pop(target, None)

    # -- L106 bookkeeping (runs inline with the ordered scan) ---------------

    def _isend_effects(self, st, call, name):
        if name in ("Isend", "isend"):
            if (isinstance(st, ast.Assign) and len(st.targets) == 1
                    and isinstance(st.targets[0], ast.Name)
                    and call.args and isinstance(call.args[0], ast.Name)):
                self._armed[st.targets[0].id] = (call.args[0].id, call.lineno)
        elif name in WAIT_NAMES and call.args:
            a0 = call.args[0]
            if isinstance(a0, ast.Name):
                self._armed.pop(a0.id, None)
            elif isinstance(a0, (ast.List, ast.Tuple)):
                for el in a0.elts:
                    if isinstance(el, ast.Name):
                        self._armed.pop(el.id, None)

    # -- L109 bookkeeping: persistent plan lifecycle ------------------------

    @staticmethod
    def _assign_target(st) -> Optional[str]:
        if (isinstance(st, ast.Assign) and len(st.targets) == 1
                and isinstance(st.targets[0], ast.Name)):
            return st.targets[0].id
        return None

    def _persistent_effects(self, st, call, name):
        if name in PERSISTENT_INITS:
            target = self._assign_target(st)
            if target is None:
                return
            buf = None
            if name != "Barrier_init" and call.args \
                    and isinstance(call.args[0], ast.Name):
                buf = call.args[0].id
            comm = self.L._arg(call, len(call.args) - 1, kw="comm")
            self._pers[target] = {
                "kind": name, "buf": buf,
                "comm": comm.id if isinstance(comm, ast.Name) else None,
                "started": None, "freed": None, "init_line": call.lineno,
            }
        elif name in ("Start", "Startall"):
            reqs: List[str] = []
            if call.args and isinstance(call.args[0], ast.Name):
                reqs = [call.args[0].id]
            elif call.args and isinstance(call.args[0], (ast.List, ast.Tuple)):
                reqs = [el.id for el in call.args[0].elts
                        if isinstance(el, ast.Name)]
            for r in reqs:
                self._start_plan(r, call.lineno)
        elif name in WAIT_NAMES and call.args:
            a0 = call.args[0]
            names = [a0] if isinstance(a0, ast.Name) else (
                list(a0.elts) if isinstance(a0, (ast.List, ast.Tuple)) else [])
            for el in names:
                if isinstance(el, ast.Name) and el.id in self._pers:
                    self._pers[el.id]["started"] = None
        elif name in ("free", "Comm_free", "Request_free") \
                and call.args and isinstance(call.args[0], ast.Name):
            a = call.args[0].id
            if a in self._pers:
                self._pers[a]["freed"] = call.lineno
            else:
                self._freed.add(a)

    def _start_plan(self, req: str, line: int):
        p = self._pers.get(req)
        if p is None:
            return
        if p["freed"] is not None:
            self.L.diag("L109",
                        f"Start on persistent plan {req!r} after it was freed "
                        f"at line {p['freed']}",
                        line, context=f"{p['kind']} at line {p['init_line']}")
        elif p["comm"] is not None and p["comm"] in self._freed:
            self.L.diag("L109",
                        f"Start on persistent plan {req!r} whose communicator "
                        f"{p['comm']!r} was already freed",
                        line, context=f"{p['kind']} at line {p['init_line']}")
        elif p["started"] is not None:
            self.L.diag("L109",
                        f"Start on persistent plan {req!r} which is already "
                        f"started (line {p['started']}) — call Wait before "
                        f"restarting",
                        line, context=f"{p['kind']} at line {p['init_line']}")
        p["started"] = line

    # -- L116 bookkeeping: gradient-bucket handle lifecycle -----------------

    @staticmethod
    def _is_arm_bucket(call: ast.Call) -> bool:
        """A call that mints a training-tier bucket handle: bare
        ``arm_bucket(...)`` or ``<anything>.arm_bucket(...)`` (the
        distinctive producer name is the whole point — see
        tpu_mpi.train.ddp.arm_bucket)."""
        f = call.func
        return (isinstance(f, ast.Name) and f.id == "arm_bucket") or \
            (isinstance(f, ast.Attribute) and f.attr == "arm_bucket")

    def _bucket_effects(self, st, call, name):
        if self._is_arm_bucket(call):
            target = self._assign_target(st)
            if target is not None:
                self._bucket[target] = {"started": None,
                                        "init_line": call.lineno}
            return
        if name in ("Start", "Startall"):
            reqs: List[str] = []
            if call.args and isinstance(call.args[0], ast.Name):
                reqs = [call.args[0].id]
            elif call.args and isinstance(call.args[0], (ast.List, ast.Tuple)):
                reqs = [el.id for el in call.args[0].elts
                        if isinstance(el, ast.Name)]
            for r in reqs:
                self._start_bucket(r, call.lineno)
        elif name in WAIT_NAMES and call.args:
            a0 = call.args[0]
            names = [a0] if isinstance(a0, ast.Name) else (
                list(a0.elts) if isinstance(a0, (ast.List, ast.Tuple)) else [])
            for el in names:
                if isinstance(el, ast.Name):
                    self._wait_bucket(el.id, call.lineno)

    def _start_bucket(self, req: str, line: int):
        b = self._bucket.get(req)
        if b is None:
            return
        if b["started"] is not None:
            self.L.diag("L116",
                        f"gradient bucket {req!r} Started twice (previous "
                        f"Start at line {b['started']}) with no intervening "
                        f"Wait — the second round's reduction is lost",
                        line, context=f"arm_bucket at line {b['init_line']}")
        b["started"] = line

    def _wait_bucket(self, req: str, line: int):
        b = self._bucket.get(req)
        if b is None:
            return
        if b["started"] is None:
            self.L.diag("L116",
                        f"Wait on gradient bucket {req!r} which is not "
                        f"started — blocks forever on the legacy lane",
                        line, context=f"arm_bucket at line {b['init_line']}")
        b["started"] = None

    # -- L110 bookkeeping: revoked / shrunk communicators -------------------

    def _ft_effects(self, st, call, name):
        if name == "Comm_revoke":
            if call.args and isinstance(call.args[0], ast.Name):
                self._ft[call.args[0].id] = ("revoked", call.lineno)
            return
        if name == "Comm_shrink":
            if call.args and isinstance(call.args[0], ast.Name):
                self._ft[call.args[0].id] = ("shrunk", call.lineno)
            return
        comm = self.L._arg(call, len(call.args) - 1, kw="comm") \
            if call.args or call.keywords else None
        cname = comm.id if isinstance(comm, ast.Name) else None
        if name == "Comm_agree":
            # the group ran the decision protocol: reuse is deliberate now
            if call.args and isinstance(call.args[0], ast.Name):
                self._ft.pop(call.args[0].id, None)
            return
        if name in COMM_OPS and cname is not None and cname in self._ft:
            state, ftline = self._ft[cname]
            if state == "revoked":
                why = (f"{cname!r} was revoked at line {ftline} — run "
                       f"Comm_agree or switch to the Comm_shrink result first")
            else:
                why = (f"{cname!r} is the parent of a Comm_shrink at line "
                       f"{ftline} — use the shrunk communicator")
            self.L.diag("L110", f"{name} on communicator {why}",
                        call.lineno, context=f"comm variable {cname!r}")

    # -- L111 bookkeeping: serve-tier client sessions -----------------------

    def _session_attach(self, st, call) -> bool:
        """True if ``call`` is serve.attach(...); records the session var."""
        f = call.func
        is_attach = False
        if isinstance(f, ast.Name) and f.id == "attach":
            is_attach = True
        elif isinstance(f, ast.Attribute) and f.attr == "attach":
            base = f.value
            if isinstance(base, ast.Name) and base.id == "serve":
                is_attach = True
            elif (isinstance(base, ast.Attribute) and base.attr == "serve"
                    and isinstance(base.value, ast.Name)
                    and base.value.id in _MPI_BASES):
                is_attach = True
        if is_attach:
            target = self._assign_target(st)
            if target is not None:
                self._sessions[target] = None
        return is_attach

    def _session_effects(self, st, call, base, meth):
        detached = self._sessions[base]
        if meth in ("detach", "close"):
            self._sessions[base] = call.lineno
            return
        if meth not in SESSION_OPS:
            return
        if detached is not None:
            self.L.diag("L111",
                        f"{meth}() on session {base!r} after it was detached "
                        f"at line {detached}",
                        call.lineno, context=f"session variable {base!r}")
        if meth == "comm_dup":
            target = self._assign_target(st)
            if target is not None:
                self._sess_comms[target] = base
        for val in list(call.args) + [k.value for k in call.keywords]:
            if isinstance(val, ast.Name):
                owner = self._sess_comms.get(val.id)
                if owner is not None and owner != base:
                    self.L.diag(
                        "L111",
                        f"{meth}() on session {base!r} is passed communicator "
                        f"{val.id!r} that belongs to session {owner!r} — "
                        f"session comms are tenant-scoped",
                        call.lineno, context=f"comm variable {val.id!r}")

    def _method_effects(self, st, call):
        # req.wait() / req.test() disarm; buf.fill()-style calls mutate
        if self._session_attach(st, call):
            return
        f = call.func
        if not (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)):
            return
        base, meth = f.value.id, f.attr
        if base in self._sessions:
            self._session_effects(st, call, base, meth)
            return
        if meth in ("wait", "test", "Wait", "Test"):
            self._armed.pop(base, None)
            if base in self._pers:
                self._pers[base]["started"] = None
            if base in self._bucket:
                self._wait_bucket(base, call.lineno)
        elif meth in ("start", "Start") and base in self._pers:
            self._start_plan(base, call.lineno)
        elif meth in ("start", "Start") and base in self._bucket:
            self._start_bucket(base, call.lineno)
        elif meth == "free":
            if base in self._pers:
                self._pers[base]["freed"] = call.lineno
            else:
                self._freed.add(base)
        elif meth in ("fill", "sort", "put", "setfield", "resize"):
            self._flag_mutation(base, call.lineno)

    def _mutation_effects(self, st):
        targets: List[ast.expr] = []
        if isinstance(st, ast.Assign):
            targets = list(st.targets)
        elif isinstance(st, ast.AugAssign):
            targets = [st.target]
        for t in targets:
            if isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name):
                self._flag_mutation(t.value.id, st.lineno)
            elif isinstance(t, ast.Name) and isinstance(st, ast.AugAssign):
                self._flag_mutation(t.id, st.lineno)

    def _flag_mutation(self, varname: str, line: int):
        for req, (buf, post_line) in list(self._armed.items()):
            if buf == varname:
                self.L.diag("L106",
                            f"buffer {varname!r} of the Isend posted at line "
                            f"{post_line} is mutated before its Wait",
                            line, context=f"request variable {req!r}")
                del self._armed[req]
        for req, p in self._pers.items():
            # partitioned plans are EXPECTED to fill partitions between
            # Start and Wait — Pready/Parrived carry the per-slice contract
            if p["kind"] in ("Psend_init", "Precv_init"):
                continue
            if p["started"] is not None and p["buf"] == varname:
                self.L.diag("L109",
                            f"buffer {varname!r} of persistent plan {req!r} "
                            f"is mutated between Start (line {p['started']}) "
                            f"and its Wait",
                            line, context=f"{p['kind']} at line "
                                          f"{p['init_line']}")
                p["buf"] = None         # one diagnostic per plan
        src = self._auto_live.pop(varname, None)
        if src is not None:
            self.L.diag("L109",
                        f"result {varname!r} of the allocating Allreduce at "
                        f"line {src} is mutated in place — with "
                        f"TPU_MPI_AUTO_ARM_DONATE=1 the auto-armed plan may "
                        f"re-donate this buffer on a later round; copy it "
                        f"before writing",
                        line,
                        context="TPU_MPI_AUTO_ARM_DONATE=1 set in this unit")

    def _assign_clears(self, st):
        """Rebinding a tracked name retires whatever it pointed at."""
        target = self._assign_target(st)
        if target is None:
            return
        self._ft.pop(target, None)
        self._freed.discard(target)
        if not (isinstance(st.value, ast.Call)
                and _call_name(st.value) in PERSISTENT_INITS):
            self._pers.pop(target, None)
        if not (isinstance(st.value, ast.Call)
                and self._is_arm_bucket(st.value)):
            self._bucket.pop(target, None)
        if not (isinstance(st.value, ast.Call)
                and self._session_is_attach_value(st.value)):
            self._sessions.pop(target, None)
            self._sess_comms.pop(target, None)

    @staticmethod
    def _session_is_attach_value(call: ast.Call) -> bool:
        f = call.func
        return (isinstance(f, ast.Name) and f.id == "attach") or \
            (isinstance(f, ast.Attribute) and f.attr in ("attach", "comm_dup"))


class _Linter:
    """One source file: prescan + per-unit checks."""

    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.tree = tree
        self.out: List[Diagnostic] = []
        self.rank_vars = set(_RANK_SEEDS)
        self.var_int: Dict[str, int] = {}
        self.var_buf: Dict[str, tuple] = {}     # name -> (size, dtype src)
        self._prescan()

    def diag(self, code: str, msg: str, line: int, context: str = "",
             related: tuple = ()):
        self.out.append(Diagnostic(code, msg, file=self.path, line=line,
                                   context=context, related=related))

    # -- whole-file prescan: rank vars, int vars, buffer shapes -------------

    def _prescan(self):
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            name = node.targets[0].id
            val = node.value
            if isinstance(val, ast.Constant) and isinstance(val.value, int) \
                    and not isinstance(val.value, bool):
                self.var_int[name] = val.value
            if isinstance(val, ast.Call):
                cn = _call_name(val)
                if cn in ("Comm_rank", "Get_rank"):
                    self.rank_vars.add(name)
                    continue
                if (isinstance(val.func, ast.Attribute)
                        and val.func.attr == "Get_rank"):
                    self.rank_vars.add(name)
                    continue
                self._note_buffer(name, val)
            if any(isinstance(n, ast.Name) and n.id in self.rank_vars
                   for n in ast.walk(val)):
                self.rank_vars.add(name)        # rank-derived

    def _note_buffer(self, name: str, call: ast.Call):
        f = call.func
        maker = None
        if isinstance(f, ast.Attribute) and f.attr in _BUF_MAKERS:
            maker = f.attr
        elif isinstance(f, ast.Name) and f.id in _BUF_MAKERS:
            maker = f.id
        if maker is None or not call.args:
            return
        size = None
        a0 = call.args[0]
        if maker == "array" and isinstance(a0, (ast.List, ast.Tuple)):
            size = len(a0.elts)
        else:
            shape = a0
            if isinstance(shape, (ast.Tuple, ast.List)) and len(shape.elts) == 1:
                shape = shape.elts[0]
            size = self.literal_int(shape)
        if size is None:
            return
        dtype = None
        for kw in call.keywords:
            if kw.arg == "dtype":
                dtype = _unparse(kw.value)
        if dtype is None and maker in ("zeros", "ones", "empty") \
                and len(call.args) > 1:
            dtype = _unparse(call.args[1])
        self.var_buf[name] = (size, dtype)

    # -- small resolvers ----------------------------------------------------

    def literal_int(self, node: Optional[ast.expr]) -> Optional[int]:
        if node is None:
            return None
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and not isinstance(node.value, bool):
            return node.value
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            inner = self.literal_int(node.operand)
            return -inner if inner is not None else None
        if isinstance(node, ast.Name):
            return self.var_int.get(node.id)
        return None

    def is_rank_test(self, test: ast.expr) -> bool:
        return any(isinstance(n, ast.Name) and n.id in self.rank_vars
                   for n in ast.walk(test))

    def uses_rank(self, node: Optional[ast.expr]) -> bool:
        return node is not None and any(
            isinstance(n, ast.Name) and n.id in self.rank_vars
            for n in ast.walk(node))

    @staticmethod
    def _arg(call: ast.Call, pos: int, kw: Optional[str] = None
             ) -> Optional[ast.expr]:
        if kw is not None:
            for k in call.keywords:
                if k.arg == kw:
                    return k.value
        try:
            return call.args[pos]
        except IndexError:
            return None

    def _root_of(self, op: _Op) -> Optional[ast.expr]:
        return self._arg(op.call, len(op.call.args) - 2, kw="root")

    def _reduce_op_of(self, op: _Op) -> Optional[ast.expr]:
        pos = REDUCE_OP_POS.get(op.name)
        if pos is None:
            return None
        return self._arg(op.call, len(op.call.args) + pos, kw="op")

    def _buf_dtype_of(self, op: _Op) -> Optional[str]:
        if not op.call.args or not isinstance(op.call.args[0], ast.Name):
            return None
        info = self.var_buf.get(op.call.args[0].id)
        return info[1] if info else None

    # -- driver -------------------------------------------------------------

    def run(self) -> List[Diagnostic]:
        units = [_Unit("<module>", list(self.tree.body), self)]
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                units.append(_Unit(node.name, list(node.body), self))
        for u in units:
            self._check_rank_ifs(u)
            self._check_truncation(u)
            self._check_unmatched_sends(u)
            self._check_cycles(u)
            self._check_rma(u)
        self.out.sort(key=lambda d: (d.line, d.code))
        return self.out

    # -- L101 / L102 / L103 -------------------------------------------------

    def _check_rank_ifs(self, u: _Unit):
        for node, arms, has_else, test_src in u.rank_ifs:
            if not any(arms):
                continue                    # no collectives anywhere: fine
            seqs = [[o.name for o in arm] for arm in arms]
            if not has_else:
                seqs.append([])             # the implicit empty branch
                arms = arms + [[]]
            if self._flag_sequence_divergence(arms, seqs, test_src, node):
                continue
            # identical sequences: compare signatures position by position
            base = arms[0]
            for other in arms[1:]:
                for a, b in zip(base, other):
                    self._compare_signatures(a, b, test_src)

    def _flag_sequence_divergence(self, arms, seqs, test_src, node) -> bool:
        longest = max(len(s) for s in seqs)
        for i in range(longest):
            names = [s[i] if i < len(s) else None for s in seqs]
            if len(set(names)) == 1:
                continue
            # first divergence: anchor on the first arm that HAS a
            # collective at this position
            armno = next(k for k, s in enumerate(seqs) if i < len(s))
            op = arms[armno][i]
            present = sorted({n for n in names if n is not None})
            if names.count(None):
                detail = "no matching call on the other branch"
            else:
                detail = f"the branches call {present}"
            self.diag("L101",
                      f"collective {op.name} is reached on only some ranks: "
                      f"sequence position {i} diverges across the branches "
                      f"of `if {test_src}:` ({detail})",
                      op.line, context=f"if {test_src}")
            return True
        return False

    def _compare_signatures(self, a: _Op, b: _Op, test_src: str):
        if a.name in ROOTED:
            ra, rb = self._root_of(a), self._root_of(b)
            va, vb = self.literal_int(ra), self.literal_int(rb)
            if (va is not None and vb is not None and va != vb) or \
               (va is None and vb is None and ra is not None and
                    rb is not None and _unparse(ra) != _unparse(rb)):
                self.diag("L102",
                          f"root of {a.name} differs across the branches of "
                          f"`if {test_src}:`: {_unparse(ra)} vs {_unparse(rb)}",
                          b.line, context=f"if {test_src}",
                          related=((self.path, a.line, "the other branch"),))
        if a.name in REDUCE_OP_POS:
            oa, ob = self._reduce_op_of(a), self._reduce_op_of(b)
            if oa is not None and ob is not None and \
                    _unparse(oa) != _unparse(ob):
                self.diag("L103",
                          f"reduction op of {a.name} differs across the "
                          f"branches of `if {test_src}:`: {_unparse(oa)} vs "
                          f"{_unparse(ob)}",
                          b.line, context=f"if {test_src}",
                          related=((self.path, a.line, "the other branch"),))
                return
        da, db = self._buf_dtype_of(a), self._buf_dtype_of(b)
        if da is not None and db is not None and da != db:
            self.diag("L103",
                      f"buffer dtype of {a.name} differs across the branches "
                      f"of `if {test_src}:`: {da} vs {db}",
                      b.line, context=f"if {test_src}",
                      related=((self.path, a.line, "the other branch"),))

    # -- L104 ---------------------------------------------------------------

    def _check_truncation(self, u: _Unit):
        sends, recvs = [], []
        for op in u.ops:
            if op.name in ("Send", "Isend", "Send_init"):
                tag = self.literal_int(self._arg(op.call, 2, kw="tag"))
                buf = op.call.args[0] if op.call.args else None
                if tag is not None and isinstance(buf, ast.Name):
                    info = self.var_buf.get(buf.id)
                    if info:
                        sends.append((tag, info[0], op))
            elif op.name in ("Recv", "Irecv", "Recv_init"):
                tag = self.literal_int(self._arg(op.call, 2, kw="tag"))
                buf = op.call.args[0] if op.call.args else None
                if tag is not None and isinstance(buf, ast.Name):
                    info = self.var_buf.get(buf.id)
                    if info:
                        recvs.append((tag, info[0], op))
        for stag, ssize, sop in sends:
            for rtag, rsize, rop in recvs:
                if stag == rtag and rsize < ssize:
                    self.diag("L104",
                              f"receive buffer holds {rsize} elements but "
                              f"the matching send (line {sop.line}, tag "
                              f"{stag}) ships {ssize}",
                              rop.line,
                              related=((self.path, sop.line, "the send"),))

    # -- L105 ---------------------------------------------------------------

    def _check_unmatched_sends(self, u: _Unit):
        recv_tags = set()
        wildcard = False
        n_recvs = 0
        for op in u.ops:
            if op.name in RECV_TAG_POS:
                n_recvs += 1
                pos, _ = RECV_TAG_POS[op.name]
                tnode = self._arg(op.call, pos, kw="tag")
                t = self.literal_int(tnode)
                if t is None:
                    wildcard = True     # ANY_TAG / computed tag: stay silent
                else:
                    recv_tags.add(t)
            elif op.name == "Sendrecv":
                n_recvs += 1
                t = self.literal_int(self._arg(op.call, 5, kw="recvtag"))
                if t is None:
                    wildcard = True
                else:
                    recv_tags.add(t)
        if wildcard:
            return
        if u.name != "<module>" and n_recvs == 0:
            return      # a send-only helper may be matched by its caller
        for op in u.ops:
            tag = None
            if op.name in SEND_TAG_POS:
                tag = self.literal_int(
                    self._arg(op.call, SEND_TAG_POS[op.name], kw="tag"))
            elif op.name == "Sendrecv":
                tag = self.literal_int(self._arg(op.call, 2, kw="sendtag"))
            if tag is not None and tag not in recv_tags:
                self.diag("L105",
                          f"{op.name} with tag {tag} has no receive with a "
                          f"matching tag in this scope "
                          f"(receive tags seen: {sorted(recv_tags)})",
                          op.line)

    # -- L107 ---------------------------------------------------------------

    def _first_blocking(self, ops: List[_Op]) -> Optional[_Op]:
        for op in ops:
            if op.name in BLOCKING_RECV or op.name in BLOCKING_SEND:
                return op
        return None

    def _check_cycles(self, u: _Unit):
        # flow A: every rank's first unconditional blocking P2P op is a
        # receive from a rank-dependent source -> nobody ever sends first.
        flat = [op for op in u.ops if op.arm == () and not op.cond]
        first = self._first_blocking(flat)
        if first is not None and first.name in BLOCKING_RECV:
            src = self._arg(first.call,
                            0 if first.name in ("recv", "Probe") else 1,
                            kw="src")
            if self.uses_rank(src) and "PROC_NULL" not in _unparse(src):
                later_send = any(
                    op.name in BLOCKING_SEND or op.name in ("Isend", "isend")
                    for op in flat if op.line > first.line)
                if later_send:
                    self.diag("L107",
                              f"every rank blocks in {first.name} (source "
                              f"{_unparse(src)}) before any rank sends — "
                              f"a send/recv cycle",
                              first.line,
                              context="first blocking operation is a receive "
                                      "on all ranks")
        # flow B: a rank-If with else where EVERY arm receives first
        for node, _arms, has_else, test_src in u.rank_ifs:
            if not has_else:
                continue
            ifid = id(node)
            per_arm: Dict[int, List[_Op]] = {}
            for op in u.ops:
                for (i, idx) in op.arm:
                    if i == ifid:
                        per_arm.setdefault(idx, []).append(op)
            firsts = [self._first_blocking(ops)
                      for ops in per_arm.values() if ops]
            firsts = [f for f in firsts if f is not None]
            if len(firsts) >= 2 and all(f.name in BLOCKING_RECV
                                        for f in firsts):
                self.diag("L107",
                          f"every branch of `if {test_src}:` blocks in a "
                          f"receive first — no rank can reach its send",
                          firsts[0].line, context=f"if {test_src}")

    # -- L108 ---------------------------------------------------------------

    def _rma_range(self, op: _Op):
        """(target literal, lo, hi) of a Put/Get/Accumulate, or None."""
        args = op.call.args
        if op.name in ("Put", "Get"):
            if len(args) == 5:
                count = self.literal_int(args[1])
                target = self.literal_int(args[2])
                disp = self.literal_int(args[3])
            elif len(args) == 3:
                target = self.literal_int(args[1])
                disp, count = 0, None
                if isinstance(args[0], ast.Name):
                    info = self.var_buf.get(args[0].id)
                    count = info[0] if info else None
            else:
                return None
        elif op.name == "Accumulate" and len(args) >= 5:
            count = self.literal_int(args[1])
            target = self.literal_int(args[2])
            disp = self.literal_int(args[3])
        else:
            return None
        if target is None or disp is None or count is None:
            return None
        return (target, disp, disp + count)

    def _check_rma(self, u: _Unit):
        accesses = []
        for op in u.ops:
            if op.name in RMA_ACCESS:
                rng = self._rma_range(op)
                if rng is not None:
                    accesses.append((op, rng))
        for i in range(len(accesses)):
            a, (ta, loa, hia) = accesses[i]
            for j in range(i + 1, len(accesses)):
                b, (tb, lob, hib) = accesses[j]
                if ta != tb or a.epoch != b.epoch:
                    continue
                if hia <= lob or hib <= loa:
                    continue
                if a.name == "Get" and b.name == "Get":
                    continue
                if a.name == "Accumulate" and b.name == "Accumulate":
                    continue
                if a.locked and b.locked:
                    continue        # serialized by an exclusive lock
                # different rank arms, or both unconditional (every rank
                # runs both) -> concurrent origins, one target, same epoch
                if a.arm != b.arm or (a.arm == () and b.arm == ()):
                    self.diag("L108",
                              f"{a.name} (line {a.line}) and {b.name} both "
                              f"touch [{max(loa, lob)}, {min(hia, hib)}) of "
                              f"rank {ta}'s window in the same fence epoch "
                              f"with no ordering between them",
                              b.line,
                              related=((self.path, a.line,
                                        "the other access"),))


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

def lint_source(src: str, path: str = "<string>") -> List[Diagnostic]:
    """Lint one source string."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Diagnostic("L100", f"could not parse: {e.msg}", file=path,
                           line=e.lineno or 0)]
    return _Linter(path, tree).run()


def _expand(paths) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, _dirs, files in os.walk(p):
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(files) if f.endswith(".py"))
        else:
            out.append(p)
    return out


def lint_paths(paths) -> List[Diagnostic]:
    """Lint files and directories (directories recurse over ``*.py``)."""
    out: List[Diagnostic] = []
    for path in _expand(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
        except OSError as e:
            out.append(Diagnostic("L100", f"could not read: {e}", file=path))
            continue
        out.extend(lint_source(src, path))
    return out


def main(argv: Optional[List[str]] = None) -> int:
    """CLI: ``python -m tpu_mpi.lint file.py dir/ …`` — prints diagnostics,
    exits 1 if any were found."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    diags = lint_paths(argv)
    for d in diags:
        print(d)
    if diags:
        print(f"{len(diags)} diagnostic(s) in {len(_expand(argv))} file(s)")
        return 1
    return 0
