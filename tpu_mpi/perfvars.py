"""MPI_T-inspired performance variables (pvars): always-on counters + spans.

The reference ships no tracing at all (SURVEY.md: only ``Wtime``/``Wtick``;
external PMPI/MPI_T tools are assumed) — this module is the layer those
tools would have provided, owned by the runtime itself. Three cooperating
pieces:

- **Per-comm counters** keyed ``(world rank, cid)``: bytes sent/received,
  op counts per ``(collective, algorithm, dtype)``, time blocked in the
  Wait family, host-path phase time split rendezvous / fold / copy,
  chunk-pipeline overlap inputs, RMA epoch counts, and per-collective
  latency histograms (log2-µs buckets, ``config.pvars_hist_bins`` wide).
  Plan-cache hits/misses ride along at snapshot time from
  ``overlap.plans.stats()``.
- **Timed spans** on the event IR: when tracing is on, the op scope opened
  here stamps the recorded :class:`~tpu_mpi.analyze.events.Event` with
  ``t_start``/``t_end`` and the phase spans the channels observed, which
  :mod:`tpu_mpi.analyze.timeline` renders as a Chrome-trace / Perfetto
  timeline.
- **Runtime control**: the MPI-standard ``Pcontrol(level)``
  (:func:`tpu_mpi.environment.Pcontrol` delegates here) — 0 disables, 1
  enables (the default), >= 2 enables AND flushes a dump immediately.

Overhead discipline (the ``analyze.events.enabled()`` contract): every hot
hook front-loads :func:`enabled` — one tuple compare against
``config.GENERATION`` — so a ``TPU_MPI_PVARS=0`` run pays a single
predictable branch per operation; the committed
``benchmarks/results/overhead-pvars-cpusim.json`` artifact pins that.

Span-attribution caveat: phase spans collect into a thread-local op scope,
so a BLOCKING collective that routes through the nonblocking worker (only
when that comm has outstanding ``I*`` ops) keeps its counters but loses its
per-phase spans — the worker thread owns no scope for it.
"""

from __future__ import annotations

import json
import os
import threading
import time

from . import config
from . import tracectx as _tc
from typing import Any, Dict, List, Optional, Tuple

monotonic = time.monotonic

PHASES = ("rendezvous", "fold", "copy",
          # hierarchical-composite sub-phases (backend._run_hier_*)
          "intra_fold", "inter_exchange", "allgather")

_UNSET = object()
_enabled_cache: Tuple[Any, bool] = (_UNSET, False)
# Pcontrol's runtime override: None = follow config.pvars.
_level_override: Optional[int] = None
_store_lock = threading.Lock()
_store: Dict[Tuple[int, int], "CommPvars"] = {}
# bumped whenever accumulators are dropped from _store, so the per-thread
# _acct caches never keep writing into an orphaned accumulator
_store_gen = 0


class _TLS(threading.local):
    # class-attribute defaults: fresh threads read these without the
    # AttributeError/getattr-default dance on the hot path
    scope = None                      # the open _OpScope of this thread
    acct = None                       # (store_gen, {key: CommPvars}) cache
    wait_owned = False                # a wait-time owner is on the stack


_tls = _TLS()


def _config_level() -> int:
    if _level_override is not None:
        config.load()               # keep GENERATION meaningful for the gate
        return _level_override
    return int(config.load().pvars)


def enabled() -> bool:
    """Whether pvar collection is on — cached on ``config.GENERATION`` so
    the per-operation cost of a disabled run is one tuple compare."""
    global _enabled_cache
    cached_gen, val = _enabled_cache
    if cached_gen == config.GENERATION:
        return val
    val = _config_level() >= 1
    _enabled_cache = (config.GENERATION, val)
    return val


def level() -> int:
    """The effective collection level (0 off, 1 on; >= 2 behaves as 1 —
    the flush side effect belongs to :func:`pcontrol` itself)."""
    return _config_level()


def pcontrol(lvl: int) -> int:
    """Runtime toggle (the ``MPI_Pcontrol`` contract): 0 disables
    collection, 1 restores the default (the ``pvars`` config knob), and
    any level >= 2 enables collection and immediately flushes a dump to
    ``config.pvars_dump`` (when set). Returns the effective level."""
    global _level_override, _enabled_cache
    lvl = int(lvl)
    if lvl < 0:
        lvl = 0
    _level_override = None if lvl == 1 else lvl
    _enabled_cache = (config.GENERATION, _config_level() >= 1)
    if lvl >= 2:
        finalize_dump(force=True)
    return _config_level()


class CommPvars:
    """The counter set of one ``(world rank, cid)`` pair."""

    __slots__ = ("rank", "cid", "size", "bytes_sent", "bytes_recv", "sends",
                 "recvs", "wait_ns", "ops", "times", "phase_ns", "rma",
                 "hist", "pipe_ops", "pipe_chunks", "pipe_fold_ns",
                 "pipe_wait_ns", "explore_calls", "explore_explored",
                 "table_swaps", "last_swap_gen", "batch_flushes",
                 "batch_ops")

    def __init__(self, rank: int, cid: int):
        self.rank = rank
        self.cid = cid
        self.size = 0
        self.bytes_sent = 0
        self.bytes_recv = 0
        self.sends = 0
        self.recvs = 0
        self.wait_ns = 0
        # (coll, algo, dtype) -> op count
        self.ops: Dict[Tuple[str, str, str], int] = {}
        # (coll, algo, nbytes) -> [count, total_ns, min_ns, max_ns]
        self.times: Dict[Tuple[str, str, int], List[int]] = {}
        self.phase_ns = {p: 0 for p in PHASES}
        self.rma = {"fence": 0, "lock": 0, "flush": 0}
        self.hist: Dict[str, List[int]] = {}      # coll -> log2-µs buckets
        # chunk-pipeline overlap inputs (see snapshot() for the derived
        # fraction): fold time + post-first-chunk rendezvous waits of
        # pipelined star roots
        self.pipe_ops = 0
        self.pipe_chunks = 0
        self.pipe_fold_ns = 0
        self.pipe_wait_ns = 0
        # online bandit autotuner (tpu_mpi.tune_online): decisions seen,
        # decisions routed to an alternate arm, hot-swaps performed on this
        # comm, and the config generation of the last swap.
        self.explore_calls = 0
        self.explore_explored = 0
        self.table_swaps = 0
        self.last_swap_gen = 0
        # batched rendezvous submission (ISSUE-11): flushes and the ops
        # they carried — occupancy = ops / flushes
        self.batch_flushes = 0
        self.batch_ops = 0

    def snapshot(self) -> dict:
        bins = max(4, int(config.load().pvars_hist_bins))
        pipe_busy = self.pipe_fold_ns + self.pipe_wait_ns
        return {
            "rank": self.rank, "cid": self.cid, "size": self.size,
            "bytes_sent": self.bytes_sent, "bytes_recv": self.bytes_recv,
            "sends": self.sends, "recvs": self.recvs,
            "wait_s": self.wait_ns / 1e9,
            "ops": {"|".join(k): v for k, v in sorted(self.ops.items())},
            "times": [{"coll": c, "algo": a, "nbytes": b, "count": t[0],
                       "total_s": t[1] / 1e9, "min_s": t[2] / 1e9,
                       "max_s": t[3] / 1e9}
                      for (c, a, b), t in sorted(self.times.items())],
            "phase_s": {p: ns / 1e9 for p, ns in self.phase_ns.items()},
            "rma": dict(self.rma),
            "hist_bins": bins,
            "hist": {c: list(h) for c, h in sorted(self.hist.items())},
            "pipeline": {
                "ops": self.pipe_ops, "chunks": self.pipe_chunks,
                "fold_s": self.pipe_fold_ns / 1e9,
                "wait_after_first_s": self.pipe_wait_ns / 1e9,
                # 1.0 = every post-first-chunk contribution had already
                # landed when the root finished the previous fold (transfer
                # fully hidden behind compute); 0.0 = fully serial
                "overlap_fraction": (round(self.pipe_fold_ns / pipe_busy, 4)
                                     if pipe_busy else None),
            },
            "explore": {
                "calls": self.explore_calls,
                "explored": self.explore_explored,
                "fraction": (round(self.explore_explored
                                   / self.explore_calls, 4)
                             if self.explore_calls else None),
                "table_swaps": self.table_swaps,
                "last_swap_gen": self.last_swap_gen,
            },
            "batch": {
                "flushes": self.batch_flushes,
                "ops": self.batch_ops,
                "occupancy": (round(self.batch_ops / self.batch_flushes, 4)
                              if self.batch_flushes else None),
            },
        }


def _acct(comm: Any = None, cid: Optional[int] = None,
          size: int = 0) -> Optional[CommPvars]:
    """The accumulator of (current world rank, comm's cid), creating it on
    first touch; None outside an SPMD environment."""
    from ._runtime import current_env
    env = current_env()
    if env is None:
        return None
    rank = env[1]
    if comm is not None:
        cid = comm.cid
    elif cid is None:
        cid = -1                      # unattributed (no comm at the hook)
    key = (rank, cid)
    cached = _tls.acct
    if cached is not None and cached[0] == _store_gen:
        acct = cached[1].get(key)
        if acct is not None:
            if comm is not None and not acct.size:
                acct.size = size or len(comm.group)
            return acct
    with _store_lock:
        acct = _store.get(key)
        if acct is None:
            acct = _store[key] = CommPvars(rank, cid)
        if comm is not None and not acct.size:
            acct.size = size or len(comm.group)
    if cached is None or cached[0] != _store_gen:
        cached = _tls.acct = (_store_gen, {})
    cached[1][key] = acct
    return acct


# ---------------------------------------------------------------------------
# Op scope: per-op span collection shared with the event IR
# ---------------------------------------------------------------------------

class _OpScope:
    __slots__ = ("t0", "spans", "ev", "trace")

    def __init__(self):
        self.t0 = monotonic()
        self.spans: List[Tuple[str, float, float]] = []
        self.ev: Any = None           # the trace Event of this op, if any
        self.trace: Any = None        # the request TraceCtx, when sampled


def scope() -> Optional[_OpScope]:
    """The open op scope of this thread (channels append phase spans to
    ``scope().spans``), or None."""
    return _tls.scope


def op_begin() -> Optional[_OpScope]:
    """Open an op scope on this thread. Returns None when one is already
    open — the outermost owner finalizes (``_reduce_family`` wraps ``_run``
    so the copy-out phase lands inside the same scope)."""
    if _tls.scope is not None:
        return None
    sc = _OpScope()
    if _tc.enabled():
        # request tracing: adopt the TraceCtx the serve-tier rank worker
        # bound to this thread, so the op's phase spans become children of
        # the request span (one tuple compare when sampling is off)
        sc.trace = _tc.current()
    _tls.scope = sc
    return sc


def op_end(sc: _OpScope, comm: Any = None, coll: Optional[str] = None,
           algo: Optional[str] = None, dtype: Optional[str] = None,
           nbytes: Optional[int] = None) -> None:
    """Close the scope: stamp the op's trace event (t_start/t_end/phases)
    and fold duration + spans into the per-comm counters."""
    _tls.scope = None
    shim = _shim_map()
    if shim and coll is not None:
        # test/debug latency shim (config.tune_shim): the sleep lands
        # BEFORE t1 so it is part of the measured span and is attributed
        # to this (coll, algo) arm — the knob the bandit-convergence tests
        # use to make one arm deterministically lose.
        pause = shim.get((coll, algo or "star"))
        if pause:
            time.sleep(pause)
    t1 = monotonic()
    ev = sc.ev
    if ev is not None:
        ev.t_start = sc.t0
        ev.t_end = t1
        if sc.spans:
            ev.phases = list(sc.spans)
    if sc.trace is not None:
        # per-rank request span: the op bracket parents under the request
        # context, and each measured phase nests under the op span
        from ._runtime import current_env
        env = current_env()
        who = f"rank {env[1]}" if env is not None else "rank ?"
        rec = _tc.emit_span(sc.trace, coll or "op", who, sc.t0, t1,
                            algo=algo, nbytes=nbytes)
        if rec is not None and sc.spans:
            pctx = _tc.TraceCtx(rec["trace"], rec["span"], True)
            for name, s0, s1 in sc.spans:
                _tc.emit_span(pctx, name, who, s0, s1)
    if not enabled() or coll is None:
        return
    acct = _acct(comm)
    if acct is None:
        return
    bins = max(4, int(config.load().pvars_hist_bins))
    dur_ns = int((t1 - sc.t0) * 1e9)
    key = (coll, algo or "star", -1 if nbytes is None else int(nbytes))
    with _store_lock:
        okey = (coll, algo or "star", dtype or "?")
        acct.ops[okey] = acct.ops.get(okey, 0) + 1
        t = acct.times.get(key)
        if t is None:
            acct.times[key] = [1, dur_ns, dur_ns, dur_ns]
        else:
            t[0] += 1
            t[1] += dur_ns
            if dur_ns < t[2]:
                t[2] = dur_ns
            if dur_ns > t[3]:
                t[3] = dur_ns
        for name, s0, s1 in sc.spans:
            if name in acct.phase_ns:
                acct.phase_ns[name] += int((s1 - s0) * 1e9)
        hist = acct.hist.get(coll)
        if hist is None:
            hist = acct.hist[coll] = [0] * bins
        idx = (dur_ns // 1000).bit_length()   # log2 bucket of the µs latency
        hist[min(idx, len(hist) - 1)] += 1


# -- test/debug latency shim (config.tune_shim) ------------------------------

_shim_cache: Tuple[Any, Optional[Dict[Tuple[str, str], float]]] = (_UNSET, None)


def _shim_map() -> Optional[Dict[Tuple[str, str], float]]:
    """Parsed ``tune_shim`` spec ("coll:algo=microseconds,...") as
    {(coll, algo): seconds}, or None when unset. Generation-cached: the
    default (empty) spec costs one tuple compare per op."""
    global _shim_cache
    cached_gen, val = _shim_cache
    if cached_gen == config.GENERATION:
        return val
    spec = config.load().tune_shim
    out: Optional[Dict[Tuple[str, str], float]] = None
    if spec:
        out = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            key, _, us = part.partition("=")
            coll, _, algo = key.partition(":")
            try:
                out[(coll.strip(), (algo or "star").strip())] = \
                    float(us) / 1e6
            except ValueError:
                pass
        out = out or None
    _shim_cache = (config.GENERATION, out)
    return out


def payload_nbytes(contrib: Any) -> Optional[int]:
    """Wire size of a collective contribution for the bandwidth counters
    (rooted contributions arrive as ``(root, payload)`` tuples)."""
    if isinstance(contrib, tuple) and len(contrib) == 2:
        contrib = contrib[1]
    nb = getattr(contrib, "nbytes", None)
    if nb is None:
        return None
    dt = getattr(contrib, "dtype", None)
    if dt is None or dt == object:
        return None
    return int(nb)


# ---------------------------------------------------------------------------
# Hot-path counter hooks (call sites gate on enabled())
# ---------------------------------------------------------------------------

def add_send(comm: Any, nbytes: int, wait_ns: int = 0) -> None:
    acct = _acct(comm)
    if acct is None:
        return
    with _store_lock:
        acct.sends += 1
        acct.bytes_sent += int(nbytes or 0)
        acct.wait_ns += int(wait_ns)


def add_recv(comm: Any, nbytes: int, wait_ns: int = 0) -> None:
    acct = _acct(comm)
    if acct is None:
        return
    with _store_lock:
        acct.recvs += 1
        acct.bytes_recv += int(nbytes or 0)
        acct.wait_ns += int(wait_ns)


def add_wait(wait_s: float, comm: Any = None, cid: Optional[int] = None) -> None:
    """Time blocked in the Wait/Test family (unattributed waits land on the
    pseudo-cid -1)."""
    acct = _acct(comm, cid=cid)
    if acct is None:
        return
    with _store_lock:
        acct.wait_ns += int(wait_s * 1e9)


# -- wait-time ownership (the outermost-owner rule for wait_ns) -------------
#
# A persistent collective round is fully accounted by the op scope its
# worker (or the inline registered fast path) owns: the round's wall clock
# lands in ``times`` and its blocked share in ``phase_ns["rendezvous"]``.
# The caller blocked in ``Wait`` covers the SAME wall clock, so letting the
# inner ``CollRequest.wait`` also bump ``wait_ns`` double-counts it — the
# overhead_probe --pvars bug ISSUE-6 names. ``PersistentCollRequest`` claims
# ownership around its inner wait; nested add_wait callers check
# :func:`wait_owned` first and stand down.

def own_wait() -> bool:
    """Claim wait-time ownership for this thread. Returns True when the
    claim is fresh (caller must :func:`disown_wait` in a finally); False
    when an outer owner already holds it."""
    if _tls.wait_owned:
        return False
    _tls.wait_owned = True
    return True


def disown_wait() -> None:
    """Release the wait-time claim taken by :func:`own_wait`."""
    _tls.wait_owned = False


def wait_owned() -> bool:
    """True while an outer wait-time owner is on this thread's stack —
    nested waits must not call :func:`add_wait`."""
    return _tls.wait_owned


def note_rma(comm: Any, kind: str) -> None:
    """One RMA epoch event: kind in {fence, lock, flush}."""
    acct = _acct(comm)
    if acct is None:
        return
    with _store_lock:
        if kind in acct.rma:
            acct.rma[kind] += 1


def note_pipelined(cid: int, nchunks: int, fold_ns: int,
                   wait_after_first_ns: int) -> None:
    """One chunk-pipelined star fold at the root: the overlap-fraction
    inputs (fold time vs rendezvous waits AFTER the first chunk — waits
    that a perfectly overlapped pipeline hides behind the fold)."""
    acct = _acct(cid=cid)
    if acct is None:
        return
    with _store_lock:
        acct.pipe_ops += 1
        acct.pipe_chunks += int(nchunks)
        acct.pipe_fold_ns += int(fold_ns)
        acct.pipe_wait_ns += int(wait_after_first_ns)


def note_batch(cid: int, nops: int) -> None:
    """One batched-submission flush on this comm (ISSUE-11): ``nops``
    queued ops went through one rendezvous round trip."""
    acct = _acct(cid=cid)
    if acct is None:
        return
    with _store_lock:
        acct.batch_flushes += 1
        acct.batch_ops += int(nops)


# -- inference-engine block (tpu_mpi.infer) ----------------------------------
#
# Process-global (the engine spans every pool rank, so per-comm attribution
# would just smear one logical step over three comms): counters accumulate,
# gauges overwrite. Snapshot surfaces them as the top-level "infer" block
# next to plan_cache.

_infer: Dict[str, int] = {}
_infer_gauges: Dict[str, int] = {}


def note_infer(**counts: int) -> None:
    """Accumulate inference-engine counters (steps, tokens, batch_slots,
    prefills, step_ns, pwait_ns, stage_serial_ns, slo_hits/misses/
    evictions, ...)."""
    with _store_lock:
        for k, v in counts.items():
            _infer[k] = _infer.get(k, 0) + int(v)


def set_infer_gauges(**vals: int) -> None:
    """Overwrite inference-engine gauges (KV pressure, max_batch)."""
    with _store_lock:
        for k, v in vals.items():
            _infer_gauges[k] = int(v)


def infer_snapshot() -> dict:
    """The infer block of :func:`snapshot` (may be empty): accumulated
    counters plus the latest gauges under ``"gauges"``."""
    with _store_lock:
        if not _infer and not _infer_gauges:
            return {}
        return {**_infer, "gauges": dict(_infer_gauges)}


# -- training block (tpu_mpi.train) ------------------------------------------
#
# Process-global like the infer block: a training step spans every rank of
# the job, and the trainer lives above any single comm. Counters (steps,
# buckets, bucket_flushes, starts, waits, reshards, wait_ns,
# comm_window_ns, step_ns) accumulate; gauges (nbuckets, bucket_bytes,
# world) overwrite. A bounded per-step sample list feeds the stats
# renderer's p50/p99 without unbounded growth.

_train: Dict[str, int] = {}
_train_gauges: Dict[str, int] = {}
_train_steps: List[int] = []
_TRAIN_STEP_CAP = 4096


def note_train(**counts: int) -> None:
    """Accumulate training counters (steps, bucket_flushes, starts,
    waits, reshards, wait_ns, comm_window_ns, step_ns, ...)."""
    with _store_lock:
        for k, v in counts.items():
            _train[k] = _train.get(k, 0) + int(v)


def set_train_gauges(**vals: int) -> None:
    """Overwrite training gauges (nbuckets, bucket_bytes, world)."""
    with _store_lock:
        for k, v in vals.items():
            _train_gauges[k] = int(v)


def note_train_step(ns: int) -> None:
    """Record one optimizer-step duration sample (nanoseconds) for the
    p50/p99 rendering; also accumulates steps/step_ns counters."""
    with _store_lock:
        _train["steps"] = _train.get("steps", 0) + 1
        _train["step_ns"] = _train.get("step_ns", 0) + int(ns)
        if len(_train_steps) < _TRAIN_STEP_CAP:
            _train_steps.append(int(ns))


def train_snapshot() -> dict:
    """The train block of :func:`snapshot` (may be empty): accumulated
    counters, latest gauges under ``"gauges"``, and the bounded step-time
    sample list under ``"step_ns_samples"``."""
    with _store_lock:
        if not _train and not _train_gauges:
            return {}
        return {**_train, "gauges": dict(_train_gauges),
                "step_ns_samples": list(_train_steps)}


# -- elastic-capacity block (tpu_mpi.elastic) ---------------------------------
#
# Process-global like the infer block: resizes span the whole pool, so
# per-comm attribution is meaningless. Counters (resizes, rebinds, grown,
# shrunk, failures) accumulate; gauges (pool_size, target_size, degraded)
# overwrite.

_elastic: Dict[str, int] = {}
_elastic_gauges: Dict[str, int] = {}


def note_elastic(**counts: int) -> None:
    """Accumulate elastic-capacity counters (resizes, rebinds, grown,
    shrunk, failures, ...)."""
    with _store_lock:
        for k, v in counts.items():
            _elastic[k] = _elastic.get(k, 0) + int(v)


def set_elastic_gauges(**vals: int) -> None:
    """Overwrite elastic-capacity gauges (pool_size, target_size,
    degraded)."""
    with _store_lock:
        for k, v in vals.items():
            _elastic_gauges[k] = int(v)


def elastic_snapshot() -> dict:
    """The elastic block of :func:`snapshot` (may be empty): accumulated
    counters plus the latest gauges under ``"gauges"``."""
    with _store_lock:
        if not _elastic and not _elastic_gauges:
            return {}
        return {**_elastic, "gauges": dict(_elastic_gauges)}


# -- serve frame-path block (tpu_mpi.serve) ----------------------------------
#
# Process-global like the infer block: the session/mailbox frame path spans
# every tenant connection, so per-comm attribution would smear one wire hop
# over many comms. ``ops`` counts OP/RESULT frames carrying array payloads,
# ``copies`` counts payload materializations (ascontiguousarray / tobytes /
# non-view marshalling) on that path — the zero-copy acceptance gate is
# copies/ops <= 1 — ``sg_writes`` counts scatter-gather sendmsg calls and
# ``zc_bytes`` the payload bytes that travelled as views.

_serve_frame: Dict[str, int] = {}


def note_serve_frame(**counts: int) -> None:
    """Accumulate serve frame-path counters (ops, copies, sg_writes,
    zc_bytes, ...)."""
    with _store_lock:
        for k, v in counts.items():
            _serve_frame[k] = _serve_frame.get(k, 0) + int(v)


def serve_frame_snapshot() -> dict:
    """The serve_frame block of :func:`snapshot` (may be empty)."""
    with _store_lock:
        return dict(_serve_frame)


# -- front-door block (tpu_mpi.serve.frontdoor) ------------------------------
#
# Process-global like the serve_frame block: the event-driven session
# transport multiplexes every attached socket on one readiness loop, so
# per-comm attribution would smear loop mechanics over tenants. Counters
# accumulate (attaches, wakeups, frames, lease_hits/lease_misses/
# lease_drops, splice_bytes); gauges overwrite (open_sockets, workers,
# workers_busy).

_front_door: Dict[str, int] = {}
_front_door_gauges: Dict[str, int] = {}


def note_front_door(**counts: int) -> None:
    """Accumulate front-door counters (attaches, wakeups, frames,
    lease_hits, lease_misses, lease_drops, splice_bytes, ...)."""
    with _store_lock:
        for k, v in counts.items():
            _front_door[k] = _front_door.get(k, 0) + int(v)


def set_front_door_gauges(**vals: int) -> None:
    """Overwrite front-door gauges (open_sockets, workers, workers_busy)."""
    with _store_lock:
        for k, v in vals.items():
            _front_door_gauges[k] = int(v)


def front_door_snapshot() -> dict:
    """The front_door block of :func:`snapshot` (may be empty): accumulated
    counters plus the latest gauges under ``"gauges"``."""
    with _store_lock:
        if not _front_door and not _front_door_gauges:
            return {}
        return {**_front_door, "gauges": dict(_front_door_gauges)}


# -- lock-contention block (tpu_mpi.locksmith) -------------------------------
#
# Populated only when the lock witness is armed (TPU_MPI_LOCKCHECK=1):
# per named lock, how many acquisitions there were, how many had to wait
# behind another holder, and the longest single hold in nanoseconds.
# Process-global like the serve_frame block — lock names already carry
# their subsystem (``broker.dispatch``, ``pool.queues``, ...).

_locks: Dict[str, Dict[str, int]] = {}


def note_lock(name: str, acquires: int = 0, contended: int = 0,
              held_ns: int = 0) -> None:
    """Accumulate contention counters for one named lock. ``held_ns`` is
    a single observed hold time; the block keeps the max."""
    with _store_lock:
        row = _locks.get(name)
        if row is None:
            row = _locks[name] = {"acquires": 0, "contended": 0,
                                  "max_held_ns": 0}
        row["acquires"] += int(acquires)
        row["contended"] += int(contended)
        if held_ns > row["max_held_ns"]:
            row["max_held_ns"] = int(held_ns)


def locks_snapshot() -> dict:
    """The locks block of :func:`snapshot` (empty when the witness is off)."""
    with _store_lock:
        return {k: dict(v) for k, v in _locks.items()}


def note_explore(comm: Any, explored: bool) -> None:
    """One online-autotuner decision on this comm (tpu_mpi.tune_online):
    ``explored`` when the call was routed to an alternate arm."""
    acct = _acct(comm)
    if acct is None:
        return
    with _store_lock:
        acct.explore_calls += 1
        if explored:
            acct.explore_explored += 1


def note_swap(comm: Any, generation: int) -> None:
    """One online table hot-swap on this comm."""
    acct = _acct(comm)
    if acct is None:
        return
    with _store_lock:
        acct.table_swaps += 1
        acct.last_swap_gen = int(generation)


def arm_stats(comm: Any) -> List[Tuple[str, str, int, int, int]]:
    """This rank's accumulated latency stats on one comm as
    ``(coll, algo, nbytes, count, total_ns)`` rows — the payload the
    online autotuner's lockstep swap round allgathers so that every rank
    merges the IDENTICAL cross-rank arm statistics."""
    from ._runtime import current_env
    env = current_env()
    if env is None:
        return []
    key = (env[1], comm.cid)
    with _store_lock:
        acct = _store.get(key)
        if acct is None:
            return []
        return [(c, a, b, t[0], t[1])
                for (c, a, b), t in sorted(acct.times.items())]


# ---------------------------------------------------------------------------
# Snapshot / reset / dump
# ---------------------------------------------------------------------------

def _topology_stamp() -> str:
    """The ``topology_key`` of the world these counters describe — stamped
    into every dump record so ``tune merge`` can attribute samples to the
    right fabric without a side channel. Derived from the live context
    (domain map over the full world) when one is attached, else from
    config alone (a flat default — better unstamped-conservative than
    wrong)."""
    from . import topology as _topo
    try:
        from ._runtime import current_env
        env = current_env()
        if env is not None:
            ctx = env[0]
            n = int(getattr(ctx, "size", 0) or 0)
            if n >= 2:
                dom = _topo.domain_count(ctx, tuple(range(n)))
                return _topo.topology_key(dom, n)
    except Exception:
        pass
    return _topo.topology_key(int(config.load().domains), 0)


def snapshot(rank: Optional[int] = None, reset: bool = False) -> dict:
    """Machine-readable dump of every counter (one rank, or all ranks this
    process has accumulated). Stable schema — ``tpu_mpi.stats`` and
    ``tune.table_from_pvars`` consume exactly this."""
    global _store_gen
    from .overlap import plans
    with _store_lock:
        # cids mix ints and recovery tuples (("shrink", cid, epoch)) in one
        # store — sort through str so the dump order is still deterministic
        keys = [k for k in sorted(_store, key=lambda k: (k[0], str(k[1])))
                if rank is None or k[0] == rank]
        comms = [_store[k].snapshot() for k in keys]
        if reset:
            for k in keys:
                del _store[k]
            _store_gen += 1
    return {"schema": 1, "kind": "tpu_mpi-pvars", "level": level(),
            "topology": _topology_stamp(),
            "comms": comms, "plan_cache": plans.stats(),
            "infer": infer_snapshot(), "train": train_snapshot(),
            "elastic": elastic_snapshot(),
            "serve_frame": serve_frame_snapshot(),
            "front_door": front_door_snapshot(),
            "locks": locks_snapshot()}


def comm_snapshot(comm: Any, reset: bool = False) -> dict:
    """``Comm.get_pvars`` backend: this rank's counters on one comm."""
    global _store_gen
    from ._runtime import require_env
    _, rank = require_env()
    key = (rank, comm.cid)
    with _store_lock:
        acct = _store.get(key)
        snap = acct.snapshot() if acct is not None \
            else CommPvars(rank, comm.cid).snapshot()
        if reset and acct is not None:
            del _store[key]
            _store_gen += 1
    return snap


def reset() -> None:
    """Drop every accumulated counter (all ranks of this process)."""
    global _store_gen
    with _store_lock:
        _store.clear()
        _infer.clear()
        _infer_gauges.clear()
        _train.clear()
        _train_gauges.clear()
        _train_steps.clear()
        _elastic.clear()
        _elastic_gauges.clear()
        _serve_frame.clear()
        _front_door.clear()
        _front_door_gauges.clear()
        _locks.clear()
        _store_gen += 1


def dump(path: str, rank: Optional[int] = None, reset: bool = False) -> str:
    """Write :func:`snapshot` as JSON; returns the path."""
    rec = snapshot(rank=rank, reset=reset)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_dumps(paths) -> List[dict]:
    """Read pvar dump records from files and/or directories (a directory
    contributes every ``pvars-rank*.json`` / ``*.json`` file in it).
    Consumers: ``tpu_mpi.stats`` and ``tune.table_from_pvars``."""
    files: List[str] = []
    for p in paths:
        p = os.path.expanduser(p)
        if os.path.isdir(p):
            names = sorted(os.listdir(p))
            picked = [n for n in names if n.startswith("pvars-rank")
                      and n.endswith(".json")]
            files.extend(os.path.join(p, n) for n in
                         (picked or [n for n in names if n.endswith(".json")]))
        else:
            files.append(p)
    recs = []
    for f in files:
        with open(f) as fh:
            rec = json.load(fh)
        if rec.get("kind") != "tpu_mpi-pvars":
            raise ValueError(f"{f}: not a tpu_mpi pvar dump")
        rec["_path"] = f
        recs.append(rec)
    return recs


def finalize_dump(force: bool = False) -> Optional[str]:
    """Per-rank dump at Finalize (and at ``Pcontrol(level >= 2)``): when
    ``config.pvars_dump`` names a directory, this rank writes
    ``pvars-rank<R>.json`` there. Costs one branch when pvars are off."""
    if not (enabled() or force):
        return None
    from ._runtime import current_env
    d = config.load().pvars_dump
    if not d:
        return None
    env = current_env()
    rank = env[1] if env is not None else 0
    return dump(os.path.join(os.path.expanduser(d), f"pvars-rank{rank}.json"),
                rank=rank)
