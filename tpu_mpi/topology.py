"""Cartesian topology: process grids, neighbor discovery, sub-grids.

Reference: /root/reference/src/topology.jl — Dims_create! (:9-20), Cart_create
(:30-49), Cart_rank (:60-72), Cart_get (:85-96), Cartdim_get (:106-113),
Cart_coords (:123-144), Cart_shift (:155-164), Cart_sub (:178-194).

TPU mapping (SURVEY.md §2.3): a Cartesian communicator *is* the device-mesh
concept — ``jax.sharding.Mesh`` is an N-d grid of devices with named axes.
``CartComm`` carries (dims, periods) and exposes ``mesh_axes()`` so the
in-graph layer can bind mesh axes to grid dimensions; ``Cart_shift`` yields
exactly the permutation ``lax.ppermute`` needs for halo exchange or ring
steps. Rank ordering is row-major (last dim fastest), matching MPI.
"""

from __future__ import annotations

import math
import os
from typing import Optional, Sequence, Tuple

import numpy as np

from ._runtime import PROC_NULL
from .comm import COMM_NULL, Comm, Comm_split
from . import error as _ec
from .error import MPIError


def _mapping_devices() -> list:
    """Device list used for torus-aware rank mapping (monkeypatchable in
    tests to simulate a multi-chip torus on the CPU substrate)."""
    try:
        import jax
        return list(jax.devices())
    except Exception:
        return []


def _arrange_devices(dims: Sequence[int], devices: Sequence) -> Optional[list]:
    """Arrange ``devices`` into a row-major grid of shape ``dims`` such that
    grid neighbors are physical ICI neighbors, or None when no such
    arrangement is derivable (SURVEY.md §2.3: "map ranks to physical torus
    coordinates for bandwidth"; reference substrate src/topology.jl:30-49).

    Strategy: match each non-trivial grid dimension to a distinct physical
    torus axis of equal size (``device.coords``); a device's grid position is
    then its physical coordinate along the matched axes, so a ±1 grid shift
    is a ±1 move on the physical torus — exactly an ICI link. Falls back to
    ``mesh_utils.create_device_mesh`` (which optimizes harder shapes) when
    exact axis matching fails."""
    dims = [int(d) for d in dims]
    n = math.prod(dims)
    if len(devices) != n or n <= 1:
        return None
    coords = [tuple(getattr(d, "coords", None) or ()) for d in devices]
    ndim_phys = len(coords[0]) if coords[0] else 0
    if ndim_phys and all(len(c) == ndim_phys for c in coords):
        bounds = [max(c[j] for c in coords) + 1 for j in range(ndim_phys)]
        # greedily bind each non-trivial grid axis to an unused physical
        # axis of the same size (largest first, so equal sizes pair up)
        phys_axis: dict[int, int] = {}
        free = [j for j in range(ndim_phys) if bounds[j] > 1]
        ok = True
        for i in sorted((i for i, d in enumerate(dims) if d > 1),
                        key=lambda i: -dims[i]):
            for j in free:
                if bounds[j] == dims[i]:
                    phys_axis[i] = j
                    free.remove(j)
                    break
            else:
                ok = False
                break
        if ok and not free:        # every non-trivial physical axis consumed
            pos: dict[tuple, object] = {}
            for dev, c in zip(devices, coords):
                gc = tuple(c[phys_axis[i]] if i in phys_axis else 0
                           for i in range(len(dims)))
                if gc in pos:      # >1 device per chip coord (multi-core)
                    pos = {}
                    break
                pos[gc] = dev
            if len(pos) == n:
                out = []
                for p in range(n):
                    gc, r = [], p
                    for d in reversed(dims):
                        gc.append(r % d)
                        r //= d
                    out.append(pos[tuple(reversed(gc))])
                return out
    try:
        from jax.experimental import mesh_utils
        mesh = mesh_utils.create_device_mesh(tuple(dims), devices=list(devices))
        return list(mesh.flat)
    except Exception:
        return None


def Dims_create(nnodes: int, dims: Sequence[int]) -> list[int]:
    """Balanced factorization of nnodes over len(dims) dimensions
    (ref ``Dims_create!`` :9-20). Nonzero entries are constraints; zero
    entries are filled so the dims are as close to each other as possible
    (larger dims first), and prod(dims) == nnodes.

    Torus-aware: when every entry is free and the job spans a physical ICI
    torus of the same dimensionality and size
    (:func:`tpu_mpi.implementations.ici_topology`), the fill is the torus
    bounds themselves (in MPI's non-increasing order) — so a subsequent
    ``Cart_create(..., reorder=True)`` can bind every grid axis to a
    physical axis exactly and grid neighbors ride single ICI links."""
    dims = [int(d) for d in dims]
    if any(d < 0 for d in dims):
        raise MPIError(f"negative entry in dims {dims}", code=_ec.ERR_DIMS)
    if dims and all(d == 0 for d in dims):
        from .implementations import ici_topology
        torus = ici_topology()
        if torus:
            bounds = sorted((b for b in torus if b > 1), reverse=True)
            if len(bounds) == len(dims) and math.prod(bounds) == nnodes:
                return bounds
    fixed = math.prod(d for d in dims if d > 0) if any(d > 0 for d in dims) else 1
    free = [i for i, d in enumerate(dims) if d == 0]
    if fixed <= 0 or nnodes % fixed != 0:
        raise MPIError(f"cannot partition {nnodes} nodes over fixed dims {dims}",
                       code=_ec.ERR_DIMS)
    rem = nnodes // fixed
    if not free:
        if rem != 1:
            raise MPIError(f"dims {dims} do not multiply to {nnodes}",
                           code=_ec.ERR_DIMS)
        return dims
    # Greedy balanced factorization: repeatedly take the largest factor of
    # `rem` not exceeding its k-th root.
    k = len(free)
    factors: list[int] = []
    for i in range(k, 0, -1):
        target = round(rem ** (1.0 / i))
        f = 1
        for cand in range(target, 0, -1):
            if rem % cand == 0:
                f = cand
                break
        # Prefer a slightly larger divisor when the rounded root misses.
        cand = target + 1
        while f == 1 and cand <= rem:
            if rem % cand == 0:
                f = cand
                break
            cand += 1
        factors.append(f)
        rem //= f
    factors.sort(reverse=True)
    for i, f in zip(free, factors):
        dims[i] = f
    return dims


class CartComm(Comm):
    """A communicator with an attached N-d grid (ref Cart_create :30-49)."""

    def __init__(self, group, cid, dims: Sequence[int], periods: Sequence[bool],
                 name: str = "cart", devices: Optional[list] = None):
        super().__init__(group, cid, name=name)
        self.dims = tuple(int(d) for d in dims)
        self.periods = tuple(bool(p) for p in periods)
        # grid-ordered device list (cart rank r owns _devices[r]) when the
        # rank<->device contract holds; basis of device_mesh()
        self._devices = devices

    # -- rank <-> coords (row-major, last dim fastest: MPI order) ------------
    def rank_of_coords(self, coords: Sequence[int]) -> int:
        r = 0
        for d, p, c in zip(self.dims, self.periods, coords):
            c = int(c)
            if c < 0 or c >= d:
                if not p:
                    raise MPIError(f"coordinate {c} out of range for non-periodic "
                                   f"dim of size {d}")
                c %= d
            r = r * d + c
        return r

    def coords_of_rank(self, rank: int) -> list[int]:
        coords = []
        r = int(rank)
        for d in reversed(self.dims):
            coords.append(r % d)
            r //= d
        return list(reversed(coords))

    def mesh_axes(self) -> dict[str, int]:
        """Axis-name → size mapping for building a jax.sharding.Mesh with the
        same shape as this grid (the TPU-native face of Cart topology)."""
        return {f"cart{i}": d for i, d in enumerate(self.dims)}

    def device_mesh(self, axis_names: Optional[Sequence[str]] = None):
        """The ``jax.sharding.Mesh`` whose axis layout honors this grid:
        position ``coords`` of the mesh holds cart rank
        ``rank_of_coords(coords)``'s device, so with ``reorder=True`` mesh
        neighbors are physical ICI neighbors. This is the bridge from MPI
        Cart topology to the in-graph tier (``tpu_mpi.xla`` collectives run
        inside ``shard_map`` over this mesh)."""
        from jax.sharding import Mesh
        devs = self._devices
        if devs is None:
            devices = _mapping_devices()
            if len(devices) < self.size() or not all(
                    w < len(devices) for w in self.group):
                raise MPIError(
                    "no rank<->device mapping for this communicator: the "
                    "grid has no attached devices and world ranks exceed "
                    "the device inventory")
            devs = [devices[w] for w in self.group]
        arr = np.empty(len(devs), dtype=object)
        for i, d in enumerate(devs):
            arr[i] = d
        return Mesh(arr.reshape(self.dims),
                    tuple(axis_names) if axis_names is not None
                    else tuple(f"cart{i}" for i in range(len(self.dims))))


def Cart_create(comm: Comm, *args) -> Comm:
    """``Cart_create(comm, [ndims,] dims, periods, reorder)`` — collective;
    ranks beyond prod(dims) get COMM_NULL (ref :30-49).

    ``reorder=True`` honors the physical ICI torus: when the job's ranks map
    1:1 onto the device inventory (the SPMD rank<->device-index contract)
    and an arrangement exists that makes grid neighbors physical neighbors
    (:func:`_arrange_devices`), each rank's new cart rank is its device's
    grid position — so ``Cart_shift`` neighbors are one ICI hop apart and
    halo exchanges never cross the torus diagonally. Without a derivable
    arrangement (CPU sim, thread tier over one chip, rank/device mismatch)
    rank order is preserved, matching the reference's freedom to ignore
    reorder (src/topology.jl:30-49)."""
    if len(args) == 4:
        ndims, dims, periods, reorder = args
        dims = list(dims)[:int(ndims)]
        periods = list(periods)[:int(ndims)]
    elif len(args) == 3:
        dims, periods, reorder = args
        dims = [int(d) for d in np.ravel(np.asarray(dims))]
        periods = list(np.ravel(np.asarray(periods)))
    else:
        raise TypeError("Cart_create(comm, [ndims,] dims, periods, reorder)")
    dims = [int(d) for d in dims]
    periods = [bool(p) for p in periods]
    n = math.prod(dims)
    if n > comm.size():
        raise MPIError(f"grid {dims} needs {n} ranks, comm has {comm.size()}",
                       code=_ec.ERR_TOPOLOGY)
    rank = comm.rank()
    key = rank
    grid_devices = None
    if reorder and n == comm.size():
        devices = _mapping_devices()
        if len(devices) == n and all(w < n for w in comm.group):
            arranged = _arrange_devices(dims, devices)
            if arranged is not None:
                # cart rank of a member = grid position of its device; the
                # split's (key, rank) sort realizes the permutation. Every
                # rank computes the same arrangement deterministically.
                pos_of_id = {d.id: p for p, d in enumerate(arranged)}
                key = pos_of_id[devices[comm.group[rank]].id]
                grid_devices = arranged
    color = 0 if rank < n else None
    sub = Comm_split(comm, color, key if rank < n else rank)
    if sub is COMM_NULL:
        return COMM_NULL
    return CartComm(sub.group, sub.cid, dims, periods,
                    name=f"{comm.name}.cart{tuple(dims)}",
                    devices=grid_devices)


def Cart_rank(comm: CartComm, coords: Sequence[int]) -> int:
    """Rank at grid coordinates (ref :60-72)."""
    return comm.rank_of_coords(coords)


def Cart_coords(comm: CartComm, rank: Optional[int] = None) -> list[int]:
    """Grid coordinates of a rank (calling rank by default) (ref :123-144)."""
    return comm.coords_of_rank(comm.rank() if rank is None else rank)


def Cart_get(comm: CartComm):
    """(dims, periods, coords) of the calling rank (ref :85-96)."""
    return (list(comm.dims), [int(p) for p in comm.periods],
            comm.coords_of_rank(comm.rank()))


def Cartdim_get(comm: CartComm) -> int:
    """Number of grid dimensions (ref :106-113)."""
    return len(comm.dims)


def Cart_shift(comm: CartComm, direction: int, disp: int):
    """(source, dest) ranks for a shift along a dimension (ref :155-164).

    ``dest`` is ``disp`` steps forward, ``source`` is ``disp`` steps backward;
    off-grid neighbors of non-periodic dimensions are PROC_NULL — exactly the
    permutation table a ``ppermute`` halo exchange needs."""
    coords = comm.coords_of_rank(comm.rank())
    d = comm.dims[direction]
    periodic = comm.periods[direction]

    def neighbor(offset: int) -> int:
        c = coords[direction] + offset
        if 0 <= c < d or periodic:
            nc = list(coords)
            nc[direction] = c % d
            return comm.rank_of_coords(nc)
        return PROC_NULL

    return neighbor(-disp), neighbor(disp)


def Cart_sub(comm: CartComm, remain_dims: Sequence) -> Comm:
    """Sub-grid keeping the dimensions flagged in remain_dims (ref :178-194).

    Ranks sharing the coordinates of the *dropped* dimensions form one
    sub-communicator — axis subsetting of the device mesh."""
    remain = [bool(r) for r in remain_dims]
    if len(remain) != len(comm.dims):
        raise MPIError("remain_dims length mismatch", code=_ec.ERR_TOPOLOGY)
    coords = comm.coords_of_rank(comm.rank())
    dropped = tuple(c for c, r in zip(coords, remain) if not r)
    # Color by dropped coordinates -> unique int
    color = 0
    for c, d in zip(dropped, (dim for dim, r in zip(comm.dims, remain) if not r)):
        color = color * d + c
    key = comm.rank()
    sub = Comm_split(comm, color, key)
    sub_dims = [d for d, r in zip(comm.dims, remain) if r]
    sub_periods = [p for p, r in zip(comm.periods, remain) if r]
    sub_devices = None
    if comm._devices is not None:
        # keep the torus-honoring device attachment: a member's device is
        # its slot in the parent grid, re-indexed into the sub-grid order
        parent_rank = {w: r for r, w in enumerate(comm.group)}
        sub_devices = [comm._devices[parent_rank[w]] for w in sub.group]
    return CartComm(sub.group, sub.cid, sub_dims or [1], sub_periods or [False],
                    name=f"{comm.name}.sub", devices=sub_devices)


# ---------------------------------------------------------------------------
# Neighborhood collectives (MPI-3 MPI_Neighbor_allgather / _alltoall —
# absent from the reference v0.14.2; provided beyond parity). The
# neighborhood of a Cartesian rank is its 2*ndims Cart_shift neighbors in
# MPI order (per dimension: negative-displacement neighbor first), with
# PROC_NULL at non-periodic boundaries leaving the matching slot untouched
# (zeros in the allocating variant) — exactly the halo-exchange access
# pattern (SURVEY.md §2.5 halo row) as one collective call.
# ---------------------------------------------------------------------------

# Internal tag for neighborhood exchanges, above any sane user tag space.
_NEIGHBOR_TAG = (1 << 29) + 101


def _neighbor_list(comm: CartComm) -> list[int]:
    nbrs: list[int] = []
    for d in range(len(comm.dims)):
        src, dst = Cart_shift(comm, d, 1)
        nbrs.extend((src, dst))
    return nbrs


def _neighbor_exchange(sendblocks, recvbuf, count: int, comm: CartComm,
                       template) -> Any:
    """Shared engine: sendblocks[i] goes to neighbor i; block i of the
    result comes from neighbor i. PROC_NULL slots are zeros in the
    allocating variant and LEFT UNTOUCHED in a caller-provided recvbuf
    (MPI PROC_NULL semantics: the receive never happens, so pre-filled
    boundary values survive)."""
    from .buffers import clone_like, extract_array, write_range
    from .pointtopoint import Irecv, Isend, Waitall

    nbrs = _neighbor_list(comm)
    dtype = extract_array(template).dtype
    rows = np.zeros((len(nbrs), count), dtype=dtype)
    reqs = []
    for i, nb in enumerate(nbrs):
        if nb != PROC_NULL:
            reqs.append(Irecv(rows[i], nb, _NEIGHBOR_TAG, comm))
    for i, nb in enumerate(nbrs):
        if nb != PROC_NULL:
            reqs.append(Isend(sendblocks[i], nb, _NEIGHBOR_TAG, comm))
    Waitall(reqs)
    if recvbuf is None:
        return clone_like(template, rows)
    for i, nb in enumerate(nbrs):
        if nb != PROC_NULL:
            write_range(recvbuf, i * count, rows[i])
    return recvbuf


def Neighbor_allgather(*args) -> Any:
    """``Neighbor_allgather(send, [recv,] comm)`` — every rank sends its
    whole buffer to each Cartesian neighbor and receives each neighbor's
    buffer into slot i of the (2*ndims, count) result (MPI-3
    MPI_Neighbor_allgather; neighbor order per :func:`Cart_shift`)."""
    if len(args) == 2:
        sendbuf, comm = args
        recvbuf = None
    elif len(args) == 3:
        sendbuf, recvbuf, comm = args
    else:
        raise TypeError("Neighbor_allgather(send, [recv,] comm)")
    if not isinstance(comm, CartComm):
        raise MPIError("Neighbor_allgather requires a Cartesian communicator",
                       code=_ec.ERR_TOPOLOGY)
    from .buffers import element_count
    count = element_count(sendbuf)
    nbrs = _neighbor_list(comm)
    return _neighbor_exchange([sendbuf] * len(nbrs), recvbuf, count, comm,
                              sendbuf)


def Neighbor_alltoall(*args) -> Any:
    """``Neighbor_alltoall(send, [recv,] count, comm)`` — block i of the
    send buffer goes to neighbor i; block i of the result arrives from
    neighbor i (MPI-3 MPI_Neighbor_alltoall). ``send`` holds 2*ndims
    blocks of ``count`` elements in neighbor order."""
    if len(args) == 3:
        sendbuf, count, comm = args
        recvbuf = None
    elif len(args) == 4:
        sendbuf, recvbuf, count, comm = args
    else:
        raise TypeError("Neighbor_alltoall(send, [recv,] count, comm)")
    if not isinstance(comm, CartComm):
        raise MPIError("Neighbor_alltoall requires a Cartesian communicator",
                       code=_ec.ERR_TOPOLOGY)
    from .buffers import assert_minlength, to_wire
    count = int(count)
    nbrs = _neighbor_list(comm)
    n = len(nbrs)
    assert_minlength(sendbuf, n * count)   # the package-wide bounds guard
    flat = to_wire(sendbuf, n * count).reshape(n, count)
    return _neighbor_exchange(list(flat), recvbuf, count, comm, sendbuf)


# ---------------------------------------------------------------------------
# Domain map — the intra/inter split the hierarchical collectives run on
# ---------------------------------------------------------------------------
#
# A *domain* is a set of ranks with a fast interconnect among them (one
# host's shm segment, one ICI slice) separated from the other domains by
# a slower fabric (sockets, DCN). The two-level composite runners in
# backend.py fold inside a domain first and cross the slow fabric once
# per segment instead of once per rank. Everything here is a pure
# function of the communicator's member list plus rank-uniform inputs
# (config, the replicated rendezvous address table), so every rank of a
# communicator derives the IDENTICAL map — the property the lockstep
# selection and exploration guarantees rest on.


def domain_map(ctx, group) -> Optional[Tuple[int, ...]]:
    """Domain id per communicator position, or None when the world is
    flat. ``TPU_MPI_DOMAINS=k`` (k >= 2) partitions the communicator
    into k contiguous equal blocks — the cpu-sim override that emulates
    a multi-host split on one box. Otherwise domains come from the host
    part of the rendezvous address table (``ctx.addrs``), first
    appearance ordered; fewer than two distinct hosts means flat."""
    from . import config as _config
    n = len(group)
    if n < 2:
        return None
    k = int(_config.load().domains)
    if k >= 2:
        if k > n or n % k:
            return None
        r = n // k
        return tuple(m // r for m in range(n))
    if k == 1:
        return None            # explicit "treat as one domain" = flat
    addrs = getattr(ctx, "addrs", None) if ctx is not None else None
    if not addrs:
        return None
    try:
        hosts = [str(addrs[m]).rsplit(":", 1)[0] for m in group]
    except (IndexError, TypeError):
        return None
    ids: dict = {}
    out = []
    for h in hosts:
        if h not in ids:
            ids[h] = len(ids)
        out.append(ids[h])
    if len(ids) < 2:
        return None
    return tuple(out)


def domain_shape(dmap: Optional[Tuple[int, ...]]) -> Optional[Tuple[int, int]]:
    """``(ndomains, ranks_per_domain)`` when the map is CONTIGUOUS
    (domain ids non-decreasing along rank order) and UNIFORM (equal
    sizes), else None. The hierarchical Allreduce chains partial left
    folds across domains in rank order; only a contiguous uniform
    layout keeps that chain bit-identical to the flat star's fold, so
    anything else degrades to the flat portfolio."""
    if dmap is None:
        return None
    nd = max(dmap) + 1
    if nd < 2:
        return None
    sizes = [0] * nd
    prev = 0
    for d in dmap:
        if d < prev:
            return None        # non-contiguous: ids must be non-decreasing
        prev = d
        sizes[d] += 1
    if len(set(sizes)) != 1 or sizes[0] < 2:
        return None
    return nd, sizes[0]


def domain_count(ctx, group) -> int:
    """Number of hierarchy-usable domains for this communicator (0 when
    flat or the layout is not contiguous-uniform). This is the single
    ``domains`` signal threaded through ``_coll_select`` → ``tune``."""
    shape = domain_shape(domain_map(ctx, group))
    return shape[0] if shape is not None else 0


def topology_key(domains: int = 0, nranks: int = 0,
                 arch: Optional[str] = None) -> str:
    """Fleet-DB topology key shared by the runtime, ``tune`` sweeps and
    ``tune merge``: ``single-host/<arch>`` for flat worlds, else
    ``<D>d<R>r/<arch>`` (domain count x ranks per domain). Keys never
    contain dots so they survive both tomllib and the vendored
    mini-TOML section parser when quoted."""
    if arch is None:
        arch = os.uname().machine
    if domains < 2 or nranks < domains or nranks % domains:
        return f"single-host/{arch}"
    return f"{domains}d{nranks // domains}r/{arch}"
