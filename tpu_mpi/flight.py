"""Crash flight recorder: what the process was doing right before it died
(docs/observability.md "Flight recorder").

An always-on bounded ring of recent lifecycle notes — op dispatches, lease
grants/revocations, typed errors, failure-detector verdicts — kept cheap
enough to leave enabled in production: :func:`note` on the disabled path is
one generation-gated tuple compare (the ``analyze/events.enabled()``
discipline), and on the enabled path a lock-free slot write (one fixed list,
a monotonically increasing index modulo capacity; each slot store is atomic
under the GIL, so writers never take a lock and a torn snapshot can at worst
show one stale slot).

The ring auto-dumps to a CRC-stamped JSON file when the process hits a
fatal event: ProcFailedError / RevokedError / DeadlockError construction
(hooked in ``error.py``), a failure-detector death verdict
(``_runtime.FailureDetector``), a broker lease revocation, or SIGTERM
(:func:`install_signal_hook`). ``python -m tpu_mpi.analyze flight <dump>``
verifies the CRC and renders the timeline.

Knobs: ``TPU_MPI_FLIGHT_RING`` (capacity; 0 disables recorder and hooks),
``TPU_MPI_FLIGHT_DIR`` (dump directory, default the system temp dir).
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from . import config

_UNSET = object()
# (generation, capacity) — capacity 0 means disabled
_cap_cache: Tuple[Any, int] = (_UNSET, 0)


def _capacity() -> int:
    """The effective ring capacity, cached on ``config.GENERATION``.

    Reads ``config._cached`` directly instead of ``config.load()``: the
    error-raise hook can fire from *inside* a ``load()`` (a malformed knob
    raising under the config lock), and a recursive ``load()`` there would
    self-deadlock. Before the first successful load the recorder simply
    reports disabled."""
    global _cap_cache
    cached_gen, cap = _cap_cache
    if cached_gen == config.GENERATION:
        return cap
    cfg = config._cached
    if cfg is None:
        return 0                      # config not loaded yet; don't cache
    cap = max(0, int(cfg.flight_ring))
    _cap_cache = (config.GENERATION, cap)
    return cap


def enabled() -> bool:
    """Whether the recorder is armed (ring capacity > 0)."""
    return _capacity() > 0


class _Ring:
    """Lock-free bounded record store: one fixed slot list, writers claim
    slots through an atomic counter. A reader's snapshot may interleave
    with writers — acceptable for a post-mortem artifact."""

    __slots__ = ("cap", "slots", "_next")

    def __init__(self, cap: int):
        self.cap = cap
        self.slots: List[Optional[dict]] = [None] * cap
        self._next = itertools.count()

    def append(self, rec: dict) -> None:
        i = next(self._next)
        rec["i"] = i
        self.slots[i % self.cap] = rec

    def snapshot(self) -> List[dict]:
        recs = [r for r in self.slots if r is not None]
        recs.sort(key=lambda r: r["i"])
        return recs


_ring: Optional[_Ring] = None
_ring_gate = threading.Lock()      # ring construction only, never on append


def _get_ring() -> Optional[_Ring]:
    cap = _capacity()
    if cap <= 0:
        return None
    global _ring
    r = _ring
    if r is not None and r.cap == cap:
        return r
    with _ring_gate:
        if _ring is None or _ring.cap != cap:
            _ring = _Ring(cap)
        return _ring


def note(kind: str, **fields: Any) -> None:
    """Record one lifecycle note. Disabled path: one tuple compare."""
    if _capacity() <= 0:
        return
    ring = _get_ring()
    if ring is None:
        return
    rec: Dict[str, Any] = {"t": time.time(), "mono": time.monotonic(),
                           "kind": kind,
                           "thread": threading.current_thread().name}
    for k, v in fields.items():
        if v is not None:
            rec[k] = v if isinstance(v, (str, int, float, bool)) else repr(v)
    ring.append(rec)


def note_span(rec: dict) -> None:
    """Mirror a closed trace span into the ring (the recorder's view of
    recent request activity; called by tracectx consumers, sampled path)."""
    note("span", name=rec.get("name"), who=rec.get("who"),
         trace=rec.get("trace"), status=rec.get("status"),
         dur_us=int(((rec.get("t1") or 0) - (rec.get("t0") or 0)) * 1e6))


# ---------------------------------------------------------------------------
# Error hook (called lazily from tpu_mpi.error.MPIError.__init__)
# ---------------------------------------------------------------------------

# error codes whose construction is a crash-grade event worth a dump
_FATAL_CODES = frozenset((64, 69, 70))   # DEADLOCK, PROC_FAILED, REVOKED


def on_error(exc: BaseException) -> None:
    """Every typed MPIError lands a note; crash-grade codes auto-dump."""
    if _capacity() <= 0:
        return
    code = int(getattr(exc, "code", 0) or 0)
    note("error", type=type(exc).__name__, code=code,
         message=str(exc.args[0]) if exc.args else str(exc))
    if code in _FATAL_CODES:
        auto_dump(f"error-{type(exc).__name__}")


# ---------------------------------------------------------------------------
# Auto-dump: CRC-stamped JSON, rate-limited per reason.
# ---------------------------------------------------------------------------

_dump_lock = threading.Lock()
_last_dump: Dict[str, float] = {}
_DUMP_MIN_INTERVAL_S = 2.0


def dump_path(reason: str) -> str:
    import tempfile
    cfg = config._cached
    d = (cfg.flight_dir if cfg is not None else "") or tempfile.gettempdir()
    safe = "".join(c if c.isalnum() or c in "-_." else "-" for c in reason)
    return os.path.join(d, f"flight-{os.getpid()}-{safe}.json")


def dump(path: str, reason: str = "manual") -> str:
    """Write the ring to ``path`` with a CRC32 stamp over the event body."""
    ring = _get_ring()
    events = ring.snapshot() if ring is not None else []
    body = json.dumps(events, separators=(",", ":"), sort_keys=True)
    payload = {"version": 1, "pid": os.getpid(), "reason": reason,
               "t": time.time(), "crc32": zlib.crc32(body.encode()),
               "events": events}
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path


def auto_dump(reason: str) -> Optional[str]:
    """Dump on a fatal event — best-effort (a dump failure must never mask
    the error being raised) and rate-limited per reason."""
    if _capacity() <= 0:
        return None
    now = time.monotonic()
    with _dump_lock:
        last = _last_dump.get(reason, -1e9)
        if now - last < _DUMP_MIN_INTERVAL_S:
            return None
        _last_dump[reason] = now
    try:
        return dump(dump_path(reason), reason)
    except OSError:
        return None


def read_dump(path: str) -> dict:
    """Load and CRC-verify a flight dump; raises ValueError on corruption."""
    with open(path) as f:
        payload = json.load(f)
    events = payload.get("events", [])
    body = json.dumps(events, separators=(",", ":"), sort_keys=True)
    crc = zlib.crc32(body.encode())
    if crc != payload.get("crc32"):
        raise ValueError(f"flight dump {path!r} failed its CRC check "
                         f"(stored {payload.get('crc32')}, computed {crc})")
    return payload


def render(payload: dict) -> str:
    """Human-readable timeline of a verified dump (the CLI's output)."""
    lines = [f"flight recorder dump — pid {payload.get('pid')} "
             f"reason {payload.get('reason')!r} "
             f"at {time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(payload.get('t', 0)))}"]
    events = payload.get("events", [])
    if not events:
        lines.append("  (ring empty)")
        return "\n".join(lines)
    t0 = events[0].get("mono", 0.0)
    for rec in events:
        dt = (rec.get("mono", t0) - t0) * 1e3
        core = {k: v for k, v in rec.items()
                if k not in ("t", "mono", "kind", "i", "thread")}
        detail = " ".join(f"{k}={v}" for k, v in core.items())
        lines.append(f"  +{dt:10.3f} ms  [{rec.get('thread', '?')}] "
                     f"{rec.get('kind', '?'):<12} {detail}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# SIGTERM hook: install explicitly (launcher / broker main), never at import.
# ---------------------------------------------------------------------------

_prev_sigterm: Any = None
_hook_installed = False


def _on_sigterm(signum, frame):
    note("sigterm")
    auto_dump("sigterm")
    prev = _prev_sigterm
    if callable(prev):
        prev(signum, frame)
    else:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)


def install_signal_hook() -> bool:
    """Chain a SIGTERM handler that dumps the ring before the previous
    disposition runs. Main-thread only (signal module contract); returns
    whether the hook is installed."""
    global _prev_sigterm, _hook_installed
    if _hook_installed or not enabled():
        return _hook_installed
    if threading.current_thread() is not threading.main_thread():
        return False
    try:
        _prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):
        return False
    _hook_installed = True
    return True


def reset() -> None:
    """Drop the ring and dump rate-limits (test isolation)."""
    global _ring, _cap_cache
    with _ring_gate:
        _ring = None
        _cap_cache = (_UNSET, 0)
    with _dump_lock:
        _last_dump.clear()
