"""Typed exception hierarchy.

Reference: MPI.jl wraps every ccall in ``@mpichk`` and raises ``MPIError(code)``
(/root/reference/src/error.jl:1-23). There is no C error-code table here — the
TPU-native runtime raises typed Python exceptions directly, with an ``MPIError``
root so user code can catch the whole family.
"""

from __future__ import annotations


class MPIError(RuntimeError):
    """Root of all framework errors (analog of MPI.jl's MPIError, src/error.jl:1-3)."""

    def __init__(self, msg: str = "MPI error", code: int = 1):
        super().__init__(msg)
        self.code = code

    def __str__(self) -> str:  # pretty-print like src/error.jl:21-23
        return f"{self.args[0]} (code {self.code})"


class AbortError(MPIError):
    """Raised in every rank when the job is fate-shared down.

    The reference's ``MPI.Abort`` kills the whole job (src/environment.jl:252-254)
    and a single failing rank fails the run (test/runtests.jl:37-39). In the
    threaded host runtime, failure is propagated by raising this in every rank
    blocked in the runtime.
    """


class DeadlockError(MPIError):
    """A blocking operation exceeded the runtime's deadlock timeout."""


class TruncationError(MPIError):
    """Receive buffer smaller than the incoming message (MPI_ERR_TRUNCATE)."""


class CollectiveMismatchError(MPIError):
    """Ranks of one communicator called different collectives in the same round.

    The reference has no such check (libmpi would hang or corrupt); SURVEY.md §5
    calls for a debug-mode sequence check — here it is always on, since the host
    rendezvous sees every call.
    """


class InvalidCommError(MPIError):
    """Operation on COMM_NULL or a freed communicator."""


# Code → description, in the spirit of MPI_Error_string
# (/root/reference/src/error.jl:11-19 wraps it). The TPU-native runtime
# raises typed exceptions rather than integer codes, so the table simply
# names the classes' codes for FFI-shaped callers.
_ERROR_STRINGS = {
    0: "MPI_SUCCESS: no error",
    1: "MPI error (see the raised exception's message for detail)",
}


def Error_string(code: int) -> str:
    """Human-readable description of an error code
    (src/error.jl:11-19 ``error_string``). Exceptions carry their full
    message already; this exists for MPI-API parity."""
    return _ERROR_STRINGS.get(int(code), f"unknown MPI error code {code}")
