"""Typed exception hierarchy + the MPI error-code space.

Reference: MPI.jl wraps every ccall in ``@mpichk`` and raises ``MPIError(code)``
whose message comes from ``MPI_Error_string`` (/root/reference/src/error.jl:1-23).
The TPU-native runtime raises typed Python exceptions directly — the message is
always complete — but every exception also carries a ``code`` drawn from the
standard MPI error-class space (MPI 4.0 §9.4, MPICH numbering), so FFI-shaped
callers and ``Error_string`` round-trip the way the reference's do.
"""

from __future__ import annotations

# --------------------------------------------------------------------------
# MPI error classes (MPI 4.0 §9.4; values follow MPICH, the ABI the reference
# defaults to — /root/reference/deps/consts_mpich.jl). SUCCESS..ERR_PENDING
# are the MPI-1 classes; the 20+ block is the MPI-2 IO/RMA/spawn classes.
# --------------------------------------------------------------------------
SUCCESS = 0
ERR_BUFFER = 1
ERR_COUNT = 2
ERR_TYPE = 3
ERR_TAG = 4
ERR_COMM = 5
ERR_RANK = 6
ERR_ROOT = 7
ERR_GROUP = 8
ERR_OP = 9
ERR_TOPOLOGY = 10
ERR_DIMS = 11
ERR_ARG = 12
ERR_UNKNOWN = 13
ERR_TRUNCATE = 14
ERR_OTHER = 15
ERR_INTERN = 16
ERR_IN_STATUS = 17
ERR_PENDING = 18
ERR_REQUEST = 19
ERR_ACCESS = 20
ERR_AMODE = 21
ERR_ASSERT = 22
ERR_FILE = 30
ERR_INFO_KEY = 31
ERR_INFO_VALUE = 33
ERR_INFO = 34
ERR_IO = 35
ERR_LOCKTYPE = 37
ERR_NO_SUCH_FILE = 42
ERR_RMA_SYNC = 47
ERR_SIZE = 49
ERR_SPAWN = 50
ERR_UNSUPPORTED_OPERATION = 52
ERR_WIN = 53
# Implementation-specific classes (past MPI_ERR_LASTCODE's standard block),
# for conditions libmpi cannot detect but this runtime does:
ERR_DEADLOCK = 64
ERR_COLLECTIVE_MISMATCH = 65
ERR_ABORTED = 66
ERR_RMA_RACE = 67
ERR_ANALYZE = 68
ERR_PROC_FAILED = 69
ERR_REVOKED = 70
ERR_QUOTA = 71
ERR_SERVE_BUSY = 72
ERR_SESSION = 73
ERR_SLO_EXPIRED = 74
ERR_POOL_DEGRADED = 75
ERR_LOCK_ORDER = 76

_ERROR_STRINGS = {
    SUCCESS: "MPI_SUCCESS: no error",
    ERR_BUFFER: "MPI_ERR_BUFFER: invalid buffer pointer or operand",
    ERR_COUNT: "MPI_ERR_COUNT: invalid count argument",
    ERR_TYPE: "MPI_ERR_TYPE: invalid datatype argument",
    ERR_TAG: "MPI_ERR_TAG: invalid tag argument",
    ERR_COMM: "MPI_ERR_COMM: invalid communicator (null, freed, or wrong kind)",
    ERR_RANK: "MPI_ERR_RANK: invalid rank for this communicator",
    ERR_REQUEST: "MPI_ERR_REQUEST: invalid or inactive request handle",
    ERR_ROOT: "MPI_ERR_ROOT: invalid root rank for this communicator",
    ERR_GROUP: "MPI_ERR_GROUP: invalid group argument",
    ERR_OP: "MPI_ERR_OP: invalid or non-applicable reduction operation",
    ERR_TOPOLOGY: "MPI_ERR_TOPOLOGY: invalid topology or topology-less communicator",
    ERR_DIMS: "MPI_ERR_DIMS: invalid dimension specification",
    ERR_ARG: "MPI_ERR_ARG: invalid argument",
    ERR_UNKNOWN: "MPI_ERR_UNKNOWN: unknown error",
    ERR_TRUNCATE: "MPI_ERR_TRUNCATE: receive buffer smaller than incoming message",
    ERR_OTHER: "MPI_ERR_OTHER: known error not in this list "
               "(see the raised exception's message)",
    ERR_INTERN: "MPI_ERR_INTERN: internal runtime error",
    ERR_IN_STATUS: "MPI_ERR_IN_STATUS: error code is in the status object",
    ERR_PENDING: "MPI_ERR_PENDING: operation pending, not failed",
    ERR_ACCESS: "MPI_ERR_ACCESS: permission denied on file",
    ERR_AMODE: "MPI_ERR_AMODE: invalid file access-mode combination",
    ERR_ASSERT: "MPI_ERR_ASSERT: invalid assertion argument",
    ERR_FILE: "MPI_ERR_FILE: invalid file handle",
    ERR_INFO_KEY: "MPI_ERR_INFO_KEY: info key too long or not ASCII",
    ERR_INFO_VALUE: "MPI_ERR_INFO_VALUE: info value too long or not ASCII",
    ERR_INFO: "MPI_ERR_INFO: invalid info object",
    ERR_IO: "MPI_ERR_IO: file I/O error",
    ERR_LOCKTYPE: "MPI_ERR_LOCKTYPE: invalid RMA lock type",
    ERR_NO_SUCH_FILE: "MPI_ERR_NO_SUCH_FILE: file does not exist",
    ERR_RMA_SYNC: "MPI_ERR_RMA_SYNC: RMA call out of epoch / wrong synchronization",
    ERR_SIZE: "MPI_ERR_SIZE: invalid size argument",
    ERR_SPAWN: "MPI_ERR_SPAWN: could not spawn processes",
    ERR_UNSUPPORTED_OPERATION: "MPI_ERR_UNSUPPORTED_OPERATION: operation not "
                               "supported on this object or backend",
    ERR_WIN: "MPI_ERR_WIN: invalid RMA window",
    ERR_DEADLOCK: "TPU_ERR_DEADLOCK: blocking operation exceeded the runtime's "
                  "deadlock timeout",
    ERR_COLLECTIVE_MISMATCH: "TPU_ERR_COLLECTIVE_MISMATCH: ranks of one "
                             "communicator called different collectives in the "
                             "same round",
    ERR_ABORTED: "TPU_ERR_ABORTED: job fate-shared down by MPI.Abort or a "
                 "failing rank",
    ERR_RMA_RACE: "TPU_ERR_RMA_RACE: concurrent overlapping RMA accesses in "
                  "one exposure epoch (tpu_mpi.analyze race detector)",
    ERR_ANALYZE: "TPU_ERR_ANALYZE: communication-correctness diagnostic "
                 "(tpu_mpi.analyze)",
    ERR_PROC_FAILED: "TPU_ERR_PROC_FAILED: a peer process died (heartbeat "
                     "timeout or closed transport socket) — shrink or abort",
    ERR_REVOKED: "TPU_ERR_REVOKED: communicator revoked by Comm_revoke after "
                 "a failure; only Comm_shrink/Comm_agree remain legal on it",
    ERR_QUOTA: "TPU_ERR_QUOTA: tenant byte/op quota exhausted; the broker "
               "rejected the operation (raise the quota or detach)",
    ERR_SERVE_BUSY: "TPU_ERR_SERVE_BUSY: broker admission queue full for this "
                    "tenant — retriable backpressure, resubmit after a backoff",
    ERR_SESSION: "TPU_ERR_SESSION: session handshake or lease violation "
                 "(bad token, tenant limit, revoked lease, or a cid outside "
                 "the leased namespace)",
    ERR_SLO_EXPIRED: "TPU_ERR_SLO_EXPIRED: generation request evicted — its "
                     "latency-SLO deadline expired before completion; "
                     "retriable under lighter load",
    ERR_POOL_DEGRADED: "TPU_ERR_POOL_DEGRADED: the serve pool lost ranks and "
                       "is running degraded — this tenant's communicators "
                       "span a dead rank; retriable once the autoscaler "
                       "restores capacity and rebinds the lease",
    ERR_LOCK_ORDER: "TPU_ERR_LOCK_ORDER: two threads established inverted "
                    "lock-acquisition order (tpu_mpi.locksmith witness) — a "
                    "potential deadlock caught before any thread blocked",
}

# tpu_mpi.analyze diagnostic code -> MPI error class. The analyzer's own
# code space (Lxxx static lint, Txxx trace verifier, Rxxx race detector —
# docs/analysis.md) projects onto the MPI classes above so FFI-shaped
# callers can Error_string any Diagnostic.mpi_code.
DIAGNOSTIC_CODES = {
    "L100": ERR_ARG,                    # unparseable source
    "L101": ERR_COLLECTIVE_MISMATCH,    # rank-divergent collective sequence
    "L102": ERR_ROOT,                   # root mismatch across rank branches
    "L103": ERR_TYPE,                   # op/dtype mismatch across branches
    "L104": ERR_TRUNCATE,               # recv-count truncation
    "L105": ERR_PENDING,                # send with no matching receive
    "L106": ERR_BUFFER,                 # send-buffer reuse before Wait
    "L107": ERR_DEADLOCK,               # blocking send/recv cycle pattern
    "L108": ERR_RMA_RACE,               # static RMA epoch race
    "L109": ERR_REQUEST,                # persistent-request misuse
    "L110": ERR_REVOKED,                # op on revoked/shrunk communicator
    "L111": ERR_SESSION,                # serve-session misuse
    "L112": ERR_LOCK_ORDER,             # static lock-order cycle
    "L113": ERR_DEADLOCK,               # blocking under a dispatch/pool lock
    "L114": ERR_INTERN,                 # unguarded cross-thread field write
    "L115": ERR_LOCK_ORDER,             # release path differs from acquire
    "L116": ERR_REQUEST,                # gradient-bucket handle misuse
    "T201": ERR_COLLECTIVE_MISMATCH,    # collective order mismatch (traced)
    "T202": ERR_COLLECTIVE_MISMATCH,    # collective signature mismatch
    "T203": ERR_PENDING,                # sent message never received
    "T206": ERR_BUFFER,                 # Isend buffer modified before Wait
    "T207": ERR_REVOKED,                # agree/shrink protocol divergence
    "T208": ERR_SESSION,                # measured books don't partition pool
    "T210": ERR_DEADLOCK,               # alternate-schedule deadlock
    "T211": ERR_PENDING,                # alternate-schedule orphaned message
    "T212": ERR_ARG,                    # schedule-dependent wildcard values
    "T213": ERR_COLLECTIVE_MISMATCH,    # per-rank algorithm selection split
    "T214": ERR_COLLECTIVE_MISMATCH,    # rank skipped elastic rebind barrier
    "T215": ERR_COLLECTIVE_MISMATCH,    # dispatch sections failed to serialize
    "C401": ERR_DEADLOCK,               # blocked while holding a witnessed lock
    "R301": ERR_RMA_RACE,               # vector-clock RMA race
    "R302": ERR_BUFFER,                 # donated fold result read after inval
}


def diagnostic_error_code(diag_code: str) -> int:
    """MPI error class for a tpu_mpi.analyze diagnostic code."""
    return DIAGNOSTIC_CODES.get(str(diag_code), ERR_ANALYZE)


class MPIError(RuntimeError):
    """Root of all framework errors (analog of MPI.jl's MPIError,
    src/error.jl:1-3). ``code`` defaults to the class's MPI error class
    (``CODE``), so every exception type is distinguishable by code alone the
    way libmpi's error classes are."""

    CODE = ERR_OTHER

    def __init__(self, msg: str = "MPI error", code: "int | None" = None):
        super().__init__(msg)
        self.code = self.CODE if code is None else int(code)
        # flight-recorder note (docs/observability.md): crash-grade codes
        # auto-dump the ring. Lazy import — config imports this module, so
        # flight (which imports config) can only be reached from here at
        # call time; any failure must never mask the error being raised.
        try:
            from . import flight
            flight.on_error(self)
        except Exception:
            pass

    def __str__(self) -> str:  # pretty-print like src/error.jl:21-23
        return f"{self.args[0]} (code {self.code})"

    def Get_error_string(self) -> str:
        """The MPI_Error_string of this exception's error class — covers the
        standard table, the runtime-specific classes, and every
        tpu_mpi.analyze diagnostic (whose codes project onto MPI classes via
        ``DIAGNOSTIC_CODES``)."""
        return Error_string(self.code)


class AbortError(MPIError):
    """Raised in every rank when the job is fate-shared down.

    The reference's ``MPI.Abort`` kills the whole job (src/environment.jl:252-254)
    and a single failing rank fails the run (test/runtests.jl:37-39). In the
    threaded host runtime, failure is propagated by raising this in every rank
    blocked in the runtime. ``code`` is the user's Abort errorcode when one was
    given, else ERR_ABORTED.
    """

    CODE = ERR_ABORTED


class DeadlockError(MPIError):
    """A blocking operation exceeded the runtime's deadlock timeout."""

    CODE = ERR_DEADLOCK


class LockOrderError(MPIError):
    """Two threads established inverted lock-acquisition order.

    Raised by the :mod:`tpu_mpi.locksmith` witness (``TPU_MPI_LOCKCHECK=1``)
    the moment the global order graph gains a cycle — no thread has to
    actually deadlock for this to fire. The message carries both
    acquisition paths as file:line chains."""

    CODE = ERR_LOCK_ORDER


class TruncationError(MPIError):
    """Receive buffer smaller than the incoming message (MPI_ERR_TRUNCATE)."""

    CODE = ERR_TRUNCATE


class CollectiveMismatchError(MPIError):
    """Ranks of one communicator called different collectives in the same round.

    The reference has no such check (libmpi would hang or corrupt); SURVEY.md §5
    calls for a debug-mode sequence check — here it is always on, since the host
    rendezvous sees every call.
    """

    CODE = ERR_COLLECTIVE_MISMATCH


class InvalidCommError(MPIError):
    """Operation on COMM_NULL or a freed communicator."""

    CODE = ERR_COMM


class ProcFailedError(MPIError):
    """A peer process died while this rank was communicating with it.

    The ULFM MPI_ERR_PROC_FAILED analog: raised out of a blocked receive or a
    collective rendezvous when the failure detector (heartbeat timeout or a
    closed transport socket — docs/fault-tolerance.md) declares a peer of the
    operation dead, instead of hanging until the deadlock timeout. ``ranks``
    lists the world ranks known dead at raise time."""

    CODE = ERR_PROC_FAILED

    def __init__(self, msg: str = "peer process failed",
                 code: "int | None" = None,
                 ranks: "tuple[int, ...] | None" = None):
        super().__init__(msg, code=code)
        self.ranks = tuple(ranks) if ranks else ()


class RevokedError(MPIError):
    """The communicator was revoked (ULFM MPI_ERR_REVOKED analog).

    After ``Comm_revoke`` floods the group, every pending and future
    operation on the communicator raises this deterministically on every
    surviving rank; only ``Comm_shrink``/``Comm_agree`` remain legal."""

    CODE = ERR_REVOKED


class QuotaExceededError(MPIError):
    """A tenant's byte/op quota was exhausted (docs/serving.md).

    Raised by the broker's admission path — the op is REJECTED, never run,
    and never hangs. ``tenant`` names the offender; ``used``/``quota`` are
    byte counts at rejection time."""

    CODE = ERR_QUOTA

    def __init__(self, msg: str = "tenant quota exhausted",
                 code: "int | None" = None, tenant: "str | None" = None,
                 used: int = 0, quota: int = 0):
        super().__init__(msg, code=code)
        self.tenant = tenant
        self.used = int(used)
        self.quota = int(quota)


class ServeBusyError(MPIError):
    """Broker admission queue full for this tenant (docs/serving.md).

    The retriable backpressure status of the serve tier: nothing was
    admitted or charged; resubmitting after a backoff is always safe.
    ``retriable`` is True by construction so clients can branch on the
    attribute instead of the code."""

    CODE = ERR_SERVE_BUSY
    retriable = True

    def __init__(self, msg: str = "serve queue full, retry later",
                 code: "int | None" = None, tenant: "str | None" = None,
                 depth: int = 0):
        super().__init__(msg, code=code)
        self.tenant = tenant
        self.depth = int(depth)


class SLOExpiredError(MPIError):
    """A generation request's latency-SLO deadline expired before it could
    finish, and the inference scheduler evicted it (docs/serving.md
    "Inference engine"). Like :class:`ServeBusyError` this is retriable
    backpressure: the request was rolled back, nothing is half-generated on
    the wire, and resubmitting under lighter load is always safe.
    ``tenant``/``rid`` identify the evicted request; ``slo_ms`` is the
    deadline it missed."""

    CODE = ERR_SLO_EXPIRED
    retriable = True

    def __init__(self, msg: str = "generation SLO deadline expired",
                 code: "int | None" = None, tenant: "str | None" = None,
                 rid: "int | None" = None, slo_ms: int = 0):
        super().__init__(msg, code=code)
        self.tenant = tenant
        self.rid = rid
        self.slo_ms = int(slo_ms)


class PoolDegradedError(MPIError):
    """The serve pool lost ranks and this tenant's communicators span one
    (docs/serving.md "Degraded mode"). Retriable backpressure like
    :class:`ServeBusyError`: nothing was run or charged, surviving tenants
    whose communicators avoid the dead ranks keep streaming, and the
    autoscaler will re-spawn capacity and rebind the lease — resubmitting
    after a backoff is always safe. ``dead`` lists the world ranks known
    dead at rejection time; ``headroom`` is the healthy-rank count clients
    can still attach against."""

    CODE = ERR_POOL_DEGRADED
    retriable = True

    def __init__(self, msg: str = "serve pool degraded, retry later",
                 code: "int | None" = None, tenant: "str | None" = None,
                 dead: "tuple[int, ...] | None" = None, headroom: int = 0):
        super().__init__(msg, code=code)
        self.tenant = tenant
        self.dead = tuple(dead) if dead else ()
        self.headroom = int(headroom)


class SessionError(MPIError):
    """Session handshake or lease violation (docs/serving.md): bad session
    token, tenant limit reached, an op on a revoked lease, or a cid outside
    the leased namespace (cross-tenant cid use)."""

    CODE = ERR_SESSION


class AnalyzerError(MPIError):
    """A communication-correctness diagnostic escalated to an exception.

    Raised when tpu_mpi.analyze findings are surfaced as errors; ``code``
    is the diagnostic's MPI error class (``diagnostic_error_code``), so
    ``Get_error_string`` describes the underlying defect class."""

    CODE = ERR_ANALYZE

    def __init__(self, msg: str = "analyzer diagnostic",
                 code: "int | None" = None, diag_code: "str | None" = None):
        if code is None and diag_code is not None:
            code = diagnostic_error_code(diag_code)
        super().__init__(msg, code=code)
        self.diag_code = diag_code


def Error_string(code: int) -> str:
    """Human-readable description of an error code (src/error.jl:11-19
    ``error_string``). Covers every code the package raises — the full MPI
    error-class table plus the runtime-specific classes."""
    return _ERROR_STRINGS.get(int(code), f"unknown MPI error code {code}")


# MPI-4 naming alias (mpi4py spells it Get_error_string on the module too).
Get_error_string = Error_string
