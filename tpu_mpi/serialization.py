"""Extended object serialization: functions and classes travel by value.

Reference parity: MPI.jl ships arbitrary Julia objects — including closures
— between OS processes via Julia's ``Serialization`` stdlib
(``/root/reference/src/MPI.jl:9-18``; ``test/test_bcast.jl:38-55``
broadcasts a *function* under ``mpiexec``). CPython's ``pickle`` refuses
any function that is not importable by qualified name, so the procs tier
needs its own codec: this module subclasses :class:`pickle.Pickler` with a
by-value path for lambdas, closures, nested functions, ``__main__``-level
definitions, and locally-defined classes.

Design (no third-party cloudpickle):

* the ``__code__`` object travels via :mod:`marshal`;
* closure cells, defaults, ``__dict__`` and *referenced globals* (found by
  scanning ``LOAD_GLOBAL``/``STORE_GLOBAL`` bytecode, recursing into nested
  code constants) travel through the same pickler, so a closure over
  another closure — or a recursive function — round-trips;
* reconstruction is two-phase (skeleton, then state via a pickle
  ``state_setter``) so self-referential functions hit the memo;
* modules serialize by import name; everything plain pickle already
  handles is left to plain pickle, so the wire format stays standard
  pickle bytecode and :func:`loads` is just ``pickle.loads``.

Trust model: identical to pickle — ``loads`` executes arbitrary code.
Only feed it frames produced by peer ranks of the same job (the launcher's
transport already assumes this for pickle itself).
"""
from __future__ import annotations

import builtins
import dis
import importlib
import importlib.util
import io
import marshal
import pickle
import sys
import types
from typing import Any, Callable, Optional

from .error import ERR_TYPE, MPIError

__all__ = ["dumps", "loads", "Pickler", "dumps_oob"]


_GLOBAL_OPS = frozenset(("LOAD_GLOBAL", "STORE_GLOBAL", "DELETE_GLOBAL"))


# -- marshal'd bytecode, tagged with the interpreter's magic -----------------
# marshal's format is only stable within ONE CPython bytecode version; a
# mixed-interpreter job would otherwise die in marshal.loads with a cryptic
# "bad marshal data". The pyc magic number identifies the bytecode version
# exactly, so prepending it turns that crash into a diagnosable error.

_MAGIC = importlib.util.MAGIC_NUMBER


def _dump_code(code: types.CodeType) -> bytes:
    return _MAGIC + marshal.dumps(code)


def _load_code(blob: bytes) -> types.CodeType:
    n = len(_MAGIC)
    if bytes(blob[:n]) != _MAGIC:
        raise MPIError(
            "by-value function was marshalled by a different interpreter "
            f"(bytecode magic {bytes(blob[:n])!r}, this interpreter "
            f"{_MAGIC!r}, Python {sys.version.split()[0]}): marshal'd "
            "bytecode only round-trips between identical CPython versions — "
            "run every rank of the job with the same interpreter",
            code=ERR_TYPE)
    return marshal.loads(blob[n:])


def _global_names(code: types.CodeType) -> set:
    """Names a code object (or any nested code constant) reads/writes as
    globals. Precise per-opcode scan — ``co_names`` alone would also pull
    attribute names."""
    names: set = set()
    stack = [code]
    while stack:
        co = stack.pop()
        for ins in dis.get_instructions(co):
            if ins.opname in _GLOBAL_OPS:
                names.add(ins.argval)
        for const in co.co_consts:
            if isinstance(const, types.CodeType):
                stack.append(const)
    return names


def _lookup_qualname(obj: Any) -> Any:
    """Resolve obj's (module, qualname) back to an object, or None."""
    mod = sys.modules.get(getattr(obj, "__module__", None) or "")
    if mod is None:
        return None
    found: Any = mod
    for part in obj.__qualname__.split("."):
        if part == "<locals>":
            return None
        found = getattr(found, part, None)
        if found is None:
            return None
    return found


def _by_value(obj: Any) -> bool:
    """Ship by value when by-reference pickling cannot work: local /
    lambda / deleted definitions, and anything from ``__main__`` (peer
    processes run a different ``__main__`` under the launcher)."""
    if getattr(obj, "__module__", None) == "__main__":
        return True
    return _lookup_qualname(obj) is not obj


# -- closure cells (first-class, two-phase) ----------------------------------
# Cells pickle as objects so the memo preserves IDENTITY: two functions
# sharing one cell (a `nonlocal` writer + a reader) re-knit to one shared
# cell on the peer. Two-phase (empty cell, then contents) lets a cell
# contain its own function (recursive defs) — the memo breaks the cycle.

def _make_cell() -> types.CellType:
    return types.CellType()


def _set_cell_state(cell: types.CellType, st) -> None:
    if st["has"]:
        cell.cell_contents = st["contents"]


def _reduce_cell(cell: types.CellType):
    try:
        st = {"has": True, "contents": cell.cell_contents}
    except ValueError:              # declared but never filled
        st = {"has": False, "contents": None}
    return (_make_cell, (), st, None, None, _set_cell_state)


# -- function reconstruction -------------------------------------------------

def _make_function(code_bytes: bytes, name: str,
                   cells: Optional[tuple], fglobals: Optional[dict] = None):
    code = _load_code(code_bytes)
    # ``fglobals`` is the per-source-module namespace dict the Pickler
    # threaded through every function from that module — pickle's memo makes
    # all of them reconstruct to the SAME dict, so a global one function
    # writes is visible to its siblings, like functions sharing a module.
    if fglobals is None:
        fglobals = {}
    fglobals.setdefault("__builtins__", builtins)
    return types.FunctionType(code, fglobals, name, None, cells or None)


def _set_function_state(fn, st) -> None:
    fn.__globals__.update(st["globals"])
    fn.__defaults__ = st["defaults"]
    fn.__kwdefaults__ = st["kwdefaults"]
    if st["dict"]:
        fn.__dict__.update(st["dict"])
    fn.__qualname__ = st["qualname"]
    fn.__module__ = st["module"]
    fn.__doc__ = st["doc"]
    if st["annotations"]:
        fn.__annotations__ = st["annotations"]


def _reduce_function(fn: types.FunctionType, shared_globals: dict):
    code = fn.__code__
    fglobals = fn.__globals__
    globs = {name: fglobals[name]
             for name in _global_names(code) if name in fglobals}
    st = {
        "globals": globs,
        "defaults": fn.__defaults__,
        "kwdefaults": fn.__kwdefaults__,
        "dict": dict(fn.__dict__),
        "qualname": fn.__qualname__,
        "module": fn.__module__,
        "doc": fn.__doc__,
        "annotations": dict(getattr(fn, "__annotations__", None) or {}),
    }
    return (_make_function,
            (_dump_code(code), fn.__name__, fn.__closure__, shared_globals),
            st, None, None, _set_function_state)


# -- class reconstruction ----------------------------------------------------
# Skeleton + state (two-phase, so methods may reference the class), but the
# skeleton is built through ``mcls.__prepare__`` with the creation-critical
# namespace entries in place: ``__slots__`` (so slot descriptors exist) and
# enum members (EnumMeta's invariants only hold for members present at class
# creation — the functional-API path). Everything else lands via setattr,
# with ``__set_name__`` re-fired for descriptors that define it.

_CLASS_DICT_SKIP = frozenset((
    "__dict__", "__weakref__", "__module__", "__qualname__", "__doc__",
))

# enum internals recreated by class creation itself — never state-set
_ENUM_INTERNAL = frozenset((
    "_member_names_", "_member_map_", "_value2member_map_", "_member_type_",
    "_value_repr_", "_new_member_", "_use_args_", "_unhashable_values_",
    "_hashable_values_", "_singletons_", "_sort_order_", "__new__",
    "_generate_next_value_",
))

_SLOT_DESCRIPTOR_TYPES = (types.MemberDescriptorType,
                          types.GetSetDescriptorType)


def _make_class(mcls: type, name: str, bases: tuple,
                slots, enum_members):
    ns = mcls.__prepare__(name, bases)
    if slots is not None:
        ns["__slots__"] = slots
    if enum_members is not None:
        for k, v in enum_members.items():
            ns[k] = v
    return mcls(name, bases, ns)


def _set_class_state(cls: type, st) -> None:
    for k, v in st["dict"].items():
        try:
            setattr(cls, k, v)
        except (AttributeError, TypeError):
            continue                # read-only descriptor slots
        set_name = getattr(type(v), "__set_name__", None)
        if set_name is not None:
            set_name(v, cls, k)
    cls.__qualname__ = st["qualname"]
    cls.__module__ = st["module"]
    if st["doc"] is not None:
        try:
            cls.__doc__ = st["doc"]
        except (AttributeError, TypeError):
            pass


def _reduce_class(cls: type):
    import enum as _enum
    mcls = type(cls)
    skip = set(_CLASS_DICT_SKIP)
    enum_members = None
    if isinstance(cls, _enum.EnumMeta):
        enum_members = {n: cls._member_map_[n]._value_
                        for n in cls._member_names_}
        skip |= _ENUM_INTERNAL | set(enum_members)
    slots = vars(cls).get("__slots__")
    if slots is not None:
        skip.add("__slots__")
        skip |= {slots} if isinstance(slots, str) else set(slots)
    d = {k: v for k, v in vars(cls).items()
         if k not in skip and not isinstance(v, _SLOT_DESCRIPTOR_TYPES)}
    st = {
        "dict": d,
        "qualname": cls.__qualname__,
        "module": cls.__module__,
        "doc": cls.__doc__,
    }
    return (_make_class,
            (mcls, cls.__name__, cls.__bases__, slots, enum_members),
            st, None, None, _set_class_state)


# -- descriptor / helper reducers (needed once classes go by value) ----------

def _make_mappingproxy(d: dict):
    return types.MappingProxyType(d)

def _reduce_property(p: property):
    return (property, (p.fget, p.fset, p.fdel, p.__doc__))


def _reduce_staticmethod(sm):
    return (staticmethod, (sm.__func__,))


def _reduce_classmethod(cm):
    return (classmethod, (cm.__func__,))


class Pickler(pickle.Pickler):
    """Pickler with a by-value fallback for functions, classes and modules.

    Standard pickle behavior is preserved for everything importable —
    the hook returns ``NotImplemented`` and the default machinery runs —
    so frames decode with plain :func:`pickle.loads` on the peer.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # module name -> the placeholder dict every by-value function from
        # that module reconstructs its __globals__ into. Pickling the SAME
        # dict object for each of them lets the memo share it, so two
        # siblings from one module see each other's globals on the peer
        # (one dict per source module per payload, fresh per payload).
        self._shared_globals: dict = {}

    def _globals_anchor(self, fn: types.FunctionType) -> dict:
        # Keyed by source MODULE NAME, not globals-dict identity: two
        # by-value functions from one module re-knit to one shared
        # namespace on the peer even when their ``__globals__`` dicts
        # differ by identity (module reload; exec-built namespaces that
        # set ``__name__``). Functions without a module fall back to
        # identity keying so unrelated anonymous namespaces stay separate.
        # The registry lives on the Pickler — one per payload — so
        # separate payloads still reconstruct disjoint namespaces.
        key = (getattr(fn, "__module__", None)
               or f"<anonymous:{id(fn.__globals__)}>")
        anchor = self._shared_globals.get(key)
        if anchor is None:
            anchor = self._shared_globals[key] = {}
        return anchor

    def reducer_override(self, obj: Any):
        if isinstance(obj, types.FunctionType):
            if _by_value(obj):
                return _reduce_function(obj, self._globals_anchor(obj))
            return NotImplemented
        if isinstance(obj, type):
            if _by_value(obj) and obj.__module__ != "builtins":
                return _reduce_class(obj)
            return NotImplemented
        if isinstance(obj, types.CodeType):
            return (_load_code, (_dump_code(obj),))
        if isinstance(obj, types.ModuleType):
            return (importlib.import_module, (obj.__name__,))
        if isinstance(obj, property):
            return _reduce_property(obj)
        if isinstance(obj, staticmethod):
            return _reduce_staticmethod(obj)
        if isinstance(obj, classmethod):
            return _reduce_classmethod(obj)
        if isinstance(obj, types.MappingProxyType):
            return (_make_mappingproxy, (dict(obj),))
        if isinstance(obj, types.CellType):
            return _reduce_cell(obj)
        return NotImplemented


def dumps(obj: Any, protocol: int = pickle.DEFAULT_PROTOCOL) -> bytes:
    """Like :func:`pickle.dumps`, but closures/lambdas/local classes work."""
    buf = io.BytesIO()
    Pickler(buf, protocol=protocol).dump(obj)
    return buf.getvalue()


def dumps_oob(obj: Any, buffer_callback: Callable) -> bytes:
    """Protocol-5 out-of-band dump (the procs wire codec's skeleton lane,
    :func:`tpu_mpi.backend.dumps_oob_parts`) with the extended reducers."""
    buf = io.BytesIO()
    Pickler(buf, protocol=5, buffer_callback=buffer_callback).dump(obj)
    return buf.getvalue()


def loads(data: bytes) -> Any:
    """Alias of :func:`pickle.loads` — the wire format is standard pickle;
    by-value objects reconstruct through this module's importable helpers."""
    return pickle.loads(data)
