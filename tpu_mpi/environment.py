"""Environment / lifecycle: Init, Finalize, Abort, thread levels, wall clock.

Reference: /root/reference/src/environment.jl — Init (:80-89), Init_thread +
ThreadLevel (:111-162), Query_thread (:173-180), Is_thread_main (:191-197),
Finalize (:220-236), Abort (:252-254), Initialized/Finalized (:267-287),
Wtick/Wtime (:289-295), has_cuda (:308-323).

TPU-native mapping: there is no C library to spin up. ``Init`` attaches the
calling rank-thread to the ambient :class:`~tpu_mpi._runtime.SpmdContext`
(created by ``spmd_run``/``tpurun``); run standalone it creates a singleton
world of size 1, exactly like running an MPI program without mpiexec. The
reference's REFCOUNT machinery (src/environment.jl:26-62) exists to defer
MPI_Finalize past C-object finalizers; with no C resources we keep only the
init-once / finalize-once contract and the query functions.
"""

from __future__ import annotations

import enum
import os
import threading
import time
from typing import Optional

from . import _runtime
from ._runtime import SpmdContext, current_env, require_env, set_env
from .error import AbortError, MPIError


class ThreadLevel(enum.IntEnum):
    """Thread support levels (src/environment.jl:111-116)."""
    THREAD_SINGLE = 0
    THREAD_FUNNELED = 1
    THREAD_SERIALIZED = 2
    THREAD_MULTIPLE = 3


THREAD_SINGLE = ThreadLevel.THREAD_SINGLE
THREAD_FUNNELED = ThreadLevel.THREAD_FUNNELED
THREAD_SERIALIZED = ThreadLevel.THREAD_SERIALIZED
THREAD_MULTIPLE = ThreadLevel.THREAD_MULTIPLE


def Init(session: "str | None" = None) -> None:
    """Initialize the environment on this rank (src/environment.jl:80-89).

    Must be called exactly once per rank before any communication. Under
    ``spmd_run``/``tpurun`` it attaches to the launcher's world; standalone it
    creates a world of size 1.

    ``session=`` is the serve-tier attach path (docs/serving.md): instead of
    paying a cold start, the process attaches to a running ``tpurun --serve``
    broker at the given address (or ``TPU_MPI_SERVE_SOCKET`` when the string
    is empty) and receives a lease on the broker's warm world. The attached
    :class:`~tpu_mpi.serve.ClientSession` is reachable via
    ``MPI.serve.current_session()`` and is detached by ``Finalize``.
    """
    Init_thread(ThreadLevel.THREAD_MULTIPLE, session=session)


def Init_thread(required: ThreadLevel,
                session: "str | None" = None) -> ThreadLevel:
    """Initialize requesting a thread level (src/environment.jl:148-162).

    The host runtime is thread-safe by construction (it *is* threads), so the
    granted level is always THREAD_MULTIPLE. See :func:`Init` for the
    ``session=`` serve-tier attach path.
    """
    if session is not None:
        from . import serve
        if serve.current_session() is not None:
            raise MPIError("MPI.Init(session=...) but a session is already "
                           "attached on this process")
        serve._set_current(serve.attach(session or None))
    env = current_env()
    if env is None:
        if os.environ.get("TPU_MPI_PROC_RANK") is not None:
            # Launched as one process of a multi-process world
            # (tpurun --procs): rendezvous over the native transport.
            from .backend import proc_attach
            env = proc_attach()
        else:
            ctx = SpmdContext(1)
            set_env((ctx, 0))
            env = (ctx, 0)
    ctx, rank = env
    if ctx.initialized[rank]:
        raise MPIError("MPI.Init() was already called on this rank")
    if ctx.finalized[rank]:
        raise MPIError("MPI.Init() called after MPI.Finalize()")
    ctx.initialized[rank] = True
    ctx.thread_level[rank] = ThreadLevel(required)
    ctx.main_threads[rank] = threading.get_ident()
    return ThreadLevel.THREAD_MULTIPLE


def Query_thread() -> ThreadLevel:
    """Granted thread level (src/environment.jl:173-180)."""
    require_env()
    return ThreadLevel.THREAD_MULTIPLE


def Is_thread_main() -> bool:
    """True on the thread that called Init (src/environment.jl:191-197)."""
    ctx, rank = require_env()
    return ctx.main_threads[rank] == threading.get_ident()


def Initialized() -> bool:
    """Whether Init has been called on this rank (src/environment.jl:267-273)."""
    env = current_env()
    if env is None:
        return False
    ctx, rank = env
    return ctx.initialized[rank]


def Finalized() -> bool:
    """Whether Finalize has been called on this rank (src/environment.jl:281-287)."""
    env = current_env()
    if env is None:
        return False
    ctx, rank = env
    return ctx.finalized[rank]


def Finalize() -> None:
    """Tear down the environment on this rank (src/environment.jl:220-236).

    After this, communication calls on this rank raise. Unlike the reference
    there are no C finalizers to sequence, so no refcount dance is needed.
    """
    ctx, rank = require_env()
    if not ctx.initialized[rank]:
        raise MPIError("MPI.Finalize() before MPI.Init()")
    if ctx.finalized[rank]:
        raise MPIError("MPI.Finalize() was already called on this rank")
    # reclaim every I-collective worker this rank created (one thread per
    # communicator that saw a nonblocking collective)
    from .collective import nb_shutdown
    nb_shutdown(ctx, world_rank=rank)
    # flush this rank's perf counters when a dump dir is configured
    # (TPU_MPI_PVARS_DUMP) — one branch when pvars are off
    from . import perfvars
    perfvars.finalize_dump()
    # likewise flush this rank's event trace (TPU_MPI_TRACE_DUMP) for
    # offline schedule exploration — a no-op unless tracing is on
    from .analyze import events as _trace_events
    _trace_events.finalize_dump()
    # detach the serve-tier session Init(session=...) opened, releasing the
    # lease cleanly (broker reclaims the cid namespace as detached)
    import sys
    serve = sys.modules.get("tpu_mpi.serve")
    if serve is not None and serve.current_session() is not None:
        serve.current_session().detach()
        serve._set_current(None)
    ctx.finalized[rank] = True


def Abort(comm=None, errorcode: "int | None" = None) -> None:
    """Terminate the whole job (src/environment.jl:252-254).

    Fate-shares: every rank blocked in the runtime raises AbortError. In the
    multi-process launcher the process additionally exits with ``errorcode``.
    With no explicit errorcode the AbortError carries ERR_ABORTED (code 1
    would collide with MPI_ERR_BUFFER in the error-class table).
    """
    env = current_env()
    if env is None:
        raise SystemExit(1 if errorcode is None else errorcode)
    ctx, rank = env
    suffix = "" if errorcode is None else f" with errorcode {errorcode}"
    err = AbortError(f"MPI.Abort called on rank {rank}{suffix}")
    if errorcode is not None:
        err.code = errorcode
    ctx.fail(err, rank)
    raise err


def Wtime() -> float:
    """High-resolution wall clock in seconds (src/environment.jl:295)."""
    return time.perf_counter()


_measured_tick: Optional[float] = None


def Wtick() -> float:
    """Resolution of Wtime (src/environment.jl:289).

    Returns the platform's ADVERTISED ``perf_counter`` resolution when it is
    plausible (strictly between 0 and 1 second — the MPI contract: Wtick is
    the seconds between ticks, and e.g. Windows advertises a bogus 1e-7 /
    some platforms report whole seconds). Otherwise falls back to a MEASURED
    tick — the minimum nonzero delta observed over a short spin — cached for
    the life of the process.
    """
    res = time.get_clock_info("perf_counter").resolution
    if 0.0 < res < 1.0:
        return res
    global _measured_tick
    if _measured_tick is None:
        best = 1.0
        for _ in range(1000):
            a = time.perf_counter()
            b = time.perf_counter()
            while b == a:           # spin until the clock visibly advances
                b = time.perf_counter()
            if b - a < best:
                best = b - a
        _measured_tick = best
    return _measured_tick


def Pcontrol(level: int) -> int:
    """MPI-standard profiling-level control, wired to the pvar subsystem
    (docs/observability.md): ``Pcontrol(0)`` disables counter collection,
    ``Pcontrol(1)`` restores the configured default (the ``pvars`` knob),
    and ``Pcontrol(level >= 2)`` enables collection AND immediately flushes
    a per-rank dump to ``pvars_dump`` (when set). Returns the effective
    collection level."""
    from . import perfvars
    return perfvars.pcontrol(level)


class profile_trace:
    """Context manager wrapping the JAX profiler: collectives issued inside
    the block are visible in the XPlane trace (view with TensorBoard or
    xprof). The concrete form of SURVEY.md §5's tracing subsystem — the
    reference has only Wtime/Wtick and points users at external PMPI tools;
    here the XLA profiler *is* the communication profiler, since every
    in-graph collective is an XLA op.

    The JAX profiler is a process singleton: under the thread-rank tier only
    the designated rank (default world rank 0) starts it and the rest no-op,
    so every rank can execute the same ``with`` block. Under the
    multi-process tier each rank IS its own process with its own profiler,
    so every rank traces (per-host xplane files land side by side in
    logdir). Callers outside SPMD always trace.

    >>> with MPI.profile_trace("/tmp/trace"):
    ...     step(params, batch)
    """

    def __init__(self, logdir: str, rank: int = 0):
        self.logdir = logdir
        self.rank = rank
        self._active = False

    def __enter__(self):
        env = current_env()
        multiproc = env is not None and getattr(env[0], "local_rank", None) is not None
        if env is None or multiproc or env[1] == self.rank:
            import jax
            jax.profiler.start_trace(self.logdir)
            self._active = True
        return self

    def __exit__(self, *exc):
        if self._active:
            import jax
            jax.profiler.stop_trace()
            self._active = False
        return False


def universe_size() -> Optional[int]:
    """Max processes the runtime can host (src/comm.jl:171-181 attribute)."""
    ctx, _ = require_env()
    return ctx.universe_size


def has_tpu() -> bool:
    """Whether a real TPU backend is attached (analog of has_cuda,
    src/environment.jl:308-323, including the env-var override)."""
    flag = os.environ.get("TPU_MPI_HAS_TPU")
    if flag is not None:
        return flag.lower() in ("1", "true", "yes")
    try:
        import jax
        return any(d.platform not in ("cpu",) for d in jax.devices())
    except Exception:
        return False
