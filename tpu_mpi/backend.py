"""Multi-process backend: one OS process per rank over the native transport.

The scale-out tier (SURVEY.md §2.5 "distributed communication backend"):
where the default runtime executes ranks as threads of one controller
process, this backend runs each rank in its own process — the deployment
shape of one process per TPU host over DCN — wired through the C++ framed
transport in ``tpu_mpi._native`` (the libmpi-analog progress engine,
/root/reference deps model: external native transport + in-language object
model).

Reused unchanged from the threaded runtime: the Mailbox matching engine
(tags/wildcards/probe), all of pointtopoint/collective/topology/io, and the
per-communicator collective protocol. What changes is the rendezvous: the
:class:`ProcChannel` gathers pickled contributions to the communicator's
rank-0 process, runs ``combine`` there, and scatters per-rank results —
the same "last arriver combines" contract, executed at a distinguished
process. Shared-object features (one-sided windows, Comm_spawn) require a
shared address space and raise in this mode.

Launch: ``tpurun -n N --procs script.py``. The launcher is the rendezvous
server: children report their transport ports, receive the full address map,
then run the script.
"""

from __future__ import annotations

import itertools
import json
import os
import pickle
import socket
import threading
from typing import Any, Callable, Optional, Sequence

import numpy as np

from . import config
from ._runtime import (ANY_SOURCE, Mailbox, Message, SpmdContext, _Waitable,
                       set_env)
from .error import AbortError, CollectiveMismatchError, MPIError

_POLL_MS = 50


def _is_jax(x: Any) -> bool:
    return type(x).__module__.startswith("jax") or type(x).__name__ == "ArrayImpl"


class _JaxLeaf:
    """Pickle surrogate for a jax.Array (device placement is per-process)."""

    __slots__ = ("value",)

    def __init__(self, arr):
        self.value = np.asarray(arr)


def _pack(obj: Any) -> Any:
    """Recursively replace jax arrays with host surrogates for the wire."""
    if _is_jax(obj):
        return _JaxLeaf(obj)
    if isinstance(obj, tuple):
        return tuple(_pack(o) for o in obj)
    if isinstance(obj, list):
        return [_pack(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    return obj


def _unpack(obj: Any) -> Any:
    if isinstance(obj, _JaxLeaf):
        import jax.numpy as jnp
        return jnp.asarray(obj.value)
    if isinstance(obj, tuple):
        return tuple(_unpack(o) for o in obj)
    if isinstance(obj, list):
        return [_unpack(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _unpack(v) for k, v in obj.items()}
    return obj


class _RemoteMailbox:
    """Sender-side proxy: post() ships the Message to the owning process."""

    def __init__(self, ctx: "ProcContext", world_rank: int):
        self.ctx = ctx
        self.world_rank = world_rank

    def post(self, msg: Message) -> None:
        if msg.kind == "objref":
            raise MPIError(
                "cannot send an unpicklable object to another process; "
                "multi-process ranks do not share an address space")
        frame = pickle.dumps(
            ("p2p", msg.src, msg.tag, msg.cid, _pack(msg.payload),
             msg.count, msg.dtype, msg.kind))
        self.ctx.transport.send(self.world_rank, frame)

    def notify(self) -> None:  # failure broadcast reaches processes via abort
        pass


class ProcChannel(_Waitable):
    """Cross-process collective rendezvous for one communicator.

    Protocol per round (rounds serialize per communicator because every rank
    blocks in run()): non-root ranks send (opname, contrib) to the comm's
    rank 0 process; rank 0 verifies opnames match, executes combine, and
    sends each rank its result slot. Equivalent observable behavior to the
    threaded CollectiveChannel, including mismatch fail-fast.
    """

    def __init__(self, ctx: "ProcContext", cid: Any, group: tuple[int, ...]):
        self.ctx = ctx
        self.cid = cid
        self.group = group
        self.lock = threading.RLock()
        self.cond = threading.Condition(self.lock)
        self.round = 0
        # (round, comm_rank) -> (opname, contrib) at root;
        # (round,) -> result at non-root. Fed by the drainer thread.
        self.inbox: dict[Any, Any] = {}

    # -- drainer entry points -------------------------------------------------
    def deliver_contrib(self, rnd: int, src: int, opname: str, contrib: Any) -> None:
        with self.cond:
            self.inbox[(rnd, src)] = (opname, contrib)
            self.cond.notify_all()

    def deliver_result(self, rnd: int, result: Any) -> None:
        with self.cond:
            self.inbox[(rnd,)] = result
            self.cond.notify_all()

    # -- the collective contract ---------------------------------------------
    def run(self, rank: int, contrib: Any,
            combine: Callable[[list[Any]], Sequence[Any]], opname: str) -> Any:
        ctx = self.ctx
        n = len(self.group)
        with self.cond:
            rnd = self.round
            self.round += 1
        root_world = self.group[0]
        if ctx.local_rank != root_world:
            frame = self._encode(("coll", self.cid, rnd, rank, opname,
                                  _pack(contrib)), opname)
            ctx.transport.send(root_world, frame)
            with self.cond:
                self._wait_for(lambda: (rnd,) in self.inbox,
                               f"collective {opname}")
                res = self.inbox.pop((rnd,))
            return _unpack(res)

        # root: gather, verify, combine, scatter
        with self.cond:
            self._wait_for(
                lambda: all((rnd, r) in self.inbox for r in range(n) if r != rank),
                f"collective {opname} (gather)")
            gathered: list[Any] = [None] * n
            for r in range(n):
                if r == rank:
                    gathered[r] = (opname, contrib)
                else:
                    gathered[r] = self.inbox.pop((rnd, r))
        names = {op for op, _ in gathered}
        if len(names) > 1:
            err = CollectiveMismatchError(
                f"ranks disagree on the collective for cid {self.cid}: "
                f"{sorted(names)}")
            self.ctx.fail(err)
            raise err
        try:
            results = list(combine([_unpack(c) for _, c in gathered]))
        except BaseException as e:
            self.ctx.fail(e)
            raise
        if len(results) != n:
            err = MPIError(f"combine for {opname} returned {len(results)} "
                           f"results for {n} ranks")
            self.ctx.fail(err)
            raise err
        for r in range(n):
            if r == rank:
                continue
            frame = self._encode(("collres", self.cid, rnd, _pack(results[r])),
                                 opname)
            ctx.transport.send(self.group[r], frame)
        return results[rank]

    def _encode(self, item: Any, opname: str) -> bytes:
        """Pickle a protocol frame; an unpicklable payload fate-shares with a
        clear error instead of a raw PicklingError mid-protocol (the p2p
        proxy already guards its equivalent case)."""
        try:
            return pickle.dumps(item)
        except Exception as e:
            err = MPIError(
                f"collective {opname} payload is not picklable and "
                f"multi-process ranks do not share an address space: {e}")
            self.ctx.fail(err)
            raise err from None


class ProcContext(SpmdContext):
    """A world whose ranks are OS processes; this instance represents one.

    `size` is the world size but only ``local_rank`` runs here. Mailbox
    index ``local_rank`` is the real matching engine; all other slots are
    wire proxies. Failure fate-sharing crosses processes via abort frames
    (and the launcher kills the job on any nonzero exit, mpiexec-style).
    """

    def __init__(self, local_rank: int, size: int, transport,
                 universe_size: Optional[int] = None):
        super().__init__(size, universe_size=universe_size)
        self.local_rank = local_rank
        self.transport = transport
        self._cid_counter = itertools.count(0)
        self.mailboxes = [
            Mailbox(self) if r == local_rank else _RemoteMailbox(self, r)
            for r in range(size)
        ]
        self._drainer = threading.Thread(target=self._drain, daemon=True,
                                         name="tpu-mpi-drainer")
        self._drainer_stop = threading.Event()
        self._drainer.start()

    # -- frame pump -----------------------------------------------------------
    def _drain(self) -> None:
        while not self._drainer_stop.is_set():
            try:
                got = self.transport.recv(_POLL_MS)
            except ConnectionResetError:
                return
            if got is None:
                continue
            src_world, frame = got
            try:
                item = pickle.loads(frame)
            except Exception as e:              # corrupted frame: fate-share
                self.fail(MPIError(f"undecodable frame from {src_world}: {e}"))
                continue
            try:
                self._dispatch(src_world, item)
            except Exception as e:
                # A failure while dispatching a decoded frame (malformed
                # tuple, error inside deliver/post) must fate-share, not
                # silently kill the drainer thread (ADVICE r1).
                self.fail(MPIError(
                    f"error dispatching frame from {src_world}: "
                    f"{type(e).__name__}: {e}"))

    def _dispatch(self, src_world: int, item: Any) -> None:
        kind = item[0]
        if kind == "p2p":
            _, src, tag, cid, payload, count, dtype, mkind = item
            msg = Message(src, tag, cid, _unpack(payload), count, dtype,
                          mkind)
            self.mailboxes[self.local_rank].post(msg)
        elif kind == "coll":
            _, cid, rnd, src, opname, contrib = item
            self._proc_channel(cid).deliver_contrib(rnd, src, opname,
                                                    contrib)
        elif kind == "collres":
            _, cid, rnd, result = item
            self._proc_channel(cid).deliver_result(rnd, result)
        elif kind == "abort":
            _, text = item
            with self._failure_lock:
                if self.failure is None:
                    self.failure = AbortError(text)
            self.mailboxes[self.local_rank].notify()
            for ch in list(self._channels.values()):
                with ch.cond:
                    ch.cond.notify_all()

    # -- channel management ---------------------------------------------------
    def _proc_channel(self, cid: Any) -> ProcChannel:
        with self._channels_lock:
            ch = self._channels.get(cid)
            if ch is None:
                # Drainer can see a contribution before the local rank enters
                # the collective; group is filled in on first local entry but
                # rank-0 routing only needs the cid until then.
                ch = ProcChannel(self, cid, ())
                self._channels[cid] = ch
            return ch

    def channel(self, cid: Any, size: int, group: Optional[tuple[int, ...]] = None):
        if group is None:
            raise MPIError("this communicator type is not supported in "
                           "multi-process mode")
        ch = self._proc_channel(cid)
        if not ch.group:
            ch.group = tuple(group)
        return ch

    def alloc_cid(self) -> int:
        """Process-namespaced context ids. alloc_cid runs inside combine(),
        which executes only at the allocating comm's ROOT process — each
        process has its own counter, so two different roots would mint the
        same id (observed: a split-of-a-split deadlocks on the reused
        channel). Stride by world size, offset by this process's rank:
        disjoint id spaces, still plain ints."""
        return 2 + self.local_rank + self.size * next(self._cid_counter)

    # -- overrides: shared-address-space features -----------------------------
    def add_ranks(self, n: int, world_cid: Any):
        raise MPIError("Comm_spawn is not supported in multi-process mode; "
                       "launch the full world up front (tpurun -n N --procs)")

    @property
    def supports_shared_objects(self) -> bool:
        return False

    def device_for(self, rank: int):
        import jax
        devs = jax.devices()
        return devs[rank % len(devs)]

    # -- failure fate-sharing -------------------------------------------------
    def fail(self, exc: BaseException, rank: Optional[int] = None) -> None:
        super().fail(exc, rank)
        text = f"{type(exc).__name__}: {exc}" + (
            f" originating on rank {rank}" if rank is not None else
            f" originating on rank {self.local_rank}")
        frame = pickle.dumps(("abort", text))
        for r in range(self.size):
            if r != self.local_rank:
                try:
                    self.transport.send(r, frame)
                except Exception:
                    pass

    def shutdown(self) -> None:
        self._drainer_stop.set()
        self.transport.stop()


# ---------------------------------------------------------------------------
# rendezvous: child side
# ---------------------------------------------------------------------------

def proc_attach() -> tuple[ProcContext, int]:
    """Join the multi-process world described by the TPU_MPI_PROC_* env
    (set by the launcher): start the native transport, rendezvous with the
    coordinator for the address map, and bind this process as its rank."""
    from ._native import NativeTransport

    rank = int(os.environ["TPU_MPI_PROC_RANK"])
    size = int(os.environ["TPU_MPI_PROC_SIZE"])
    coord = os.environ["TPU_MPI_PROC_COORD"]
    host, port = coord.rsplit(":", 1)

    transport = NativeTransport(rank, size)
    with socket.create_connection((host, int(port)), timeout=60) as s:
        # The address map only arrives once ALL siblings have joined; sibling
        # startup skew (native build, cold jax import) routinely exceeds the
        # connect timeout, so wait much longer for the map itself.
        s.settimeout(config.load().rendezvous_timeout)
        s.sendall(json.dumps({"rank": rank, "port": transport.port}).encode()
                  + b"\n")
        buf = b""
        while not buf.endswith(b"\n"):
            try:
                chunk = s.recv(65536)
            except socket.timeout:
                raise MPIError(
                    f"rendezvous timed out waiting for the world address map "
                    f"(rank {rank}; are all {size} ranks up?)") from None
            if not chunk:
                raise MPIError("coordinator closed during rendezvous")
            buf += chunk
    addrs = json.loads(buf.decode())
    if isinstance(addrs, dict) and "error" in addrs:
        raise MPIError(f"rendezvous failed: {addrs['error']}")
    transport.set_peers(addrs)
    ctx = ProcContext(rank, size, transport)
    set_env((ctx, rank))
    # Deterministic teardown: stop the drainer + native progress thread at
    # interpreter exit rather than relying on GC-order __del__.
    import atexit
    atexit.register(ctx.shutdown)
    return ctx, rank


# ---------------------------------------------------------------------------
# rendezvous: coordinator (launcher) side
# ---------------------------------------------------------------------------

class Coordinator:
    """Address-map rendezvous server run by the launcher process."""

    def __init__(self, nprocs: int, host: str = "127.0.0.1"):
        self.nprocs = nprocs
        self.host = host
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, 0))
        self.sock.listen(nprocs + 4)
        self.port = self.sock.getsockname()[1]
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _serve(self) -> None:
        conns: dict[int, socket.socket] = {}     # rank -> connection
        addrs: dict[int, str] = {}               # rank -> "host:port"
        try:
            while len(conns) < self.nprocs:
                c, peer = self.sock.accept()
                buf = b""
                while not buf.endswith(b"\n"):
                    chunk = c.recv(65536)
                    if not chunk:
                        break
                    buf += chunk
                try:
                    info = json.loads(buf.decode())
                    rank = int(info["rank"])
                    port = int(info["port"])
                except Exception:
                    c.close()                    # garbled registration
                    continue
                if rank in conns or not (0 <= rank < self.nprocs):
                    # Duplicate or out-of-range rank: reject THIS registrant
                    # with a diagnostic instead of overwriting a sibling's
                    # slot and later dying on a missing rank (ADVICE r1).
                    try:
                        c.sendall((json.dumps(
                            {"error": f"rendezvous rejected rank {rank}: "
                                      + ("already registered" if rank in conns
                                         else "out of range")}) + "\n").encode())
                    except Exception:
                        pass
                    c.close()
                    continue
                # A child on another host reports its transport port; pair it
                # with the address it connected from (loopback children report
                # the coordinator-visible host).
                chost = peer[0] if peer[0] not in ("127.0.0.1", "::1") else self.host
                addrs[rank] = f"{chost}:{port}"
                conns[rank] = c
            world = [addrs[r] for r in range(self.nprocs)]
            payload = (json.dumps(world) + "\n").encode()
            for c in conns.values():
                try:
                    c.sendall(payload)
                finally:
                    c.close()
        except Exception as e:
            # Serve-side failure: tell every connected child so it fails fast
            # instead of blocking out the full rendezvous timeout.
            err = (json.dumps({"error": f"coordinator failed: {e}"}) + "\n").encode()
            for c in conns.values():
                try:
                    c.sendall(err)
                except Exception:
                    pass
                c.close()

    def close(self) -> None:
        try:
            self.sock.close()
        except Exception:
            pass
