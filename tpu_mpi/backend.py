"""Multi-process backend: one OS process per rank over the native transport.

The scale-out tier (SURVEY.md §2.5 "distributed communication backend"):
where the default runtime executes ranks as threads of one controller
process, this backend runs each rank in its own process — the deployment
shape of one process per TPU host over DCN — wired through the C++ framed
transport in ``tpu_mpi._native`` (the libmpi-analog progress engine,
/root/reference deps model: external native transport + in-language object
model).

Reused unchanged from the threaded runtime: the Mailbox matching engine
(tags/wildcards/probe), all of pointtopoint/collective/topology/io, and the
per-communicator collective protocol. What changes is the rendezvous: the
:class:`ProcChannel` gathers pickled contributions to the communicator's
rank-0 process, runs ``combine`` there, and scatters per-rank results —
the same "last arriver combines" contract, executed at a distinguished
process. One-sided windows work across processes via the RMA wire engine
(``tpu_mpi._rma_wire``): owners apply Put/Get/Accumulate/lock frames shipped
by origins, and shared windows are real POSIX shared memory.

Launch: ``tpurun -n N --procs script.py``. The launcher is the rendezvous
server: children report their transport ports, receive the full address map,
then run the script.
"""

from __future__ import annotations

import itertools
import json
import os
import pickle
import socket
import struct
import sys
import threading
import time
import zlib
from typing import Any, Callable, Optional, Sequence

import numpy as np

from . import config
from . import perfvars as _pv
from . import serialization
from .buffers import is_wire_snapshot
from ._runtime import (ANY_SOURCE, FailureDetector, Mailbox, Message,
                       SpmdContext, _Waitable, collective_wait_limit,
                       deadlock_timeout, set_env, set_process_env)
from .error import (AbortError, CollectiveMismatchError, DeadlockError,
                    MPIError, ProcFailedError)

_POLL_MS = 50

# Below this payload size the star rendezvous wins on latency (2 hops vs
# 2(P-1) ring steps); above it the ring's O(bytes/P) per-process traffic wins.
_RING_MIN_BYTES = int(os.environ.get("TPU_MPI_RING_MIN_BYTES", str(64 * 1024)))


# ---------------------------------------------------------------------------
# Zero-copy wire encoding: pickle protocol 5 with out-of-band buffers.
# A frame is [magic][nbufs u32][skel_len u64][skeleton pickle]
# [flag u8 + len u64 + body]*. Array payloads (numpy, and jax via _JaxLeaf)
# travel out of band — no pickle byte-copy — by one of two lanes per buffer:
#
# - flag 0 (inline): raw buffer bytes in the TCP stream, decoded as zero-copy
#   views into the received frame (the reference gets this from libmpi's
#   typed transport; VERDICT r1 weak item 7);
# - flag 1 (shm): for large buffers bound for a SAME-HOST rank, the body is
#   just the name of a one-shot POSIX shm segment holding the bytes — the
#   libmpi shared-memory-BTL analog. The sender writes the segment (tmpfs:
#   one memcpy), the receiver maps it, unlinks it immediately (the mapping
#   keeps it alive) and decodes arrays as views straight into the mapping, so
#   the payload never crosses a socket and is copied exactly once end to end.
#   The launcher sweeps any segments orphaned by a crashed rank.
# ---------------------------------------------------------------------------

_OOB_MAGIC = b"\x01TMB6"
_STAR = object()     # "no algorithm applies; use the generic star rendezvous"

_SHM_DIR = "/dev/shm"
_shm_counter = itertools.count()


_shm_min_cached: Optional[int] = None


def _shm_min_bytes() -> int:
    """Payload threshold for the shm lane; 0 (or a missing /dev/shm)
    disables. Resolved once — this sits on the per-message send path, and
    neither the config nor /dev/shm's existence changes mid-job."""
    global _shm_min_cached
    if _shm_min_cached is None:
        _shm_min_cached = (config.load().shm_min_bytes
                           if os.path.isdir(_SHM_DIR) else 0)
    return _shm_min_cached


def shm_job_tag() -> str:
    """Per-job namespace for shm segment names (the coordinator port is
    shared by every rank of a job and by the launcher, which sweeps
    ``tpumpi_<tag>_*`` leftovers after the job ends). Comm_spawn'ed children
    inherit the job tag via TPU_MPI_SHM_TAG — their PROC_COORD points at an
    ephemeral spawn coordinator nothing would ever sweep."""
    tag = os.environ.get("TPU_MPI_SHM_TAG")
    if tag:
        return tag
    coord = os.environ.get("TPU_MPI_PROC_COORD", "")
    return coord.rsplit(":", 1)[-1] or "local"


def _shm_spill(mv: memoryview) -> bytes:
    """Write a buffer into a fresh one-shot shm segment; return its name."""
    name = f"tpumpi_{shm_job_tag()}_{os.getpid()}_{next(_shm_counter)}"
    path = os.path.join(_SHM_DIR, name)
    fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o600)
    try:
        view = mv.cast("B")
        off = 0
        while off < view.nbytes:
            off += os.write(fd, view[off:])
    except BaseException:
        os.close(fd)
        try:                       # don't leave a partial segment pinning RAM
            os.unlink(path)
        except OSError:
            pass
        raise
    os.close(fd)
    return name.encode()


def sweep_segments(tag: str, only_dead_creators: bool = False) -> None:
    """Unlink shm-lane segments for a job tag. The launcher calls this after
    every child has exited (a clean run leaves nothing — receivers unlink at
    load time); ranks launched by an external scheduler call it with
    ``only_dead_creators=True`` at attach, reclaiming segments whose creating
    process (the pid embedded in the name) is gone."""
    import glob
    for seg in glob.glob(os.path.join(_SHM_DIR, f"tpumpi_{tag}_*")):
        if only_dead_creators:
            try:
                pid = int(os.path.basename(seg).split("_")[2])
            except (IndexError, ValueError):
                continue
            if os.path.exists(f"/proc/{pid}"):
                continue
        try:
            os.unlink(seg)
        except OSError:
            pass


def _shm_load(name: str) -> memoryview:
    """Map a one-shot segment and unlink it; the returned view (and any
    arrays decoded over it) keeps the mapping alive until GC."""
    import mmap as _mmap
    path = os.path.join(_SHM_DIR, name)
    fd = os.open(path, os.O_RDWR)
    try:
        os.unlink(path)
        size = os.fstat(fd).st_size
        m = _mmap.mmap(fd, size)
    finally:
        os.close(fd)
    return memoryview(m)


def dumps_oob_parts(item: Any, shm_ok: bool = False) -> list:
    """Encode as a list of wire segments (header/skeleton bytes + raw array
    buffers). Sent with ``transport.sendv`` so array payloads go from their
    own memory straight to the socket — no join copy. With ``shm_ok`` (the
    destination shares this host), large buffers take the shm lane instead."""
    bufs: list[pickle.PickleBuffer] = []
    # extended pickler: closures/local classes inside frames (spawn
    # commands, custom ops, object payloads) travel by value cross-process
    skel = serialization.dumps_oob(item, buffer_callback=bufs.append)
    parts = [_OOB_MAGIC + struct.pack("<IQ", len(bufs), len(skel)), skel]
    shm_min = _shm_min_bytes() if shm_ok else 0
    for pb in bufs:
        mv = pb.raw()
        if not mv.c_contiguous:
            mv = memoryview(bytes(mv))
        if shm_min and mv.nbytes >= shm_min:
            name = _shm_spill(mv)
            parts.append(struct.pack("<BQ", 1, len(name)))
            parts.append(name)
        else:
            parts.append(struct.pack("<BQ", 0, mv.nbytes))
            parts.append(mv.cast("B"))
    return parts


def dumps_oob(item: Any) -> bytes:
    return b"".join(dumps_oob_parts(item))


def send_frame(transport, world_dst: int, item: Any,
               shm_ok: bool = False) -> None:
    """Encode + send a protocol frame with scatter-gather zero-copy."""
    transport.sendv(world_dst, dumps_oob_parts(item, shm_ok=shm_ok))


def loads_oob(frame: bytes) -> Any:
    if frame[:len(_OOB_MAGIC)] != _OOB_MAGIC:
        return pickle.loads(frame)       # legacy/plain frames (abort, …)
    mv = memoryview(frame)
    off = len(_OOB_MAGIC)
    nbufs, skel_len = struct.unpack_from("<IQ", frame, off)
    off += 12
    skel = mv[off:off + skel_len]
    off += skel_len
    bufs = []
    for _ in range(nbufs):
        flag, ln = struct.unpack_from("<BQ", frame, off)
        off += 9
        if flag == 1:
            bufs.append(_shm_load(bytes(mv[off:off + ln]).decode()))
        else:
            bufs.append(mv[off:off + ln])
        off += ln
    return pickle.loads(skel, buffers=bufs)


def _is_jax(x: Any) -> bool:
    return type(x).__module__.startswith("jax") or type(x).__name__ == "ArrayImpl"


# ---------------------------------------------------------------------------
# Binary P2P fast lane (VERDICT r2 weak #4: ~180 us small-message latency,
# dominated by pickle-protocol-5 framing of a 9-tuple per message). Typed
# numpy payloads with simple dtypes — the OSU-style hot path — skip pickle
# entirely: a fixed struct header + dtype tag + raw payload bytes. Complex
# cases (structured dtypes, jax payloads, shm-lane-sized frames, arbitrary
# objects) keep the generic OOB pickle codec.
# ---------------------------------------------------------------------------

_FAST_MAGIC = b"\x02TMP"
# magic, src, tag, cid-form (0: plain int in c1 | 1: the proc-tier tuple
# ("c", rank, counter) in (c1, c2)), c1, c2, count, seq (-1 = unstamped),
# kind (0 typed / 1 object-bytes), dtype tag length. The magic is part of
# the struct so the header packs in ONE call (no bytes concat per message).
_FAST_HDR = struct.Struct("<4siiBqqqqBB")
_FAST_JOIN_MAX = 8192        # below this, join into ONE buffer: a single
                             # FFI call + write beats per-part view setup
                             # (matches the transport's single-recv window)

_fast_dt_tag: dict = {}      # np.dtype -> tag bytes (send side)
_fast_dt_cache: dict = {}    # tag bytes -> (np.dtype, Datatype) (recv side)


def _fast_p2p_parts(msg: Message, seq: Optional[int]) -> Optional[list]:
    """Encode a P2P message on the fast lane, or None if ineligible."""
    payload = msg.payload
    if msg.kind == "typed" and isinstance(payload, np.ndarray):
        dt = _fast_dt_tag.get(payload.dtype)
        if dt is None:
            if payload.dtype.names is not None or payload.dtype.hasobject:
                return None      # structured/object dtypes: .str is lossy
            dt = payload.dtype.str.encode()
            _fast_dt_tag[payload.dtype] = dt
        if not payload.flags.c_contiguous:
            payload = np.ascontiguousarray(payload)
        kind = 0
    elif msg.kind == "object" and isinstance(payload, (bytes, bytearray)):
        dt = b""
        kind = 1
    else:
        return None
    if len(dt) > 255:
        return None
    cid = msg.cid
    if isinstance(cid, int):
        cform, c1, c2 = 0, cid, 0
    elif (isinstance(cid, tuple) and len(cid) == 3 and cid[0] == "c"
          and isinstance(cid[1], int) and isinstance(cid[2], int)):
        # the multi-process tier's process-namespaced context ids
        # (ProcContext.alloc_cid: ("c", world rank, counter))
        cform, c1, c2 = 1, cid[1], cid[2]
    else:
        return None
    hdr = _FAST_HDR.pack(_FAST_MAGIC, msg.src, msg.tag, cform, c1, c2,
                         msg.count, -1 if seq is None else seq, kind,
                         len(dt)) + dt
    if kind == 0:
        nbytes = payload.nbytes
        if nbytes <= _FAST_JOIN_MAX:
            return [hdr + payload.tobytes()]
        return [hdr, payload]
    if len(payload) <= _FAST_JOIN_MAX:
        return [hdr + payload]
    return [hdr, payload]


def _fast_p2p_decode(frame) -> Optional[Message]:
    """Decode a fast-lane frame (memoryview) into a Message, or None."""
    if frame[:4] != _FAST_MAGIC:     # memoryview == bytes: no copy
        return None
    (_, src, tag, cform, c1, c2, count, seq, kind,
     dtlen) = _FAST_HDR.unpack_from(frame, 0)
    cid = c1 if cform == 0 else ("c", c1, c2)
    off = _FAST_HDR.size
    if kind == 0:
        dts = bytes(frame[off:off + dtlen])
        cached = _fast_dt_cache.get(dts)
        if cached is None:
            from .datatypes import to_datatype
            np_dt = np.dtype(dts.decode())
            cached = (np_dt, to_datatype(np_dt))
            _fast_dt_cache[dts] = cached
        np_dt, dtype = cached
        payload = np.frombuffer(frame[off + dtlen:], dtype=np_dt,
                                count=count)
        return Message(src, tag, cid, payload, count, dtype, "typed",
                       seq=None if seq < 0 else seq)
    payload = bytes(frame[off:])
    return Message(src, tag, cid, payload, count, None, "object",
                   seq=None if seq < 0 else seq)


class _JaxLeaf:
    """Pickle surrogate for a jax.Array (device placement is per-process)."""

    __slots__ = ("value",)

    def __init__(self, arr):
        self.value = np.asarray(arr)


def _pack(obj: Any) -> Any:
    """Recursively replace jax arrays with host surrogates for the wire."""
    if _is_jax(obj):
        return _JaxLeaf(obj)
    if isinstance(obj, tuple):
        return tuple(_pack(o) for o in obj)
    if isinstance(obj, list):
        return [_pack(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    return obj


def _unpack(obj: Any) -> Any:
    if isinstance(obj, _JaxLeaf):
        import jax.numpy as jnp
        return jnp.asarray(obj.value)
    if isinstance(obj, tuple):
        return tuple(_unpack(o) for o in obj)
    if isinstance(obj, list):
        return [_unpack(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _unpack(v) for k, v in obj.items()}
    return obj


class _RemoteMailbox:
    """Sender-side proxy: post() ships the Message to the owning process.

    Flow control (the cross-process half of the blocking-send backpressure):
    a receiver whose unexpected queue crosses the high-water mark sends a
    ``choke`` frame; ``post_blocking`` waits while this destination has us
    choked, resuming on its ``unchoke``. Buffered Isend traffic is exempt,
    mirroring the thread tier."""

    def __init__(self, ctx: "ProcContext", world_rank: int):
        self.ctx = ctx
        self.world_rank = world_rank

    def post_blocking(self, msg: Message, what: str) -> None:
        ctx = self.ctx
        # Lock-free peek (hot path): choked_by only has entries while this
        # destination is over its high-water mark. Missing a just-added
        # choke lets at most one extra message through — backpressure is a
        # sustained-imbalance mechanism, not an exact credit count.
        if self.world_rank in ctx.choked_by:
            from ._runtime import deadlock_timeout
            deadline = time.monotonic() + deadlock_timeout()
            with ctx._choke_cond:
                while self.world_rank in ctx.choked_by:
                    ctx.check_failure()
                    if self.world_rank in ctx.failed_ranks:
                        raise ProcFailedError(
                            f"rank {self.world_rank} died while it had this "
                            f"sender choked ({what})",
                            ranks=(self.world_rank,))
                    if time.monotonic() > deadline:
                        raise DeadlockError(
                            f"deadlock suspected: rank {self.world_rank} kept "
                            f"this sender choked >{deadlock_timeout()}s in {what}")
                    ctx._choke_cond.wait(0.02)
        self.post(msg)

    def post(self, msg: Message) -> None:
        if msg.kind == "objref":
            raise MPIError(
                "cannot send an unpicklable object to another process; "
                "multi-process ranks do not share an address space")
        if self.ctx.debug_seq:
            # Stamp AND ship under one lock: a concurrent sender thread that
            # stamped first must also hit the wire first, or the receiver's
            # monotonic check would flag legal THREAD_MULTIPLE interleavings.
            # Serializing sends per process is an acceptable debug-mode cost.
            with self.ctx._seq_lock:
                seq = self.ctx._seq_counters.get(
                    (self.world_rank, msg.cid, msg.src), 0) + 1
                self.ctx._seq_counters[(self.world_rank, msg.cid, msg.src)] = seq
                self._ship(msg, seq)
            return
        self._ship(msg, None)

    def _ship(self, msg: Message, seq: Optional[int]) -> None:
        ctx = self.ctx
        # fast lane: pickle-free binary frame for typed/bytes payloads,
        # unless the payload should ride the shm lane instead (large +
        # same-host — the generic codec handles the spill)
        nbytes = getattr(msg.payload, "nbytes", None)
        # cheapest test first: small payloads (the latency path) resolve the
        # whole predicate on the threshold compare alone
        shm_wins = (nbytes is not None and (m := _shm_min_bytes())
                    and nbytes >= m and ctx.shm_ok(self.world_rank))
        parts = None
        if not shm_wins:
            try:
                parts = _fast_p2p_parts(msg, seq)
            except Exception:
                # any unexpected shape falls back to the generic codec —
                # an encode hiccup must never poison the job (found live:
                # tuple cids from sub-communicators)
                parts = None
        try:
            if parts is not None:
                if len(parts) == 1:
                    ctx.transport.send(self.world_rank, parts[0])
                else:
                    ctx.transport.sendv(self.world_rank, parts)
                return
            ctx.send_frame(self.world_rank,
                           ("p2p", msg.src, msg.tag, msg.cid,
                            _pack(msg.payload), msg.count, msg.dtype,
                            msg.kind, seq))
        except ConnectionError:
            if ctx._detector is None:
                raise
            # typed ULFM error for a send to a dead peer (detector active)
            ctx.peer_failed(self.world_rank)
            raise ProcFailedError(
                f"rank {self.world_rank} died before this send completed",
                ranks=(self.world_rank,)) from None

    def notify(self) -> None:  # failure broadcast reaches processes via abort
        pass


class _ShmColl:
    """One mmap'd /dev/shm segment shared by every rank of a same-host
    communicator — the libmpi ``coll/sm`` analog, and the latency tier the
    tuned table selects for small Allreduce/Barrier on single-host jobs.

    Layout: (n+1) cache-line header slots (seq, nbytes, ophash, dthash)
    followed by (n+1) data slots of ``coll_shm_max_bytes`` each; slot i
    belongs to comm rank i, slot n is the fold rank's result. The round
    protocol is a seqlock in one direction only: a writer publishes data
    first and its monotonically-increasing seq word LAST, readers spin for
    the exact seq value of their round (``rnd + 1``). The channel round
    counter and the run()-side blocking make slot reuse safe: a rank can
    only overwrite its contribution slot after it consumed the previous
    round's result, which the fold rank publishes only after consuming
    every previous contribution.

    Every rank opens the segment with O_CREAT (idempotent create +
    ftruncate), and the fold rank unlinks the path after its FIRST complete
    contribution gather — by then every rank has provably mapped the same
    inode, so the name is dead weight (the mappings keep it alive) and a
    crashed job leaves at most one transient name for the launcher sweep.
    A seq word ever observed ABOVE the expected round is a protocol error
    (stale segment from a previous job reusing the tag, or divergent
    configs) and fails loudly instead of hanging.
    """

    SLOT = 64                              # one cache line per header
    HDR = struct.Struct("<qqII")           # seq, nbytes, ophash, dthash

    def __init__(self, ctx: "ProcContext", cid: Any, group: tuple):
        import mmap as _mmap
        self.ctx = ctx
        self.cid = cid
        self.n = n = len(group)
        self.cap = max(int(config.load().coll_shm_max_bytes), 1)
        slug = ("-".join(str(p) for p in cid) if isinstance(cid, tuple)
                else str(cid))
        # non-numeric third name field: the external-scheduler
        # dead-creator sweep (which parses a pid there) skips these
        self.path = os.path.join(
            _SHM_DIR, f"tpumpi_{shm_job_tag()}_coll-{slug}")
        self.size = (n + 1) * (self.SLOT + self.cap)
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o600)
        try:
            st = os.fstat(fd)
            if st.st_size not in (0, self.size):
                raise MPIError(
                    f"shm collective segment {self.path} is {st.st_size} "
                    f"bytes, expected {self.size} — stale segment from a "
                    f"previous job sharing tag {shm_job_tag()!r}, or "
                    f"TPU_MPI_COLL_SHM_MAX_BYTES differs across ranks")
            os.ftruncate(fd, self.size)
            self.mm = _mmap.mmap(fd, self.size)
        finally:
            os.close(fd)
        self.unlinked = False
        # registered-plan slot leases (overlap.PlanRegistration.shm_release):
        # a persistent Allreduce pre-maps the segment at plan creation and
        # holds a lease until released; Comm.free asserts (strict mode) that
        # every lease was dropped before the mapping may be torn down
        self.leases = 0

    def _hdr(self, slot: int) -> int:
        return slot * self.SLOT

    def data_off(self, slot: int) -> int:
        return (self.n + 1) * self.SLOT + slot * self.cap

    def publish(self, slot: int, want: int, ophash: int, dthash: int,
                data) -> None:
        """Data first, header fields next, the seq word LAST (the readiness
        flag readers spin on; the GIL + x86 TSO order the stores)."""
        nb = 0
        if data is not None:
            nb = data.nbytes
            off = self.data_off(slot)
            self.mm[off:off + nb] = data
        h = self._hdr(slot)
        struct.pack_into("<qII", self.mm, h + 8, nb, ophash, dthash)
        struct.pack_into("<q", self.mm, h, want)

    def header(self, slot: int) -> tuple:
        return self.HDR.unpack_from(self.mm, self._hdr(slot))

    def spin(self, slot: int, want: int, opname: str) -> None:
        """Exact-value seq spin with escalating back-off (yield → sleep(0)
        → 200 us naps): on an oversubscribed host the other ranks need this
        core to make the progress being waited for."""
        limit = collective_wait_limit(opname) or deadlock_timeout()
        deadline = time.monotonic() + limit
        yield_ = getattr(os, "sched_yield", None)
        it = 0
        while True:
            v = struct.unpack_from("<q", self.mm, self._hdr(slot))[0]
            if v == want:
                return
            if v > want:
                err = MPIError(
                    f"shm collective protocol error in {opname!r}: slot "
                    f"{slot} seq {v} is past round {want} — stale segment "
                    f"from a previous job sharing tag {shm_job_tag()!r}?")
                self.ctx.fail(err)
                raise err
            self.ctx.check_failure()
            if self.ctx.failed_ranks or self.ctx.revoked_cids:
                self.ctx.check_fault(self.cid)   # dead peer / revoked comm
            it += 1
            if it < 200 and yield_ is not None:
                yield_()
            elif it < 2000:
                time.sleep(0)
            else:
                time.sleep(0.0002)
            if time.monotonic() > deadline:
                raise DeadlockError(
                    f"deadlock suspected: shm collective {opname!r} waited "
                    f">{limit:.0f}s on slot {slot} (round {want}); are all "
                    f"ranks in the same collective?")

    def maybe_unlink(self) -> None:
        if not self.unlinked:
            self.unlinked = True
            try:
                os.unlink(self.path)
            except OSError:
                pass


class ProcChannel(_Waitable):
    """Cross-process collective rendezvous for one communicator.

    Two tiers (the libmpi collective-algorithm analog, SURVEY.md §2.4 L0):

    - **Algorithm tier** for the hot collectives, selected by the ``plan``
      hint from ``tpu_mpi.collective``: ring reduce-scatter + allgather for
      commutative Allreduce (O(bytes/P) per-process traffic instead of the
      star's O(P·bytes) root ingress), binomial-tree Bcast (log P depth),
      dissemination Barrier (log P rounds). Frames carry the opname and
      (for rooted ops) the claimed root, so mismatched collectives and
      divergent roots still fail loudly on all ranks.
    - **Chunked star tier** (overlap engine) for bulk elementwise Allreduce
      the ring declines (non-commutative op, or ring disabled): payloads
      above ``pipeline_min_bytes`` travel as K chunk frames; the root folds
      chunk k while its drainer still receives chunks k+1.. and ships each
      result chunk immediately — transfer overlaps fold, bitwise-equal to
      the monolithic star.
    - **Star tier** for everything else (arbitrary combine closures): ranks
      send (opname, contrib) to the comm's first process, which verifies,
      combines and scatters per-rank results. Rooted Gather/Scatter stay
      here deliberately — all bytes must land at / leave one process, so a
      tree only helps latency, not bandwidth.
    """

    def __init__(self, ctx: "ProcContext", cid: Any, group: tuple[int, ...]):
        self.ctx = ctx
        self.cid = cid
        self.group = group
        self.lock = threading.RLock()
        self.cond = threading.Condition(self.lock)
        self.round = 0
        # (round, comm_rank) -> (opname, contrib) at root;
        # (round,) -> result at non-root; ("alg", round, *tag) -> in-flight
        # algorithm-tier fragments. Fed by the drainer thread.
        self.inbox: dict[Any, Any] = {}
        # round -> (opname, "star"|"alg") while this process is inside run():
        # a frame for the same round arriving from a rank in a DIFFERENT
        # collective (other protocol tier) must fail loudly, not leave this
        # rank waiting for frames its tier will never see.
        self.inflight: dict[int, tuple[str, str]] = {}
        # rounds whose waiter is mid-busy-probe: pongs are stored only while
        # the round is here, so a pong racing the collres can't leak forever
        self.probing: set[int] = set()
        # lazily-mapped same-host shared-memory collective segment
        self._shm: Optional[_ShmColl] = None

    def _wait_for(self, pred, what, timeout=None, limit=None) -> bool:
        """Collective wait with blocked-receiver direct drain (VERDICT r3
        #4, extended to the collective rendezvous): the waiting rank thread
        pumps its own transport instead of depending on the drainer, which
        stays parked during and shortly after direct activity
        (_runtime.pump_wait, the shared loop)."""
        from ._runtime import pump_wait
        return pump_wait(self.ctx, self.cond, pred, what,
                         timeout=timeout, limit=limit, fault_cid=self.cid)

    def _mismatch(self, theirs: str, mine: str) -> None:
        """Record a cross-tier mismatch (drainer-side: fail, don't raise —
        blocked ranks surface it via check_failure)."""
        self.ctx.fail(CollectiveMismatchError(
            f"ranks disagree on the collective for cid {self.cid}: "
            f"{sorted({theirs, mine})}"))

    def _tier_mismatch(self, opname: str, who: Any) -> None:
        """Same collective, different algorithm tier — would hang silently
        (frames land in keys the other tier never waits on); fail loudly."""
        self.ctx.fail(CollectiveMismatchError(
            f"ranks disagree on the algorithm tier for {opname!r} "
            f"(rank {who} took the other path — non-uniform counts?)"))

    # -- drainer entry points -------------------------------------------------
    def deliver_contrib(self, rnd: int, src: int, opname: str, contrib: Any) -> None:
        with self.cond:
            cur = self.inflight.get(rnd)
            self.inbox[(rnd, src)] = (opname, contrib)
            self.cond.notify_all()
        if cur is not None and cur[1] != "star":
            # a monolithic star contribution while this rank runs another
            # tier (ring/tree or the chunked star): either a different
            # collective (opname) or — same opname — a TIER divergence
            # (e.g. non-uniform counts making the eligibility gate
            # disagree); both would hang, fail loudly
            if cur[0] != opname:
                self._mismatch(opname, cur[0])
            else:
                self._tier_mismatch(opname, src)

    def deliver_result(self, rnd: int, result: Any) -> None:
        with self.cond:
            self.inbox[(rnd,)] = result
            self.cond.notify_all()

    def deliver_chunk(self, rnd: int, src: int, opname: str, idx: int,
                      nchunks: int, part: Any) -> None:
        """A pipelined star contribution chunk (frame kind "collc")."""
        with self.cond:
            cur = self.inflight.get(rnd)
            self.inbox[(rnd, src, "c", idx)] = (opname, nchunks, part)
            self.cond.notify_all()
        if cur is not None and cur[1] != "starc":
            if cur[0] != opname:
                self._mismatch(opname, cur[0])
            else:
                self._tier_mismatch(opname, src)

    def deliver_chunk_result(self, rnd: int, idx: int, result: Any) -> None:
        with self.cond:
            self.inbox[(rnd, "cres", idx)] = result
            self.cond.notify_all()

    def deliver_alg(self, rnd: int, tag: tuple, src: int, opname: str,
                    payload: Any) -> None:
        with self.cond:
            cur = self.inflight.get(rnd)
            self.inbox[("alg", rnd) + tag] = (src, opname, payload)
            self.cond.notify_all()
        if cur is not None and cur[0] != opname:
            self._mismatch(opname, cur[0])
        elif cur is not None and cur[1] != "alg":
            self._tier_mismatch(opname, src)

    # -- algorithm tier -------------------------------------------------------
    def _send_alg(self, world_dst: int, rnd: int, tag: tuple, rank: int,
                  opname: str, payload: Any) -> None:
        self.ctx.send_frame(world_dst, ("alg", self.cid, rnd, tag, rank,
                                        opname, _pack(payload)))

    def _wait_alg(self, rnd: int, tag: tuple, opname: str) -> Any:
        key = ("alg", rnd) + tag
        with self.cond:
            self._wait_for(lambda: key in self.inbox, f"collective {opname}")
            src, got_op, payload = self.inbox.pop(key)
        if got_op != opname:
            err = CollectiveMismatchError(
                f"rank {src} is in {got_op!r} while this rank is in "
                f"{opname!r} on the same communicator")
            self.ctx.fail(err)
            raise err
        return _unpack(payload)

    def _run_barrier(self, rank: int, rnd: int, contrib: Any,
                     opname: str) -> None:
        """Dissemination barrier: ceil(log2 P) rounds, no distinguished root."""
        n = len(self.group)
        k, step = 1, 0
        while k < n:
            self._send_alg(self.group[(rank + k) % n], rnd, ("bar", step),
                           rank, opname, None)
            self._wait_alg(rnd, ("bar", step), opname)
            k <<= 1
            step += 1
        return None

    def _run_tree_bcast(self, rank: int, rnd: int, contrib: Any,
                        opname: str) -> Any:
        """Binomial-tree broadcast; every frame carries the claimed root so
        divergent roots are detected at the first hop."""
        n = len(self.group)
        claimed_root, payload = contrib
        v = (rank - claimed_root) % n           # virtual rank, root at 0
        if v != 0:
            got_root, payload = self._wait_alg(rnd, ("tree",), opname)
            if got_root != claimed_root:
                err = CollectiveMismatchError(
                    f"ranks disagree on the root of {opname}: "
                    f"{sorted({got_root, claimed_root})}")
                self.ctx.fail(err)
                raise err
        # children of v in the binomial tree: v | 2^k with parent(c) == v
        for k in range(max(n - 1, 1).bit_length()):
            c = v | (1 << k)
            if c != v and c < n and (c & (c - 1)) == v:
                dst = self.group[(c + claimed_root) % n]
                self._send_alg(dst, rnd, ("tree",), rank, opname,
                               (claimed_root, payload))
        return payload

    def _run_ring_allreduce(self, rank: int, rnd: int, contrib: Any, op,
                            opname: str) -> Any:
        """Ring reduce-scatter + ring allgather (the classic bandwidth-optimal
        algorithm libmpi uses for large Allreduce): each process sends
        2(P-1)/P of the payload total, versus the star's P·payload ingress at
        one process. Requires a commutative op (ring order ≠ rank order)."""
        n = len(self.group)
        arr = np.asarray(contrib)
        if (is_wire_snapshot(arr) and arr.flags.writeable
                and arr.flags.c_contiguous):
            # explicitly-marked private to_wire snapshot (ADVICE r2: the
            # provenance marker, not inferred flags, authorizes the
            # in-place fast path — an owning array shared with the user
            # can never carry the mark) — mutate instead of a second copy
            work = arr.reshape(-1)
        else:
            work = np.ascontiguousarray(arr).reshape(-1).copy()
        base, rem = divmod(len(work), n)
        sizes = [base + (1 if i < rem else 0) for i in range(n)]
        offs = np.concatenate([[0], np.cumsum(sizes)])
        right = self.group[(rank + 1) % n]

        def seg(i: int):
            return work[offs[i]:offs[i + 1]]

        ufunc = getattr(op, "ufunc", None)
        for step in range(n - 1):           # reduce-scatter
            si = (rank - step) % n
            self._send_alg(right, rnd, ("ring", step), rank, opname, seg(si))
            incoming = self._wait_alg(rnd, ("ring", step), opname)
            ri = (rank - step - 1) % n
            if ufunc is not None:           # in-place: no temp allocation
                ufunc(seg(ri), incoming, out=seg(ri))
            else:
                seg(ri)[...] = op(seg(ri), incoming)
        for step in range(n - 1):           # allgather
            gi = (rank + 1 - step) % n
            self._send_alg(right, rnd, ("rga", step), rank, opname, seg(gi))
            incoming = self._wait_alg(rnd, ("rga", step), opname)
            wi = (rank - step) % n
            seg(wi)[...] = incoming
        return self._from_host(work.reshape(arr.shape), contrib)

    @staticmethod
    def _alg_array(contrib: Any, n: int,
                   threshold: bool = True) -> Optional[np.ndarray]:
        """The payload as a host array IF it is eligible for an algorithm
        tier (big enough, numeric, splittable n ways); None → use the star.
        One rule shared by every chooser branch so the tiers cannot drift.
        ``threshold=False`` skips the byte floor: an explicitly-selected
        algorithm (tuned table / force-override) already made the size
        decision, only the structural gates remain."""
        try:
            arr = np.asarray(contrib)
        except Exception:
            return None
        if arr.dtype == object or arr.size % n:
            return None
        if threshold and arr.nbytes < _RING_MIN_BYTES:
            return None
        return arr

    @staticmethod
    def _from_host(result: np.ndarray, like: Any):
        """Re-wrap an algorithm-tier result to match the contrib's kind."""
        if _is_jax(like):
            import jax.numpy as jnp
            return jnp.asarray(result)
        return result

    def _run_ring_allgather(self, rank: int, rnd: int, contrib: Any,
                            opname: str) -> Any:
        """Ring allgather (each block travels n-1 single hops): every rank
        forwards the newest block to its right neighbor, so total wire
        traffic is (n-1)·block per rank versus the star root's P·block
        ingress plus P²·block egress. Result = rank-ordered concatenation,
        matching the star combine."""
        n = len(self.group)
        arr = np.asarray(contrib).reshape(-1)
        per = arr.size
        out = np.empty(n * per, arr.dtype)
        blocks = out.reshape(n, per)
        blocks[rank] = arr
        right = self.group[(rank + 1) % n]
        cur = rank
        for step in range(n - 1):
            self._send_alg(right, rnd, ("rag", step), rank, opname,
                           blocks[cur])
            cur = (rank - step - 1) % n
            incoming = np.asarray(self._wait_alg(rnd, ("rag", step), opname))
            if incoming.size != per or incoming.dtype != arr.dtype:
                err = MPIError(
                    f"Allgather blocks disagree across ranks "
                    f"(got {incoming.size} x {incoming.dtype}, expected "
                    f"{per} x {arr.dtype}); Allgather requires uniform "
                    f"counts — use Allgatherv for ragged blocks")
                self.ctx.fail(err)
                raise err
            blocks[cur] = incoming.reshape(-1)
        return self._from_host(out, contrib)

    def _run_ring_allgatherv(self, rank: int, rnd: int, contrib: Any,
                             opname: str, counts: Sequence[int]) -> Any:
        """Ragged ring allgather: blocks of differing (replicated-counts)
        sizes forward around the ring; written straight into a preallocated
        rank-ordered output, each incoming block validated against the
        counts contract like the uniform ring tier."""
        n = len(self.group)
        arr = np.asarray(contrib).reshape(-1)
        displs = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        out = np.empty(int(displs[-1]), arr.dtype)

        def blk(i: int):
            return out[displs[i]:displs[i + 1]]

        blk(rank)[...] = arr
        right = self.group[(rank + 1) % n]
        cur = rank
        for step in range(n - 1):
            self._send_alg(right, rnd, ("ragv", step), rank, opname,
                           blk(cur))
            cur = (rank - step - 1) % n
            incoming = np.asarray(
                self._wait_alg(rnd, ("ragv", step), opname)).reshape(-1)
            if incoming.size != counts[cur] or incoming.dtype != arr.dtype:
                err = MPIError(
                    f"Allgatherv block from rank {cur} is "
                    f"{incoming.size} x {incoming.dtype}, but the replicated "
                    f"counts promise {counts[cur]} x {arr.dtype}")
                self.ctx.fail(err)
                raise err
            blk(cur)[...] = incoming
        return self._from_host(out, contrib)

    def _run_pairwise_alltoallv(self, rank: int, rnd: int, contrib: Any,
                                opname: str) -> Any:
        """Variable-count pairwise exchange: like the Alltoall tier but each
        (src, dst) segment has its own length, carried by the frame itself
        (the star combine also slices by the SENDER's counts, so semantics
        agree even if a buggy caller's rcounts disagree)."""
        n = len(self.group)
        wire, scounts = contrib
        arr = np.asarray(wire).reshape(-1)
        sd = np.concatenate([[0], np.cumsum(scounts)]).astype(np.int64)
        for k in range(1, n):
            dst = (rank + k) % n
            self._send_alg(self.group[dst], rnd, ("a2av", rank), rank,
                           opname, arr[sd[dst]:sd[dst + 1]])
        parts: list = [None] * n
        parts[rank] = arr[sd[rank]:sd[rank + 1]]
        for k in range(1, n):
            src = (rank - k) % n
            parts[src] = self._wait_alg(rnd, ("a2av", src), opname)
        out = np.concatenate([np.asarray(p).reshape(-1) for p in parts])
        return self._from_host(out, wire)

    def _run_pairwise_alltoall(self, rank: int, rnd: int, contrib: Any,
                               opname: str) -> Any:
        """Direct pairwise exchange (MPI_Alltoall's large-message algorithm):
        each of my P-1 foreign segments travels ONE hop to its owner, versus
        the star's P·payload ingress at the root. Result for slot s = rank
        s's segment for me, matching the star combine exactly."""
        n = len(self.group)
        arr = np.asarray(contrib)
        segs = arr.reshape(n, arr.size // n)
        for k in range(1, n):
            dst = (rank + k) % n
            self._send_alg(self.group[dst], rnd, ("a2a", rank), rank, opname,
                           segs[dst])
        out = np.empty_like(segs)
        out[rank] = segs[rank]
        for k in range(1, n):
            src = (rank - k) % n
            out[src] = self._wait_alg(rnd, ("a2a", src), opname)
        return self._from_host(out.reshape(-1), contrib)

    def _run_rdouble_allreduce(self, rank: int, rnd: int, contrib: Any,
                               combine: Callable, opname: str) -> Any:
        """Recursive-doubling Allreduce in its concatenation form (a Bruck
        allgather of the raw contributions, then the star's OWN rank-order
        fold at every rank): ceil(log2 P) pairwise exchange rounds, each
        shipping everything accumulated so far, versus the star's
        serialized O(P) root ingress. Running the same ``combine`` closure
        the star root runs, over the same rank-ordered contribution list,
        makes the result bitwise-identical to the star by construction —
        any op (commutative or not), any picklable payload."""
        n = len(self.group)
        have = {rank: contrib}
        k, step = 1, 0
        while k < n:
            dst = self.group[(rank + k) % n]
            self._send_alg(dst, rnd, ("rd", step), rank, opname,
                           list(have.items()))
            for src, c in self._wait_alg(rnd, ("rd", step), opname):
                have.setdefault(src, c)
            k <<= 1
            step += 1
        results = list(combine([have[r] for r in range(n)]))
        if len(results) != n:
            err = MPIError(f"combine for {opname} returned {len(results)} "
                           f"results for {n} ranks")
            self.ctx.fail(err)
            raise err
        return results[rank]

    def _run_rabenseifner_allreduce(self, rank: int, rnd: int, contrib: Any,
                                    op, opname: str) -> Any:
        """Rabenseifner's algorithm: a direct-exchange reduce-scatter (each
        rank becomes the owner of one payload segment and folds the P
        per-rank pieces of it) followed by an allgather of the folded
        segments — 2·bytes·(P-1)/P wire traffic per rank like the ring,
        but in 2·log-ish phases of P-1 concurrent single-hop messages
        instead of 2(P-1) serialized ring steps. Each segment folds in
        RANK ORDER with the same ``functools.reduce`` the star's
        ``_reduce_arrays`` bottoms out in; the elementwise ops this tier
        admits are segment-separable, so the concatenated result is
        bitwise-identical to the star's monolithic fold."""
        import functools as _ft
        n = len(self.group)
        host = np.asarray(contrib)
        work = np.ascontiguousarray(host).reshape(-1)
        base, rem = divmod(work.size, n)
        sizes = [base + (1 if i < rem else 0) for i in range(n)]
        offs = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)

        # phase 1 (reduce-scatter): ship my copy of segment d to its owner
        for k in range(1, n):
            dst = (rank + k) % n
            self._send_alg(self.group[dst], rnd, ("rsp", rank), rank,
                           opname, work[offs[dst]:offs[dst + 1]])
        pieces: list = [None] * n
        pieces[rank] = work[offs[rank]:offs[rank + 1]]
        for k in range(1, n):
            src = (rank - k) % n
            pieces[src] = np.asarray(
                self._wait_alg(rnd, ("rsp", src), opname)).reshape(-1)
        folded = np.asarray(_ft.reduce(op, pieces)).reshape(-1)

        # phase 2: Bruck allgather of the folded segments
        merged = {rank: folded}
        k, step = 1, 0
        while k < n:
            dst = self.group[(rank + k) % n]
            self._send_alg(dst, rnd, ("rag2", step), rank, opname,
                           list(merged.items()))
            for src, seg in self._wait_alg(rnd, ("rag2", step), opname):
                merged.setdefault(src, np.asarray(seg).reshape(-1))
            k <<= 1
            step += 1
        out = np.concatenate([merged[r] for r in range(n)])
        return self._from_host(out.reshape(host.shape), contrib)

    # -- hierarchical (two-level) composites --------------------------------
    #
    # The domain map (tpu_mpi/topology.py) splits this communicator into D
    # contiguous equal blocks of r ranks (one block per host, or the
    # TPU_MPI_DOMAINS emulation); member i is (domain i // r, position
    # i % r). Intra-domain traffic is cheap (shm/loopback), inter-domain
    # traffic crosses the slow fabric — each composite sends O(D) inter
    # messages per member where the flat algorithms send O(n).

    def _hier_layout(self) -> Optional[tuple]:
        """(ndomains, ranks_per_domain) for this group, or None when the
        world is flat or the layout is not contiguous-uniform (the only
        shape whose cross-domain fold chain stays bitwise-equal to the
        star — see topology.domain_shape)."""
        from . import topology as _topo
        return _topo.domain_shape(_topo.domain_map(self.ctx, self.group))

    def _run_hier_allreduce(self, rank: int, rnd: int, contrib: Any,
                            op, opname: str, layout: tuple) -> Any:
        """Two-level Allreduce: intra-domain gather of raw segment pieces,
        a cross-domain CHAIN of partial left folds, then backfill +
        intra-domain allgather. The payload splits into r segments (one
        per domain position, rabenseifner-style); segment p's owner in
        domain d is position p. The chain runs in domain order — domain 0
        folds its r pieces of segment p in rank order, ships the partial
        to domain 1 whose owner folds ``[carried] + its r pieces``, and so
        on — so the final domain holds EXACTLY the star's left fold of all
        n pieces in rank order (left folds compose under chunking), and
        the elementwise ops this tier admits are segment-separable. Inter
        traffic: 2·(D-1) segment-sized hops per position, vs the star's
        n-1 full-payload root ingress crossing the fabric."""
        import functools as _ft
        D, r = layout
        n = len(self.group)
        host = np.asarray(contrib)
        work = np.ascontiguousarray(host).reshape(-1)
        base, rem = divmod(work.size, r)
        sizes = [base + (1 if p < rem else 0) for p in range(r)]
        offs = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
        dom, pos = rank // r, rank % r
        sc = _pv.scope()    # pvar phase spans; None when pvars+tracing off

        # phase 1 (intra gather): my piece of segment q goes to my
        # domain's position-q member; I collect my co-members' pieces of
        # MY segment, in position (= rank) order
        t0 = _pv.monotonic() if sc is not None else 0.0
        for q in range(r):
            if q == pos:
                continue
            self._send_alg(self.group[dom * r + q], rnd, ("hrs", rank),
                           rank, opname, work[offs[q]:offs[q + 1]])
        pieces: list = [None] * r
        pieces[pos] = work[offs[pos]:offs[pos + 1]]
        for q in range(r):
            if q != pos:
                pieces[q] = np.asarray(self._wait_alg(
                    rnd, ("hrs", dom * r + q), opname)).reshape(-1)
        if sc is not None:
            sc.spans.append(("intra_fold", t0, _pv.monotonic()))
            t0 = _pv.monotonic()

        # phase 2 (inter chain): fold and carry the partial down the
        # domain chain; the last domain ends with the full rank-order fold
        if dom == 0:
            partial = np.asarray(_ft.reduce(op, pieces)).reshape(-1)
        else:
            carried = np.asarray(self._wait_alg(
                rnd, ("hch", (dom - 1) * r + pos), opname)).reshape(-1)
            partial = np.asarray(
                _ft.reduce(op, [carried] + pieces)).reshape(-1)
        if dom < D - 1:
            self._send_alg(self.group[(dom + 1) * r + pos], rnd,
                           ("hch", rank), rank, opname, partial)
            final = np.asarray(self._wait_alg(
                rnd, ("hbf", (D - 1) * r + pos), opname)).reshape(-1)
        else:
            final = partial
            for d in range(D - 1):
                self._send_alg(self.group[d * r + pos], rnd, ("hbf", rank),
                               rank, opname, final)
        if sc is not None:
            sc.spans.append(("inter_exchange", t0, _pv.monotonic()))
            t0 = _pv.monotonic()

        # phase 3 (intra allgather): everyone shares their finished
        # segment with their co-members and reassembles in segment order
        for q in range(r):
            if q != pos:
                self._send_alg(self.group[dom * r + q], rnd, ("hag", rank),
                               rank, opname, final)
        segs: list = [None] * r
        segs[pos] = final
        for q in range(r):
            if q != pos:
                segs[q] = np.asarray(self._wait_alg(
                    rnd, ("hag", dom * r + q), opname)).reshape(-1)
        out = np.concatenate(segs)
        if sc is not None:
            sc.spans.append(("allgather", t0, _pv.monotonic()))
        return self._from_host(out.reshape(host.shape), contrib)

    def _run_hier_allgather(self, rank: int, rnd: int, contrib: Any,
                            opname: str, layout: tuple) -> Any:
        """Two-level Allgather: intra-domain pairwise allgather of the
        blocks, then one bundle (the domain's r blocks) per member to its
        position peer in every other domain — D-1 inter messages per
        member instead of the (D-1)·r a flat pairwise exchange crosses
        the fabric with. Pure rank-ordered concatenation, so bitwise
        equality to the star is structural."""
        D, r = layout
        n = len(self.group)
        blk = np.asarray(contrib).reshape(-1)
        dom, pos = rank // r, rank % r
        sc = _pv.scope()
        t0 = _pv.monotonic() if sc is not None else 0.0
        for q in range(r):
            if q != pos:
                self._send_alg(self.group[dom * r + q], rnd, ("hga", rank),
                               rank, opname, blk)
        bundle: list = [None] * r
        bundle[pos] = blk
        for q in range(r):
            if q != pos:
                got = np.asarray(self._wait_alg(
                    rnd, ("hga", dom * r + q), opname)).reshape(-1)
                if got.size != blk.size or got.dtype != blk.dtype:
                    err = MPIError(
                        f"Allgather block mismatch in {opname}: rank "
                        f"{dom * r + q} sent {got.size}x{got.dtype}, "
                        f"rank {rank} holds {blk.size}x{blk.dtype}")
                    self.ctx.fail(err)
                    raise err
                bundle[q] = got
        if sc is not None:
            sc.spans.append(("intra_fold", t0, _pv.monotonic()))
            t0 = _pv.monotonic()
        for d in range(D):
            if d != dom:
                self._send_alg(self.group[d * r + pos], rnd, ("hgb", rank),
                               rank, opname, np.concatenate(bundle))
        blocks: list = [None] * n
        for q in range(r):
            blocks[dom * r + q] = bundle[q]
        for d in range(D):
            if d == dom:
                continue
            got = np.asarray(self._wait_alg(
                rnd, ("hgb", d * r + pos), opname)).reshape(-1)
            if got.size != r * blk.size:
                err = MPIError(
                    f"Allgather bundle mismatch in {opname}: domain {d} "
                    f"sent {got.size} elements, expected {r * blk.size}")
                self.ctx.fail(err)
                raise err
            for q in range(r):
                blocks[d * r + q] = got[q * blk.size:(q + 1) * blk.size]
        if sc is not None:
            sc.spans.append(("inter_exchange", t0, _pv.monotonic()))
            t0 = _pv.monotonic()
        out = np.concatenate(blocks)
        if sc is not None:
            sc.spans.append(("allgather", t0, _pv.monotonic()))
        return self._from_host(out, contrib)

    def _run_hier_alltoall(self, rank: int, rnd: int, contrib: Any,
                           opname: str, layout: tuple) -> Any:
        """Two-level Alltoall: segments for co-members travel directly;
        segments for a foreign domain ride ONE bundle to my position peer
        there, who forwards each piece intra-domain to its final owner —
        D-1 inter messages per member (bundle size r·seg) instead of the
        flat pairwise exchange's (D-1)·r fabric crossings. A pure
        permutation: every slot receives exactly the sender's segment,
        bitwise."""
        D, r = layout
        n = len(self.group)
        arr = np.asarray(contrib)
        segs = arr.reshape(n, arr.size // n)
        dom, pos = rank // r, rank % r
        sc = _pv.scope()
        t0 = _pv.monotonic() if sc is not None else 0.0
        # intra: direct segment to each co-member
        for q in range(r):
            if q != pos:
                self._send_alg(self.group[dom * r + q], rnd, ("hai", rank),
                               rank, opname, segs[dom * r + q])
        if sc is not None:
            sc.spans.append(("intra_fold", t0, _pv.monotonic()))
            t0 = _pv.monotonic()
        # inter: one bundle (their domain's r segments, position order)
        # to my position peer in every other domain
        for d in range(D):
            if d != dom:
                self._send_alg(
                    self.group[d * r + pos], rnd, ("hab", rank), rank,
                    opname,
                    np.concatenate([segs[d * r + q] for q in range(r)]))
        out = np.empty_like(segs)
        out[rank] = segs[rank]
        seg_sz = segs.shape[1]
        # receive + forward: peer bundles carry my whole domain's pieces
        # from the sender's domain; mine I keep, the rest I relay
        for d in range(D):
            if d == dom:
                continue
            src = d * r + pos
            got = np.asarray(self._wait_alg(
                rnd, ("hab", src), opname)).reshape(r, seg_sz)
            out[src] = got[pos]
            for q in range(r):
                if q != pos:
                    self._send_alg(self.group[dom * r + q], rnd,
                                   ("haf", src), rank, opname, got[q])
        if sc is not None:
            sc.spans.append(("inter_exchange", t0, _pv.monotonic()))
            t0 = _pv.monotonic()
        # collect: co-members' direct segments, then forwarded foreign
        # segments (from the co-member at the original sender's position)
        for q in range(r):
            if q != pos:
                out[dom * r + q] = self._wait_alg(
                    rnd, ("hai", dom * r + q), opname)
        for d in range(D):
            if d == dom:
                continue
            for q in range(r):
                if q != pos:
                    out[d * r + q] = self._wait_alg(
                        rnd, ("haf", d * r + q), opname)
        if sc is not None:
            sc.spans.append(("allgather", t0, _pv.monotonic()))
        return self._from_host(out.reshape(-1), contrib)

    def _run_tree_gather_fold(self, rank: int, rnd: int, contrib: Any,
                              combine: Callable, opname: str) -> Any:
        """Binomial-tree gather for rooted Reduce/Gather: contributions
        merge up a binomial tree to COMM rank 0 (the star's fold site) in
        log P rounds instead of P-1 serialized root receives; comm rank 0
        runs the star's OWN rooted combine — root-divergence validation
        and rank-order fold included, so results are bitwise-identical —
        and ships the (single) non-None result to the claimed root. The
        contribs are the ``_run_rooted`` (claimed_root, payload) pairs:
        each rank knows from its own pair whether a result is due."""
        n = len(self.group)
        bundle = {rank: contrib}
        for k in range(max(n - 1, 1).bit_length()):
            c = rank | (1 << k)
            if c != rank and c < n and (c & (c - 1)) == rank:
                bundle.update(self._wait_alg(rnd, ("btg", c), opname))
        if rank != 0:
            parent = rank & (rank - 1)
            self._send_alg(self.group[parent], rnd, ("btg", rank), rank,
                           opname, bundle)
            if contrib[0] == rank:       # I am the claimed root: result due
                return self._wait_alg(rnd, ("btr",), opname)
            return None
        results = list(combine([bundle[r] for r in range(n)]))
        if len(results) != n:
            err = MPIError(f"combine for {opname} returned {len(results)} "
                           f"results for {n} ranks")
            self.ctx.fail(err)
            raise err
        for r in range(1, n):
            if results[r] is not None:
                self._send_alg(self.group[r], rnd, ("btr",), rank, opname,
                               results[r])
        return results[0]

    def _run_tree_scatter(self, rank: int, rnd: int, contrib: Any,
                          combine: Callable, opname: str) -> Any:
        """Binomial-tree scatter rooted at the claimed root (virtual rank
        0): the root runs the star's combine to slice its payload into
        per-rank blocks, then each tree hop forwards the contiguous
        virtual-rank block range its child subtree owns — log P hops of
        geometrically-shrinking bundles instead of P-1 serialized root
        sends. Every frame carries the claimed root (like the binomial
        Bcast), so divergent roots fail loudly at the first hop rather
        than through the star's gathered-pair check."""
        n = len(self.group)
        claimed_root = contrib[0]
        v = (rank - claimed_root) % n          # virtual rank, root at 0

        def vchildren(vr: int):
            for k in range(max(n - 1, 1).bit_length()):
                c = vr | (1 << k)
                if c != vr and c < n and (c & (c - 1)) == vr:
                    yield c, min(c + (1 << k), n)

        if v == 0:
            # Synthesize the star's gathered view. Only the root's payload
            # feeds the scatter combine; peer claimed-roots are validated
            # at the receive hops below instead of here.
            cs: list = [(claimed_root, None)] * n
            cs[rank] = contrib
            results = list(combine(cs))
            if len(results) != n:
                err = MPIError(f"combine for {opname} returned "
                               f"{len(results)} results for {n} ranks")
                self.ctx.fail(err)
                raise err
            blocks = {u: results[(u + claimed_root) % n] for u in range(n)}
        else:
            got_root, blocks = self._wait_alg(rnd, ("sctr", v), opname)
            if got_root != claimed_root:
                err = CollectiveMismatchError(
                    f"ranks disagree on the root of {opname}: "
                    f"{sorted({got_root, claimed_root})}")
                self.ctx.fail(err)
                raise err
        for c, end in vchildren(v):
            self._send_alg(self.group[(c + claimed_root) % n], rnd,
                           ("sctr", c), rank, opname,
                           (claimed_root,
                            {u: blocks[u] for u in range(c, end)}))
        return blocks[v]

    def shm_bind(self, nbytes: int) -> Optional[Callable[[], None]]:
        """Pre-map the same-host shm collective segment for a registered
        plan (tpu_mpi.collective._register_allreduce) and take a slot
        lease, so the first Start pays neither the eligibility walk nor
        the lazy mmap. Returns the release callback the registration hands
        to ``Comm.free``, or None when the tier is not eligible (not
        same-host, payload exceeds the mapped slot size) — the plan then
        simply runs without a segment lease."""
        ok = getattr(self.ctx, "coll_shm_ok", None)
        if ok is None or not self.group or not ok(self.group):
            return None
        try:
            sc = self._shm_coll()
        except MPIError:
            return None             # plan creation must not fate-share
        if nbytes > sc.cap:
            return None
        sc.leases += 1

        def release() -> None:
            sc.leases = max(0, sc.leases - 1)
        return release

    def drop_shm(self) -> None:
        """Tear down the mapped segment once every registered-plan lease is
        gone (``Comm.free``): unlink the name and close the mapping. A
        BufferError (a live numpy view still pins the map) keeps the
        mapping — the view owner drops it with the comm object."""
        sc = self._shm
        if sc is None or sc.leases > 0:
            return
        self._shm = None
        sc.maybe_unlink()
        try:
            sc.mm.close()
        except BufferError:
            self._shm = sc          # a slot view is still alive; keep it

    def _shm_coll(self) -> _ShmColl:
        if self._shm is None:
            try:
                self._shm = _ShmColl(self.ctx, self.cid, self.group)
            except MPIError:
                raise
            except OSError as e:
                # eligibility said same-host + /dev/shm exists, so a map
                # failure here is environmental (full tmpfs, perms) and
                # must fate-share — a silent per-rank star fallback would
                # diverge the protocol
                err = MPIError(
                    f"could not map the shm collective segment: {e}")
                self.ctx.fail(err)
                raise err from None
        return self._shm

    def _run_shm(self, rank: int, rnd: int, contrib: Any,
                 combine: Callable, opname: str) -> Any:
        """Same-host shared-memory collective (Allreduce with a raw array
        payload; Barrier with ``contrib=None``): ranks publish through one
        mmap'd segment and comm rank 0 folds with the star's OWN combine
        closure over the rank-ordered slot views — bitwise-identical by
        construction — then publishes the (rank-uniform) result slot. No
        transport frames at all, which on a single host beats every
        message-passing algorithm by an order of magnitude at small sizes
        (the measured crossovers in benchmarks/results/coll-algos-*.json
        are what put this tier in the tuned table)."""
        ctx = self.ctx
        sc = self._shm_coll()
        n = len(self.group)
        want = rnd + 1
        ophash = zlib.crc32(opname.encode())
        if contrib is None:                       # Barrier
            flat = host = None
            dthash = 0
        else:
            host = np.asarray(contrib)
            flat = np.ascontiguousarray(host).reshape(-1)
            dthash = zlib.crc32(flat.dtype.str.encode())
            if flat.nbytes > sc.cap:
                err = MPIError(
                    f"shm collective payload ({flat.nbytes} B) exceeds the "
                    f"mapped slot size ({sc.cap} B) — "
                    f"TPU_MPI_COLL_SHM_MAX_BYTES changed mid-job?")
                ctx.fail(err)
                raise err
        if rank != 0:
            sc.publish(rank, want, ophash, dthash,
                       None if flat is None else memoryview(flat).cast("B"))
            sc.spin(sc.n, want, opname)
            _, nb, r_oph, _ = sc.header(sc.n)
            if r_oph != ophash:
                err = CollectiveMismatchError(
                    f"ranks disagree on the collective for cid {self.cid} "
                    f"(shm result slot carries another op than {opname!r})")
                ctx.fail(err)
                raise err
            if flat is None:
                return None
            # .copy(): the mapping is reused next round; the result dtype
            # is the contribution dtype (elementwise same-dtype fold)
            out = np.frombuffer(sc.mm, dtype=flat.dtype,
                                count=nb // flat.dtype.itemsize,
                                offset=sc.data_off(sc.n)).copy()
            return self._from_host(out.reshape(host.shape), contrib)

        # comm rank 0: spin per slot, validate, fold in rank order, publish
        cs: list = [None] * n
        cs[0] = contrib
        for r in range(1, n):
            sc.spin(r, want, opname)
            _, nb, c_oph, c_dth = sc.header(r)
            if c_oph != ophash or c_dth != dthash:
                err = CollectiveMismatchError(
                    f"ranks disagree on the collective for cid {self.cid}: "
                    f"rank {r}'s shm contribution carries another "
                    f"op/dtype than {opname!r}")
                ctx.fail(err)
                raise err
            if flat is not None:
                if nb != flat.nbytes:
                    err = MPIError(
                        f"shm {opname} contributions disagree on size "
                        f"(rank {r}: {nb} B, expected {flat.nbytes} B) — "
                        f"non-uniform counts?")
                    ctx.fail(err)
                    raise err
                cs[r] = np.frombuffer(sc.mm, dtype=flat.dtype,
                                      count=flat.size,
                                      offset=sc.data_off(r)
                                      ).reshape(host.shape)
        # every rank has provably mapped this inode now — drop the name
        sc.maybe_unlink()
        if flat is None:
            sc.publish(sc.n, want, ophash, 0, None)
            return None
        try:
            results = list(combine(cs))
        except BaseException as e:
            ctx.fail(e)
            raise
        res = np.ascontiguousarray(np.asarray(results[0])).reshape(-1)
        if res.dtype != flat.dtype or res.nbytes > sc.cap:
            err = MPIError(
                f"shm {opname} fold changed dtype/size "
                f"({flat.dtype}->{res.dtype}); this op is not eligible "
                f"for the shm tier")
            ctx.fail(err)
            raise err
        sc.publish(sc.n, want, ophash, dthash, memoryview(res).cast("B"))
        return results[rank]

    def _choose_algorithm(self, contrib: Any, plan,
                          combine: Callable) -> Optional[tuple]:
        """Resolve a plan's algorithm to a ``(mode, runner)`` pair, or None
        for the star (monolithic or chunk-pipelined). Plans from the
        current ``tpu_mpi.collective`` carry the ``tune.select`` decision
        as their last element; legacy hints without it keep the historical
        gates. The decision must stay a deterministic function of values
        every rank shares (plan kind, op, payload size, uniform config) or
        the protocols would diverge — and an explicitly-selected algorithm
        still passes the STRUCTURAL gates (numeric payload, divisibility),
        so a tuned table degrades to the star instead of crashing on an
        object payload. ``mode`` is the inflight tier tag cross-checked by
        the deliver_* mismatch detection ("alg" message algorithms, "shm"
        the shared-memory fold)."""
        kind = plan[0]
        n = len(self.group)
        if kind == "barrier":
            algo = plan[1] if len(plan) > 1 else "dissemination"
            if algo == "shm":
                return ("shm", lambda rank, rnd, c, opname:
                        self._run_shm(rank, rnd, None, combine, opname))
            if algo == "dissemination":
                return ("alg", self._run_barrier)
            return None
        if kind == "bcast":
            algo = plan[2] if len(plan) > 2 else "binomial"
            if algo == "binomial":
                return ("alg", self._run_tree_bcast)
            return None
        if kind == "allreduce":
            op = plan[1]
            algo = plan[2] if len(plan) > 2 else None
            if algo is None:                 # legacy hint: historical gate
                if (getattr(op, "commutative", False)
                        and self._alg_array(contrib, 1) is not None):
                    algo = "ring"
                else:
                    return None
            if algo == "shm":
                if self._alg_array(contrib, 1, threshold=False) is None:
                    return None
                return ("shm", lambda rank, rnd, c, opname:
                        self._run_shm(rank, rnd, c, combine, opname))
            if algo == "rdouble":
                return ("alg", lambda rank, rnd, c, opname:
                        self._run_rdouble_allreduce(rank, rnd, c, combine,
                                                    opname))
            if algo == "rabenseifner":
                if self._alg_array(contrib, 1, threshold=False) is None:
                    return None
                return ("alg", lambda rank, rnd, c, opname:
                        self._run_rabenseifner_allreduce(rank, rnd, c, op,
                                                         opname))
            if algo == "ring":
                if self._alg_array(contrib, 1, threshold=False) is None:
                    return None
                return ("alg", lambda rank, rnd, c, opname:
                        self._run_ring_allreduce(rank, rnd, c, op, opname))
            if algo == "hier":
                if self._alg_array(contrib, 1, threshold=False) is None:
                    return None
                lay = self._hier_layout()
                if lay is None:     # flat world: degrade to the star
                    return None
                return ("alg", lambda rank, rnd, c, opname:
                        self._run_hier_allreduce(rank, rnd, c, op, opname,
                                                 lay))
            return None
        if kind in ("reduce", "gather"):
            if plan[-1] == "binomial":
                return ("alg", lambda rank, rnd, c, opname:
                        self._run_tree_gather_fold(rank, rnd, c, combine,
                                                   opname))
            return None
        if kind == "scatter":
            if plan[-1] == "binomial":
                return ("alg", lambda rank, rnd, c, opname:
                        self._run_tree_scatter(rank, rnd, c, combine,
                                               opname))
            return None
        if kind == "alltoall":
            algo = plan[1] if len(plan) > 1 else "pairwise"
            legacy = len(plan) == 1
            if (algo == "pairwise" and self._alg_array(
                    contrib, n, threshold=legacy) is not None):
                return ("alg", self._run_pairwise_alltoall)
            if (algo == "hier" and self._alg_array(
                    contrib, n, threshold=False) is not None):
                lay = self._hier_layout()
                if lay is not None:
                    return ("alg", lambda rank, rnd, c, opname:
                            self._run_hier_alltoall(rank, rnd, c, opname,
                                                    lay))
            return None
        if kind == "allgather":
            algo = plan[1] if len(plan) > 1 else "ring"
            legacy = len(plan) == 1
            if (algo == "ring" and self._alg_array(
                    contrib, 1, threshold=legacy) is not None):
                return ("alg", self._run_ring_allgather)
            if (algo == "hier" and self._alg_array(
                    contrib, 1, threshold=False) is not None):
                lay = self._hier_layout()
                if lay is not None:
                    return ("alg", lambda rank, rnd, c, opname:
                            self._run_hier_allgather(rank, rnd, c, opname,
                                                     lay))
            return None
        if kind == "allgatherv":
            algo = plan[3] if len(plan) > 3 else "ring"
            dt = getattr(contrib, "dtype", None)
            if (algo != "ring" or dt is None or dt == object
                    or (len(plan) <= 3          # legacy: replicated total
                        and plan[1] < _RING_MIN_BYTES)):
                return None
            counts = plan[2]
            return ("alg", lambda rank, rnd, c, opname:
                    self._run_ring_allgatherv(rank, rnd, c, opname, counts))
        if kind == "alltoallv":
            # counts differ per rank, so a SIZE-based gate would let ranks
            # disagree on the tier (protocol divergence); gate on the dtype
            # only, which the MPI datatype contract makes uniform. Read it
            # via the attribute — np.asarray here would pull a jax payload
            # to host just to inspect its dtype.
            algo = plan[1] if len(plan) > 1 else "pairwise"
            dt = getattr(contrib[0], "dtype", None) \
                if isinstance(contrib, tuple) and contrib else None
            if algo != "pairwise" or dt is None or dt == object:
                return None
            return ("alg", self._run_pairwise_alltoallv)
        return None

    def _choose_chunked(self, contrib: Any, plan):
        """The chunk-pipelined star's eligibility (overlap engine): a bulk
        Allreduce the ring DECLINED (non-commutative op, or ring disabled)
        over a known-elementwise op, above ``pipeline_min_bytes``. Returns
        (op, schedule) or None. Like every tier gate, the decision is a
        deterministic function of rank-uniform values (plan kind, op,
        payload size/dtype, config) — and the chunk frames carry the chunk
        count so a divergent pipeline config still fails loudly instead of
        hanging."""
        if not plan or plan[0] != "allreduce":
            return None
        from .operators import is_elementwise
        op = plan[1]
        if not is_elementwise(op):
            return None
        try:
            arr = np.asarray(contrib)
        except Exception:
            return None
        if arr.dtype == object:
            return None
        from .overlap import ChunkSchedule
        sched = ChunkSchedule.maybe(arr.size, arr.dtype.itemsize)
        if sched is None:
            return None
        return (op, sched)

    # -- the collective contract ---------------------------------------------
    def run(self, rank: int, contrib: Any,
            combine: Callable[[list[Any]], Sequence[Any]], opname: str,
            plan=None) -> Any:
        ctx = self.ctx
        n = len(self.group)
        chosen = (self._choose_algorithm(contrib, plan, combine)
                  if (plan and n > 1) else None)
        chunked = None
        if chosen is None and plan and n > 1:
            chunked = self._choose_chunked(contrib, plan)
        mode = chosen[0] if chosen is not None \
            else ("starc" if chunked else "star")
        with self.cond:
            rnd = self.round
            self.round += 1
            self.inflight[rnd] = (opname, mode)
            # Frames of this round may have arrived before we entered: sweep
            # them for cross-tier mismatches the delivery check couldn't see.
            stale = tier_diverged = None
            for key, val in self.inbox.items():
                if key[0] == "alg" and key[1] == rnd:
                    if mode == "alg":
                        continue
                    if val[1] != opname:
                        stale = val[1]
                    else:
                        tier_diverged = val[0]   # same op, other tier
                elif not (isinstance(key[0], int) and key[0] == rnd):
                    continue
                elif len(key) == 2:              # monolithic star contrib
                    if mode == "star":
                        continue
                    if val[0] != opname:
                        stale = val[0]
                    else:
                        tier_diverged = key[1]
                elif len(key) == 4 and key[2] == "c":   # chunked contrib
                    if mode == "starc":
                        continue
                    if val[0] != opname:
                        stale = val[0]
                    else:
                        tier_diverged = key[1]
        if stale is not None:
            self._mismatch(stale, opname)
            ctx.check_failure()
        if tier_diverged is not None:
            self._tier_mismatch(opname, tier_diverged)
            ctx.check_failure()
        try:
            if chosen is not None:
                return chosen[1](rank, rnd, contrib, opname)
            if chunked is not None:
                return self._run_star_chunked(rank, rnd, contrib,
                                              chunked[0], chunked[1], opname)
            return self._run_star(rank, rnd, contrib, combine, opname)
        except BaseException as e:
            # ULFM errors stay LOCAL: the failure detector already woke
            # every survivor, and each raises its own typed error —
            # broadcasting an abort here would replace recoverable
            # ProcFailedError/RevokedError with fatal AbortError job-wide
            # and poison this rank's own recovery path (Comm_shrink).
            from .error import ProcFailedError as _PF, RevokedError as _RV
            if ctx.failure is None and not isinstance(e, (_PF, _RV)):
                ctx.fail(e)
            raise
        finally:
            with self.cond:
                self.inflight.pop(rnd, None)

    def _result_wait(self, rnd: int, key: Any, opname: str) -> Any:
        """Wait for ``inbox[key]`` (a star/chunked result from the root) with
        the busy-probe escape hatch, and pop it. The root may be legitimately
        slow INSIDE combine (a >60s XLA compile on big shapes — VERDICT r1
        weak item 6): before declaring deadlock, ask its drainer whether the
        round is still in flight; a dead root surfaces via abort frames in
        check_failure instead. The ping ships with the cond RELEASED
        (ADVICE r2): a blocking transport send under the lock the drainer
        needs to deliver frames here could wedge both this thread and the
        drainer on a backed-up socket."""
        ctx = self.ctx
        root_world = self.group[0]
        while True:
            with self.cond:
                try:
                    self._wait_for(lambda: key in self.inbox,
                                   f"collective {opname}",
                                   limit=collective_wait_limit(opname))
                    return self.inbox.pop(key)
                except DeadlockError as e:
                    deadlock = e
                    self.probing.add(rnd)
            got = busy = False
            try:
                self._send(root_world, ("collping", self.cid, rnd,
                                        ctx.local_rank), opname)
                with self.cond:
                    got = self._wait_for(
                        lambda: (key in self.inbox
                                 or ("pong", rnd) in self.inbox),
                        f"collective {opname} (busy probe)",
                        timeout=15.0)
                    busy = self.inbox.pop(("pong", rnd), False)
            finally:
                # discard AND sweep under one cond hold: a pong landing
                # between the probe wait's exit and the discard would
                # otherwise sit in the inbox forever (the collpong
                # handler gates on probing membership under this cond)
                with self.cond:
                    self.probing.discard(rnd)
                    self.inbox.pop(("pong", rnd), None)
            with self.cond:
                if key in self.inbox:
                    return self.inbox.pop(key)
            if not (got and busy):
                raise deadlock

    def _run_star_chunked(self, rank: int, rnd: int, contrib: Any, op,
                          schedule, opname: str) -> Any:
        """Chunk-pipelined star Allreduce (overlap engine): contributions
        travel as K chunk frames; the root folds chunk k in rank order AS
        SOON as every rank's chunk k has landed — while its drainer keeps
        receiving chunks k+1..K-1 concurrently (the fold runs with the cond
        released) — and ships each result chunk immediately. Transfer and
        fold genuinely overlap, and peers start receiving results before the
        last contribution chunk was even sent. Bitwise-equal to the
        monolithic star: same rank-order fold over the same elements, just
        chunk-separated (the eligibility gate admits elementwise ops only)."""
        import functools as _ft
        from .overlap import progress_begin, progress_note

        ctx = self.ctx
        n = len(self.group)
        K = schedule.nchunks
        root_world = self.group[0]
        arr = np.asarray(contrib).reshape(-1)
        prog = progress_begin(K, "chunks")
        sc = _pv.scope()    # pvar phase spans; None when pvars+tracing off
        if ctx.local_rank != root_world:
            t0 = _pv.monotonic() if sc is not None else 0.0
            # one coalesced flush for the whole chunk run: K contribution
            # frames ride one framed message / one writev (ISSUE-11)
            self._send_batch(
                root_world,
                [("collc", self.cid, rnd, rank, opname, idx, K,
                  _pack(arr[lo:hi])) for idx, (lo, hi) in enumerate(schedule)],
                opname)
            if sc is not None:
                sc.spans.append(("copy", t0, _pv.monotonic()))
                t0 = _pv.monotonic()
            parts = []
            for idx in range(K):
                parts.append(np.asarray(_unpack(
                    self._result_wait(rnd, (rnd, "cres", idx), opname)))
                    .reshape(-1))
                progress_note(prog)
            if sc is not None:
                sc.spans.append(("rendezvous", t0, _pv.monotonic()))
            return self._from_host(np.concatenate(parts), contrib)

        # root: per-chunk gather -> rank-order fold -> immediate scatter.
        # The per-phase sums double as the overlap-fraction inputs: chunk-k
        # rendezvous waits AFTER the first chunk are exactly the transfer
        # time the pipeline failed to hide behind the chunk-(k-1) fold.
        others = [r for r in range(n) if r != rank]
        res_parts = []
        fold_ns = wait_after_first_ns = 0
        for idx, (lo, hi) in enumerate(schedule):
            tw = _pv.monotonic() if sc is not None else 0.0
            with self.cond:
                self._wait_for(
                    lambda: all((rnd, r, "c", idx) in self.inbox
                                for r in others),
                    f"collective {opname} (chunk {idx})",
                    limit=collective_wait_limit(opname))
                gathered = {r: self.inbox.pop((rnd, r, "c", idx))
                            for r in others}
            if sc is not None:
                tw1 = _pv.monotonic()
                sc.spans.append(("rendezvous", tw, tw1))
                if idx > 0:
                    wait_after_first_ns += int((tw1 - tw) * 1e9)
            for r, (got_op, got_k, _) in gathered.items():
                if got_op != opname:
                    err = CollectiveMismatchError(
                        f"rank {r} is in {got_op!r} while this rank is in "
                        f"{opname!r} on the same communicator")
                    ctx.fail(err)
                    raise err
                if got_k != K:
                    err = MPIError(
                        f"ranks disagree on the pipeline chunking of "
                        f"{opname!r} ({got_k} vs {K} chunks) — "
                        f"TPU_MPI_PIPELINE_* must be uniform across ranks")
                    ctx.fail(err)
                    raise err
            # fold OUTSIDE the cond hold: the drainer delivers later chunks
            # while this one reduces — that concurrency IS the overlap
            tf = _pv.monotonic() if sc is not None else 0.0
            pieces = [arr[lo:hi] if r == rank
                      else np.asarray(_unpack(gathered[r][2])).reshape(-1)
                      for r in range(n)]
            if (op.ufunc is not None
                    and all(p.dtype == arr.dtype for p in pieces)):
                red = np.empty(hi - lo, dtype=arr.dtype)
                np.copyto(red, pieces[0])
                for p in pieces[1:]:
                    op.ufunc(red, p, out=red)
            else:
                red = np.asarray(_ft.reduce(op, pieces))
            if sc is not None:
                tf1 = _pv.monotonic()
                sc.spans.append(("fold", tf, tf1))
                fold_ns += int((tf1 - tf) * 1e9)
                tf = tf1
            res_parts.append(red)
            for r in others:
                self._send(self.group[r],
                           ("collcres", self.cid, rnd, idx, _pack(red)),
                           opname)
            if sc is not None:
                sc.spans.append(("copy", tf, _pv.monotonic()))
            progress_note(prog)
        if sc is not None and _pv.enabled():
            _pv.note_pipelined(self.cid, K, fold_ns, wait_after_first_ns)
        return self._from_host(np.concatenate(res_parts), contrib)

    def _run_star(self, rank: int, rnd: int, contrib: Any,
                  combine: Callable[[list[Any]], Sequence[Any]],
                  opname: str) -> Any:
        ctx = self.ctx
        n = len(self.group)
        root_world = self.group[0]
        sc = _pv.scope()    # pvar phase spans; None when pvars+tracing off
        if ctx.local_rank != root_world:
            t0 = _pv.monotonic() if sc is not None else 0.0
            self._send(root_world, ("coll", self.cid, rnd, rank, opname,
                                    _pack(contrib)), opname)
            if sc is not None:
                sc.spans.append(("copy", t0, _pv.monotonic()))
                t0 = _pv.monotonic()
            res = self._result_wait(rnd, (rnd,), opname)
            if sc is not None:
                sc.spans.append(("rendezvous", t0, _pv.monotonic()))
            return _unpack(res)

        # root: gather, verify, combine, scatter
        t0 = _pv.monotonic() if sc is not None else 0.0
        with self.cond:
            self._wait_for(
                lambda: all((rnd, r) in self.inbox for r in range(n) if r != rank),
                f"collective {opname} (gather)")
            gathered: list[Any] = [None] * n
            for r in range(n):
                if r == rank:
                    gathered[r] = (opname, contrib)
                else:
                    gathered[r] = self.inbox.pop((rnd, r))
        if sc is not None:
            sc.spans.append(("rendezvous", t0, _pv.monotonic()))
        names = {op for op, _ in gathered}
        if len(names) > 1:
            err = CollectiveMismatchError(
                f"ranks disagree on the collective for cid {self.cid}: "
                f"{sorted(names)}")
            self.ctx.fail(err)
            raise err
        t0 = _pv.monotonic() if sc is not None else 0.0
        try:
            results = list(combine([_unpack(c) for _, c in gathered]))
        except BaseException as e:
            self.ctx.fail(e)
            raise
        if sc is not None:
            sc.spans.append(("fold", t0, _pv.monotonic()))
        if len(results) != n:
            err = MPIError(f"combine for {opname} returned {len(results)} "
                           f"results for {n} ranks")
            self.ctx.fail(err)
            raise err
        t0 = _pv.monotonic() if sc is not None else 0.0
        for r in range(n):
            if r == rank:
                continue
            self._send(self.group[r],
                       ("collres", self.cid, rnd, _pack(results[r])), opname)
        if sc is not None:
            sc.spans.append(("copy", t0, _pv.monotonic()))
        return results[rank]

    def _send(self, world_dst: int, item: Any, opname: str) -> None:
        """Encode + send a protocol frame (zero-copy for array payloads); an
        unpicklable payload fate-shares with a clear error instead of a raw
        PicklingError mid-protocol (the p2p proxy already guards its
        equivalent case)."""
        try:
            parts = dumps_oob_parts(item, shm_ok=self.ctx.shm_ok(world_dst))
        except OSError as e:
            err = MPIError(
                f"collective {opname} could not stage its payload in the shm "
                f"lane (/dev/shm full or unwritable?): {e}")
            self.ctx.fail(err)
            raise err from None
        except Exception as e:
            err = MPIError(
                f"collective {opname} payload is not picklable and "
                f"multi-process ranks do not share an address space: {e}")
            self.ctx.fail(err)
            raise err from None
        try:
            self.ctx.transport.sendv(world_dst, parts)
        except ConnectionError:
            if self.ctx._detector is None:
                raise
            # failure detection is on: a refused protocol send IS a death
            # signal — surface the typed ULFM error instead of fate-sharing
            self.ctx.peer_failed(world_dst)
            raise ProcFailedError(
                f"rank {world_dst} died mid-collective ({opname})",
                ranks=(world_dst,)) from None

    def _send_batch(self, world_dst: int, items: list, opname: str) -> None:
        """Coalesce a run of protocol frames to one peer into ``("batchv",
        [...])`` wrapper frames (ISSUE-11 batched submission): each flush is
        ONE framed message — one ``writev`` scatter-gather on the native
        transport, one receiver wakeup — instead of one per item. Grouping
        honors ``config.batch_max_ops`` / ``config.batch_max_bytes``; a cap
        of <= 1 falls back to per-item sends. Array payloads still travel
        out-of-band (``dumps_oob_parts`` encodes the whole wrapper), so the
        zero-copy / shm lanes are unchanged."""
        cfg = config.load()
        cap = int(cfg.batch_max_ops)
        if cap <= 1 or len(items) <= 1:
            for item in items:
                self._send(world_dst, item, opname)
            return
        max_bytes = int(cfg.batch_max_bytes)

        def _nb(item) -> int:
            tail = item[-1]
            return int(getattr(tail, "nbytes", 0) or 0)

        i = 0
        while i < len(items):
            group = [items[i]]
            nbytes = _nb(items[i])
            i += 1
            while i < len(items) and len(group) < cap:
                b = _nb(items[i])
                if max_bytes > 0 and nbytes + b > max_bytes:
                    break
                group.append(items[i])
                nbytes += b
                i += 1
            if len(group) == 1:
                self._send(world_dst, group[0], opname)
            else:
                self._send(world_dst, ("batchv", group), opname)
            if _pv.enabled():
                _pv.note_batch(self.cid, len(group))


class ProcContext(SpmdContext):
    """A world whose ranks are OS processes; this instance represents one.

    `size` is the world size but only ``local_rank`` runs here. Mailbox
    index ``local_rank`` is the real matching engine; all other slots are
    wire proxies. Failure fate-sharing crosses processes via abort frames
    (and the launcher kills the job on any nonzero exit, mpiexec-style).
    """

    def __init__(self, local_rank: int, size: int, transport,
                 universe_size: Optional[int] = None,
                 same_host: Optional[Sequence[bool]] = None,
                 addrs: Optional[Sequence[str]] = None):
        super().__init__(size, universe_size=universe_size)
        self.local_rank = local_rank
        self.transport = transport
        # which peers share this host (shm lane eligibility); default: all,
        # the single-launcher `tpurun --procs` shape.
        self._same_host = tuple(same_host) if same_host is not None \
            else (True,) * size
        # world address table ("host:port" per rank) — the basis for
        # Comm_spawn world growth; empty when unknown (no spawn possible).
        self.addrs: list[str] = list(addrs or [])
        # lazily-cached TPU_MPI_DOMAINS split (see _domain_split)
        self._domain_split_cache: Optional[int] = None
        # snapshot of the debug-sequence flag (read per message on the wire
        # path; a config.load() there would take the config lock per send)
        self.debug_seq = config.load().debug_sequence_check
        # cross-process flow control: peers that told us to stop blocking-
        # sending to them (choke/unchoke frames), and the peers WE choked
        self.choked_by: set[int] = set()
        self.choke_count = 0               # monotonic; see _dispatch "choke"
        self._choke_cond = threading.Condition()
        self._choked_peers: set[int] = set()
        self._choke_high = config.load().send_highwater_bytes
        self._grow_lock = threading.Lock()
        self._spawned_procs: list = []
        self._cid_counter = itertools.count(0)
        self.mailboxes = [
            Mailbox(self) if r == local_rank else _RemoteMailbox(self, r)
            for r in range(size)
        ]
        self._choke_peers_lock = threading.Lock()
        # unchoke decisions are made under the mailbox lock but SENT from
        # the drainer loop (never I/O under the lock that delivers frames)
        self._pending_unchokes: set[int] = set()
        self.mailboxes[local_rank].drain_hook = self._maybe_unchoke
        self.mailboxes[local_rank].pending_recv_hook = self._unchoke_all
        # Blocked-receiver direct drain (VERDICT r3 #4): one lease on the
        # transport's recv side, shared by the drainer thread and any rank
        # thread blocked in Recv/Wait/Probe. While a receiver waits, the
        # DRAINER IS PARKED (event) and the receiver owns the socket: the
        # message path is sender-process → this thread's own poll(), no
        # drainer→mailbox→scheduler hops and no polling thread competing
        # for the core. ``_last_direct`` keeps the drainer's poll slices
        # short for a grace period after direct activity, so a ping-pong
        # receiver re-entering Recv reclaims the lease without waiting out
        # a full _POLL_MS slice.
        self._pump_lock = threading.Lock()
        self._last_direct = 0.0
        self._direct_waiters = 0
        self._waiters_lock = threading.Lock()
        self._drainer_resume = threading.Event()
        mb = self.mailboxes[local_rank]
        mb.direct_pump = self._direct_pump
        mb.pump_begin = self._pump_begin
        mb.pump_end = self._pump_end
        # Fault-tolerant agreement state (Comm_agree/Comm_shrink substrate):
        # contributions and decisions keyed by ("ftag", cid, epoch). Decisions
        # are kept for the life of the job so a rank that finished an
        # agreement round can answer a straggler's late (re)contribution from
        # its dispatch loop (coordinator-failover correctness).
        self._ft_lock = threading.Lock()
        self._ft_cond = threading.Condition(self._ft_lock)
        self._ft_contribs: dict[Any, dict[int, tuple[int, frozenset]]] = {}
        self._ft_decided: dict[Any, tuple[int, frozenset]] = {}
        # Failure detection (ULFM-shaped fault tolerance): heartbeat frames
        # on the transport poll loop plus a poll-side silence clock. Off by
        # default (heartbeat_ms == 0) — the fault path is pay-for-use.
        # Created BEFORE the drainer starts: the drain loop reads it.
        cfg = config.load()
        self._detector = None
        if cfg.heartbeat_ms > 0 and hasattr(transport, "hb_enable"):
            self._detector = FailureDetector(
                self, transport, cfg.heartbeat_ms, cfg.failure_timeout_ms)
        self._drainer = threading.Thread(target=self._drain, daemon=True,
                                         name="tpu-mpi-drainer")
        self._drainer_stop = threading.Event()
        self._drainer.start()

    @property
    def host_token(self) -> str:
        """Physical-host identity of this rank (VERDICT r2 missing #2).

        Derived from the rendezvous address table: ranks whose transport
        addresses share a host part live on one machine and can share POSIX
        shm. ``TPU_MPI_HOST_ID`` overrides it — for NATed networks where
        addresses don't identify machines, and for exercising multi-host
        code paths on one machine. Comm_split_type gathers these tokens
        over the communicator (no rank ever guesses a peer's token) and
        Win_allocate_shared refuses comms that span distinct tokens."""
        override = os.environ.get("TPU_MPI_HOST_ID")
        if override:
            return f"override:{override}"
        if self.addrs:
            return self.addrs[self.local_rank].rsplit(":", 1)[0]
        return "local"

    def _maybe_unchoke(self, queued_bytes: int) -> None:
        """Mailbox drain hook (lock held — no I/O): once the unexpected
        queue falls to the low-water mark, queue every choked sender for an
        unchoke frame; the drainer loop ships them."""
        if queued_bytes > self._choke_high // 2:
            return
        self._unchoke_all()

    def _unchoke_all(self) -> None:
        """Queue unchoke frames for every choked peer (also the
        lock-free-peek fast path: this runs on EVERY posted receive —
        taking the lock with nobody choked is per-message overhead)."""
        if not self._choked_peers:
            return
        self._unchoke_all_locked()

    def _unchoke_all_locked(self) -> None:
        """Queue unchoke frames for every choked peer (also the
        pending-recv hook: a receiver waiting on an unmatched recv may be
        waiting for a choked sender's message — release them all, the
        cross-process analog of the thread tier's posted-receive
        admission bypass)."""
        with self._choke_peers_lock:
            if not self._choked_peers:
                return
            self._pending_unchokes |= self._choked_peers
            self._choked_peers = set()

    def _flush_unchokes(self) -> None:
        """Drainer-loop tail: ship queued unchoke frames. A failed unchoke
        fate-shares — the peer would otherwise hang choked until a
        misleading DeadlockError."""
        if not self._pending_unchokes:     # lock-free peek: hot-path no-op
            return
        with self._choke_peers_lock:
            if not self._pending_unchokes:
                return
            peers, self._pending_unchokes = self._pending_unchokes, set()
        for p in peers:
            try:
                self.send_frame(p, ("unchoke",))
            except Exception as e:
                self.fail(MPIError(
                    f"could not unchoke rank {p}: {type(e).__name__}: {e}"))

    # -- frame transmit -------------------------------------------------------
    def _domain_split(self) -> int:
        """Ranks-per-domain of the ``TPU_MPI_DOMAINS`` world split (0 when
        the override is off or does not divide the world). Cached: procs
        children fix the env before Init and the per-send hot path cannot
        afford a config.load() per frame."""
        spl = self._domain_split_cache
        if spl is None:
            k = int(config.load().domains)
            spl = self.size // k if (2 <= k <= self.size
                                     and self.size % k == 0) else 0
            self._domain_split_cache = spl
        return spl

    def shm_ok(self, world_dst: int) -> bool:
        """Whether the shm lane may carry payloads to this peer: same host
        AND same domain. ``TPU_MPI_DOMAINS`` emulates a multi-host split
        on one box; traffic crossing the emulated host boundary must ride
        the socket fabric, or the "slow inter / fast intra" asymmetry the
        override exists to model would silently vanish."""
        if not (0 <= world_dst < len(self._same_host)
                and self._same_host[world_dst]):
            return False
        spl = self._domain_split()
        return spl == 0 or world_dst // spl == self.local_rank // spl

    def coll_shm_ok(self, group) -> bool:
        """Whether a communicator may use the shared-memory collective fold
        (tune.select's ``shm`` eligibility flag): every member shares this
        host — and this domain, under the ``TPU_MPI_DOMAINS`` emulation —
        and /dev/shm exists. Same-host membership comes from the
        rendezvous address table, so all ranks of a single-host comm agree
        — the rank-uniformity every tier gate requires. A group contained
        in ONE domain keeps the fold (intra-domain sub-comms are exactly
        the fast fabric); a group spanning domains loses it."""
        return (os.path.isdir(_SHM_DIR)
                and all(self.shm_ok(r) for r in group))

    def send_frame(self, world_dst: int, item: Any) -> None:
        send_frame(self.transport, world_dst, item,
                   shm_ok=self.shm_ok(world_dst))

    # -- frame pump -----------------------------------------------------------
    def _handle_frame(self, src_world: int, frame) -> None:
        """Decode + dispatch one received frame (drainer and direct-pump
        shared body; caller holds the pump lease, so frame order is
        preserved across the two entry points)."""
        try:
            fast = _fast_p2p_decode(frame)
            item = None if fast is not None else loads_oob(frame)
        except Exception as e:                  # corrupted frame: fate-share
            self.fail(MPIError(f"undecodable frame from {src_world}: {e}"))
            return
        try:
            if fast is not None:
                self._deliver_p2p(src_world, fast)
            else:
                self._dispatch(src_world, item)
        except Exception as e:
            # A failure while dispatching a decoded frame (malformed
            # tuple, error inside deliver/post) must fate-share, not
            # silently kill the drainer thread (ADVICE r1).
            self.fail(MPIError(
                f"error dispatching frame from {src_world}: "
                f"{type(e).__name__}: {e}"))

    def _pump_begin(self) -> None:
        """A rank thread is entering a blocked receive: park the drainer."""
        with self._waiters_lock:
            self._direct_waiters += 1
            self._drainer_resume.clear()

    def _pump_end(self) -> None:
        with self._waiters_lock:
            self._direct_waiters -= 1
            if self._direct_waiters == 0:
                self._last_direct = time.monotonic()
        # no resume-event set here: waking the drainer per completed receive
        # costs a context switch per message on small-core hosts. The
        # drainer's parked wait has a 50 ms cap, and every blocking wait
        # (P2P and collective) pumps for itself, so nothing depends on the
        # drainer for latency.

    def _direct_pump(self, timeout_s: float, done=None) -> bool:
        """Blocked-receiver drain: poll the transport from the waiting rank
        thread itself (the drainer is parked by _pump_begin). Returns True
        iff a frame was delivered or ``done()`` turned true while acquiring
        the lease (e.g. the drainer delivered our message during its last
        slice); False on idle socket or when a sibling holds the lease."""
        # non-blocking first: the uncontended acquire (the per-message hot
        # case — the drainer is parked) skips the timed-acquire setup cost
        if not self._pump_lock.acquire(False):
            if not self._pump_lock.acquire(timeout=0.001):
                # the drainer holds the lease, possibly blocked deep in its
                # poll slice: ask it to yield (tm_poke -> its non-direct
                # recv returns as a timeout in microseconds), then wait for
                # the handover
                poke = getattr(self.transport, "poke", None)
                if poke is not None:
                    poke()
                if not self._pump_lock.acquire(timeout=timeout_s):
                    return False
        try:
            if done is not None and done():
                return True                 # delivered while we waited
            self._last_direct = time.monotonic()
            if self._detector is not None:
                self._detector.poll()
            self._flush_unchokes()
            try:
                got = self.transport.recv(max(1, int(timeout_s * 1000)),
                                          direct=True)
            except ConnectionResetError:
                return False                    # shutting down
            if got is None:
                return False
            self._handle_frame(*got)
            return True
        finally:
            self._pump_lock.release()

    def _drain(self) -> None:
        while not self._drainer_stop.is_set():
            if self._detector is not None:
                self._detector.poll()
            self._flush_unchokes()
            # park while any rank thread is pumping its own socket — zero
            # CPU from this thread during a blocked receive (the wait has a
            # cap only so stop/failure are still noticed)
            if self._direct_waiters > 0:
                # parked nap, capped at 50 ms. Deliberately NOT woken per
                # completed receive (_pump_end) — that would cost a context
                # switch per message; every blocking wait pumps for itself,
                # so only shutdown() needs to wake us early (it sets the
                # event).
                self._drainer_resume.wait(0.05)
                self._drainer_resume.clear()
                continue
            # grace period after direct activity: the main thread is mid
            # message loop (e.g. between ping-pong Recvs) and will re-take
            # the lease within microseconds — touching the socket here would
            # make it wait out our poll slice. Sleep without the lease;
            # frames sit in the C++ inbox at most this long if the main
            # thread never comes back.
            if time.monotonic() - self._last_direct < 0.02:
                time.sleep(0.005)
                continue
            # recv AND dispatch under one lease hold: releasing between the
            # two would let a direct pumper deliver a later frame first,
            # breaking non-overtaking order
            self._pump_lock.acquire()
            try:
                try:
                    got = self.transport.recv(_POLL_MS)
                except ConnectionResetError:
                    return
                if got is not None:
                    self._handle_frame(*got)
            finally:
                self._pump_lock.release()

    def _deliver_p2p(self, src_world: int, msg: Message) -> None:
        mb = self.mailboxes[self.local_rank]
        mb.post(msg)
        # cross-process flow control: over the mark, tell this sender to
        # pause its BLOCKING sends until we drain (drain_hook unchokes).
        # Record under the lock, ship AFTER releasing it (ADVICE r2:
        # blocking I/O under a lock _flush_unchokes also takes would let
        # one slow peer socket stall the whole frame pump). Ordering is
        # safe: a concurrently queued unchoke is only flushed at the
        # next drainer-loop top, after this dispatch returns.
        if self._choke_high > 0 and src_world != self.local_rank:
            send_choke = False
            with self._choke_peers_lock:
                if (mb.queued_bytes > self._choke_high
                        and src_world not in self._choked_peers):
                    self._choked_peers.add(src_world)
                    send_choke = True
            if send_choke:
                self.send_frame(src_world, ("choke",))

    def _dispatch(self, src_world: int, item: Any) -> None:
        kind = item[0]
        if kind == "batchv":
            # coalesced submission flush: unwrap in order — sub-frames see
            # exactly the dispatch they would have seen arriving singly
            for sub in item[1]:
                self._dispatch(src_world, sub)
            return
        if kind == "p2p":
            _, src, tag, cid, payload, count, dtype, mkind, seq = item
            self._deliver_p2p(src_world, Message(src, tag, cid,
                                                 _unpack(payload), count,
                                                 dtype, mkind, seq=seq))
        elif kind == "choke":
            with self._choke_cond:
                self.choked_by.add(src_world)
                # sticky observability: choked_by empties the instant the
                # receiver unchokes (e.g. it posted a recv), so transient
                # membership is unobservable to a poller — tests and
                # diagnostics read this monotonic counter instead
                self.choke_count += 1
        elif kind == "unchoke":
            with self._choke_cond:
                self.choked_by.discard(src_world)
                self._choke_cond.notify_all()
        elif kind == "coll":
            _, cid, rnd, src, opname, contrib = item
            self._proc_channel(cid).deliver_contrib(rnd, src, opname,
                                                    contrib)
        elif kind == "collres":
            _, cid, rnd, result = item
            self._proc_channel(cid).deliver_result(rnd, result)
        elif kind == "collc":
            _, cid, rnd, src, opname, idx, k, part = item
            self._proc_channel(cid).deliver_chunk(rnd, src, opname, idx, k,
                                                  part)
        elif kind == "collcres":
            _, cid, rnd, idx, result = item
            self._proc_channel(cid).deliver_chunk_result(rnd, idx, result)
        elif kind == "collping":
            # busy probe: is this round still in flight here (e.g. the star
            # root mid-combine)? Answered by the drainer so a long combine
            # on the main thread can't stall the reply.
            _, cid, rnd, src = item
            ch = self._proc_channel(cid)
            with ch.cond:
                busy = rnd in ch.inflight
            self.send_frame(src, ("collpong", cid, rnd, busy))
        elif kind == "collpong":
            _, cid, rnd, busy = item
            ch = self._proc_channel(cid)
            with ch.cond:
                if rnd in ch.probing:   # a late pong nobody waits on is noise
                    ch.inbox[("pong", rnd)] = busy
                    ch.cond.notify_all()
        elif kind == "alg":
            _, cid, rnd, tag, src, opname, payload = item
            self._proc_channel(cid).deliver_alg(rnd, tuple(tag), src, opname,
                                                payload)
        elif kind == "rma":
            from ._rma_wire import dispatch_rma
            dispatch_rma(self, src_world, _unpack(item))
        elif kind == "abort":
            _, text = item
            with self._failure_lock:
                if self.failure is None:
                    self.failure = AbortError(text)
            self.mailboxes[self.local_rank].notify()
            for ch in list(self._channels.values()):
                with ch.cond:
                    ch.cond.notify_all()
        elif kind == "revoke":
            # Comm_revoke flood. Re-flood once before marking (dedup via
            # revoked_cids): if the original revoker died mid-flood, every
            # receiver completes the propagation, so all survivors converge.
            _, cid, group = item
            if cid not in self.revoked_cids:
                self.revoke_comm(cid)
                for r in group:
                    if r != self.local_rank and r not in self.failed_ranks:
                        try:
                            self.send_frame(r, ("revoke", cid, tuple(group)))
                        except Exception:
                            pass
        elif kind == "bye":
            # clean Finalize announcement: this peer is about to close its
            # sockets on purpose — the failure detector must not read the
            # resulting EOF as a death (staggered-shutdown false positive)
            self.peer_departed(src_world)
        elif kind == "ftag":
            # agreement contribution (possibly resent after a coordinator
            # failover). If the decision is already known here, answer the
            # straggler directly instead of stashing.
            _, cid, epoch, src, flag, dead = item
            key = ("ftag", cid, epoch)
            with self._ft_cond:
                dec = self._ft_decided.get(key)
                if dec is None:
                    self._ft_contribs.setdefault(key, {})[src] = (
                        int(flag), frozenset(dead))
                    self._ft_cond.notify_all()
            if dec is not None and src != self.local_rank:
                try:
                    self.send_frame(src, ("ftagd", cid, epoch, dec[0],
                                          tuple(sorted(dec[1]))))
                except Exception:
                    pass
        elif kind == "ftagd":
            _, cid, epoch, flag, dead = item
            key = ("ftag", cid, epoch)
            with self._ft_cond:
                self._ft_decided[key] = (int(flag), frozenset(dead))
                self._ft_cond.notify_all()

    # -- fault tolerance (ULFM-shaped: revoke / agree / shrink substrate) -----
    def peer_failed(self, rank: int) -> None:
        if rank in self.failed_ranks:
            return
        super().peer_failed(rank)
        # a dead peer can never unchoke us; drop its choke so blocked
        # senders wake (they re-check failed_ranks and raise typed)
        with self._choke_cond:
            self.choked_by.discard(rank)
            self._choke_cond.notify_all()
        with self._ft_cond:
            self._ft_cond.notify_all()
        self._drainer_resume.set()

    def flood(self, group: Sequence[int], item: Any) -> None:
        """Best-effort broadcast of a control frame to every live member of
        ``group`` (revoke/bye propagation — failures along the way are the
        very condition being handled)."""
        for r in group:
            if r != self.local_rank and r not in self.failed_ranks:
                try:
                    self.send_frame(r, item)
                except Exception:
                    pass

    def drain_failed_state(self, old_cid: Any) -> None:
        """Drop per-communicator state tied to a revoked communicator before
        its shrink replacement goes live: the collective channel (and any
        frames a dead rank parked in its inbox) and the overlap plan cache."""
        with self._channels_lock:
            self._channels.pop(old_cid, None)
        try:
            from .overlap import plans
            plans.invalidate(old_cid)
        except Exception:
            pass

    def ft_agree(self, me: int, group: Sequence[int], cid: Any, epoch: int,
                 flag: int) -> tuple[int, frozenset]:
        """Fault-tolerant agreement round over ``group`` (world ranks).

        Returns ``(value, dead)`` where ``value`` is the bitwise AND of every
        contributing rank's ``flag`` and ``dead`` the union of every
        contributor's failed-set view restricted to the group — the same
        round serves MPI_Comm_agree (callers use the value) and Comm_shrink
        (callers use the dead set).

        Protocol: the lowest-indexed live member of the group coordinates;
        everyone else sends it ``("ftag", ...)`` and waits for the
        ``("ftagd", ...)`` decision. A coordinator death mid-round is
        detected by the heartbeat plane; survivors fail over to the next
        live member and resend. Decisions are remembered for the life of
        the job so late resends are answered from _dispatch even after the
        caller has moved on."""
        group = tuple(group)
        key = ("ftag", cid, epoch)
        deadline = time.monotonic() + deadlock_timeout()
        with self._ft_cond:
            self._ft_contribs.setdefault(key, {})[me] = (
                int(flag), frozenset(self.failed_ranks & set(group)))
        while True:
            if time.monotonic() > deadline:
                raise DeadlockError(
                    f"Comm_agree(cid={cid!r}, epoch={epoch}) did not "
                    f"complete within {deadlock_timeout()}s")
            with self._ft_cond:
                dec = self._ft_decided.get(key)
            if dec is not None:
                return dec
            live = [r for r in group if r not in self.failed_ranks]
            coord = live[0] if live else me
            if coord == me:
                dec = self._ft_coordinate(key, group, deadline)
                for r in group:
                    if r != me and r not in self.failed_ranks:
                        try:
                            self.send_frame(r, ("ftagd", key[1], key[2],
                                                dec[0],
                                                tuple(sorted(dec[1]))))
                        except Exception:
                            pass
                return dec
            # participant: (re)send our contribution to the current
            # coordinator, then wait for a decision or its death
            with self._ft_cond:
                my_flag, my_dead = self._ft_contribs[key][me]
            try:
                self.send_frame(coord, ("ftag", key[1], key[2], me,
                                        my_flag, tuple(sorted(my_dead))))
            except Exception:
                # a refused control send IS a death signal
                self.peer_failed(coord)
                continue
            resend_at = time.monotonic() + 0.5
            with self._ft_cond:
                while (key not in self._ft_decided
                       and coord not in self.failed_ranks
                       and time.monotonic() < resend_at):
                    self._ft_cond.wait(0.02)
                dec = self._ft_decided.get(key)
            if dec is not None:
                return dec
            # coordinator dead or slow: loop (re-elect / resend)

    def _ft_coordinate(self, key: Any, group: tuple[int, ...],
                       deadline: float) -> tuple[int, frozenset]:
        """Coordinator side of ft_agree: wait for every live member's
        contribution (members that die mid-round are excluded as the
        detector marks them), then fold and record the decision."""
        with self._ft_cond:
            while True:
                if key in self._ft_decided:
                    return self._ft_decided[key]
                contribs = self._ft_contribs.get(key, {})
                if all(r in contribs or r in self.failed_ranks
                       for r in group):
                    break
                if time.monotonic() > deadline:
                    raise DeadlockError(
                        f"Comm_agree coordinator (cid={key[1]!r}) timed out "
                        f"waiting for contributions")
                self._ft_cond.wait(0.02)
            value = ~0
            dead = set(self.failed_ranks)
            for f, d in contribs.values():
                value &= f
                dead |= set(d)
            dec = (value, frozenset(dead & set(group)))
            self._ft_decided[key] = dec
            return dec

    # -- channel management ---------------------------------------------------
    def _proc_channel(self, cid: Any) -> ProcChannel:
        with self._channels_lock:
            ch = self._channels.get(cid)
            if ch is None:
                # Drainer can see a contribution before the local rank enters
                # the collective; group is filled in on first local entry but
                # rank-0 routing only needs the cid until then.
                ch = ProcChannel(self, cid, ())
                self._channels[cid] = ch
            return ch

    def channel(self, cid: Any, size: int, group: Optional[tuple[int, ...]] = None):
        if group is None:
            raise MPIError("this communicator type is not supported in "
                           "multi-process mode")
        ch = self._proc_channel(cid)
        if not ch.group:
            ch.group = tuple(group)
        return ch

    def alloc_cid(self):
        """Process-namespaced context ids. alloc_cid runs inside combine(),
        which executes only at the allocating comm's ROOT process — each
        process has its own counter, so two different roots would mint the
        same id (observed: a split-of-a-split deadlocks on the reused
        channel). Tuple of (world rank, local counter): disjoint by
        construction, and — unlike the old size-strided ints — immune to the
        world growing mid-job (Comm_spawn changes self.size, which would
        change the stride and re-collide)."""
        return ("c", self.local_rank, next(self._cid_counter))

    # -- dynamic process management (MPI_Comm_spawn, src/comm.jl:135-147) -----
    def spawn_processes(self, n: int, command, argv, parent_group):
        """Launch ``n`` child OS processes that join this world's transport
        mesh as world ranks [W, W+n) while forming their own COMM_WORLD.
        Runs at the spawning comm's star-root process only (inside combine).
        Returns (child_group, inter_cid, world_cid, world_addrs) — shipped
        to every parent, which then applies the growth locally.

        Concurrent spawns from communicators with different roots are not
        coordinated (no resource-manager universe); the reference delegates
        that to mpiexec's universe."""
        import pickle
        import subprocess
        import tempfile

        from .comm import _worker_argv

        if not self.addrs:
            raise MPIError("Comm_spawn needs the world address table; this "
                           "process was not attached via rendezvous")
        with self._grow_lock:
            base = len(self.addrs)
        child_group = tuple(range(base, base + n))
        inter_cid = self.alloc_cid()
        world_cid = self.alloc_cid()
        if callable(command):
            command_wire: Any = serialization.dumps(command)
        else:
            command_wire = str(command)
        spec = {
            "command": command_wire,
            "argv": [str(a) for a in (argv or [])],
            "worker_argv": _worker_argv(command, argv),
            "parent_group": tuple(parent_group),
            "child_group": child_group,
            "inter_cid": inter_cid,
            "world_cid": world_cid,
        }
        fd, spec_path = tempfile.mkstemp(prefix="tpu_mpi_spawn_", suffix=".pkl")
        with os.fdopen(fd, "wb") as f:
            pickle.dump(spec, f)
        cfg = config.load()
        # bind/advertise like the launcher's coordinator: children run on
        # THIS host, so in a multi-host world their transport addresses must
        # be advertised as this host's routable name, not loopback
        coord = Coordinator(n, host=cfg.coordinator_bind, rank_base=base,
                            base_addrs=list(self.addrs),
                            advertise=cfg.coordinator_advertise or None)
        pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        procs = []
        try:
            for i in range(n):
                env = dict(os.environ)
                old_pp = env.get("PYTHONPATH", "")
                env["PYTHONPATH"] = (pkg_parent
                                     + (os.pathsep + old_pp if old_pp else ""))
                env["TPU_MPI_PROC_RANK"] = str(base + i)
                env["TPU_MPI_PROC_SIZE"] = str(base + n)
                env["TPU_MPI_PROC_COORD"] = coord.address
                env["TPU_MPI_SPAWN_SPEC"] = spec_path
                # children inherit the JOB's shm namespace, not the ephemeral
                # spawn-coordinator port, so the launcher's end-of-job sweep
                # reclaims their segments too
                env["TPU_MPI_SHM_TAG"] = shm_job_tag()
                procs.append(subprocess.Popen(
                    [sys.executable, "-m", "tpu_mpi._spawn_child"], env=env))
            world_addrs = coord.wait_map(config.load().rendezvous_timeout)
        except BaseException:
            for p in procs:
                p.terminate()
            raise
        finally:
            coord.close()
            # every child reads the spec before it rendezvouses, so once the
            # map is (or fails to be) complete the file is dead weight
            try:
                os.unlink(spec_path)
            except OSError:
                pass
        self._spawned_procs.extend(procs)
        return (child_group, inter_cid, world_cid, world_addrs)

    def apply_growth(self, world_addrs: Sequence[str]) -> None:
        """Extend this process's view of the world to the new address table
        (idempotent; every parent rank calls it after a spawn completes)."""
        with self._grow_lock:
            if len(world_addrs) <= len(self.addrs):
                return
            self.transport.grow(list(world_addrs))
            my_host = (self.addrs[self.local_rank].rsplit(":", 1)[0]
                       if self.addrs else "")
            for r in range(len(self.addrs), len(world_addrs)):
                self.mailboxes.append(_RemoteMailbox(self, r))
                self.initialized.append(False)
                self.finalized.append(False)
                self.thread_level.append(None)
                self.main_threads.append(None)
            self._same_host = tuple(
                a.rsplit(":", 1)[0] == my_host for a in world_addrs)
            self.addrs = list(world_addrs)
            self.size = len(world_addrs)

    # -- overrides: shared-address-space features -----------------------------
    def add_ranks(self, n: int, world_cid: Any):
        raise MPIError("internal: thread-tier add_ranks called on the "
                       "multi-process context (use spawn_processes)")

    @property
    def supports_shared_objects(self) -> bool:
        return False

    def device_for(self, rank: int):
        import jax
        devs = jax.devices()
        return devs[rank % len(devs)]

    # -- failure fate-sharing -------------------------------------------------
    def fail(self, exc: BaseException, rank: Optional[int] = None) -> None:
        super().fail(exc, rank)
        text = f"{type(exc).__name__}: {exc}" + (
            f" originating on rank {rank}" if rank is not None else
            f" originating on rank {self.local_rank}")
        frame = pickle.dumps(("abort", text))
        for r in range(self.size):
            if r != self.local_rank:
                try:
                    self.transport.send(r, frame)
                except Exception:
                    pass

    def shutdown(self) -> None:
        # Reap spawned children first: their intercomm traffic rides this
        # process's transport, so stopping it while they still run would
        # strand them (mpiexec waits for the whole universe). One shared
        # 60 s budget; stragglers get SIGTERM, then SIGKILL, and are always
        # wait()ed so nothing stays a zombie.
        import time as _time
        deadline = _time.monotonic() + 60
        for p in self._spawned_procs:
            try:
                p.wait(timeout=max(0.0, deadline - _time.monotonic()))
            except Exception:
                p.terminate()
                try:
                    p.wait(timeout=5)
                except Exception:
                    p.kill()
                    try:
                        p.wait(timeout=5)
                    except Exception:
                        pass
        # Clean departure announcement: with the failure detector active,
        # closing our sockets looks exactly like dying. The "bye" frame
        # tells survivors this EOF is a Finalize, not a failure
        # (staggered-shutdown false-positive suppression).
        if self._detector is not None:
            self.flood(range(self.size), ("bye",))
        self._drainer_stop.set()
        self._drainer_resume.set()      # wake a parked drainer promptly
        self.transport.stop()


# ---------------------------------------------------------------------------
# rendezvous: child side
# ---------------------------------------------------------------------------

def proc_attach() -> tuple[ProcContext, int]:
    """Join the multi-process world described by the TPU_MPI_PROC_* env
    (set by the launcher): start the native transport, rendezvous with the
    coordinator for the address map, and bind this process as its rank."""
    from ._native import NativeTransport

    rank = int(os.environ["TPU_MPI_PROC_RANK"])
    size = int(os.environ["TPU_MPI_PROC_SIZE"])
    coord = os.environ["TPU_MPI_PROC_COORD"]
    host, port = coord.rsplit(":", 1)

    transport = NativeTransport(rank, size)
    with socket.create_connection((host, int(port)), timeout=60) as s:
        # The address map only arrives once ALL siblings have joined; sibling
        # startup skew (native build, cold jax import) routinely exceeds the
        # connect timeout, so wait much longer for the map itself.
        s.settimeout(config.load().rendezvous_timeout)
        s.sendall(json.dumps({"rank": rank, "port": transport.port}).encode()
                  + b"\n")
        buf = b""
        while not buf.endswith(b"\n"):
            try:
                chunk = s.recv(65536)
            except socket.timeout:
                raise MPIError(
                    f"rendezvous timed out waiting for the world address map "
                    f"(rank {rank}; are all {size} ranks up?)") from None
            if not chunk:
                raise MPIError("coordinator closed during rendezvous")
            buf += chunk
    addrs = json.loads(buf.decode())
    if isinstance(addrs, dict) and "error" in addrs:
        raise MPIError(f"rendezvous failed: {addrs['error']}")
    transport.set_peers(addrs)
    my_host = addrs[rank].rsplit(":", 1)[0]
    same_host = [a.rsplit(":", 1)[0] == my_host for a in addrs]
    # Scheduler-launched jobs have no tpurun parent to sweep crashed ranks'
    # shm segments; reclaim any whose creating process is gone.
    sweep_segments(shm_job_tag(), only_dead_creators=True)
    ctx = ProcContext(rank, size, transport, same_host=same_host, addrs=addrs)
    set_env((ctx, rank))
    # one rank per process: let every thread of it call MPI without the
    # thread-tier's explicit set_env attachment (THREAD_MULTIPLE semantics)
    set_process_env((ctx, rank))
    # Deterministic teardown: stop the drainer + native progress thread at
    # interpreter exit rather than relying on GC-order __del__.
    import atexit
    atexit.register(ctx.shutdown)
    return ctx, rank


# ---------------------------------------------------------------------------
# rendezvous: coordinator (launcher) side
# ---------------------------------------------------------------------------

class Coordinator:
    """Address-map rendezvous server run by the launcher process.

    ``host`` is the bind interface; ``advertise`` is the address children
    dial AND the host loopback-connected children are paired with in the
    world map. For multi-host jobs bind "0.0.0.0" and advertise a routable
    name (config ``coordinator_bind`` / ``coordinator_advertise``)."""

    def __init__(self, nprocs: int, host: str = "127.0.0.1",
                 port: int = 0, advertise: Optional[str] = None,
                 rank_base: int = 0,
                 base_addrs: Optional[list[str]] = None):
        # rank_base/base_addrs: spawn rendezvous (MPI_Comm_spawn) — the
        # ``nprocs`` registrants carry absolute world ranks
        # [rank_base, rank_base+nprocs) and every side receives the FULL
        # world map (existing ranks' addresses + the new ones).
        self.nprocs = nprocs
        self.rank_base = rank_base
        self.base_addrs = list(base_addrs or [])
        self._map: Optional[list[str]] = None
        self._map_ready = threading.Event()
        self.host = host
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port))
        self.sock.listen(nprocs + 4)
        self.port = self.sock.getsockname()[1]
        if advertise:
            self.advertise_host = advertise
        elif host in ("0.0.0.0", "::", ""):
            self.advertise_host = socket.gethostname()
        else:
            self.advertise_host = host
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        return f"{self.advertise_host}:{self.port}"

    def _serve(self) -> None:
        conns: dict[int, socket.socket] = {}     # rank -> connection
        addrs: dict[int, str] = {}               # rank -> "host:port"
        try:
            while len(conns) < self.nprocs:
                c, peer = self.sock.accept()
                buf = b""
                while not buf.endswith(b"\n"):
                    chunk = c.recv(65536)
                    if not chunk:
                        break
                    buf += chunk
                try:
                    info = json.loads(buf.decode())
                    rank = int(info["rank"])
                    port = int(info["port"])
                except Exception:
                    c.close()                    # garbled registration
                    continue
                rank -= self.rank_base
                if rank in conns or not (0 <= rank < self.nprocs):
                    # Duplicate or out-of-range rank: reject THIS registrant
                    # with a diagnostic instead of overwriting a sibling's
                    # slot and later dying on a missing rank (ADVICE r1).
                    try:
                        c.sendall((json.dumps(
                            {"error": f"rendezvous rejected rank {rank}: "
                                      + ("already registered" if rank in conns
                                         else "out of range")}) + "\n").encode())
                    except Exception:
                        pass
                    c.close()
                    continue
                # A child on another host reports its transport port; pair it
                # with the address it connected from (loopback children report
                # the coordinator-visible host).
                chost = (peer[0] if peer[0] not in ("127.0.0.1", "::1")
                         else self.advertise_host)
                addrs[rank] = f"{chost}:{port}"
                conns[rank] = c
            world = self.base_addrs + [addrs[r] for r in range(self.nprocs)]
            payload = (json.dumps(world) + "\n").encode()
            self._map = world
            self._map_ready.set()
            for c in conns.values():
                try:
                    c.sendall(payload)
                finally:
                    c.close()
        except Exception as e:
            # Serve-side failure: tell every connected child so it fails fast
            # instead of blocking out the full rendezvous timeout.
            err = (json.dumps({"error": f"coordinator failed: {e}"}) + "\n").encode()
            for c in conns.values():
                try:
                    c.sendall(err)
                except Exception:
                    pass
                c.close()

    def wait_map(self, timeout: float) -> list[str]:
        """Block until every expected registrant arrived; the full world
        address table (spawn rendezvous: the spawner needs it to grow the
        parents)."""
        if not self._map_ready.wait(timeout):
            raise MPIError(f"spawn rendezvous timed out waiting for "
                           f"{self.nprocs} children")
        assert self._map is not None
        return list(self._map)

    def close(self) -> None:
        try:
            self.sock.close()
        except Exception:
            pass
