"""Collectives over a communicator's rendezvous channel (host path).

Reference: /root/reference/src/collective.jl — Barrier (:15-19), Bcast! (:29-42)
+ serialized bcast (:44-60), Scatter(!*) (:90-129), Scatterv(!*) (:156-196),
Gather(!*) (:230-275), Allgather(!*) (:295-335), Gatherv(!*) (:363-403),
Allgatherv(!*) (:424-461), Alltoall(!*) (:489-532), Alltoallv(!*) (:545-578),
Reduce(!*) (:605-666), Allreduce(!*) (:691-738), Scan(!*) (:760-808),
Exscan(!*) (:834-882). Each exists in mutating, allocating, IN_PLACE and
scalar-object flavors; ``*v`` displacements are exclusive prefix sums.
``Reduce_scatter`` is absent in v0.14.2 — added here natively since XLA has it
(SURVEY.md §2.3 note).

API convention (Julia ``!`` does not exist in Python): one name per collective;
the *arity and argument kinds* select the flavor exactly as the reference's
method table does — ``Allreduce(send, op, comm)`` allocates,
``Allreduce(send, recv, op, comm)`` mutates, ``Allreduce(IN_PLACE, buf, op,
comm)`` is in-place; the scatter/gather family also accepts ``None`` for the
insignificant buffer like the reference accepts ``nothing``.

This is the *semantic* path, running over the thread rendezvous with zero-copy
shared-memory data placement. The compiled high-bandwidth path — the same
operations as XLA ICI collectives inside jit/shard_map — lives in
``tpu_mpi.xla`` (SURVEY.md §3.2: the whole stack collapses to one lax op).
"""

from __future__ import annotations

import functools
import pickle
import threading

from . import serialization as _serialization
from collections import OrderedDict
from typing import Any, Optional, Sequence

import numpy as np

from .buffers import (IN_PLACE, DeviceBuffer, _InPlace, assert_minlength,
                      clone_like, element_count, extract_array, is_jax_array,
                      to_wire, wire_view, write_flat)
from .comm import Comm, Intercomm, ROOT
from ._runtime import PROC_NULL
from . import error as _ec
from . import perfvars as _pv
from . import tune_online as _tune_online
from .analyze import events as _ev
from .error import CollectiveMismatchError, MPIError
from .operators import Op, as_op
from .overlap import (ChunkSchedule, CollectivePlan, PersistentCollRequest,
                      PlanRegistration, demote_fast_armed as _demote_fast_armed,
                      plans as _plans, progress_begin, progress_note,
                      registry as _registry)


def _run(comm: Comm, contrib: Any, combine, opname: str, plan=None,
         _sig=None) -> Any:
    # _ordered_run (defined with the nonblocking machinery below) keeps a
    # blocking collective from racing this rank's in-flight nonblocking
    # ones to the rendezvous: with outstanding work it runs through the
    # same single worker, preserving program order.
    # ``_sig`` is the trace verifier's precise cross-rank-checkable
    # signature (root/dtype/count) when the caller knows one.
    traced = _ev.enabled()
    # pvar op scope: channels drop phase spans into it; op_end stamps the
    # trace event and the per-comm counters. op_begin() returns None when an
    # outer owner (e.g. _reduce_family, capturing the copy-out phase too)
    # already opened one — then the owner finalizes, not us.
    sc = _pv.op_begin() if (traced or _pv.enabled()) else None
    try:
        if not traced:
            return _ordered_run(comm, lambda: comm.channel().run(
                comm.rank(), contrib, combine, opname, plan=plan))
        ev = _ev.record_collective(comm, opname, sig=_sig)
        if sc is not None:
            sc.ev = ev
        elif traced:
            outer = _pv.scope()
            if outer is not None and outer.ev is None:
                outer.ev = ev
        from ._runtime import require_env
        ctx, _ = require_env()
        bev = _ev.blocked_event(comm, "coll", opname)
        _ev.set_blocked(ctx, bev)
        try:
            return _ordered_run(comm, lambda: comm.channel().run(
                comm.rank(), contrib, combine, opname, plan=plan))
        finally:
            _ev.clear_blocked(ctx, bev)
    finally:
        if sc is not None:
            sig = _sig or {}
            # plan opnames carry the cid ("Allreduce@0") — strip for the key
            _pv.op_end(sc, comm, coll=opname.split("@", 1)[0].lower(),
                       algo=sig.get("algo"),
                       dtype=(str(sig["dtype"]) if sig.get("dtype") is not None
                              else None),
                       nbytes=_pv.payload_nbytes(contrib))


def _run_rooted(comm: Comm, root: int, contrib: Any, combine, opname: str,
                plan=None, _sig=None) -> Any:
    """Rendezvous for rooted collectives: every rank ships its claimed root
    inside its contribution, and divergent roots raise CollectiveMismatchError
    on all ranks instead of silently electing whoever arrives first (the
    Scatterv root-shipped-counts pattern, applied to the whole rooted family).
    ``combine(contribs, root)`` sees the validated root."""
    size = comm.size()
    if not isinstance(root, (int, np.integer)) or not (0 <= root < size):
        raise MPIError(f"invalid root {root!r} for a size-{size} communicator",
                       code=_ec.ERR_ROOT)
    root = int(root)

    def outer(cs):
        roots = sorted({r for r, _ in cs})
        if len(roots) > 1:
            raise CollectiveMismatchError(
                f"ranks disagree on the root of {opname}: {roots}")
        return combine([c for _, c in cs], roots[0])

    sig = dict(_sig or {})
    sig.setdefault("root", root)
    return _run(comm, (root, contrib), outer, opname, plan=plan, _sig=sig)


# Algorithm selections resolved this config generation, keyed on the full
# decision signature — one tune.select() (config read + table stat + table
# walk) per distinct collective shape instead of per call. Plans cache their
# selection too; this layer covers the plan-less collectives (Barrier,
# Bcast, the gather/scatter family).
_select_cache: "OrderedDict[Any, str]" = OrderedDict()
_SELECT_CAP = 512


def _coll_select(comm: Comm, coll: str, nbytes: Optional[int], *,
                 commutative: bool = False, elementwise: bool = False,
                 numeric: bool = True) -> str:
    """The collective-algorithm decision for one signature: ``tune.select``
    (force-override → measured tuning table → built-in heuristic) with this
    communicator's topology filled in (same-host shm eligibility from the
    rendezvous address table). The selection rides the plan to the
    multi-process tier and into the event IR (``sig["algo"]``); the thread
    tier shares one address space and always runs its in-process star, so
    there the recorded selection documents what the proc tier would do."""
    from . import backend as _backend
    from . import config as _config
    from . import topology as _topo
    from . import tune
    ctx = getattr(comm, "ctx", None)
    shm = False
    chk = getattr(ctx, "coll_shm_ok", None)
    if chk is not None:
        shm = bool(chk(comm.group))
    # hierarchy-usable domain count: rank-uniform (a function of the
    # member list, config.domains and the replicated address table), so
    # every rank of the communicator selects the same tier
    dom = _topo.domain_count(ctx, comm.group)
    # _RING_MIN_BYTES is a live module knob (tests move it mid-run to force
    # or suppress the bulk tiers) — key on it so the memo can't pin a
    # selection across a threshold change
    key = (comm.cid, coll, nbytes, commutative, elementwise, numeric, shm,
           dom, _config.GENERATION, _backend._RING_MIN_BYTES)
    algo = _select_cache.get(key)
    if algo is None:
        algo = tune.select(coll, comm.size(), nbytes, commutative=commutative,
                           elementwise=elementwise, shm=shm, numeric=numeric,
                           domains=dom)
        _select_cache[key] = algo
        while len(_select_cache) > _SELECT_CAP:
            _select_cache.popitem(last=False)
    return algo


def _maybe_explore(comm: Comm, coll: str, nbytes: Optional[int], algo: str, *,
                   commutative: bool = False, elementwise: bool = False,
                   numeric: bool = True) -> str:
    """Online-autotuner hook at the decision point (docs/performance.md
    "Online tuning"): with exploration off — the default — this costs one
    generation-cached tuple compare; with it on, the bandit may reroute
    this call to an eligible alternate arm on its deterministic lockstep
    schedule. Called exactly once per user-facing collective call (never
    from plan build or registration), so the shared counters advance
    identically on every rank."""
    st = _tune_online.state()
    if st is None:
        return algo
    from . import topology as _topo
    ctx = getattr(comm, "ctx", None)
    chk = getattr(ctx, "coll_shm_ok", None)
    shm = bool(chk(comm.group)) if chk is not None else False
    dom = _topo.domain_count(ctx, comm.group)
    return st.decide(comm, coll, nbytes, algo, commutative=commutative,
                     elementwise=elementwise, numeric=numeric, shm=shm,
                     domains=dom)


def _wire_nbytes(payload: Any) -> Optional[int]:
    """Payload size for the algorithm decision: bytes when the wire payload
    is a fixed-dtype array, None (size unknown / object payload) otherwise.
    Must be rank-uniform — callers only pass buffers whose count and dtype
    the MPI contract replicates."""
    dt = getattr(payload, "dtype", None)
    if dt is None or dt == object:
        return None
    return int(getattr(payload, "nbytes", 0))


_NOT_JITTABLE = object()

# Compiled-fold caches, keyed by the *underlying fn* so that as_op() wrapping
# the same user function in a fresh Op each call still hits. Bounded LRU:
# compiled executables are retained for at most _FOLD_CAP distinct
# (fn, mode, nranks, dtype, shapes) signatures. A signature is only compiled
# on its SECOND encounter (_fold_seen), so a one-shot lambda never pays the
# trace+compile cost — it runs the eager fold like before.
_FOLD_CAP = 64
_fold_compiled: "OrderedDict[Any, Any]" = OrderedDict()
_fold_seen: "OrderedDict[Any, None]" = OrderedDict()
_fold_lock = threading.Lock()


def _jitted_fold(arrs: Sequence[Any], op: Op, mode: str):
    """One-dispatch combine for device arrays: the whole rank-ordered fold is
    compiled into a single XLA computation (fused: one pass over the operands
    instead of n-1 round trips through HBM — the hot loop the reference gets
    from libmpi's tuned ring, src/collective.jl:691-738). Sequential left
    fold, so results are bit-identical to the eager rank-order reduction.

    Returns the combined array ("reduce"), the tuple of inclusive prefixes
    ("scan"), or _NOT_JITTABLE when the op can't trace (host-only custom fn)
    or the signature isn't worth compiling yet."""
    n = len(arrs)
    if n <= 1 or not all(is_jax_array(a) for a in arrs):
        return _NOT_JITTABLE
    try:
        key = (op.fn, mode, n, str(arrs[0].dtype), tuple(a.shape for a in arrs))
        hash(key)
    except TypeError:
        return _NOT_JITTABLE
    with _fold_lock:
        hit = _fold_compiled.get(key)
        if hit is None:
            if key not in _fold_seen:
                _fold_seen[key] = None
                while len(_fold_seen) > 4 * _FOLD_CAP:
                    _fold_seen.popitem(last=False)
                return _NOT_JITTABLE
    if hit is _NOT_JITTABLE:
        return _NOT_JITTABLE
    if hit is not None:
        return hit(*arrs)

    import jax

    if mode == "reduce":
        def fold(*xs):
            acc = xs[0]
            for x in xs[1:]:
                acc = op.fn(acc, x)
            return acc
        # the Pallas single-pass kernel first (same left fold, explicit
        # HBM schedule), the chained XLA fold as the compile fallback
        candidates = [c for c in (_fused_reduce_candidate(op, arrs), fold)
                      if c is not None]
    else:  # scan: all inclusive prefixes
        def fold(*xs):
            outs = [xs[0]]
            for x in xs[1:]:
                outs.append(op.fn(outs[-1], x))
            return tuple(outs)
        candidates = [fold]
    jitted = out = _NOT_JITTABLE
    for cand in candidates:
        try:
            j = jax.jit(cand)
            out = j(*arrs)  # traces now; host-only ops raise here
            jitted = j
            break
        except Exception:
            jitted, out = _NOT_JITTABLE, _NOT_JITTABLE
    with _fold_lock:
        _fold_compiled[key] = jitted
        while len(_fold_compiled) > _FOLD_CAP:
            _fold_compiled.popitem(last=False)
    return out


def _fused_reduce_candidate(op: Op, arrs: Sequence[Any]):
    """The Pallas fused multi-operand fold as a jit candidate for
    mode="reduce" (the ISSUE-1 tentpole): one traversal reads all nranks
    HBM streams and writes one output, replacing the chained elementwise
    fold when the ``fused_fold`` config gate allows it. Returns None when
    gated off or the operands don't fit the kernel's contract; any trace
    failure falls back to the chained fold in the caller."""
    from . import config
    mode = config.load().fused_fold
    if mode == "off":
        return None
    if len({(a.shape, str(a.dtype)) for a in arrs}) != 1:
        return None                 # kernel folds same-shape streams only
    import jax
    if mode != "interp" and jax.default_backend() != "tpu":
        return None                 # interpret machine is test-only slow

    from .xla import pallas_kernels as pk

    def fused(*xs):
        return pk.fused_multi_reduce(xs, op)
    return fused


def _reduce_arrays(arrs: Sequence[Any], op: Op,
                   schedule: Optional[ChunkSchedule] = None) -> Any:
    """Rank-ordered elementwise reduction (deterministic; MPI rank order).
    With a chunk ``schedule`` (overlap engine), host folds run chunk-by-chunk
    — cache-resident working set, progress notes per chunk, and on the
    multi-process tier the per-chunk structure is what lets the star root
    fold chunk k while the drainer still receives chunk k+1."""
    out = _jitted_fold(arrs, op, "reduce")
    if out is not _NOT_JITTABLE:
        return out
    if schedule is not None and len(arrs) > 1:
        out = _chunked_fold(arrs, op, schedule)
        if out is not None:
            return out
    return functools.reduce(op, arrs)


def _chunked_fold(arrs: Sequence[Any], op: Op,
                  schedule: ChunkSchedule) -> Optional[Any]:
    """Chunk-pipelined host fold. Elementwise rank-order folds are
    chunk-separable, so this is BITWISE-IDENTICAL to the monolithic
    ``functools.reduce``: ufunc-backed ops (SUM/PROD/MIN/MAX/B*) fold each
    chunk in place into one preallocated output (zero temporaries — the
    monolithic fold allocates n-1 full-size intermediates); other
    elementwise ops fold per-chunk and concatenate, preserving the exact
    dtype-promotion behavior. Returns None when the operands don't fit
    (non-numpy, object dtype, ragged sizes) and the caller's monolithic
    fold applies."""
    from .operators import is_elementwise
    if not is_elementwise(op):
        return None     # unknown custom fn might couple elements: monolithic
    first = arrs[0]
    if any(not isinstance(a, np.ndarray) or a.dtype == object for a in arrs):
        return None
    if any(a.size != schedule.count for a in arrs):
        return None
    flats = [a.reshape(-1) for a in arrs]
    prog = progress_begin(schedule.nchunks, "fold")
    if op.ufunc is not None and all(a.dtype == first.dtype for a in arrs):
        out = np.empty(schedule.count, dtype=first.dtype)
        for lo, hi in schedule:
            np.copyto(out[lo:hi], flats[0][lo:hi])
            for a in flats[1:]:
                op.ufunc(out[lo:hi], a[lo:hi], out=out[lo:hi])
            progress_note(prog)
        return out
    parts = []
    for lo, hi in schedule:
        parts.append(functools.reduce(op, [a[lo:hi] for a in flats]))
        progress_note(prog)
    return np.concatenate(parts)


def _scan_arrays(cs: Sequence[Any], op: Op) -> list:
    """Inclusive prefixes in rank order (same fold, all partials kept)."""
    pre = _jitted_fold(cs, op, "scan")
    if pre is not _NOT_JITTABLE:
        return list(pre)
    outs: list = []
    acc = None
    for c in cs:
        acc = c if acc is None else op(acc, c)
        outs.append(acc)
    return outs


def _is_none(x: Any) -> bool:
    return x is None or isinstance(x, _InPlace)


# ---------------------------------------------------------------------------
# Intercommunicator collectives (MPI_ROOT semantics; VERDICT r3 #8).
# The reference reaches these through libmpi, which honors collectives on the
# intercomms Comm_spawn creates (/root/reference/src/comm.jl:135-162). Here
# they run over the intercomm's two-group rendezvous: in the ROOT GROUP the
# sourcing rank passes MPI.ROOT and the rest pass MPI.PROC_NULL; the RECEIVING
# group passes the root's rank within the remote group.
# ---------------------------------------------------------------------------

def _inter_rooted(comm: Intercomm, root: Any, payload: Any, opname: str):
    """Two-group rooted rendezvous. Returns (got_value, value): got_value is
    True only for receiving-group ranks."""
    chan, slot, a, b = comm.two_group_channel()
    in_a = slot < len(a)
    if root == ROOT:
        contrib = ("root", payload, in_a)
    elif root == PROC_NULL:
        contrib = ("null", None, in_a)
    else:
        r = int(root)
        if not (0 <= r < comm.remote_size()):
            raise MPIError(f"invalid intercomm root {root!r}: pass MPI.ROOT "
                           f"(source), MPI.PROC_NULL (non-source, root group) "
                           f"or a remote-group rank < {comm.remote_size()}",
                           code=_ec.ERR_ROOT)
        contrib = ("recv", r, in_a)

    def combine(cs):
        roots = [i for i, c in enumerate(cs) if c[0] == "root"]
        if len(roots) != 1:
            raise CollectiveMismatchError(
                f"{opname}: exactly one rank must pass MPI.ROOT, got "
                f"{len(roots)}")
        ri = roots[0]
        root_in_a = cs[ri][2]
        root_idx = ri if root_in_a else ri - len(a)
        out = []
        for i, (role, val, ia) in enumerate(cs):
            if role == "root":
                out.append((False, None))
            elif role == "null":
                if ia != root_in_a:
                    raise CollectiveMismatchError(
                        f"{opname}: rank in the receiving group passed "
                        f"MPI.PROC_NULL; receivers must pass the root's "
                        f"remote-group rank")
                out.append((False, None))
            else:
                if ia == root_in_a:
                    raise CollectiveMismatchError(
                        f"{opname}: rank in the root group passed a root rank "
                        f"({val}); non-source root-group ranks pass "
                        f"MPI.PROC_NULL")
                if val != root_idx:
                    raise CollectiveMismatchError(
                        f"{opname}: receiving group names root {val} but the "
                        f"source is remote-group rank {root_idx}")
                out.append((True, cs[ri][1]))
        return out

    return _ordered_run(comm, lambda: chan.run(slot, contrib, combine, opname))


def _inter_barrier(comm: Intercomm) -> None:
    chan, slot, a, b = comm.two_group_channel()
    _ordered_run(comm, lambda: chan.run(
        slot, None, lambda cs: [None] * len(cs), f"IBarrier@{comm.cid}"))


def _inter_bcast_buf(buf: Any, count: Optional[int], root: Any,
                     comm: Intercomm) -> Any:
    opname = f"InterBcast@{comm.cid}"
    if root == ROOT:
        n = element_count(buf) if count is None else count
        assert_minlength(buf, n)
        _inter_rooted(comm, root, (to_wire(buf, n), n), opname)
        return buf
    got, res = _inter_rooted(comm, root, None, opname)
    if got:
        val, n_src = res
        n = n_src if count is None else count
        assert_minlength(buf, n)
        write_flat(buf, val, n)
    return buf


def _inter_bcast_obj(obj: Any, root: Any, comm: Intercomm) -> Any:
    opname = f"interbcast@{comm.cid}"
    if root == ROOT:
        try:
            payload = ("pickle", _serialization.dumps(obj))
        except Exception:
            payload = ("ref", obj)
        _inter_rooted(comm, root, payload, opname)
        return obj
    got, res = _inter_rooted(comm, root, None, opname)
    if not got:
        return obj        # PROC_NULL participant: argument untouched
    kind, data = res
    return pickle.loads(data) if kind == "pickle" else data


# ---------------------------------------------------------------------------
# Barrier
# ---------------------------------------------------------------------------

def Barrier(comm: Comm) -> None:
    """Block until every rank of comm arrives (src/collective.jl:15-19).
    On an intercommunicator: until every rank of BOTH groups arrives."""
    if isinstance(comm, Intercomm):
        return _inter_barrier(comm)
    algo = _maybe_explore(comm, "barrier", None,
                          _coll_select(comm, "barrier", None))
    _run(comm, None, lambda cs: [None] * len(cs), f"Barrier@{comm.cid}",
         plan=("barrier", algo), _sig={"algo": algo})


# ---------------------------------------------------------------------------
# Bcast / bcast
# ---------------------------------------------------------------------------

def Bcast(buf: Any, *args) -> Any:
    """``Bcast(buf, [count,] root, comm)`` — broadcast root's buffer into every
    rank's buffer, mutating (src/collective.jl:29-42). Returns buf."""
    if len(args) == 2:
        count, (root, comm) = None, args
    elif len(args) == 3:
        count, root, comm = args
    else:
        raise TypeError("Bcast(buf, [count,] root, comm)")
    if isinstance(comm, Intercomm):
        return _inter_bcast_buf(buf, count, root, comm)
    rank = comm.rank()
    n = element_count(buf) if count is None else count
    assert_minlength(buf, n)
    payload = to_wire(buf, n) if rank == root else None

    def combine(cs, rt):
        val = cs[rt]
        return [val] * len(cs)

    dt = getattr(extract_array(buf), "dtype", None)
    nbytes = int(n) * dt.itemsize if dt is not None and dt != object else None
    algo = _maybe_explore(
        comm, "bcast", nbytes,
        _coll_select(comm, "bcast", nbytes, numeric=nbytes is not None),
        numeric=nbytes is not None)
    val = _run_rooted(comm, root, payload, combine, f"Bcast@{comm.cid}",
                      plan=("bcast", root, algo),
                      _sig={"count": int(n), "dtype": str(dt), "algo": algo})
    if rank != root:
        write_flat(buf, val, n)
    return buf


def bcast(obj: Any, root: int, comm: Comm) -> Any:
    """Broadcast an arbitrary serialized object (src/collective.jl:44-60).

    The reference's two-phase length+payload dance collapses: the rendezvous
    carries dynamic sizes natively. Serialization round-trips give each rank
    its own copy; closures/lambdas/local classes travel by value on every
    tier via :mod:`tpu_mpi.serialization` (ref broadcasts a *function*,
    test/test_bcast.jl:38-55). Truly unserializable objects (sockets,
    locks) fall back to by-reference sharing, thread tier only."""
    if isinstance(comm, Intercomm):
        return _inter_bcast_obj(obj, root, comm)
    rank = comm.rank()
    if rank == root:
        try:
            payload = ("pickle", _serialization.dumps(obj))
        except Exception:
            payload = ("ref", obj)
    else:
        payload = None

    def combine(cs, rt):
        val = cs[rt]
        return [val] * len(cs)

    algo = _maybe_explore(comm, "bcast", None,
                          _coll_select(comm, "bcast", None, numeric=False),
                          numeric=False)
    kind, data = _run_rooted(comm, root, payload, combine, f"bcast@{comm.cid}",
                             plan=("bcast", root, algo), _sig={"algo": algo})
    if rank == root:
        return obj
    return pickle.loads(data) if kind == "pickle" else data


# ---------------------------------------------------------------------------
# Scatter / Scatterv
# ---------------------------------------------------------------------------

def Scatter(*args) -> Any:
    """``Scatter(send, recv, [count,] root, comm)`` mutating |
    ``Scatter(send, count, root, comm)`` allocating (src/collective.jl:90-129).
    Root's send buffer is split into comm-size equal chunks in rank order;
    ``None``/IN_PLACE marks the insignificant buffer."""
    if len(args) == 5:
        sendbuf, recvbuf, count, root, comm = args
        alloc = False
    elif len(args) == 4 and isinstance(args[1], (int, np.integer)):
        sendbuf, count, root, comm = args
        recvbuf, alloc = None, True
    elif len(args) == 4:
        sendbuf, recvbuf, root, comm = args
        count, alloc = None, False
    else:
        raise TypeError("Scatter(send, recv, [count,] root, comm) or Scatter(send, count, root, comm)")
    rank, size = comm.rank(), comm.size()
    isroot = rank == root
    if count is None and not alloc:
        count = element_count(recvbuf) if not _is_none(recvbuf) else element_count(sendbuf) // size
    if isroot:
        if _is_none(sendbuf):
            raise MPIError("root must supply a send buffer to Scatter")
        assert_minlength(sendbuf, count * size)
    if not alloc and not (isroot and _is_none(recvbuf)):
        assert_minlength(recvbuf, count)   # before the rendezvous (see Gather)
    payload = to_wire(sendbuf, count * size) if isroot else None

    def combine(cs, rt):
        data = cs[rt]
        return [data[r * count:(r + 1) * count] for r in range(len(cs))]

    # The decision size must be rank-uniform: in the allocating flavor only
    # the root holds a buffer, so size-blind selection (None) keeps every
    # rank on the same algorithm.
    if alloc:
        nbytes = None
    else:
        dt = getattr(extract_array(sendbuf if isroot else recvbuf),
                     "dtype", None)
        nbytes = (count * size * dt.itemsize
                  if dt is not None and dt != object else None)
    algo = _maybe_explore(comm, "scatter", nbytes,
                          _coll_select(comm, "scatter", nbytes))
    chunk = _run_rooted(comm, root, payload, combine, f"Scatter@{comm.cid}",
                        plan=("scatter", algo), _sig={"algo": algo})
    if alloc:
        template = sendbuf if isroot else None
        return clone_like(template, chunk) if template is not None else np.array(chunk)
    if isroot and _is_none(recvbuf):
        return sendbuf          # IN_PLACE at root: data already in place
    write_flat(recvbuf, chunk, count)
    return recvbuf


def Scatterv(*args) -> Any:
    """``Scatterv(send, recv, counts, root, comm)`` mutating |
    ``Scatterv(send, counts, root, comm)`` allocating (src/collective.jl:156-196).
    Displacements are the exclusive prefix sum of counts (:169)."""
    if len(args) == 5:
        sendbuf, recvbuf, counts, root, comm = args
        alloc = False
    elif len(args) == 4:
        sendbuf, counts, root, comm = args
        recvbuf, alloc = None, True
    else:
        raise TypeError("Scatterv(send, [recv,] counts, root, comm)")
    rank, size = comm.rank(), comm.size()
    isroot = rank == root
    counts = [int(c) for c in counts]
    if isroot:
        if _is_none(sendbuf):
            raise MPIError("root must supply a send buffer to Scatterv")
        assert_minlength(sendbuf, sum(counts))
    # counts are significant only at the root (MPI semantics): ship them in
    # the root's contribution so a divergent non-root list cannot influence
    # the slicing depending on rendezvous arrival order.
    payload = (to_wire(sendbuf, sum(counts)), counts) if isroot else None

    def combine(cs, rt):
        data, root_counts = cs[rt]
        displs = np.concatenate([[0], np.cumsum(root_counts)])
        return [data[displs[r]:displs[r] + root_counts[r]] for r in range(len(cs))]

    chunk = _run_rooted(comm, root, payload, combine, f"Scatterv@{comm.cid}")
    if alloc:
        template = sendbuf if isroot else None
        return clone_like(template, chunk) if template is not None else np.array(chunk)
    if isroot and _is_none(recvbuf):
        return sendbuf
    n = int(np.asarray(chunk).size)
    assert_minlength(recvbuf, n)
    write_flat(recvbuf, chunk, n)
    return recvbuf


# ---------------------------------------------------------------------------
# Gather / Gatherv / Allgather / Allgatherv
# ---------------------------------------------------------------------------

def Gather(*args) -> Any:
    """``Gather(send, recv, [count,] root, comm)`` mutating |
    ``Gather(send, [count,] root, comm)`` allocating — works for arrays and
    scalar objects (src/collective.jl:230-275)."""
    if len(args) == 5:
        sendbuf, recvbuf, count, root, comm = args
        alloc = False
    elif len(args) == 4 and isinstance(args[1], (int, np.integer)):
        sendbuf, count, root, comm = args
        recvbuf, alloc = None, True
    elif len(args) == 4:
        sendbuf, recvbuf, root, comm = args
        count, alloc = None, False
    elif len(args) == 3:
        sendbuf, root, comm = args
        recvbuf, count, alloc = None, None, True
    else:
        raise TypeError("Gather(send, [recv,] [count,] root, comm)")
    return _gather_impl(sendbuf, recvbuf, count, root, comm, alloc, all_ranks=False)


def Allgather(*args) -> Any:
    """``Allgather(send, recv, count, comm)`` | ``Allgather(IN_PLACE, buf,
    count, comm)`` | ``Allgather(send, [count,] comm)`` allocating
    (src/collective.jl:295-335). Every rank receives the concatenation."""
    if len(args) == 4:
        sendbuf, recvbuf, count, comm = args
        alloc = False
    elif len(args) == 3 and isinstance(args[1], (int, np.integer)):
        sendbuf, count, comm = args
        recvbuf, alloc = None, True
    elif len(args) == 2:
        sendbuf, comm = args
        recvbuf, count, alloc = None, None, True
    else:
        raise TypeError("Allgather(send, [recv,] [count,] comm)")
    return _gather_impl(sendbuf, recvbuf, count, None, comm, alloc, all_ranks=True)


def _gather_impl(sendbuf, recvbuf, count, root, comm, alloc, all_ranks):
    rank, size = comm.rank(), comm.size()
    isroot = all_ranks or rank == root
    inplace = isinstance(sendbuf, _InPlace) or sendbuf is None
    if inplace:
        # IN_PLACE: rank's own chunk already sits at recvbuf[rank*count:...]
        # (src/collective.jl:309-313 in-place Allgather!).
        if _is_none(recvbuf):
            raise MPIError("IN_PLACE gather needs the send-recv buffer")
        if count is None:
            count = element_count(recvbuf) // size
        arr = to_wire(recvbuf, element_count(recvbuf))
        payload = arr.reshape(-1)[rank * count:(rank + 1) * count]
    else:
        if count is None:
            count = element_count(sendbuf)
        assert_minlength(sendbuf, count)
        payload = to_wire(sendbuf, count)
    # Bounds-check the significant recv buffer *before* the rendezvous, like
    # the reference checks before the ccall (src/collective.jl:230-275) — a
    # failing rank must not have half-entered the collective.
    if not alloc and isroot and not _is_none(recvbuf):
        assert_minlength(recvbuf, count * size)

    def combine(cs, rt=None):
        xp = np
        try:
            if any(type(c).__module__.startswith("jax") for c in cs):
                import jax.numpy as xp  # type: ignore
        except Exception:
            pass
        full = xp.concatenate([xp.asarray(c).reshape(-1) for c in cs])
        if rt is None:                  # Allgather: everyone needs it
            return [full] * len(cs)
        # rooted Gather: only root receives the concatenation — on the
        # multi-process star this keeps egress at ~zero instead of P×payload
        # (VERDICT r2 weak #6; src/collective.jl:230-275 root-only recvbuf)
        return [full if r == rt else None for r in range(len(cs))]

    nb = _wire_nbytes(payload)
    if all_ranks:
        # multi-process tier: big uniform blocks travel a ring (one hop per
        # block per step) instead of star ingress + P x egress at the root;
        # the selection is keyed on the per-rank block size, matching the
        # ring's per-hop cost
        algo = _maybe_explore(
            comm, "allgather", nb,
            _coll_select(comm, "allgather", nb, numeric=nb is not None),
            numeric=nb is not None)
        full = _run(comm, payload, combine, f"Allgather@{comm.cid}",
                    plan=("allgather", algo), _sig={"algo": algo})
    else:
        gnb = nb * size if nb is not None else None
        algo = _maybe_explore(comm, "gather", gnb,
                              _coll_select(comm, "gather", gnb))
        full = _run_rooted(comm, root, payload, combine, f"Gather@{comm.cid}",
                           plan=("gather", algo), _sig={"algo": algo})
    if not isroot:
        return None if alloc else recvbuf
    if alloc:
        template = sendbuf if not inplace else recvbuf
        return clone_like(template, full)
    write_flat(recvbuf, full, count * size)
    return recvbuf


def Gatherv(*args) -> Any:
    """``Gatherv(send, recv, counts, root, comm)`` mutating |
    ``Gatherv(send, counts, root, comm)`` allocating (src/collective.jl:363-403)."""
    if len(args) == 5:
        sendbuf, recvbuf, counts, root, comm = args
        alloc = False
    elif len(args) == 4:
        sendbuf, counts, root, comm = args
        recvbuf, alloc = None, True
    else:
        raise TypeError("Gatherv(send, [recv,] counts, root, comm)")
    return _gatherv_impl(sendbuf, recvbuf, counts, root, comm, alloc, all_ranks=False)


def Allgatherv(*args) -> Any:
    """``Allgatherv(send, recv, counts, comm)`` | ``Allgatherv(IN_PLACE, buf,
    counts, comm)`` | allocating ``Allgatherv(send, counts, comm)``
    (src/collective.jl:424-461)."""
    if len(args) == 4:
        sendbuf, recvbuf, counts, comm = args
        alloc = False
    elif len(args) == 3:
        sendbuf, counts, comm = args
        recvbuf, alloc = None, True
    else:
        raise TypeError("Allgatherv(send, [recv,] counts, comm)")
    return _gatherv_impl(sendbuf, recvbuf, counts, None, comm, alloc, all_ranks=True)


def _gatherv_impl(sendbuf, recvbuf, counts, root, comm, alloc, all_ranks):
    rank, size = comm.rank(), comm.size()
    isroot = all_ranks or rank == root
    counts = [int(c) for c in counts]
    displs = np.concatenate([[0], np.cumsum(counts)])  # exclusive prefix (:365,:425)
    inplace = isinstance(sendbuf, _InPlace) or sendbuf is None
    if inplace:
        if _is_none(recvbuf):
            raise MPIError("IN_PLACE gatherv needs the send-recv buffer")
        arr = to_wire(recvbuf, element_count(recvbuf))
        payload = arr.reshape(-1)[displs[rank]:displs[rank] + counts[rank]]
    else:
        assert_minlength(sendbuf, counts[rank])
        payload = to_wire(sendbuf, counts[rank])
    if not alloc and isroot and not _is_none(recvbuf):
        assert_minlength(recvbuf, sum(counts))   # before the rendezvous

    def combine(cs, rt=None):
        xp = np
        if any(type(c).__module__.startswith("jax") for c in cs):
            import jax.numpy as xp  # type: ignore
        full = xp.concatenate([xp.asarray(c).reshape(-1) for c in cs])
        if rt is None:                  # Allgatherv: everyone needs it
            return [full] * len(cs)
        # rooted Gatherv: root-only result (VERDICT r2 weak #6)
        return [full if r == rt else None for r in range(len(cs))]

    if all_ranks:
        # ragged ring tier (multi-process): the counts list is replicated by
        # the API contract, so a size gate on the TOTAL is deterministic
        # across ranks even though per-rank blocks differ
        total_bytes = int(sum(counts)) * getattr(
            getattr(payload, "dtype", None), "itemsize", 0)
        dt = getattr(payload, "dtype", None)
        numeric = dt is not None and dt != object
        gnb = total_bytes if numeric else None
        algo = _maybe_explore(comm, "allgatherv", gnb,
                              _coll_select(comm, "allgatherv", gnb,
                                           numeric=numeric),
                              numeric=numeric)
        full = _run(comm, payload, combine, f"Allgatherv@{comm.cid}",
                    plan=("allgatherv", total_bytes, tuple(counts), algo),
                    _sig={"algo": algo})
    else:
        full = _run_rooted(comm, root, payload, combine, f"Gatherv@{comm.cid}")
    if not isroot:
        return None if alloc else recvbuf
    if alloc:
        template = sendbuf if not inplace else recvbuf
        return clone_like(template, full)
    write_flat(recvbuf, full, sum(counts))
    return recvbuf


# ---------------------------------------------------------------------------
# Alltoall / Alltoallv
# ---------------------------------------------------------------------------

def Alltoall(*args) -> Any:
    """``Alltoall(send, recv, count, comm)`` | ``Alltoall(IN_PLACE, buf, count,
    comm)`` | allocating ``Alltoall(send, count, comm)``
    (src/collective.jl:489-532). Rank r sends its chunk j to rank j's slot r."""
    if len(args) == 4:
        sendbuf, recvbuf, count, comm = args
        alloc = False
    elif len(args) == 3:
        sendbuf, count, comm = args
        recvbuf, alloc = None, True
    else:
        raise TypeError("Alltoall(send, [recv,] count, comm)")
    rank, size = comm.rank(), comm.size()
    count = int(count)
    inplace = isinstance(sendbuf, _InPlace) or sendbuf is None
    src = recvbuf if inplace else sendbuf
    assert_minlength(src, count * size)
    if not alloc and not inplace:
        assert_minlength(recvbuf, count * size)   # before the rendezvous
    payload = to_wire(src, count * size)

    def combine(cs):
        xp = np
        if any(type(c).__module__.startswith("jax") for c in cs):
            import jax.numpy as xp  # type: ignore
        mats = [xp.asarray(c).reshape(len(cs), count) for c in cs]
        return [xp.concatenate([m[r] for m in mats]) for r in range(len(cs))]

    # multi-process tier: large exchanges go direct pairwise (each segment
    # one hop) instead of O(P²·seg) through the star root
    nb = _wire_nbytes(payload)
    algo = _maybe_explore(
        comm, "alltoall", nb,
        _coll_select(comm, "alltoall", nb, numeric=nb is not None),
        numeric=nb is not None)
    mine = _run(comm, payload, combine, f"Alltoall@{comm.cid}",
                plan=("alltoall", algo), _sig={"algo": algo})
    if alloc:
        return clone_like(src, mine)
    write_flat(recvbuf, mine, count * size)
    return recvbuf


def Alltoallv(*args) -> Any:
    """``Alltoallv(send, recv, scounts, rcounts, comm)`` mutating | allocating
    ``Alltoallv(send, scounts, rcounts, comm)`` (src/collective.jl:545-578)."""
    if len(args) == 5:
        sendbuf, recvbuf, scounts, rcounts, comm = args
        alloc = False
    elif len(args) == 4:
        sendbuf, scounts, rcounts, comm = args
        recvbuf, alloc = None, True
    else:
        raise TypeError("Alltoallv(send, [recv,] scounts, rcounts, comm)")
    rank, size = comm.rank(), comm.size()
    scounts = [int(c) for c in scounts]
    rcounts = [int(c) for c in rcounts]
    assert_minlength(sendbuf, sum(scounts))
    if not alloc:
        assert_minlength(recvbuf, sum(rcounts))   # before the rendezvous
    payload = (to_wire(sendbuf, sum(scounts)), scounts)

    def combine(cs):
        xp = np
        if any(type(c[0]).__module__.startswith("jax") for c in cs):
            import jax.numpy as xp  # type: ignore
        outs = []
        for r in range(len(cs)):
            parts = []
            for s in range(len(cs)):
                data, sc = cs[s]
                d = int(np.sum(sc[:r]))
                parts.append(xp.asarray(data).reshape(-1)[d:d + sc[r]])
            outs.append(xp.concatenate(parts) if parts else xp.zeros(0))
        return outs

    # per-rank send totals differ, so the size-blind (None) decision keeps
    # the selection rank-uniform; pairwise is gated on dtype alone
    dt = getattr(payload[0], "dtype", None)
    numeric = dt is not None and dt != object
    algo = _maybe_explore(comm, "alltoallv", None,
                          _coll_select(comm, "alltoallv", None,
                                       numeric=numeric),
                          numeric=numeric)
    # per-peer counts ride the event IR so the trace verifier can check
    # rank i's scounts[j] against rank j's rcounts[i] (T202 family)
    mine = _run(comm, payload, combine, f"Alltoallv@{comm.cid}",
                plan=("alltoallv", algo),
                _sig={"algo": algo, "scounts": list(scounts),
                      "rcounts": list(rcounts)})
    if alloc:
        return clone_like(sendbuf, mine)
    write_flat(recvbuf, mine, sum(rcounts))
    return recvbuf


# ---------------------------------------------------------------------------
# Reduce / Allreduce / Scan / Exscan / Reduce_scatter
# ---------------------------------------------------------------------------

def _parse_reduce_args(args, has_root: bool, name: str):
    """Shared arg parsing: (send, [recv, [count,]] op, [root,] comm)."""
    tail = 2 if has_root else 1
    n = len(args)
    comm = args[-1]
    root = int(args[-2]) if has_root else None
    op = args[-(tail + 1)]
    head = args[:n - tail - 1]
    if len(head) == 1:
        sendbuf, recvbuf, count = head[0], None, None
        alloc = not isinstance(sendbuf, _InPlace)
    elif len(head) == 2:
        sendbuf, recvbuf, count = head[0], head[1], None
        alloc = False
    elif len(head) == 3:
        sendbuf, recvbuf, count = head
        count = int(count)
        alloc = False
    else:
        raise TypeError(f"{name}(send, [recv, [count,]] op, "
                        + ("root, comm)" if has_root else "comm)"))
    return sendbuf, recvbuf, count, as_op(op), root, comm, alloc


def _reduce_plan(comm: Comm, name: str, mode: str, op: Op, count: int,
                 payload: Any) -> CollectivePlan:
    """The pre-resolved plan for one reduce-family signature (the overlap
    engine's persistent-plan piece): opname tag, combine closure, trace
    signature, multi-process algorithm hint and chunk schedule are built
    once per (comm, flavor, op, count, dtype, array kind) and reused by
    every later same-shape call — the training-loop case pays dict lookups
    instead of closure/format/config work per collective."""
    from . import config
    dtype = getattr(payload, "dtype", None)
    key = (comm.cid, name, mode, op, int(count), str(dtype),
           type(payload).__name__)
    plan = _plans.get(key)
    if plan is not None:
        return plan
    itemsize = getattr(dtype, "itemsize", 0)
    schedule = (ChunkSchedule.maybe(count, itemsize)
                if mode == "reduce" else None)

    def combine(cs, rt=None):
        n = len(cs)
        if mode == "reduce":
            total = _reduce_arrays(cs, op, schedule=schedule)
            if rt is None:              # Allreduce: everyone needs it
                return [total] * n
            # rooted Reduce: ship the combined payload to root only — star
            # egress drops from P×payload to ~zero (VERDICT r2 weak #6;
            # src/collective.jl:605-666 root-only recvbuf)
            return [total if r == rt else None for r in range(n)]
        if mode == "scan":
            return _scan_arrays(cs, op)
        if mode == "exscan":
            # exscan[i] = scan over ranks 0..i-1; rank 0's slot is undefined.
            return [None, *_scan_arrays(cs[:-1], op)]
        raise AssertionError(mode)

    # The multi-process tier picks its algorithm (star / shm / recursive
    # doubling / Rabenseifner / ring / binomial) from the portfolio once
    # per signature; order-sensitive modes (Scan/Exscan) stay on the
    # monolithic star. The selection is cached inside this plan and
    # invalidated with it on config reloads.
    if mode == "reduce":
        from .operators import is_elementwise
        numeric = dtype is not None and str(dtype) != "object"
        nbytes = int(count) * itemsize if numeric and itemsize else None
        coll = "reduce" if name == "Reduce" else "allreduce"
        algo = _coll_select(comm, coll, nbytes,
                            commutative=bool(op.commutative),
                            elementwise=is_elementwise(op), numeric=numeric)
        hint = (coll, op, algo)
    else:
        algo, hint = "star", None
    sig = {"count": int(count), "dtype": str(dtype), "algo": algo}
    plan = CollectivePlan(f"{name}@{comm.cid}", op, combine, sig, hint,
                          schedule, config.GENERATION, algo=algo)
    _plans.put(key, plan)
    return plan


def _explore_reduce_variant(comm: Comm, cplan: CollectivePlan, op: Op,
                            count: int, payload: Any) -> CollectivePlan:
    """Online-tuning hook for the reduce family: plan-cache hits skip
    ``_coll_select`` entirely, so with the bandit live we re-run the
    decision through :func:`_maybe_explore` per call and — only on the
    exploration slots — hand back a shallow variant of the cached plan
    with the algorithm rebound. The variant shares the combine closure and
    chunk schedule; the cached plan itself is never mutated, so steady
    traffic keeps its zero-overhead path."""
    from .operators import is_elementwise
    coll, hop, _ = cplan.hint
    dtype = getattr(payload, "dtype", None)
    itemsize = getattr(dtype, "itemsize", 0)
    numeric = dtype is not None and str(dtype) != "object"
    nbytes = int(count) * itemsize if numeric and itemsize else None
    algo = _maybe_explore(comm, coll, nbytes, cplan.algo,
                          commutative=bool(op.commutative),
                          elementwise=is_elementwise(op), numeric=numeric)
    if algo == cplan.algo:
        return cplan
    return CollectivePlan(cplan.opname, cplan.op, cplan.combine,
                          dict(cplan.sig, algo=algo), (coll, hop, algo),
                          cplan.schedule, cplan.generation, algo=algo)


def _auto_arm_gate(comm, args, sendbuf, recvbuf, op, count, payload, alloc):
    """ISSUE-11 tentpole (a): promote a repeated plain ``Allreduce``
    signature onto the registered persistent path with zero API change.

    Returns ``(runner, model)``. ``runner`` — when the signature's
    consecutive-identical-call streak has crossed
    ``config.auto_arm_threshold`` and a :class:`PlanRegistration` bound —
    executes the whole armed round (rendezvous + copy-out) and the caller
    returns its value directly; ``None`` means take the generic path.
    ``model`` is non-None only under tracing with ``auto_arm_donate`` opted
    in: traced runs always DEMOTE to the fully-evented legacy lane (bitwise
    identical by construction), but the donation window the untraced run
    would have had is modeled with synthetic Start/Wait events so the R302
    pass can still flag a stale aliased result being fed back in — the
    caller invokes ``model(out)`` with the allocating flavor's result.

    Demotion is loud-free and total: trace arming, outstanding nonblocking
    traffic, buffer-identity churn, shape/dtype churn on the lane
    (``PlanCache.auto_note``), ``Comm.free`` (``plans.invalidate``), and
    config reloads (generation check below) all push the signature back to
    the generic star. Without ``auto_arm_donate`` the armed lane runs the
    copy-out contract (``_register_allreduce(donate=False)``), so no user-
    visible aliasing exists for R302 to worry about."""
    from . import config
    from ._runtime import current_env
    cfg = config.load()
    if not (cfg.auto_arm and cfg.registered_buffers):
        return None, None
    env = current_env()
    if env is None:
        return None, None
    ctx, world_rank = env
    cid = comm.cid
    # per-rank key: the thread tier shares ONE PlanCache across rank
    # threads, and each rank's streak/arming is its own
    key = (cid, comm.rank(), "Allreduce", op, int(count),
           str(getattr(payload, "dtype", None)), type(payload).__name__)
    e = _plans.auto_note(key, sendbuf, recvbuf)
    if e is None:
        return None, None
    threshold = max(int(cfg.auto_arm_threshold), 1)

    if _ev.enabled():
        if e.armed:
            _plans.auto_demote(e)
        if not (cfg.auto_arm_donate and alloc and e.streak >= threshold):
            return None, None
        # model the donated-result ring the untraced run would alias:
        # round k's Start re-donates the slot under round k-2's result
        rnd = e.rounds
        e.rounds += 1
        inval = None
        for r, res in e.results:
            if r == rnd - 2:
                inval = _ev.buf_id(res)
        _ev.record_start(comm, "pallreduce", id(e), rnd, invalidates=inval)

        def model(out):
            e.results.append((rnd, out))
            _ev.record_wait(comm, "pallreduce", id(e), rnd, result=out)
        return None, model

    st = _nb_state(ctx, cid, world_rank, create=False)
    if st is not None and st.outstanding:
        # in-flight I* ops own the initiation order; stay generic (the
        # generic path runs through the worker) and drop the armed round
        if e.armed:
            _plans.auto_demote(e)
        return None, None

    reg = e.reg
    if reg is not None and (reg.released or reg.generation
                            != config.GENERATION):
        _plans.auto_demote(e)
        reg = None
    if reg is None:
        if e.streak < threshold or e.ineligible_gen == config.GENERATION:
            return None, None
        reg = _register_allreduce(comm, args, donate=cfg.auto_arm_donate)
        if reg is None or not reg.knob_on:
            if reg is not None:
                _registry.discard(reg)
            e.ineligible_gen = config.GENERATION
            return None, None
        _plans.auto_bind(e, reg)

    # publish the front door: the NEXT identical call dispatches from
    # Allreduce() itself on one dict probe + identity compares, skipping
    # argument parsing and this key construction (_auto_hot_run)
    _plans.auto_hot_set((cid, key[1]),
                        (args, e, sendbuf,
                         getattr(sendbuf, "nbytes", None)))

    def runner():
        _plans.auto_hit(e)
        # flush this thread's stacked fast-armed persistent rounds first
        # so initiation order stays program order; the outstanding-work
        # check _ordered_run would redo just happened above
        if not getattr(_nb_worker_tls, "active", False):
            _demote_fast_armed(cid)
        return reg.run_round()
    return runner, None


_AUTO_MISS = object()


def _auto_hot_run(args: tuple) -> Any:
    """ISSUE-11 front door: dispatch a repeat of an already-armed plain
    ``Allreduce`` straight to its registered round on one dict probe plus
    per-element identity compares against the exact argument tuple that
    armed — skipping argument parsing and signature-key construction, the
    two per-call costs that kept the auto-armed lane measurably over the
    hand-armed Start/Wait figure. Any mismatch — different argument
    objects, tracing armed, a released or stale-generation registration,
    outstanding nonblocking traffic, an in-place resize of the send
    operand — returns ``_AUTO_MISS`` and the call falls through to
    :func:`_reduce_family`, whose full gate owns every demotion edge."""
    comm = args[-1]
    if not isinstance(comm, Comm):
        return _AUTO_MISS
    try:
        lane = (comm.cid, comm.rank())
    except Exception:
        return _AUTO_MISS               # not Init'd etc.: legacy error path
    rec = _plans.auto_hot_get(lane)
    if rec is None:
        return _AUTO_MISS
    pargs, e, send, nbytes = rec
    if len(pargs) != len(args):
        return _AUTO_MISS
    for a, b in zip(pargs, args):
        if a is not b:
            return _AUTO_MISS
    from . import config
    reg = e.reg
    if reg is None or reg.generation != config.GENERATION \
            or getattr(send, "nbytes", None) != nbytes \
            or not reg.armable():
        return _AUTO_MISS
    # stats stay truthful without the table lock: every field touched here
    # is owned by this rank's thread (the signature key is per-(cid, rank))
    # except the aggregate hit counter, which tolerates a lost update
    e.calls += 1
    e.streak += 1
    e.hits += 1
    _plans.auto_hits += 1
    # same program-order rule as the gate's runner: stacked fast-armed
    # persistent rounds on this thread initiate first
    if not getattr(_nb_worker_tls, "active", False):
        _demote_fast_armed(lane[0])
    return reg.run_round()


def _reduce_family(args, has_root: bool, mode: str, name: str) -> Any:
    sendbuf, recvbuf, count, op, root, comm, alloc = _parse_reduce_args(args, has_root, name)
    rank, size = comm.rank(), comm.size()
    scalar_in = np.isscalar(sendbuf) or isinstance(sendbuf, (int, float, complex, bool, np.generic))
    inplace = isinstance(sendbuf, _InPlace)
    if inplace:
        if _is_none(recvbuf):
            raise MPIError(f"IN_PLACE {name} needs a buffer")
        sendbuf = recvbuf
    if count is None:
        count = element_count(sendbuf)
    assert_minlength(sendbuf, count)
    if recvbuf is not None and not _is_none(recvbuf) and not inplace:
        assert_minlength(recvbuf, count)
    if mode == "reduce":
        # Zero-copy contribution: the reduce fold's distributed output is
        # always FRESH data (for n >= 2 the fold allocates; for n == 1 every
        # consumer below copies or self-assigns), and every rank is blocked
        # in the rendezvous until the fold has run — so the live buffer is
        # safe to expose and the to_wire snapshot copy is pure overhead.
        # Scan/Exscan keep the snapshot: Exscan hands rank 0's contribution
        # to rank 1 AS-IS, aliasing rank 0's buffer after it returns.
        payload = wire_view(sendbuf, count)
    else:
        payload = to_wire(sendbuf, count)

    # auto-arm (ISSUE 11): a repeated same-signature plain Allreduce is
    # promoted onto the registered persistent path; the armed runner skips
    # plan lookup AND bandit exploration (auto-armed plans never explore —
    # the explored variant would fork the call off its registered opname
    # lockstep). Under tracing the gate only returns a trace model.
    _model = None
    if mode == "reduce" and not has_root and name == "Allreduce" \
            and not scalar_in:
        _runner, _model = _auto_arm_gate(comm, args, sendbuf, recvbuf, op,
                                         count, payload, alloc)
        if _runner is not None:
            return _runner()

    cplan = _reduce_plan(comm, name, mode, op, count, payload)
    if mode == "reduce" and _tune_online.state() is not None:
        cplan = _explore_reduce_variant(comm, cplan, op, count, payload)
    # Own the pvar op scope across BOTH the rendezvous (_run) and the
    # result consumption below, so the copy-out into the user's recvbuf
    # lands in the same phase breakdown as the channel's rendezvous/fold
    # spans (the inner _run sees the open scope and defers finalization).
    sc = _pv.op_begin() if (_pv.enabled() or _ev.enabled()) else None
    # while tracing, stamp the contribution buffer's identity into the
    # signature (copy — cplan.sig may be plan-cache shared) so the R302
    # pass can see a stale donated result fed back into a reduction
    sig = dict(cplan.sig, bufid=_ev.buf_id(sendbuf)) if _ev.enabled() \
        else cplan.sig
    try:
        if has_root:
            result = _run_rooted(comm, root, payload, cplan.combine,
                                 cplan.opname, plan=cplan.hint, _sig=sig)
        else:
            result = _run(comm, payload, cplan.combine, cplan.opname,
                          plan=cplan.hint, _sig=sig)
        i_get_result = (not has_root) or rank == root
        if mode == "exscan" and result is None:
            # rank 0's Exscan output is undefined (src/collective.jl:834-855);
            # leave buffers untouched, return the input unchanged.
            if alloc:
                return sendbuf if scalar_in else clone_like(sendbuf, np.asarray(sendbuf))
            return recvbuf if not inplace else sendbuf
        if not i_get_result:
            return None if alloc else recvbuf
        if alloc:
            if scalar_in:
                out = np.asarray(result)
                return out.item() if out.ndim == 0 or out.size == 1 else out
            shaped = _shape_result(result, sendbuf, count)
            if sc is None:
                out = clone_like(sendbuf, shaped)
            else:
                t0 = _pv.monotonic()
                out = clone_like(sendbuf, shaped)
                sc.spans.append(("copy", t0, _pv.monotonic()))
            if _model is not None:
                _model(out)     # R302 donation-window model (auto-arm)
            return out
        target = sendbuf if inplace else recvbuf
        if sc is None:
            write_flat(target, result, count)
        else:
            t0 = _pv.monotonic()
            write_flat(target, result, count)
            sc.spans.append(("copy", t0, _pv.monotonic()))
        return target
    finally:
        if sc is not None:
            _pv.op_end(sc, comm, coll=name.lower(), algo=cplan.sig.get("algo"),
                       dtype=cplan.sig.get("dtype"),
                       nbytes=_pv.payload_nbytes(payload))


def _shape_result(result: Any, like: Any, count: int) -> Any:
    arr = extract_array(like)
    if arr is None or getattr(result, "shape", None) == arr.shape:
        return result   # metadata-only check; no dispatch on the hot lane
    if arr.size == count and np.asarray(result).size == count:
        return np.asarray(result).reshape(arr.shape) if not type(result).__module__.startswith("jax") \
            else result.reshape(arr.shape)
    return result


def Reduce(*args) -> Any:
    """``Reduce(send, recv, [count,] op, root, comm)`` | ``Reduce(IN_PLACE,
    buf, op, root, comm)`` | allocating ``Reduce(send, op, root, comm)``
    (src/collective.jl:605-666). Result lands on root only."""
    return _reduce_family(args, has_root=True, mode="reduce", name="Reduce")


def Allreduce(*args) -> Any:
    """``Allreduce(send, recv, [count,] op, comm)`` | ``Allreduce(IN_PLACE,
    buf, op, comm)`` | allocating ``Allreduce(send, op, comm)``
    (src/collective.jl:691-738). Deterministic rank-ordered reduction. A
    repeated identical call auto-arms onto the registered persistent path
    (ISSUE-11) and repeat hits dispatch through the front door below."""
    if len(args) >= 3:
        out = _auto_hot_run(args)
        if out is not _AUTO_MISS:
            return out
    return _reduce_family(args, has_root=False, mode="reduce", name="Allreduce")


def Scan(*args) -> Any:
    """Inclusive prefix reduction over ranks (src/collective.jl:760-808)."""
    return _reduce_family(args, has_root=False, mode="scan", name="Scan")


def Exscan(*args) -> Any:
    """Exclusive prefix reduction; rank 0's result undefined
    (src/collective.jl:834-882)."""
    return _reduce_family(args, has_root=False, mode="exscan", name="Exscan")


def Reduce_scatter(sendbuf: Any, recvbuf: Any, counts: Sequence[int], op: Any,
                   comm: Comm) -> Any:
    """Reduce then scatter by counts — absent from the reference (SURVEY.md
    §2.3: trivially composable / native in XLA as psum_scatter); provided
    natively here."""
    rank, size = comm.rank(), comm.size()
    op = as_op(op)
    counts = [int(c) for c in counts]
    total = sum(counts)
    assert_minlength(sendbuf, total)
    payload = (to_wire(sendbuf, total), counts)

    def combine(cs):
        # Reduce_scatter has no root: every rank's counts must agree.
        lists = [c[1] for c in cs]
        if any(l != lists[0] for l in lists[1:]):
            raise MPIError(f"Reduce_scatter counts differ across ranks: {lists}",
                           code=_ec.ERR_COUNT)
        red = _reduce_arrays([c[0] for c in cs], op)
        displs = np.concatenate([[0], np.cumsum(lists[0])])
        return [red.reshape(-1)[displs[r]:displs[r] + lists[0][r]]
                for r in range(len(cs))]

    mine = _run(comm, payload, combine, f"Reduce_scatter@{comm.cid}")
    if recvbuf is None:
        return clone_like(sendbuf, mine)
    assert_minlength(recvbuf, counts[rank])
    write_flat(recvbuf, mine, counts[rank])
    return recvbuf


def Reduce_scatter_block(sendbuf: Any, recvbuf: Any, op: Any, comm: Comm) -> Any:
    """Equal-block Reduce_scatter (recvcount = sendcount / comm size)."""
    size = comm.size()
    n = element_count(sendbuf)
    if n % size != 0:
        raise MPIError(f"send count {n} not divisible by comm size {size}",
                       code=_ec.ERR_COUNT)
    return Reduce_scatter(sendbuf, recvbuf, [n // size] * size, op, comm)


# ---------------------------------------------------------------------------
# Nonblocking collectives (MPI-3 Ibarrier/Ibcast/Iallreduce/… — absent from
# the reference v0.14.2, SURVEY.md §2.3 note; provided natively, beyond
# parity). Each communicator gets a per-rank single-thread worker, so this
# rank's collectives INITIATE on the rendezvous in program order (the MPI
# ordering contract) while the caller overlaps compute or P2P. Completion
# integrates with the whole Wait/Test family via a Request subclass.
# ---------------------------------------------------------------------------

class CollRequest:
    """Request handle for a nonblocking collective.

    Duck-types the :class:`tpu_mpi.pointtopoint.Request` completion
    protocol (``test``/``wait``/``active``/``cancel``), so Wait/Test/
    Waitall/Testall/Waitany/Testany/Waitsome/Testsome accept mixed lists
    of P2P and collective requests. ``result`` carries the allocating
    variant's return value after completion; errors raised inside the
    collective (mismatch, abort, deadlock) re-raise on Wait/Test.

    MPI contract (caller's side): do not touch the operation's buffers
    between initiation and completion, and initiate collectives on a
    communicator in the same order on every rank.
    """

    def __init__(self, future):
        self._future = future
        self.result = None
        self.status = None
        self._done = False
        self._inactive = False
        self.kind = "coll"
        self.buffer = None
        self.comm_cid = None     # pvar wait attribution (set by _nb_submit)
        # in-flight chunk state (overlap engine) — set by _nb_submit, advanced
        # by the progress worker, readable any time from the caller's thread
        self.progress = None

    def _complete(self) -> None:
        self.result = self._future.result()   # re-raises collective errors
        from .pointtopoint import STATUS_EMPTY
        self.status = STATUS_EMPTY
        self._done = True

    def test(self) -> bool:
        if self._done:
            return True
        if not self._future.done():
            return False
        self._complete()
        return True

    def wait(self):
        from .pointtopoint import STATUS_EMPTY
        if self._inactive:
            return self.status or STATUS_EMPTY
        if not self._done:
            # wait_owned(): an outer owner (PersistentCollRequest) already
            # accounts this round's wall clock — adding wait_ns here too
            # would double-count it (the outermost-owner rule, ISSUE-6).
            if _pv.enabled() and not _pv.wait_owned():
                t0 = _pv.monotonic()
                try:
                    self._complete()
                finally:
                    _pv.add_wait(_pv.monotonic() - t0, cid=self.comm_cid)
            else:
                self._complete()
        return self._consume()

    def _consume(self):
        """Surface the completion (Wait/Test-family contract): go inactive
        like a consumed P2P request; ``result`` stays readable."""
        from .pointtopoint import STATUS_EMPTY
        self._inactive = True
        return self.status or STATUS_EMPTY

    @property
    def active(self) -> bool:
        return not self._inactive

    def cancel(self) -> None:
        raise MPIError("nonblocking collectives cannot be cancelled")

    def __repr__(self) -> str:
        return f"<CollRequest done={self._done}>"


class _NbState:
    """Per-(comm, rank) nonblocking-collective worker: a single thread, so
    this rank's collectives INITIATE on the rendezvous in submission order,
    plus an outstanding counter that lets blocking collectives detect
    in-flight nonblocking ones and route through the same worker (ordering
    would otherwise race — an MPI-legal ``Ibarrier; Bcast; Wait`` could
    initiate in different orders on different ranks)."""

    def __init__(self, world_rank: int):
        from concurrent.futures import ThreadPoolExecutor
        self.executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"tpu-mpi-nbcoll-{world_rank}")
        self.outstanding = 0
        self.lock = threading.Lock()
        # submission id -> op name, insertion-ordered: names the in-flight
        # ops for diagnostics (Comm.free on a busy comm, lease reclamation)
        self._seq = 0
        self._pending: dict[int, str] = {}

    def submit(self, fn, opname: str = "collective"):
        with self.lock:
            self.outstanding += 1
            self._seq += 1
            sid = self._seq
            self._pending[sid] = (opname, None)
        fut = self.executor.submit(fn)
        with self.lock:
            if sid in self._pending:        # done() may already have pruned
                self._pending[sid] = (opname, fut)

        def done(_):
            with self.lock:
                self.outstanding -= 1
                self._pending.pop(sid, None)

        fut.add_done_callback(done)
        return fut

    def pending_ops(self) -> list:
        """Names of the submissions not yet completed, oldest first. A
        future can complete (its waiter unblocks) a beat before its done
        callback prunes the table, so consult the future itself — a
        ``Wait(); free()`` sequence must never see a phantom pending op."""
        with self.lock:
            return [name for name, fut in self._pending.values()
                    if fut is None or not fut.done()]

    def shutdown(self) -> None:
        self.executor.shutdown(wait=False)


_nb_worker_tls = threading.local()    # True on a collective worker thread


def _nb_state(ctx, cid, world_rank, create: bool):
    key = ("nbcoll", cid, world_rank)
    with ctx.objects_lock:
        st = ctx.objects.get(key)
        if st is None and create:
            st = _NbState(world_rank)
            ctx.objects[key] = st
        return st


def nb_pending(ctx, cid, world_rank) -> list:
    """Names of this rank's in-flight nonblocking collectives on one comm
    (empty when the worker is idle or was never created). Consulted by
    ``Comm.free`` so freeing under in-flight ops is a typed error naming
    the offenders instead of a strict-mode-only leak assert."""
    st = _nb_state(ctx, cid, world_rank, create=False)
    return st.pending_ops() if st is not None else []


def nb_shutdown(ctx, cid=None, world_rank=None) -> None:
    """Release nonblocking-collective workers: the ones of one comm+rank
    (Comm.free) or every one owned by a rank (Finalize)."""
    with ctx.objects_lock:
        keys = [k for k in ctx.objects
                if isinstance(k, tuple) and k and k[0] == "nbcoll"
                and (cid is None or k[1] == cid)
                and (world_rank is None or k[2] == world_rank)]
        states = [ctx.objects.pop(k) for k in keys]
    for st in states:
        st.shutdown()


def _nb_submit(comm: Comm, fn, opname: str = "collective") -> CollRequest:
    """Run ``fn`` on this rank's per-comm collective worker (the host-path
    progress engine: the worker thread advances the collective — including
    its pipeline chunks — while the caller is in user code; the request's
    ``progress`` exposes the in-flight chunk state)."""
    from ._runtime import require_env, set_env
    from .overlap import ChunkProgress, bind_progress, demote_fast_armed

    # a fast-armed persistent round on this comm has not rendezvoused yet:
    # it must initiate (on the worker) BEFORE this submission to keep the
    # per-comm initiation order equal to program order
    demote_fast_armed(comm.cid)
    ctx, world_rank = require_env()
    st = _nb_state(ctx, comm.cid, world_rank, create=True)
    prog = ChunkProgress()

    def run():
        # the worker impersonates the initiating rank (thread-tier ranks
        # are TLS-bound; the proc tier's process-global binding also works)
        set_env((ctx, world_rank))
        _nb_worker_tls.active = True
        bind_progress(prog)
        prog.stage = "running"
        try:
            return fn()
        finally:
            prog.stage = "done"
            bind_progress(None)
            _nb_worker_tls.active = False
            set_env(None)

    req = CollRequest(st.submit(run, opname=opname))
    req.progress = prog
    req.comm_cid = comm.cid       # attributes the caller's Wait time (pvars)
    return req


def _ordered_run(comm: Comm, call):
    """Initiation-order guard for BLOCKING collectives: when this rank's
    nonblocking worker has outstanding work on this comm, run the blocking
    collective THROUGH the worker (submission order = program order) and
    wait; otherwise call directly. Without this, an MPI-legal
    ``Ibarrier(comm); Bcast(buf, 0, comm); Wait(req)`` could initiate in
    different orders on different ranks and mispair rendezvous rounds."""
    if getattr(_nb_worker_tls, "active", False):
        return call()                      # already ON the worker
    # fast-armed persistent rounds initiate before this blocking collective
    # (same program-order rule as the worker submissions)
    from .overlap import demote_fast_armed
    demote_fast_armed(comm.cid)
    from ._runtime import current_env
    env = current_env()
    if env is None:
        return call()
    ctx, world_rank = env
    st = _nb_state(ctx, comm.cid, world_rank, create=False)
    if st is None or st.outstanding == 0:
        # an idle worker has fully completed everything it initiated, so a
        # direct call cannot overtake anything (and a CONCURRENT submitter
        # from another user thread is the user's ordering responsibility,
        # exactly as in MPI THREAD_MULTIPLE)
        return call()
    from ._runtime import set_env

    def run():
        set_env((ctx, world_rank))
        _nb_worker_tls.active = True
        try:
            return call()
        finally:
            _nb_worker_tls.active = False
            set_env(None)

    return st.submit(run).result()


def Ibarrier(comm: Comm) -> CollRequest:
    """Nonblocking barrier: complete once every rank has entered."""
    return _nb_submit(comm, lambda: Barrier(comm), opname="Ibarrier")


def Ibcast(buf: Any, root: int, comm: Comm) -> CollRequest:
    """Nonblocking Bcast; ``req.result`` is the (mutated) buffer."""
    return _nb_submit(comm, lambda: Bcast(buf, root, comm), opname="Ibcast")


def Iallreduce(*args) -> CollRequest:
    """Nonblocking Allreduce (same flavors as :func:`Allreduce`); the
    allocating variant's value arrives in ``req.result``."""
    return _nb_submit(_comm_of(args), lambda: Allreduce(*args),
                      opname="Iallreduce")


def Ireduce(*args) -> CollRequest:
    """Nonblocking rooted Reduce."""
    return _nb_submit(_comm_of(args), lambda: Reduce(*args), opname="Ireduce")


def Igather(*args) -> CollRequest:
    """Nonblocking rooted Gather."""
    return _nb_submit(_comm_of(args), lambda: Gather(*args), opname="Igather")


def Iallgather(*args) -> CollRequest:
    """Nonblocking Allgather."""
    return _nb_submit(_comm_of(args), lambda: Allgather(*args),
                      opname="Iallgather")


def Iscatter(*args) -> CollRequest:
    """Nonblocking rooted Scatter."""
    return _nb_submit(_comm_of(args), lambda: Scatter(*args), opname="Iscatter")


def Ialltoall(*args) -> CollRequest:
    """Nonblocking Alltoall."""
    return _nb_submit(_comm_of(args), lambda: Alltoall(*args),
                      opname="Ialltoall")


def Iscan(*args) -> CollRequest:
    """Nonblocking inclusive Scan."""
    return _nb_submit(_comm_of(args), lambda: Scan(*args), opname="Iscan")


def Iexscan(*args) -> CollRequest:
    """Nonblocking exclusive Scan."""
    return _nb_submit(_comm_of(args), lambda: Exscan(*args), opname="Iexscan")


def _comm_of(args) -> Comm:
    if not args or not isinstance(args[-1], Comm):
        raise TypeError("the last argument must be the communicator")
    return args[-1]


# ---------------------------------------------------------------------------
# Persistent collectives (MPI-4 MPI_Allreduce_init family), mirroring the
# persistent P2P machinery (pointtopoint.Send_init/Recv_init + Prequest):
# the arguments bind once, every Start initiates one round on the progress
# worker, and the first round populates the plan cache so later rounds skip
# per-call setup entirely — the training-loop shape.
# ---------------------------------------------------------------------------

def _registered_device_fold(op: Op, count: int, dtype: Any, size: int,
                            donate: bool = True):
    """The donated-accumulator fold executable for the registered device
    lane: ONE XLA computation compiled AOT at plan creation with
    ``donate_argnums`` on the accumulator, so every round's rank-ordered
    chain reuses the accumulator's device buffer in place instead of
    allocating a fresh output (the per-round HBM alloc + copy the generic
    ``_jitted_fold`` pays). Two pre-pinned accumulator slots alternate
    (``ring``): donation consumes a slot, so round k's result stays valid
    until round k+2's fold re-donates that slot — the persistent in-place
    contract documented in docs/performance.md. Returns the combine
    closure, or None when the op can't trace (the caller then declines the
    device registration and the generic path applies)."""
    try:
        import jax
        import jax.numpy as jnp
    except Exception:                               # pragma: no cover
        return None
    count = int(count)
    dt = np.dtype(dtype)
    sds = jax.ShapeDtypeStruct((count,), dt)

    def chain(acc, *xs):
        # the .set() seeds the donated buffer; the fold is then the same
        # rank-ordered left chain as _jitted_fold — bitwise-identical
        acc = acc.at[:].set(xs[0])
        for x in xs[1:]:
            acc = op.fn(acc, x)
        return acc

    def plain_fold(*xs):
        acc = xs[0]
        for x in xs[1:]:
            acc = op.fn(acc, x)
        return acc

    try:
        plain = jax.jit(plain_fold).lower(*([sds] * size)).compile()
        if donate:
            donated = jax.jit(chain, donate_argnums=(0,)) \
                .lower(sds, *([sds] * size)).compile()
            ring = [jnp.zeros((count,), dt), jnp.zeros((count,), dt)]
    except Exception:
        return None                 # host-only / untraceable op: no lane
    from .buffers import is_jax_array as _isjax
    state = {"k": 0}

    def combine(cs, rt=None):
        k = state["k"]
        state["k"] = k + 1
        n = len(cs)
        good = n == size and all(
            _isjax(c) and tuple(c.shape) == (count,) and c.dtype == dt
            for c in cs)
        if good:
            if not donate:
                # copy-out contract (auto-armed lane): the AOT chain still
                # skips per-round trace/lower work, but every round's output
                # is a fresh array — no slot is ever re-donated under a
                # result the user may still hold (the R302 hazard).
                return [plain(*cs)] * n
            slot = ring[k & 1]
            # an operand aliasing the accumulator (a rank fed a previous
            # result straight back) can't be donated over — fold fresh
            if slot is not None and not any(c is slot for c in cs):
                out = donated(slot, *cs)
                ring[k & 1] = out
                return [out] * n
            return [plain(*cs)] * n
        # a peer contributed a host / reshaped payload this round: generic
        total = _reduce_arrays(list(cs), op)
        return [total] * n

    return combine


def _register_allreduce(comm: Comm, args,
                        donate: bool = True) -> Optional[PlanRegistration]:
    """Build the registered-buffer fast path of one ``Allreduce_init``
    signature (the ISSUE-6 tentpole), or None when the operands are not
    eligible (every round then takes the generic worker path).

    ``donate=False`` selects the auto-arm copy-out contract (ISSUE 11):
    the allocating flavor returns a FRESH array every round instead of the
    plan-private registered result, and the device lane compiles only the
    non-donated fold — bitwise identical to the generic path with none of
    the R302 donated-reuse hazard, at the cost of one output copy.
    Hand-armed ``Allreduce_init`` callers keep ``donate=True`` (documented
    persistent in-place result semantics).

    Everything a round needs is resolved and PINNED here, at plan-creation
    time:

    - the send operand's flat wire view (``buffers.pinned_wire_view``) —
      rendezvous ships the pre-bound view, no per-call normalization;
    - the fold accumulator (``buffers.register_scratch``) — the chunked
      in-place ufunc fold lands in plan-private pinned memory (the generic
      ``_chunked_fold`` allocates its output every call);
    - the copy-out target — the user's recv buffer's pinned view, or a
      per-rank registered result array for the allocating flavor
      (returned in place round after round: ``Allreduce_init`` callers opt
      into persistent in-place result semantics, see docs/performance.md);
    - on the device lane (thread tier), the donated fold executable
      (:func:`_registered_device_fold`) compiled once per plan;
    - on the multi-process tier, the same-host shm segment lease
      (``ProcChannel.shm_bind``) so no round pays the lazy mmap.

    The round closure then does ONE rendezvous round trip inline on the
    calling thread — no arg parse, no plan lookup, no worker hop, zero
    steady-state allocation — with the thread tier's channel lock released
    during the fold (``unlocked_fold``: the combine only touches the
    plan-private scratch)."""
    from . import config
    from ._runtime import CollectiveChannel as _ThreadChannel, current_env
    from .buffers import pinned_wire_view, register_scratch

    if not isinstance(comm, Comm) or isinstance(comm, Intercomm):
        return None
    env = current_env()
    if env is None:
        return None                 # outside an SPMD env: legacy path raises
    ctx, world_rank = env
    cfg = config.load()
    if not cfg.registered_buffers:
        # knob off: keep a disabled stub so a later config reload (which
        # bumps GENERATION) re-runs this factory and can bind for real
        def _off():
            raise MPIError("registered fast path is disabled")
        return _registry.add(PlanRegistration(
            comm.cid, config.GENERATION, _off, knob_on=False))
    try:
        sendbuf, recvbuf, count, op, _root, _c, alloc = \
            _parse_reduce_args(args, False, "Allreduce")
    except Exception:
        return None                 # malformed args: legacy path raises
    inplace = isinstance(sendbuf, _InPlace)
    if inplace:
        if _is_none(recvbuf):
            return None
        sendbuf = recvbuf
    try:
        if count is None:
            count = element_count(sendbuf)
        assert_minlength(sendbuf, count)
    except Exception:
        return None
    count = int(count)
    size, rank = comm.size(), comm.rank()
    channel = comm.channel()
    thread_tier = isinstance(channel, _ThreadChannel)

    from .operators import is_elementwise
    sendview = pinned_wire_view(sendbuf, count)
    scratch: tuple
    if sendview is not None:
        # ---- host lane: pinned views + registered in-place chunk fold ----
        if op.ufunc is None or not is_elementwise(op):
            return None
        payload = sendview
        acc = register_scratch(count, sendview.dtype)
        contrib = lambda: sendview
        cplan = _reduce_plan(comm, "Allreduce", "reduce", op, count, payload)
        bounds = (tuple(cplan.schedule) if cplan.schedule is not None
                  else ((0, count),))
        shared = [acc] * size

        def combine(cs, rt=None):
            flats = []
            for c in cs:
                if isinstance(c, np.ndarray) and c.dtype == acc.dtype \
                        and c.size == count:
                    flats.append(c.reshape(-1))
                else:
                    # a peer contributed a device / promoted payload this
                    # round: fold generically, land it in the pinned scratch
                    total = _reduce_arrays(list(cs), op,
                                           schedule=cplan.schedule)
                    np.copyto(acc, np.asarray(total).reshape(-1),
                              casting="unsafe")
                    return shared
            for lo, hi in bounds:
                np.copyto(acc[lo:hi], flats[0][lo:hi])
                for f in flats[1:]:
                    op.ufunc(acc[lo:hi], f[lo:hi], out=acc[lo:hi])
            return shared

        if alloc:
            out = register_scratch(count, sendview.dtype)
            shape = np.shape(sendbuf)
            ret = out.reshape(shape) \
                if int(np.prod(shape, dtype=np.int64)) == count else out
            scratch = (acc, out)

            if donate:
                def copyout(res):
                    if res is not out:
                        np.copyto(out, np.asarray(res).reshape(-1),
                                  casting="unsafe")
                    return ret
            else:
                def copyout(res):
                    if res is not out:
                        np.copyto(out, np.asarray(res).reshape(-1),
                                  casting="unsafe")
                    return np.array(ret, copy=True)
        else:
            tgt = sendbuf if inplace else recvbuf
            tgtview = sendview if inplace else pinned_wire_view(tgt, count)
            if tgtview is None:
                return None         # unbindable recv operand: legacy path
            scratch = (acc,)

            def copyout(res):
                resarr = np.asarray(res).reshape(-1)
                if resarr is not tgtview and resarr.base is not tgtview:
                    np.copyto(tgtview, resarr, casting="unsafe")
                return tgt
    elif (isinstance(sendbuf, DeviceBuffer) or is_jax_array(sendbuf)) \
            and thread_tier:
        # ---- device lane: donated-accumulator fold, thread tier only ----
        payload = to_wire(sendbuf, count)
        cplan = _reduce_plan(comm, "Allreduce", "reduce", op, count, payload)
        combine = _registered_device_fold(op, count, payload.dtype, size,
                                          donate=donate)
        if combine is None:
            return None
        contrib = lambda: to_wire(sendbuf, count)   # rebind-aware snapshot
        scratch = ()
        if alloc:
            shape = tuple(getattr(sendbuf, "shape", ()))
            reshape = int(np.prod(shape, dtype=np.int64)) == count
            wrap = isinstance(sendbuf, DeviceBuffer)

            def copyout(res):
                val = res if (not reshape or res.shape == shape) \
                    else res.reshape(shape)
                return DeviceBuffer(val) if wrap else val
        else:
            tgt = sendbuf if inplace else recvbuf
            if not isinstance(tgt, DeviceBuffer):
                return None         # jax.Array recv is immutable: legacy
            def copyout(res):
                v = tgt.value
                if is_jax_array(res) and res.size == v.size \
                        and res.dtype == v.dtype:
                    tgt.setflat(res if res.shape == v.shape
                                else res.reshape(v.shape))
                else:
                    tgt.setflat(res, count)
                return tgt
    else:
        return None

    shm_release = None
    shm_bind = getattr(channel, "shm_bind", None)
    if shm_bind is not None:
        nbytes = int(count) * int(getattr(payload.dtype, "itemsize", 0) or 0)
        shm_release = shm_bind(nbytes)

    cid = comm.cid

    def nb_probe() -> int:
        st = _nb_state(ctx, cid, world_rank, create=False)
        return 0 if st is None else st.outstanding

    opname, hint, sig = cplan.opname, cplan.hint, cplan.sig
    runkw = {"unlocked_fold": True} if thread_tier else {}
    pv_nbytes = _pv.payload_nbytes(payload)

    def run_round():
        # the fast-armed Wait: one rendezvous round trip on THIS thread.
        # _ordered_run is unnecessary by construction — arming required an
        # idle nonblocking worker, and any later submission on this comm
        # demotes the armed round before it gets here.
        sc = _pv.op_begin() if _pv.enabled() else None
        try:
            res = channel.run(rank, contrib(), combine, opname,
                              plan=hint, **runkw)
            if sc is None:
                return copyout(res)
            t0 = _pv.monotonic()
            val = copyout(res)
            sc.spans.append(("copy", t0, _pv.monotonic()))
            return val
        finally:
            if sc is not None:
                _pv.op_end(sc, comm, coll="allreduce", algo=sig.get("algo"),
                           dtype=sig.get("dtype"), nbytes=pv_nbytes)

    # batched-submission hook (ISSUE 11): the pieces Waitall needs to
    # deposit K armed rounds through ONE rendezvous wakeup on the thread
    # tier (CollectiveChannel.run_batch). Proc-tier batching happens a
    # layer down (framed "batchv" coalescing in ProcChannel), so only the
    # thread tier publishes the parts.
    round_parts = None
    if thread_tier:
        round_parts = {
            "channel": channel, "rank": rank, "contrib": contrib,
            "combine": combine, "opname": opname, "hint": hint,
            "runkw": runkw, "copyout": copyout, "comm": comm,
            "sig": sig, "pv_nbytes": pv_nbytes,
        }

    return _registry.add(PlanRegistration(
        cid, config.GENERATION, run_round, scratch=scratch, wire=sendview,
        shm_release=shm_release, knob_on=True, nb_probe=nb_probe,
        inplace_optin=bool(inplace or (alloc and donate)),
        round_parts=round_parts))

def _persistent_round(req: PersistentCollRequest, fn):
    """Run one legacy-lane persistent round on the worker thread, tagging
    the collective event it records with the owning handle + round so
    ``analyze.explore`` models the round's timing from the Start/Wait pair
    instead of double-counting the inner event."""
    from .analyze import events as _ev
    if not _ev.enabled():
        return fn()
    with _ev.persistent_scope(id(req), req._round - 1):
        return fn()


def Allreduce_init(*args) -> PersistentCollRequest:
    """Persistent Allreduce (same flavors as :func:`Allreduce`). Arm with
    ``Start``/``Startall``; complete with the Wait/Test family; reuse. The
    allocating variant's value lands in ``req.result`` each round."""
    comm = _comm_of(args)
    req = PersistentCollRequest(
        lambda: _nb_submit(comm, lambda: _persistent_round(
            req, lambda: Allreduce(*args))),
        "pallreduce", args[0] if args else None, comm=comm)
    return req.bind_registration(lambda: _register_allreduce(comm, args))


def Bcast_init(buf: Any, root: int, comm: Comm) -> PersistentCollRequest:
    """Persistent Bcast of ``buf`` from ``root``; mutates buf every round."""
    req = PersistentCollRequest(
        lambda: _nb_submit(comm, lambda: _persistent_round(
            req, lambda: Bcast(buf, root, comm))),
        "pbcast", buf, comm=comm)
    return req


def Barrier_init(comm: Comm) -> PersistentCollRequest:
    """Persistent barrier."""
    req = PersistentCollRequest(
        lambda: _nb_submit(comm, lambda: _persistent_round(
            req, lambda: Barrier(comm))),
        "pbarrier", None, comm=comm)
    return req


