"""Unified configuration: environment variables + persisted TOML preferences.

Reference: /root/reference/deps/build.jl:14-58 reads ``JULIA_MPI_*`` env vars
and persists them to ``~/.julia/prefs/MPI.toml``; runtime knobs
(JULIA_MPIEXEC_ARGS, JULIA_MPI_TEST_*) stay env-only. The TPU analog is one
module owning every knob: the backend choice (real TPU vs CPU-sim), mesh/sim
device count, multi-process coordinator address, and timeouts — consulted by
the launcher, the runtime, and the multi-process backend instead of ad-hoc
``os.environ`` reads scattered per file (VERDICT r1, missing item 6).

Precedence per key: explicit function argument > ``TPU_MPI_*`` env var >
persisted TOML (``~/.config/tpu_mpi/config.toml`` or ``$TPU_MPI_CONFIG``) >
built-in default.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, fields
from typing import Any, Optional

from . import error as _ec
from .error import MPIError

_DEFAULT_TOML = os.path.join("~", ".config", "tpu_mpi", "config.toml")


@dataclass
class Config:
    """Every knob the framework consults, with its default."""

    # backend selection (build.jl:60-138 binary/ABI choice analog):
    # "auto" = use whatever jax.devices() yields; "cpu-sim" forces fake XLA
    # CPU devices; "tpu" requires a real TPU and errors otherwise.
    backend: str = "auto"
    # CPU-sim substrate size (xla_force_host_platform_device_count).
    sim_devices: int = 8
    # default world size for tpurun when -n is not given (0 = #devices).
    nprocs: int = 0
    # multi-process tier: coordinator address ("host:port") for joining an
    # existing rendezvous (multi-host launch), "" = launcher-local.
    coordinator: str = ""
    # interface the coordinator binds ("127.0.0.1" single-host; "0.0.0.0"
    # to serve a real cluster over DCN).
    coordinator_bind: str = "127.0.0.1"
    # address remote hosts dial for the coordinator ("" = the bind address,
    # or the hostname when binding 0.0.0.0).
    coordinator_advertise: str = ""
    # seconds a blocking wait may stall before DeadlockError.
    deadlock_timeout: float = 60.0
    # seconds a child waits for the world address map at rendezvous.
    rendezvous_timeout: float = 600.0
    # max native-transport frame size (corrupt-stream guard), bytes.
    max_frame_bytes: int = 1 << 31
    # multi-process tier: array payloads at least this large travel between
    # same-host ranks through one-shot POSIX shm segments instead of the TCP
    # stream (the libmpi shared-memory-BTL analog); 0 disables the shm lane.
    shm_min_bytes: int = 1 << 18
    # host-path overlap engine (docs/performance.md "Overlap engine"):
    # payloads at least this large are chunk-pipelined through the
    # transfer / reduce-combine stages instead of moving monolithically;
    # 0 disables pipelining entirely.
    pipeline_min_bytes: int = 1 << 20
    # number of chunks a pipelined payload splits into (clamped to at
    # least 2 when pipelining engages; the last chunk absorbs remainders).
    pipeline_chunks: int = 4
    # strict mode: poison batched-read RMA origins (Get / Fetch_and_op
    # results inside a deferred lock epoch) with a sentinel until the
    # closing synchronization, so a caller consuming them mid-epoch —
    # undefined behavior per MPI — fails loudly instead of reading stale
    # bytes (docs/performance.md "Batched read epochs").
    strict: bool = False
    # blocking-send flow control: a Send/send blocks while the destination's
    # unexpected queue holds more than this many bytes (the rendezvous-
    # protocol analog; Isend keeps buffered semantics). 0 disables.
    send_highwater_bytes: int = 1 << 26
    # debug mode (SURVEY §5 race detection): stamp every P2P message with a
    # per-(sender, dest, cid) sequence number and fail loudly on any
    # reordering/duplication/loss at delivery.
    debug_sequence_check: bool = False
    # fused multi-operand reduction fold (xla.pallas_kernels
    # .fused_multi_reduce) in the collective fold paths: "auto" = Pallas
    # kernel on real TPU, chained XLA fold elsewhere; "off" = always the
    # chained XLA fold; "interp" = force the kernel through the Pallas
    # interpreter off-TPU too (test/debug only — orders of magnitude slow).
    fused_fold: str = "auto"
    # communication-event tracing (tpu_mpi.analyze, docs/analysis.md):
    # record per-rank event ring buffers consumed by the cross-rank trace
    # verifier, the RMA race detector, and the DeadlockError dump of
    # per-rank pending operations + the wait-for cycle.
    trace: bool = False
    # per-rank event ring-buffer capacity while tracing is on.
    trace_buffer: int = 4096
    # request-scoped distributed tracing (docs/observability.md "Request
    # traces"): fraction of serve-session ops that mint a trace context
    # (trace_id + span parenting carried in frame metadata through router,
    # front door, fair queue and per-rank phase spans). 0.0 (default)
    # disables span recording entirely — ops carry no trace metadata and
    # the hot path stays one generation-gated check. 1.0 samples all.
    trace_sample: float = 0.0
    # crash flight recorder (docs/observability.md "Flight recorder"):
    # capacity of the always-on per-process ring of recent spans and
    # typed-error/lifecycle events, auto-dumped on fatal errors and
    # SIGTERM. 0 disables the recorder (and the auto-dump hooks).
    flight_ring: int = 256
    # directory flight-recorder auto-dumps are written into
    # ("flight-<pid>-<reason>.json", CRC-stamped); "" = the system temp dir.
    flight_dir: str = ""
    # fleet-wide serve SLO (docs/observability.md "SLO burn-rate"): the
    # per-op latency objective in microseconds applied to every tenant
    # without an explicit Ledger.set_objective; at most 1% of a tenant's
    # ops may take this long or longer before its burn rate crosses 1.0
    # (an elastic grow signal). 0 = no objective.
    serve_slo_us: int = 0
    # path PREFIX for per-rank trace dumps written at Finalize (one
    # ``<prefix>.rank<N>.trace.json`` per rank); consumed offline by
    # ``python -m tpu_mpi.analyze explore``. "" = no dump.
    trace_dump: str = ""
    # collective algorithm layer (tpu_mpi.tune, docs/performance.md
    # "Algorithm selection"): path of a measured tuning table written by
    # ``tpurun --tune``; "" = use the built-in heuristic crossovers.
    tune_table: str = ""
    # force-override for debugging/CI: comma list of collective=algorithm
    # pins (e.g. "allreduce=rdouble,barrier=star"), clamped by per-
    # algorithm eligibility; "" = no override.
    coll_algo: str = ""
    # online bandit autotuner (tpu_mpi.tune_online, docs/performance.md
    # "Online tuning"): fraction of live collective calls routed to an
    # eligible alternate algorithm for measurement (epsilon-greedy over a
    # shared deterministic schedule so every rank explores the same arm on
    # the same call). 0.0 disables the loop entirely — the default.
    tune_explore: float = 0.0
    # minimum observations a (coll, algo, nbytes) cell needs before it may
    # set a crossover (noise guard for `tune --from-pvars`, fleet merges,
    # and the online loop's hot-swap).
    tune_min_samples: int = 8
    # online loop: recompute + hot-swap the crossover table every this many
    # algorithm decisions per communicator (a lockstep internal round
    # merges per-rank arm stats so every rank derives the same table).
    tune_swap_period: int = 256
    # seed of the shared deterministic exploration schedule (every rank
    # must use the same value — it's part of the lockstep contract).
    tune_seed: int = 0
    # fleet tuning database written by `python -m tpu_mpi.tune merge`
    # (schema 2: sample-weighted merge of per-rank pvar dumps + measured
    # tables). Consulted by select() after tune_table, before the
    # heuristic; "" = no database layer.
    tune_db: str = ""
    # test/debug latency shim: comma list of coll:algo=microseconds added
    # to the measured op span (e.g. "allreduce:star=2000" slows the star
    # arm) so bandit convergence is deterministic under test; "" = off.
    tune_shim: str = ""
    # same-host shared-memory collective fold (the libmpi coll/sm analog):
    # Allreduce payloads strictly below this many bytes — and Barrier —
    # use one mmap'd /dev/shm segment per communicator instead of O(P)
    # transport messages when all ranks share a host; 0 disables the lane.
    coll_shm_max_bytes: int = 1 << 16
    # registered-buffer fast path (docs/performance.md "Registered
    # buffers"): persistent collectives (Allreduce_init + Start/Wait)
    # pre-pin their wire views and fold scratch at plan creation and run
    # each round allocation-free on the calling thread; off = every round
    # takes the generic per-call path (parse, plan lookup, worker hop).
    registered_buffers: bool = True
    # auto-arming (docs/performance.md "Auto-arming"): plain repeated
    # same-signature collectives (the training-loop `comm.Allreduce(x)`
    # case) are transparently promoted onto the registered persistent path
    # after `auto_arm_threshold` identical calls — no `Allreduce_init`
    # required. Results keep copy-out semantics (bitwise-identical to the
    # generic path, never aliased). Off = only hand-armed persistent
    # requests take the registered path.
    auto_arm: bool = True
    # consecutive identical calls (same comm, op, buffer objects, count,
    # dtype) before a signature auto-arms.
    auto_arm_threshold: int = 4
    # explicit donation opt-in for the AUTO-armed lane: allocating-flavor
    # results are handed out as the registered fold slot itself (zero
    # copy-out) — round k's result is re-donated by round k+2, so holding
    # a result across two later calls reads in-flight data (the R302
    # hazard the race detector models). Off (default) = copy-out.
    auto_arm_donate: bool = False
    # batched submission (docs/performance.md "Batched submission"): max
    # queued ops (chunk frames of one collective, or a Waitall run of
    # armed persistent rounds) coalesced into ONE rendezvous round trip —
    # one writev scatter-gather frame on the native transport, one
    # condvar wakeup on the thread tier. <=1 disables coalescing.
    batch_max_ops: int = 16
    # byte budget per coalesced flush: a batch frame closes early once its
    # payloads reach this size. 0 = no byte cap (count cap only).
    batch_max_bytes: int = 1 << 22
    # performance-variable (pvar) collection level (docs/observability.md):
    # 0 disables every counter (one branch per op remains), 1 collects.
    # Pcontrol(level) overrides this at runtime without a config reload.
    pvars: int = 1
    # directory for per-rank pvar dumps at Finalize / Pcontrol(>=2):
    # each rank writes pvars-rank<R>.json there; "" = no dump.
    pvars_dump: str = ""
    # per-collective latency histogram width (log2-microsecond buckets):
    # bucket i counts ops with latency in [2^(i-1), 2^i) us.
    pvars_hist_bins: int = 24
    # fault tolerance (docs/fault-tolerance.md): heartbeat period in
    # milliseconds on the native-transport poll loop. 0 (the default)
    # disables the failure detector entirely — the fault path is strictly
    # pay-for-use; fate-sharing semantics are unchanged.
    heartbeat_ms: int = 0
    # milliseconds of heartbeat silence before a peer is declared dead
    # (ProcFailedError). 0 derives 10x heartbeat_ms (min 1000 ms).
    failure_timeout_ms: int = 0
    # deadline for any single blocking recv / request Wait, milliseconds:
    # past it the op raises DeadlockError with the per-rank pending-op dump
    # even when the global deadlock_timeout is longer. 0 disables (default).
    op_timeout_ms: int = 0
    # multi-tenant serve tier (docs/serving.md): the well-known socket the
    # broker listens on and clients attach to. A value containing "/" is a
    # Unix-domain socket path; otherwise "host:port" TCP. "" = the broker
    # picks a loopback TCP port and prints it.
    serve_socket: str = ""
    # max concurrently-leased tenants the broker admits; attach past the
    # limit fails with a typed SessionError instead of queueing.
    serve_max_tenants: int = 8
    # per-tenant traffic quota, bytes moved through collectives (charged at
    # admission): past it ops are REJECTED with QuotaExceededError, never
    # hung. 0 = unlimited.
    serve_quota_bytes: int = 0
    # shared secret a client must present in the session handshake; "" (the
    # default) means the broker accepts any token — loopback/dev mode.
    session_token: str = ""
    # serve pool backend (docs/serving.md "Scale-out"): "threads" = rank
    # threads inside the broker process on one warm thread-tier world;
    # "procs" = OS-process ranks over the framed native transport, spawned
    # through the launcher rendezvous and driven by per-rank control
    # sockets (the production backend — survives rank SIGKILL, no shared
    # GIL with the broker loop).
    serve_backend: str = "threads"
    # multi-broker scale-out: comma list of broker sockets the router
    # shards tenants across (and `tpurun --serve --stats` merges).
    serve_brokers: str = ""
    # this broker's disjoint cid-range shard as "index/count" (e.g. "0/2");
    # "" = the whole namespace range (single-broker). Each shard carves
    # tenant cid namespaces from a disjoint base so N brokers can front
    # one fleet without cid collisions (serve/ledger.py CidShard).
    serve_shard: str = ""
    # zero-copy frame path: OP payload views are scatter-gather written
    # (socket sendmsg) straight from the session recv buffer to the rank
    # mailbox — no intermediate marshal; off = the legacy join+copy path
    # (the before/after comparison lane in benchmarks/serve_scale_sweep.py).
    serve_zerocopy: bool = True
    # socket the scale-out router (`tpurun --serve --router`) listens on;
    # same spec grammar as serve_socket, "" = pick a loopback TCP port.
    serve_router_socket: str = ""
    # router session handling: "splice" proxies every byte through the
    # router (clients need only its address); "redirect" answers HELLO
    # with the tenant's home broker so the data path goes direct.
    serve_router_mode: str = "splice"
    # session transport at the broker's front door (docs/serving.md "Front
    # door"): "events" multiplexes every attached session socket on one
    # edge-triggered readiness loop with a fixed worker pool (idle sockets
    # cost zero threads — the C10k path); "threads" is the legacy
    # one-handler-thread-per-connection front door, kept for A/B and as
    # the conservative fallback.
    serve_transport: str = "events"
    # size of the event-driven front door's worker pool: how many session
    # frames can be in service at once (attaches, collectives waiting on
    # the pool, stats probes). Sockets scale independently of this.
    serve_workers: int = 8
    # recv-lease window, bytes: inbound OP payloads at or under this size
    # land zero-copy in a registered buffer recycled across frames (the
    # inbound mirror of serve_zerocopy's sendmsg path); larger payloads
    # fall back to a per-frame exact-size buffer (a lease miss, counted).
    serve_lease_window: int = 1 << 16
    # inference engine (docs/serving.md "Inference engine"): per-request
    # latency SLO in milliseconds — a generation request whose deadline
    # expires before it finishes is EVICTED with a typed retriable
    # SLOExpiredError rather than hung. 0 = no deadline.
    infer_slo_ms: int = 0
    # max concurrently-decoding sessions per continuous-batching step; also
    # the per-expert routing capacity so admitted tokens are never dropped.
    infer_max_batch: int = 8
    # KV-cache paged-block granularity in tokens; also the partition size
    # for cross-stage prefill streaming over Psend_init/Precv_init.
    kv_block_tokens: int = 16
    # decode fast path (docs/serving.md "Decode fast path"): batch every
    # co-scheduled request's token rows into ONE MoE dispatch/combine per
    # layer round instead of one round per prefill partition per request.
    # Bitwise-identical outputs (row-wise math); off = the PR 12 row-loop
    # baseline, kept for A/B lanes in benchmarks/infer_sweep.py.
    infer_vectorized: bool = True
    # speculative multi-token decode: draft up to k tokens per request per
    # step from the session's own history, verify in one batched pass and
    # accept the greedy-matching prefix. <= 1 = off (the k=1 baseline).
    # Greedy acceptance keeps output streams bitwise identical to k=1.
    infer_spec_k: int = 0
    # per-step prefill token budget: a prompt longer than this is split
    # across consecutive StepPlans so one giant prefill cannot
    # head-of-line-block co-batched decodes. 0 = off (whole prompt in one
    # step). The chunk boundaries ride in the rank-uniform plan.
    infer_prefill_chunk: int = 0
    # cross-tenant KV prefix sharing: content-hash full prompt-prefix
    # blocks in the paged KV cache, refcounted + copy-on-write, so
    # requests sharing a system prompt reuse physical KV blocks and skip
    # recomputing the shared prefix. Tenants only ever match prefixes of
    # tokens they themselves presented (admission-layer isolation).
    kv_prefix_share: bool = False
    # LRU bound on the persistent-collective plan cache AND the auto-arm
    # signature table (the auto table is capped at max(8, this // 4)) —
    # the shape-churn pressure guard; evictions are counted in the pvar
    # plan-cache block. Minimum 8.
    plan_cache_max: int = 128
    # hierarchical collectives (docs/performance.md "Hierarchical
    # collectives"): emulated domain count for the two-level runners.
    # 0 (default) derives domains from the rendezvous address table (one
    # domain per distinct host); k >= 2 partitions every communicator
    # into k contiguous equal blocks — the cpu-sim way to exercise the
    # multi-host split on one machine.
    domains: int = 0
    # byte floor for the heuristic to prefer the two-level "hier"
    # composite on multi-domain worlds (measured tables override).
    hier_min_bytes: int = 4096
    # training tier (docs/training.md): gradient-bucket capacity in bytes
    # for the DDP backward pass — gradients pack into size-bounded
    # buckets (reverse-layer order) and each bucket rides one persistent
    # Allreduce, so the knob trades per-op overhead (small buckets)
    # against overlap opportunity (a single huge bucket cannot overlap).
    train_bucket_bytes: int = 1 << 20
    # ZeRO-style sharded-state mode: partition optimizer state and flat
    # master params 1/nranks (Reduce_scatter the grad, Allgather the
    # updated params) instead of replicating them per rank.
    train_shard_state: bool = False
    # elastic capacity (docs/fault-tolerance.md "Elastic recovery"):
    # enables the broker-side autoscaler loop that re-spawns ranks after a
    # failure and grows/retires capacity from the load signals the broker
    # already records (queue depth, busy-rejection rate, SLO hit rate).
    elastic: bool = False
    # pool-size floor the autoscaler will never retire below.
    elastic_min_ranks: int = 1
    # pool-size ceiling for pressure-driven growth; 0 = the starting size
    # (failure replacement always restores to the pre-failure target).
    elastic_max_ranks: int = 0
    # autoscaler tick interval.
    elastic_interval_ms: int = 200
    # refractory period after any resize before the next one may start.
    elastic_cooldown_ms: int = 2000
    # consecutive over/under-threshold ticks before a resize fires
    # (hysteresis — one noisy sample never resizes the pool).
    elastic_hysteresis: int = 3
    # queued-op depth across tenants that counts as growth pressure.
    elastic_depth_high: int = 16
    # consecutive idle ticks before a spare rank is retired; 0 = never.
    elastic_idle_ticks: int = 0
    # per-rank sidecar watchdog processes: SIGKILLing a sidecar declares
    # its rank failed (the chaos hook for the thread-tier pool, where rank
    # threads cannot be killed individually).
    elastic_sidecars: bool = False
    # runtime lock witness (tpu_mpi.locksmith): swap every named lock
    # construction site for a LockWitness that maintains the global
    # acquisition-order graph and raises LockOrderError on inversion.
    # Pay-for-use: off means plain threading primitives, zero overhead.
    lockcheck: bool = False
    # record full acquisition stacks (not just the caller's site) in
    # witness reports — costlier, for post-mortem dumps.
    lockcheck_stacks: bool = False

    def replace(self, **kw: Any) -> "Config":
        d = {f.name: getattr(self, f.name) for f in fields(self)}
        d.update({k: v for k, v in kw.items() if v is not None})
        return Config(**d)


_ENV_MAP = {
    "backend": "TPU_MPI_BACKEND",
    "sim_devices": "TPU_MPI_SIM_DEVICES",
    "nprocs": "TPU_MPI_NPROCS",
    "coordinator": "TPU_MPI_PROC_COORD",
    "coordinator_bind": "TPU_MPI_COORD_BIND",
    "coordinator_advertise": "TPU_MPI_COORD_ADVERTISE",
    "deadlock_timeout": "TPU_MPI_DEADLOCK_TIMEOUT",
    "rendezvous_timeout": "TPU_MPI_RENDEZVOUS_TIMEOUT",
    "max_frame_bytes": "TPU_MPI_MAX_FRAME_BYTES",
    "shm_min_bytes": "TPU_MPI_SHM_MIN_BYTES",
    "pipeline_min_bytes": "TPU_MPI_PIPELINE_MIN_BYTES",
    "pipeline_chunks": "TPU_MPI_PIPELINE_CHUNKS",
    "strict": "TPU_MPI_STRICT",
    "send_highwater_bytes": "TPU_MPI_SEND_HIGHWATER_BYTES",
    "debug_sequence_check": "TPU_MPI_DEBUG_SEQUENCE",
    "fused_fold": "TPU_MPI_FUSED_FOLD",
    "trace": "TPU_MPI_TRACE",
    "trace_buffer": "TPU_MPI_TRACE_BUFFER",
    "trace_sample": "TPU_MPI_TRACE_SAMPLE",
    "flight_ring": "TPU_MPI_FLIGHT_RING",
    "flight_dir": "TPU_MPI_FLIGHT_DIR",
    "serve_slo_us": "TPU_MPI_SERVE_SLO_US",
    "trace_dump": "TPU_MPI_TRACE_DUMP",
    "tune_table": "TPU_MPI_TUNE_TABLE",
    "coll_algo": "TPU_MPI_COLL_ALGO",
    "tune_explore": "TPU_MPI_TUNE_EXPLORE",
    "tune_min_samples": "TPU_MPI_TUNE_MIN_SAMPLES",
    "tune_swap_period": "TPU_MPI_TUNE_SWAP_PERIOD",
    "tune_seed": "TPU_MPI_TUNE_SEED",
    "tune_db": "TPU_MPI_TUNE_DB",
    "tune_shim": "TPU_MPI_TUNE_SHIM",
    "coll_shm_max_bytes": "TPU_MPI_COLL_SHM_MAX_BYTES",
    "registered_buffers": "TPU_MPI_REGISTERED_BUFFERS",
    "auto_arm": "TPU_MPI_AUTO_ARM",
    "auto_arm_threshold": "TPU_MPI_AUTO_ARM_THRESHOLD",
    "auto_arm_donate": "TPU_MPI_AUTO_ARM_DONATE",
    "batch_max_ops": "TPU_MPI_BATCH_MAX_OPS",
    "batch_max_bytes": "TPU_MPI_BATCH_MAX_BYTES",
    "pvars": "TPU_MPI_PVARS",
    "pvars_dump": "TPU_MPI_PVARS_DUMP",
    "pvars_hist_bins": "TPU_MPI_PVARS_HIST_BINS",
    "heartbeat_ms": "TPU_MPI_HEARTBEAT_MS",
    "failure_timeout_ms": "TPU_MPI_FAILURE_TIMEOUT_MS",
    "op_timeout_ms": "TPU_MPI_OP_TIMEOUT_MS",
    "serve_socket": "TPU_MPI_SERVE_SOCKET",
    "serve_max_tenants": "TPU_MPI_SERVE_MAX_TENANTS",
    "serve_quota_bytes": "TPU_MPI_SERVE_QUOTA_BYTES",
    "session_token": "TPU_MPI_SESSION_TOKEN",
    "serve_backend": "TPU_MPI_SERVE_BACKEND",
    "serve_brokers": "TPU_MPI_SERVE_BROKERS",
    "serve_shard": "TPU_MPI_SERVE_SHARD",
    "serve_zerocopy": "TPU_MPI_SERVE_ZEROCOPY",
    "serve_router_socket": "TPU_MPI_SERVE_ROUTER_SOCKET",
    "serve_router_mode": "TPU_MPI_SERVE_ROUTER_MODE",
    "serve_transport": "TPU_MPI_SERVE_TRANSPORT",
    "serve_workers": "TPU_MPI_SERVE_WORKERS",
    "serve_lease_window": "TPU_MPI_SERVE_LEASE_WINDOW",
    "infer_slo_ms": "TPU_MPI_INFER_SLO_MS",
    "infer_max_batch": "TPU_MPI_INFER_MAX_BATCH",
    "kv_block_tokens": "TPU_MPI_KV_BLOCK_TOKENS",
    "infer_vectorized": "TPU_MPI_INFER_VECTORIZED",
    "infer_spec_k": "TPU_MPI_INFER_SPEC_K",
    "infer_prefill_chunk": "TPU_MPI_INFER_PREFILL_CHUNK",
    "kv_prefix_share": "TPU_MPI_KV_PREFIX_SHARE",
    "plan_cache_max": "TPU_MPI_PLAN_CACHE_MAX",
    "domains": "TPU_MPI_DOMAINS",
    "hier_min_bytes": "TPU_MPI_HIER_MIN_BYTES",
    "train_bucket_bytes": "TPU_MPI_TRAIN_BUCKET_BYTES",
    "train_shard_state": "TPU_MPI_TRAIN_SHARD_STATE",
    "elastic": "TPU_MPI_ELASTIC",
    "elastic_min_ranks": "TPU_MPI_ELASTIC_MIN_RANKS",
    "elastic_max_ranks": "TPU_MPI_ELASTIC_MAX_RANKS",
    "elastic_interval_ms": "TPU_MPI_ELASTIC_INTERVAL_MS",
    "elastic_cooldown_ms": "TPU_MPI_ELASTIC_COOLDOWN_MS",
    "elastic_hysteresis": "TPU_MPI_ELASTIC_HYSTERESIS",
    "elastic_depth_high": "TPU_MPI_ELASTIC_DEPTH_HIGH",
    "elastic_idle_ticks": "TPU_MPI_ELASTIC_IDLE_TICKS",
    "elastic_sidecars": "TPU_MPI_ELASTIC_SIDECARS",
    "lockcheck": "TPU_MPI_LOCKCHECK",
    "lockcheck_stacks": "TPU_MPI_LOCKCHECK_STACKS",
}

_lock = threading.Lock()
_cached: Optional[Config] = None


def _toml_path() -> str:
    return os.path.expanduser(os.environ.get("TPU_MPI_CONFIG", _DEFAULT_TOML))


def _parse_mini_toml(text: str) -> dict:
    """Vendored minimal TOML reader for Python < 3.11 without tomli: flat
    ``key = value`` pairs with string/bool/int/float values — exactly the
    subset :func:`persist` writes. Tables, arrays and multi-line strings are
    out of scope and rejected loudly rather than misread."""
    out: dict[str, Any] = {}
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("["):
            raise ValueError(f"line {lineno}: TOML tables are not supported "
                             "by the vendored reader (install tomli)")
        if "=" not in line:
            raise ValueError(f"line {lineno}: expected 'key = value'")
        key, _, val = line.partition("=")
        key, val = key.strip(), val.strip()
        if not key:
            raise ValueError(f"line {lineno}: empty key")
        if val.startswith('"'):
            if len(val) < 2 or not val.endswith('"'):
                raise ValueError(f"line {lineno}: unterminated string")
            body = val[1:-1]
            # unescape the two sequences persist() emits (plus common ones)
            out[key] = (body.replace('\\"', '"').replace("\\\\", "\\")
                        .replace("\\n", "\n").replace("\\t", "\t"))
        elif val in ("true", "false"):
            out[key] = val == "true"
        else:
            # strip an inline comment on non-string values
            val = val.split("#", 1)[0].strip()
            try:
                out[key] = int(val)
            except ValueError:
                out[key] = float(val)   # ValueError propagates to the caller
    return out


def _read_toml(path: str) -> dict:
    try:
        import tomllib as _toml              # py>=3.11
    except ImportError:
        try:
            import tomli as _toml            # the PyPI backport, if present
        except ImportError:
            _toml = None
    if _toml is not None:
        try:
            with open(path, "rb") as f:
                return _toml.load(f)
        except FileNotFoundError:
            return {}
        except Exception as e:
            raise MPIError(f"malformed config file {path!r}: {e}") from None
    # py3.10 without tomli: the vendored flat-key reader
    try:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
    except FileNotFoundError:
        return {}
    try:
        return _parse_mini_toml(text)
    except Exception as e:
        raise MPIError(f"malformed config file {path!r}: {e}") from None


def _coerce(name: str, default: Any, raw: Any) -> Any:
    kind = type(default)
    try:
        if kind is bool:
            s = str(raw).lower()
            if s in ("1", "true", "yes", "on"):
                return True
            if s in ("0", "false", "no", "off", ""):
                return False
            raise ValueError(s)
        return kind(raw)
    except (TypeError, ValueError):
        raise MPIError(f"config key {name}={raw!r} is not a valid {kind.__name__}",
                       code=_ec.ERR_ARG) from None


def _validate(cfg: Config) -> None:
    """Range checks for knobs whose type coercion alone cannot catch a
    value that would corrupt downstream state (histogram shapes, ring
    sizes, sampling probabilities). Same loud-failure contract as
    :func:`_coerce`: a bad knob raises ERR_ARG at load, never later."""
    if not (0.0 <= cfg.trace_sample <= 1.0):
        raise MPIError(
            f"config key trace_sample={cfg.trace_sample!r} must be a "
            f"probability in [0.0, 1.0]", code=_ec.ERR_ARG)
    if cfg.flight_ring < 0:
        raise MPIError(
            f"config key flight_ring={cfg.flight_ring!r} must be >= 0 "
            f"(0 disables the flight recorder)", code=_ec.ERR_ARG)
    if cfg.pvars_hist_bins < 1:
        raise MPIError(
            f"config key pvars_hist_bins={cfg.pvars_hist_bins!r} must be "
            f">= 1 (one log2-microsecond bucket minimum)", code=_ec.ERR_ARG)
    if cfg.serve_slo_us < 0:
        raise MPIError(
            f"config key serve_slo_us={cfg.serve_slo_us!r} must be >= 0 "
            f"(0 disables the fleet SLO)", code=_ec.ERR_ARG)


# Bumped whenever the effective config is (re)computed; hot-path callers
# (``_runtime.deadlock_timeout``) key their caches on it so a
# ``load(refresh=True)`` invalidates them without taking the lock per call.
GENERATION = 0


def load(refresh: bool = False) -> Config:
    """The effective configuration (cached after first read)."""
    global _cached, GENERATION
    with _lock:
        if _cached is not None and not refresh:
            return _cached
        GENERATION += 1
        cfg = Config()
        file_vals = _read_toml(_toml_path())
        merged: dict[str, Any] = {}
        for f in fields(Config):
            raw = os.environ.get(_ENV_MAP[f.name])
            if raw is None and f.name in file_vals:
                raw = file_vals[f.name]
            if raw is not None:
                merged[f.name] = _coerce(f.name, getattr(cfg, f.name), raw)
        effective = cfg.replace(**merged)
        _validate(effective)          # raise BEFORE caching a bad config
        _cached = effective
        return _cached


def persist(path: Optional[str] = None, **overrides: Any) -> str:
    """Write the current effective config (plus overrides) as TOML — the
    analog of build.jl persisting JULIA_MPI_* into ~/.julia/prefs/MPI.toml.
    Returns the written path."""
    cfg = load().replace(**overrides)
    path = os.path.expanduser(path or _toml_path())
    os.makedirs(os.path.dirname(path), exist_ok=True)
    lines = []
    for f in fields(Config):
        v = getattr(cfg, f.name)
        if isinstance(v, str):
            sv = '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
        elif isinstance(v, bool):
            sv = "true" if v else "false"
        else:
            sv = repr(v)
        lines.append(f"{f.name} = {sv}")
    with open(path, "w") as fh:
        fh.write("# tpu_mpi persisted preferences (see tpu_mpi.config)\n")
        fh.write("\n".join(lines) + "\n")
    load(refresh=True)
    return path


def get(name: str) -> Any:
    """One config value by key name."""
    cfg = load()
    if not hasattr(cfg, name):
        raise MPIError(f"unknown config key {name!r}", code=_ec.ERR_ARG)
    return getattr(cfg, name)
