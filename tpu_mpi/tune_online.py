"""Online bandit autotuner: live-traffic algorithm selection with hot-swap.

The PR-4 tuner (:mod:`tpu_mpi.tune`) measures offline and serves a static
crossover table; this module closes ROADMAP item 4's loop by tuning *while
serving*. An epsilon-greedy bandit sits at the single algorithm decision
point (``collective._coll_select`` callers route through
:meth:`Online.decide`): on a configurable fraction of live collective
calls (``TPU_MPI_TUNE_EXPLORE``, default off) the call runs an eligible
alternate algorithm instead of the steady selection, the pvar op scope
(:mod:`tpu_mpi.perfvars`) attributes the observed latency to that
``(coll, algo, nbytes, nranks)`` arm as it already does for every
collective, and every ``TPU_MPI_TUNE_SWAP_PERIOD`` decisions the loop
recomputes the crossover table from the accumulated arm statistics and
hot-swaps it through the existing config-generation invalidation of the
selection memo and plan cache — no restart, no extra barrier.

**Lockstep safety (the invariant that makes this sound).** Every tier
gate in this engine is a deterministic function of rank-uniform values so
ranks can never pick different protocols for one round; exploration must
preserve that. Three pieces do:

1. *Deterministic schedule.* Whether call ``c`` of a decision key
   explores is ``int(c * eps) > int((c - 1) * eps)`` — a pure function of
   the per-(rank, cid, coll, nbytes) call counter, which advances
   identically on every rank because MPI programs issue the identical
   collective sequence per communicator.
2. *Shared seeded arm choice.* The explored arm is
   ``alts[crc32(seed|coll|nbytes|nranks|index) % len(alts)]`` — CRC32,
   not Python's per-process-randomized ``hash``, over rank-uniform
   inputs, so all ranks land on the same alternate.
3. *Lockstep table swap.* At a swap milestone every rank reaches the same
   internal collective round (an ordinary rendezvous over the comm) that
   allgathers per-rank arm stats; each rank merges the IDENTICAL
   cross-rank totals and derives the IDENTICAL table. Divergent tables
   are impossible by construction, not by coincidence of timing.

Registered-buffer persistent plans (``Allreduce_init`` rounds) bypass the
per-call decision point by design and therefore never explore; they pick
up a swapped table at their next generation rebind. AUTO-ARMED plans
(ISSUE 11: a plain ``Allreduce`` loop promoted onto the registered path
by ``collective._auto_arm_gate``) inherit the same rule structurally —
the armed runner returns before ``_explore_reduce_variant`` is ever
consulted. Lockstep survives the combination: arming is a deterministic
function of the per-rank call stream (identical across ranks in an SPMD
program), so every rank stops reaching the decision point at the same
call, and under tracing every rank demotes together (trace enablement is
config-global), keeping per-call ``Event.algo`` sequences rank-identical
with auto-arm and ``TPU_MPI_TUNE_EXPLORE`` both on.

The fleet angle — ``python -m tpu_mpi.tune merge`` folding per-rank pvar
dumps and measured tables into one shared database ``select()`` loads —
lives in :mod:`tpu_mpi.tune` (schema 2); this module is the in-process
loop only.
"""

from __future__ import annotations

import sys
import threading
import zlib
from typing import Dict, List, Optional, Tuple

from . import config
from . import perfvars as _pv
from . import tune

__all__ = ["Online", "state", "table", "reset"]

_UNSET = object()
_state_cache: Tuple[object, Optional["Online"]] = (_UNSET, None)
_singleton: Optional["Online"] = None
_warned_pvars = False

# The in-memory hot-swap table, same shape as ``tune.load_table``:
# {(coll, nranks): [(min_bytes, algo), ...]}. Swaps rebind the whole dict
# (never mutate in place) so concurrent readers walk a consistent table.
_table: Dict[Tuple[str, int], List[Tuple[int, str]]] = {}


def table() -> Optional[Dict[Tuple[str, int], List[Tuple[int, str]]]]:
    """The current online crossover table (consulted by ``tune.select``
    between the force-override and the static table layers), or None
    before the first swap."""
    return _table or None


def state() -> Optional["Online"]:
    """The live bandit, or None when exploration is off. Cached on
    ``config.GENERATION`` (the ``perfvars.enabled`` discipline): the
    default exploration-off run pays one tuple compare per decision."""
    global _state_cache, _singleton, _warned_pvars
    cached_gen, st = _state_cache
    if cached_gen == config.GENERATION:
        return st
    cfg = config.load()
    st = None
    if cfg.tune_explore > 0.0:
        if _singleton is None:
            _singleton = Online()
        _singleton.reconfigure(cfg)
        st = _singleton
        if not _pv.enabled() and not _warned_pvars:
            _warned_pvars = True
            print("tpu_mpi: TPU_MPI_TUNE_EXPLORE is set but pvar collection "
                  "is off — the online autotuner explores blind and can "
                  "never swap the table; set TPU_MPI_PVARS=1",
                  file=sys.stderr)
    _state_cache = (config.GENERATION, st)
    return st


class _TLS(threading.local):
    internal = False          # inside the lockstep swap round


class Online:
    """Epsilon-greedy bandit over ``tune.PORTFOLIO``.

    Counters are keyed per **rank** (thread-tier ranks share this process,
    so a process-global counter would advance size-times per round and
    desynchronize the schedule): ``(rank, cid, coll, nbytes)`` for the
    per-key exploration schedule and ``(rank, cid)`` for swap milestones.
    """

    def __init__(self):
        self.lock = threading.Lock()
        self.eps = 0.0
        self.seed = 0
        self.swap_period = 256
        self.min_samples = 8
        self.counts: Dict[Tuple, int] = {}
        self.totals: Dict[Tuple[int, int], int] = {}
        # cid -> (milestone, changed, generation): the recorded outcome of
        # the latest swap round, read by thread-tier sibling ranks so their
        # per-rank swap pvars stay identical (see _swap)
        self._applied: Dict[int, Tuple[int, bool, int]] = {}
        self.swaps = 0
        self._tls = _TLS()

    def reconfigure(self, cfg) -> None:
        """Refresh knobs on a config reload; counters survive so the loop
        keeps its schedule position across its own hot-swaps."""
        self.eps = min(1.0, max(0.0, float(cfg.tune_explore)))
        self.seed = int(cfg.tune_seed)
        self.swap_period = max(1, int(cfg.tune_swap_period))
        self.min_samples = max(1, int(cfg.tune_min_samples))

    def decide(self, comm, coll: str, nbytes: Optional[int], steady: str, *,
               commutative: bool = False, elementwise: bool = False,
               numeric: bool = True, shm: bool = False,
               domains: int = 0) -> str:
        """One algorithm decision on the live path: returns ``steady`` or,
        on this key's deterministic exploration slots, the seeded eligible
        alternate. Ticks the lockstep counters and runs the table-swap
        round at milestones."""
        from ._runtime import current_env
        env = current_env()
        if env is None or self._tls.internal:
            return steady
        nranks = comm.size()
        if nranks < 2:
            return steady
        if coll in tune.parse_override(config.load().coll_algo):
            # a force-pinned collective is never explored: the pin is a
            # debugging/CI contract, and both caches make this check cheap
            return steady
        rank = env[1]
        nb_key = -1 if nbytes is None else int(nbytes)
        key = (rank, comm.cid, coll, nb_key)
        tkey = (rank, comm.cid)
        with self.lock:
            c = self.counts.get(key, 0) + 1
            self.counts[key] = c
            total = self.totals.get(tkey, 0) + 1
            self.totals[tkey] = total
        # explore iff the integer part of c*eps advanced at this call — a
        # deterministic ~eps-fraction schedule with no RNG state to drift
        ei = int(c * self.eps)
        algo = steady
        if ei > int((c - 1) * self.eps):
            alts = [a for a in tune.candidates(
                        coll, nranks, nbytes, commutative=commutative,
                        elementwise=elementwise, shm=shm, numeric=numeric,
                        domains=domains)
                    if a != steady]
            if alts:
                h = zlib.crc32(
                    f"{self.seed}|{coll}|{nb_key}|{nranks}|{ei}".encode())
                algo = alts[h % len(alts)]
        if _pv.enabled():
            _pv.note_explore(comm, algo != steady)
        if total % self.swap_period == 0:
            self._swap(comm, total // self.swap_period)
        return algo

    # -- the lockstep hot-swap round ----------------------------------------

    def _swap(self, comm, milestone: int) -> None:
        """Allgather per-rank arm stats over ``comm`` (an ordinary internal
        rendezvous — every rank reaches this milestone at the same program
        point), merge them, recompute the crossover table, and hot-swap it
        through a config-generation bump when it changed."""
        global _table
        from .collective import _run
        self._tls.internal = True
        try:
            local = _pv.arm_stats(comm)
            merged = _run(comm, local, _merge_arm_stats,
                          f"TuneSwap@{comm.cid}")
        finally:
            self._tls.internal = False
        nranks = comm.size()
        rows = []
        for coll, algo, nbytes, cnt, total_ns in merged:
            if (coll not in tune.PORTFOLIO or cnt < self.min_samples
                    or algo not in tune.PORTFOLIO[coll]):
                continue
            rows.append({"coll": coll, "nranks": nranks,
                         "bytes": max(0, int(nbytes)), "algo": algo,
                         "lat_us": total_ns / cnt / 1e3})
        new_entries = tune._crossovers(rows)
        # Thread-tier ranks share this process (and ``_table``), so the
        # rebind must not be raced: the first rank through a milestone
        # applies it and records the outcome; siblings read the record.
        # That keeps per-rank swap pvars identical and bumps the config
        # generation once per swap, not once per rank. (A rank cannot see
        # a stale slot from the NEXT milestone: overwriting it requires
        # every rank to have passed this milestone's rendezvous first.)
        with self.lock:
            slot = self._applied.get(comm.cid)
            if slot is None or slot[0] != milestone:
                updated = dict(_table)
                changed = False
                for k, ent in new_entries.items():
                    if updated.get(k) != ent:
                        updated[k] = ent
                        changed = True
                if changed:
                    _table = updated          # atomic rebind, then:
                    config.load(refresh=True)  # selection memo misses now
                    self.swaps += 1
                slot = (milestone, changed, config.GENERATION)
                self._applied[comm.cid] = slot
        _, changed, gen = slot
        if changed and _pv.enabled():
            _pv.note_swap(comm, gen)


def _merge_arm_stats(contribs):
    """Combine closure of the swap round: sum per-rank ``(coll, algo,
    nbytes) -> (count, total_ns)`` stats — sample-count-weighted by
    construction — and hand every rank the identical sorted merge."""
    acc: Dict[Tuple[str, str, int], List[int]] = {}
    for rows in contribs:
        for coll, algo, nbytes, cnt, total_ns in rows:
            ent = acc.setdefault((coll, algo, int(nbytes)), [0, 0])
            ent[0] += int(cnt)
            ent[1] += int(total_ns)
    merged = sorted((c, a, b, v[0], v[1]) for (c, a, b), v in acc.items())
    return [merged] * len(contribs)


def reset() -> None:
    """Drop the bandit, its counters, and the online table (tests)."""
    global _state_cache, _singleton, _table, _warned_pvars
    _state_cache = (_UNSET, None)
    _singleton = None
    _table = {}
    _warned_pvars = False
