"""Host-side SPMD runtime: the TPU-native analog of libmpi's progress engine.

The reference launches N OS processes via mpiexec (/root/reference/bin/mpiexecjl:55-64)
and the external C libmpi provides message matching, collective rendezvous and
fate-sharing. On TPU the idiomatic model is a *single controller process* owning
all local devices, so this runtime executes N ranks as threads of one process:

- each rank is a thread with thread-local identity (``current_env``),
- point-to-point messages move zero-copy through per-rank :class:`Mailbox` objects
  with full MPI matching semantics (tags, ANY_SOURCE/ANY_TAG, non-overtaking order,
  Probe on unexpected messages) — the analog of libmpi's matching engine,
- collectives rendezvous through per-communicator :class:`CollectiveChannel`
  objects; the last rank to arrive performs the combine (data placement happens
  in shared memory / on device, so the "network" is a pointer exchange),
- a failure in any rank fate-shares the whole job (test/runtests.jl:37-39 asserts
  a single rank's error fails the run): every blocking wait polls the context's
  failure flag and raises :class:`~tpu_mpi.error.AbortError`.

Multi-process (one process per host over DCN) reuses the same Mailbox/Channel
interfaces backed by the socket transport in ``tpu_mpi.backend``.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Callable, Optional, Sequence

from . import locksmith
from .error import (AbortError, CollectiveMismatchError, DeadlockError,
                    MPIError, ProcFailedError, RevokedError, SessionError)
from . import perfvars as _pv

# per-instance witness-name sequence for Mailbox/CollectiveChannel locks
_lock_seq = itertools.count(1)

# Wildcards / sentinels (values mirror the MPI spec's spirit; they are our own).
ANY_SOURCE = -2
ANY_TAG = -1
PROC_NULL = -3
UNDEFINED = -32766

_dt_cache: tuple = (None, -1, 60.0)     # (env raw, config generation, value)


def deadlock_timeout() -> float:
    """Seconds a blocking wait may stall before DeadlockError. Read per wait
    (env var first for test-time overrides, then the config module) so a
    runtime change takes effect without re-importing. Cached on the exact
    env string + config generation (P2P hot path: this runs once per
    blocking receive).

    When event tracing is on (config knob ``trace`` / env ``TPU_MPI_TRACE``),
    the raised DeadlockError carries the tpu_mpi.analyze dump of per-rank
    pending operations and the wait-for cycle — see docs/analysis.md."""
    global _dt_cache
    from . import config
    raw = os.environ.get("TPU_MPI_DEADLOCK_TIMEOUT")
    craw, cgen, cval = _dt_cache
    if raw == craw and cgen == config.GENERATION:
        return cval
    val = None
    if raw is not None:
        try:
            val = float(raw)
        except ValueError:
            val = None
    if val is None:
        val = config.load().deadlock_timeout
    _dt_cache = (raw, config.GENERATION, val)
    return val


_ot_cache: tuple = (None, -1, 0.0)      # (env raw, config generation, value)


def op_timeout() -> float:
    """Per-op deadline in SECONDS (knob ``TPU_MPI_OP_TIMEOUT_MS``); 0 =
    disabled (the default). When set, every blocking recv / request Wait /
    collective wait clamps its budget to min(deadlock_timeout, this), so a
    silently dead peer fails the op loudly — with the per-rank pending-op
    dump — well before the 60 s deadlock budget. Cached like
    :func:`deadlock_timeout` (same hot path)."""
    global _ot_cache
    from . import config
    raw = os.environ.get("TPU_MPI_OP_TIMEOUT_MS")
    craw, cgen, cval = _ot_cache
    if raw == craw and cgen == config.GENERATION:
        return cval
    val = None
    if raw is not None:
        try:
            val = float(raw) / 1000.0
        except ValueError:
            val = None
    if val is None:
        val = config.load().op_timeout_ms / 1000.0
    _ot_cache = (raw, config.GENERATION, val)
    return val


def _default_wait_budget() -> float:
    """The budget of a wait that gave no explicit timeout/limit: the
    deadlock timeout, tightened by the op deadline when that knob is on."""
    budget = deadlock_timeout()
    ot = op_timeout()
    if ot > 0:
        budget = min(budget, ot)
    return budget


_POLL = 0.02


def raise_deadlock(ctx, msg: str) -> None:
    """Raise DeadlockError, appending the tpu_mpi.analyze dump of per-rank
    pending operations + the wait-for cycle when tracing recorded one
    (docs/analysis.md). Never fails for a reason other than the deadlock."""
    try:
        from .analyze.matcher import deadlock_report
        report = deadlock_report(ctx)
    except Exception:
        report = ""
    if report:
        msg = f"{msg}\n{report}"
    raise DeadlockError(msg)


_tls = threading.local()


_process_env: Optional[tuple["SpmdContext", int]] = None


def current_env() -> Optional[tuple["SpmdContext", int]]:
    """Return (context, rank) for the calling thread, or None outside SPMD.

    Falls back to the process-global binding set by the multi-process tier:
    there a process IS one rank, so every thread of it may call MPI
    (THREAD_MULTIPLE semantics) without the explicit set_env attachment the
    thread-rank tier needs (where several ranks share one process)."""
    env = getattr(_tls, "env", None)
    return env if env is not None else _process_env


def set_env(env: Optional[tuple["SpmdContext", int]]) -> None:
    _tls.env = env


def set_process_env(env: Optional[tuple["SpmdContext", int]]) -> None:
    """Bind the whole process to one rank (multi-process tier only)."""
    global _process_env
    _process_env = env


def require_env() -> tuple["SpmdContext", int]:
    env = current_env()
    if env is None:
        raise MPIError("MPI has not been initialized on this thread; call Init() "
                       "or run under spmd_run()/tpurun")
    return env


def current_tenant() -> Optional[str]:
    """Tenant id the calling thread executes on behalf of (serve tier),
    or None for single-tenant / non-broker execution."""
    return getattr(_tls, "tenant", None)


def set_current_tenant(tenant: Optional[str]) -> None:
    """Bind the calling thread to a tenant (broker worker threads only:
    every cid allocated and every collective channel touched while bound is
    attributed to — and confined to — that tenant's leased namespace)."""
    _tls.tenant = tenant


class CidNamespace:
    """A tenant's disjoint slice of the communicator context-id space
    (docs/serving.md). ``alloc`` is the only mutation; exhaustion is a
    typed error rather than a silent spill into a neighbor's range."""

    __slots__ = ("tenant", "base", "limit", "_next", "_lock")

    def __init__(self, tenant: str, base: int, limit: int):
        self.tenant = tenant
        self.base = base          # first cid of the range (== the world cid)
        self.limit = limit        # one past the last usable cid
        self._next = base
        self._lock = locksmith.make_lock(f"ns[{tenant}]")

    def alloc(self) -> int:
        with self._lock:
            if self._next >= self.limit:
                raise SessionError(
                    f"tenant {self.tenant!r} exhausted its cid namespace "
                    f"[{self.base}, {self.limit}) — free communicators or "
                    f"lease a wider span")
            cid = self._next
            self._next += 1
            return cid

    def owns(self, cid: Any) -> bool:
        return isinstance(cid, int) and self.base <= cid < self.limit

    def __repr__(self) -> str:
        return (f"<CidNamespace {self.tenant} [{self.base},{self.limit}) "
                f"next={self._next}>")


_UNSET_CID = object()   # "derive fault_cid from the waitable" sentinel


class _Waitable:
    """Mixin: condition-variable wait loop with failure + deadlock checks."""

    ctx: "SpmdContext"
    cond: threading.Condition

    def _wait_for(self, pred: Callable[[], bool], what: str,
                  timeout: Optional[float] = None,
                  limit: Optional[float] = None,
                  fault_cid: Any = _UNSET_CID) -> bool:
        """Wait (cond held) until pred() or failure/deadlock. Returns pred().

        ``timeout`` makes expiry return False (Test*-style polling);
        ``limit`` overrides the deadlock budget but keeps the raising
        semantics (ops that legitimately outlast it, e.g. Comm_spawn's
        child-process rendezvous). ``fault_cid`` names the communicator for
        the revoked-comm fault surface; by default it is read off the
        waitable itself (channels carry a ``cid`` attribute)."""
        if timeout is not None:
            limit = timeout
        elif limit is None:
            limit = _default_wait_budget()
        deadline = time.monotonic() + limit
        if fault_cid is _UNSET_CID:
            fault_cid = getattr(self, "cid", None)
        while not pred():
            self.ctx.check_failure()
            self.ctx.check_fault(fault_cid)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                if timeout is not None:
                    return False
                raise_deadlock(self.ctx,
                               f"deadlock suspected: blocked >{limit}s in {what}")
            self.cond.wait(min(_POLL, remaining))
        return True


def collective_wait_limit(opname: str) -> Optional[float]:
    """Per-op override of the deadlock budget: a Comm_spawn collective
    legitimately blocks while child processes boot (cold interpreter + jax
    import), so non-root ranks wait with the rendezvous budget, not the
    60 s deadlock one."""
    if opname.startswith("Comm_spawn"):
        from . import config
        return max(deadlock_timeout(), config.load().rendezvous_timeout)
    return None


def pump_wait(ctx, cond, pred: Callable[[], bool], what: str, *,
              timeout: Optional[float] = None,
              limit: Optional[float] = None,
              fault_cid: Any = None, fault: bool = True) -> bool:
    """Blocked-waiter loop driving the context's direct transport pump
    (VERDICT r3 #4). The single implementation behind Mailbox receives,
    ProcChannel collective waits and RmaEngine response waits: cond's lock
    must be held exactly once on entry; the loop releases it around each
    pump so deliveries (which take the same lock) can land. Returns pred()
    — False only in ``timeout`` mode; raises DeadlockError past the budget
    otherwise; ``limit`` overrides the budget but keeps raising semantics.

    ``fault_cid`` names the communicator the wait belongs to (RevokedError
    surface); ``fault=False`` suppresses the fault checks entirely — the
    recovery protocol (Comm_agree/Comm_shrink) must keep communicating
    while peers are known dead."""
    if timeout is not None:
        budget = timeout
    elif limit is not None:
        budget = limit
    else:
        budget = _default_wait_budget()
    deadline = time.monotonic() + budget
    ctx._pump_begin()
    try:
        while not pred():
            ctx.check_failure()
            if fault:
                ctx.check_fault(fault_cid)
            if time.monotonic() >= deadline:
                if timeout is not None:
                    return False
                raise_deadlock(
                    ctx, f"deadlock suspected: blocked >{budget}s in {what}")
            cond.release()
            try:
                pumped = ctx._direct_pump(0.02, pred)
            finally:
                cond.acquire()
            if not pumped:
                # pump busy (a sibling holds the lease) or idle socket:
                # brief cond wait keeps us responsive to wakeups
                cond.wait(0.002)
    finally:
        ctx._pump_end()
    return True


class Message:
    """An in-flight point-to-point message (typed buffer or serialized object)."""

    __slots__ = ("src", "tag", "cid", "payload", "count", "dtype", "kind",
                 "seq")

    def __init__(self, src: int, tag: int, cid: int, payload: Any,
                 count: int, dtype: Any, kind: str,
                 seq: Optional[int] = None):
        self.src = src
        self.tag = tag
        self.cid = cid
        self.payload = payload
        self.count = count      # element count (typed) or byte length (object)
        self.dtype = dtype
        self.kind = kind        # "typed" | "object"
        self.seq = seq          # debug sequence-check stamp (None = off)


class PendingRecv:
    """A posted receive awaiting a matching message (Irecv/Recv)."""

    __slots__ = ("src", "tag", "cid", "msg", "done", "cancelled")

    def __init__(self, src: int, tag: int, cid: int):
        self.src = src
        self.tag = tag
        self.cid = cid
        self.msg: Optional[Message] = None
        self.done = False
        self.cancelled = False

    def matches(self, m: Message) -> bool:
        # ANY_TAG is a USER wildcard: it must not capture internal
        # tuple-tagged lanes (partitioned traffic uses ("part", tag) —
        # MPI-4 forbids partitioned transfers matching normal wildcard
        # receives). An explicit tuple tag still matches exactly.
        return (m.cid == self.cid
                and (self.src == ANY_SOURCE or self.src == m.src)
                and ((self.tag == ANY_TAG and not isinstance(m.tag, tuple))
                     or self.tag == m.tag))


class Mailbox(_Waitable):
    """Per-rank message matching engine.

    Preserves MPI non-overtaking order: messages are matched FIFO, posted
    receives are matched FIFO, and an incoming message first tries pending
    receives before landing on the unexpected queue (where Probe sees it).
    Mirrors the matching semantics the reference gets from libmpi
    (/root/reference/src/pointtopoint.jl:121-148, :271-346).
    """

    def __init__(self, ctx: "SpmdContext"):
        self.ctx = ctx
        # RLock: ctx.fail() may notify a condition whose lock the failing
        # thread itself holds (observed self-deadlock on collective mismatch).
        # Witness names are per-instance: two mailboxes' locks are distinct
        # order-graph nodes, not one shared node with self-edges.
        name = f"mailbox[{next(_lock_seq)}]"
        self.lock = locksmith.make_rlock(name)
        self.cond = locksmith.make_condition(name, self.lock)
        self.queue: list[Message] = []        # unexpected messages, FIFO
        self.recvs: list[PendingRecv] = []    # posted receives, FIFO
        self.queued_bytes = 0                 # unexpected-queue footprint
        self._seq_seen: dict = {}             # (src, cid) -> last debug seq
        # called (lock held) with queued_bytes after a queue removal; the
        # multi-process backend hangs its unchoke logic here — hooks must
        # not perform I/O (the lock is the drainer's delivery path)
        self.drain_hook: Optional[Callable[[int], None]] = None
        # called (lock held) when a receive is posted with no queued match:
        # the receiver is actively waiting, possibly for a choked sender's
        # message it cannot see — the backend unchokes everyone (restores
        # the posted-receive admission bypass across processes)
        self.pending_recv_hook: Optional[Callable[[], None]] = None
        # blocked-receiver direct drain (VERDICT r3 #4): when set (the
        # multi-process backend's pump), a rank blocked in Recv/Wait/Probe
        # polls its own transport connection instead of condition-waiting
        # for the drainer thread — removing the drainer→mailbox→scheduler
        # hops from the small-message latency path. Signature:
        # pump(timeout_s) -> bool (whether a frame was delivered); must be
        # called WITHOUT the mailbox lock held. pump_begin/pump_end bracket
        # the whole wait: the backend parks its drainer thread in between,
        # so the waiting rank owns the socket and the drainer burns no CPU
        # (essential on small-core hosts).
        self.direct_pump: Optional[Callable[[float], bool]] = None
        self.pump_begin: Optional[Callable[[], None]] = None
        self.pump_end: Optional[Callable[[], None]] = None

    @staticmethod
    def _nbytes(msg: Message) -> int:
        nb = getattr(msg.payload, "nbytes", None)
        if nb is not None:
            return int(nb)
        return len(msg.payload) if isinstance(msg.payload, (bytes, bytearray)) else 0

    def post(self, msg: Message) -> None:
        """Deliver a message (called from the sender's thread)."""
        with self.cond:
            self._post_locked(msg)

    def post_blocking(self, msg: Message, what: str) -> None:
        """Deliver with flow control (libmpi's rendezvous-protocol analog,
        VERDICT r1 'no backpressure'): used by BLOCKING sends only — Isend
        keeps its buffered never-blocks semantics. Admit immediately when a
        posted receive matches (the message bypasses the unexpected queue),
        when the queue is empty (one oversized message always goes through),
        or when it fits under the high-water mark; otherwise wait. The check
        and the delivery happen under one lock hold, so concurrent senders
        serialize and cannot overshoot the mark together. A send that can
        never drain (receiver never posts a recv) surfaces as DeadlockError,
        which is exactly what that program is."""
        from . import config
        high = config.load().send_highwater_bytes
        with self.cond:
            if high > 0:
                nb = self._nbytes(msg)

                def admissible() -> bool:
                    if any(not pr.cancelled and pr.matches(msg)
                           for pr in self.recvs):
                        return True
                    return not self.queue or self.queued_bytes + nb <= high

                # Progress-aware deadlock budget (ADVICE r2): a receiver
                # that drains slowly-but-steadily is making progress, not
                # deadlocking — each observed shrink of the unexpected
                # queue restarts the budget (each _wait_for call takes a
                # fresh deadline). Only a genuinely stuck queue raises.
                floor = self.queued_bytes
                while not admissible():
                    self._wait_for(
                        lambda: admissible() or self.queued_bytes < floor,
                        f"{what} (destination unexpected-queue over "
                        f"high-water mark)")
                    floor = min(floor, self.queued_bytes)
            self._post_locked(msg)

    def _post_locked(self, msg: Message) -> None:
        if msg.seq is not None:
            # debug sequence check (SURVEY.md §5 race detection): every
            # sender stamps a per-(sender, cid) counter; delivery must see
            # it strictly increasing — a reordered/duplicated/lost frame in
            # any transport tier fails loudly here instead of corrupting
            # matching order silently.
            key = (msg.src, msg.cid)
            last = self._seq_seen.get(key, 0)
            if msg.seq != last + 1:
                err = MPIError(
                    f"P2P sequence violation from comm-rank {msg.src} "
                    f"cid {msg.cid}: got #{msg.seq} after #{last} "
                    f"(reordered, duplicated, or dropped message)")
                self.ctx.fail(err)
                raise err
            self._seq_seen[key] = msg.seq
        for pr in self.recvs:
            if not pr.cancelled and pr.matches(msg):
                self.recvs.remove(pr)
                pr.msg = msg
                pr.done = True
                self.cond.notify_all()
                return
        self.queue.append(msg)
        self.queued_bytes += self._nbytes(msg)
        self.cond.notify_all()

    def _match_or_subscribe_locked(self, pr: PendingRecv) -> bool:
        """Match pr against the unexpected queue (oldest first) or append
        it to the posted-receive list. True = matched now (pr.msg set).
        Caller holds the lock; shared by post_recv and recv_blocking so
        the blocking and nonblocking paths cannot diverge."""
        for m in self.queue:
            if pr.matches(m):
                self.queue.remove(m)
                self.queued_bytes -= self._nbytes(m)
                pr.msg = m
                pr.done = True
                self.cond.notify_all()       # senders blocked on capacity
                if self.drain_hook is not None:
                    self.drain_hook(self.queued_bytes)
                return True
        self.recvs.append(pr)
        if self.pending_recv_hook is not None:
            self.pending_recv_hook()
        return False

    def post_recv(self, src: int, tag: int, cid: int) -> PendingRecv:
        """Post a receive; matches the oldest queued message first (Irecv!)."""
        pr = PendingRecv(src, tag, cid)
        with self.cond:
            self._match_or_subscribe_locked(pr)
        return pr

    def _wait_for_rx(self, pred: Callable[[], bool], what: str,
                     cid: Any = None) -> None:
        """Receive-side wait (cond held on entry): like _wait_for, but when
        the backend provides :attr:`direct_pump`, this thread drains its own
        transport connection while it waits — no drainer hop. Falls back to
        a short condition wait whenever the pump is busy (the drainer or a
        sibling thread holds it), so THREAD_MULTIPLE receivers and the
        drainer interleave safely. ``cid`` names the communicator for the
        revoked-comm fault surface."""
        if self.direct_pump is None:
            self._wait_for(pred, what, fault_cid=cid)
            return
        pump_wait(self.ctx, self.cond, pred, what, fault_cid=cid)

    def _await_locked(self, pr: PendingRecv) -> Optional[Message]:
        """Wait for pr under the held lock; returns None if cancelled.
        Shared tail of wait_recv and recv_blocking."""
        self._wait_for_rx(lambda: pr.done or pr.cancelled, "Recv/Wait",
                          cid=pr.cid)
        if pr.cancelled and not pr.done:
            if pr in self.recvs:
                self.recvs.remove(pr)
            return None
        return pr.msg

    def wait_recv(self, pr: PendingRecv) -> Optional[Message]:
        """Block until pr completes (Wait!); returns None if cancelled."""
        with self.cond:
            return self._await_locked(pr)

    def recv_blocking(self, src: int, tag: int, cid) -> Optional[Message]:
        """Blocking-receive fast path: post_recv + wait_recv fused into ONE
        lock entry (the small-message latency lane — a second lock round
        trip per message is measurable on 1-core hosts). Semantically
        identical to post_recv followed by wait_recv; blocking receives
        expose no cancel handle, so None is only a failure surface."""
        with self.cond:
            # exact-(src, tag) head match: the already-arrived case (the
            # receiver runs behind the sender) completes with no PendingRecv
            # allocation and no matches() calls. Only the queue HEAD is
            # eligible — FIFO matching means an exact receive may not
            # overtake an older queued message it also matches.
            if self.queue and src >= 0 and not isinstance(tag, tuple):
                m = self.queue[0]
                if m.cid == cid and m.src == src and m.tag == tag:
                    self.queue.pop(0)
                    self.queued_bytes -= self._nbytes(m)
                    self.cond.notify_all()   # senders blocked on capacity
                    if self.drain_hook is not None:
                        self.drain_hook(self.queued_bytes)
                    return m
            pr = PendingRecv(src, tag, cid)
            if self._match_or_subscribe_locked(pr):
                return pr.msg
            return self._await_locked(pr)

    def test_recv(self, pr: PendingRecv) -> bool:
        with self.cond:
            return pr.done or pr.cancelled

    def cancel(self, pr: PendingRecv) -> None:
        """Cancel a posted receive (src/pointtopoint.jl:677-681)."""
        with self.cond:
            if not pr.done:
                pr.cancelled = True
                if pr in self.recvs:
                    self.recvs.remove(pr)
                self.cond.notify_all()

    def probe(self, src: int, tag: int, cid: int, block: bool) -> Optional[Message]:
        """Find (without removing) a matching unexpected message (Probe/Iprobe)."""
        probe_pr = PendingRecv(src, tag, cid)
        with self.cond:
            def find() -> Optional[Message]:
                for m in self.queue:
                    if probe_pr.matches(m):
                        return m
                return None
            if not block:
                return find()
            self._wait_for_rx(lambda: find() is not None, "Probe", cid=cid)
            return find()

    def notify(self) -> None:
        with self.cond:
            self.cond.notify_all()


_EMPTY = object()   # distinct "no contribution yet" marker (None is a valid payload)


class CollectiveChannel(_Waitable):
    """Reusable all-rank rendezvous for one communicator, ROUND-KEYED.

    Every collective round: each rank deposits a contribution; the last
    arriver runs ``combine(contribs) -> per-rank results`` (doing any data
    placement — all buffers are visible in the shared address space / on
    device); every rank picks up its slot.

    Rounds are numbered per rank and rendezvous state lives in a per-round
    slot (the multi-process ``ProcChannel`` round-counter pattern), so a
    rank that picked its round-k result enters round k+1 IMMEDIATELY —
    no wait for slow peers to drain round k. The original single-slot
    design paid two full condition barriers per op (previous-round drain +
    last-picker reset); head-of-line blocking across back-to-back ops was
    the largest share of the host-lane dispatch overhead (ISSUE-3,
    ``BENCH_r05.json`` host_lane.overhead_ms). At most two rounds are ever
    live: round k+1 cannot complete its rendezvous before every rank
    arrived in it, which requires every rank to have picked (and thereby
    freed) round k.

    The ``opname`` tag is checked across ranks every round — calling
    mismatched collectives on one communicator raises
    CollectiveMismatchError in all ranks instead of deadlocking (SURVEY.md
    §5 "race detection").
    """

    def __init__(self, ctx: "SpmdContext", size: int):
        self.ctx = ctx
        self.size = size
        # see Mailbox.__init__ on reentrancy + per-instance witness names
        name = f"channel[{next(_lock_seq)}]"
        self.lock = locksmith.make_rlock(name)
        self.cond = locksmith.make_condition(name, self.lock)
        # per-rank next-round counters + live per-round rendezvous slots
        self.rank_round = [0] * size
        self.rounds: dict[int, dict] = {}

    def _round_state(self, rnd: int) -> dict:
        st = self.rounds.get(rnd)
        if st is None:
            st = self.rounds[rnd] = {
                "contribs": [_EMPTY] * self.size, "arrived": 0,
                "results": None, "picked": 0, "opname": None}
        return st

    def run(self, rank: int, contrib: Any, combine: Callable[[list[Any]], Sequence[Any]],
            opname: str, plan=None, unlocked_fold: bool = False) -> Any:
        # ``plan`` (an algorithm hint for the multi-process tier) is ignored
        # here: threads share an address space, so the combine-in-place star
        # IS the optimal algorithm — data placement is a pointer exchange.
        #
        # ``unlocked_fold`` (registered fast path): the last arriver runs the
        # combine with the channel lock RELEASED. Safe exactly then: the
        # combine folds into a plan-private registered scratch (no shared
        # rendezvous state touched), all peer ranks of THIS round are parked
        # in cond.wait, and no rank can arrive in round k+1 before picking
        # round k — so nothing else can mutate the round slot while the lock
        # is down, and waiters, P2P progress and other communicators never
        # contend with a long fold for the condvar.
        self.cond.acquire()
        try:
            rnd = self.rank_round[rank]
            self.rank_round[rank] += 1
            st = self._round_state(rnd)
            if st["opname"] is None:
                st["opname"] = opname
            elif st["opname"] != opname:
                err = CollectiveMismatchError(
                    f"rank {rank} called {opname!r} while other ranks are in "
                    f"{st['opname']!r} on the same communicator")
                self.ctx.fail(err)
                raise err
            st["contribs"][rank] = contrib
            st["arrived"] += 1
            # pvar phase spans: last arriver's combine is the fold; every
            # other rank's block below is the rendezvous. One TLS read when
            # no scope is open (pvars and tracing both off).
            sc = _pv.scope()
            if st["arrived"] == self.size:
                contribs = list(st["contribs"])
                t0 = _pv.monotonic() if sc is not None else 0.0
                try:
                    if unlocked_fold:
                        self.cond.release()
                        try:
                            results = list(combine(contribs))
                        finally:
                            self.cond.acquire()
                    else:
                        results = list(combine(contribs))
                except BaseException as e:
                    self.ctx.fail(e)
                    raise
                if sc is not None:
                    sc.spans.append(("fold", t0, _pv.monotonic()))
                if len(results) != self.size:
                    err = MPIError(f"combine for {opname} returned {len(results)} "
                                   f"results for {self.size} ranks")
                    self.ctx.fail(err)
                    raise err
                st["results"] = results
                st["contribs"] = []      # contributions are dead: release refs
                self.cond.notify_all()
            else:
                t0 = _pv.monotonic() if sc is not None else 0.0
                self._wait_for(lambda: st["results"] is not None,
                               f"collective {opname}",
                               limit=collective_wait_limit(opname))
                if sc is not None:
                    sc.spans.append(("rendezvous", t0, _pv.monotonic()))
            res = st["results"][rank]
            st["picked"] += 1
            if st["picked"] == self.size:
                self.rounds.pop(rnd, None)   # fully drained; no reset barrier
            return res
        finally:
            self.cond.release()

    def run_batch(self, rank: int, ops: Sequence[tuple]) -> list:
        """Deposit K queued collective rounds through ONE lock acquisition
        and ONE wakeup (ISSUE-11 batched submission), then collect each
        round's result in Start order. ``ops`` is a sequence of
        ``(contrib, combine, opname, unlocked_fold)`` tuples.

        Correctness rides on the same round-keyed slots as :meth:`run`:
        each round's slot is independent, a round folds only once ALL
        ranks arrived in it, and folds serialize through the slowest
        depositor — a rank cannot complete round r+1 before every rank
        (including any rank still folding round r) deposited it. A
        batching rank pairs correctly with peers running the same rounds
        one ``run`` at a time: rounds are numbered per rank, not per call
        style. The ``run`` docstring's "at most two rounds live" bound
        relaxes to "at most two plus the largest in-flight batch"."""
        n = len(ops)
        if n == 0:
            return []
        if n == 1:
            contrib, combine, opname, ufold = ops[0]
            return [self.run(rank, contrib, combine, opname,
                             unlocked_fold=ufold)]
        sc = _pv.scope()
        deposited = []          # (rnd, st, opname) in Start order
        self.cond.acquire()
        try:
            fold_pending = False
            for contrib, combine, opname, ufold in ops:
                rnd = self.rank_round[rank]
                self.rank_round[rank] += 1
                st = self._round_state(rnd)
                if st["opname"] is None:
                    st["opname"] = opname
                elif st["opname"] != opname:
                    err = CollectiveMismatchError(
                        f"rank {rank} called {opname!r} while other ranks "
                        f"are in {st['opname']!r} on the same communicator")
                    self.ctx.fail(err)
                    raise err
                st["contribs"][rank] = contrib
                st["arrived"] += 1
                if st["arrived"] == self.size:
                    contribs = list(st["contribs"])
                    t0 = _pv.monotonic() if sc is not None else 0.0
                    try:
                        if ufold:
                            # safe for the same reason as in run(): this
                            # round's slot can take no more deposits
                            # (arrived == size) and waiters re-check
                            # results only under the lock
                            self.cond.release()
                            try:
                                results = list(combine(contribs))
                            finally:
                                self.cond.acquire()
                        else:
                            results = list(combine(contribs))
                    except BaseException as e:
                        self.ctx.fail(e)
                        raise
                    if sc is not None:
                        sc.spans.append(("fold", t0, _pv.monotonic()))
                    if len(results) != self.size:
                        err = MPIError(
                            f"combine for {opname} returned {len(results)} "
                            f"results for {self.size} ranks")
                        self.ctx.fail(err)
                        raise err
                    st["results"] = results
                    st["contribs"] = []
                    fold_pending = True
                deposited.append((rnd, st, opname))
            if fold_pending:
                self.cond.notify_all()   # one wakeup for the whole batch
            out = []
            for rnd, st, opname in deposited:
                if st["results"] is None:
                    t0 = _pv.monotonic() if sc is not None else 0.0
                    self._wait_for(lambda st=st: st["results"] is not None,
                                   f"collective {opname}",
                                   limit=collective_wait_limit(opname))
                    if sc is not None:
                        sc.spans.append(
                            ("rendezvous", t0, _pv.monotonic()))
                out.append(st["results"][rank])
                st["picked"] += 1
                if st["picked"] == self.size:
                    self.rounds.pop(rnd, None)
            return out
        finally:
            self.cond.release()


class SpmdContext:
    """State shared by all ranks of one SPMD job (the "world").

    Analog of what mpiexec + libmpi set up before/at MPI_Init
    (/root/reference/src/environment.jl:80-89): fixed world size, per-rank
    mailboxes, communicator context-id allocation, and fate-sharing.
    """

    def __init__(self, size: int, universe_size: Optional[int] = None):
        if size < 1:
            raise ValueError("world size must be >= 1")
        self.size = size
        self.universe_size = universe_size if universe_size is not None else size
        self.mailboxes = [Mailbox(self) for _ in range(size)]
        self._channels: dict[int, CollectiveChannel] = {}
        self._channels_lock = locksmith.make_lock("ctx.channels")
        # cid 0 = COMM_WORLD, 1 = COMM_SELF; dynamic cids start at 2.
        self._next_cid = itertools.count(2)
        self.failure: Optional[BaseException] = None
        self.failed_rank: Optional[int] = None
        self._failure_lock = locksmith.make_lock("ctx.failure")
        # ULFM fault state (docs/fault-tolerance.md): world ranks the
        # failure detector declared dead, ranks that left cleanly (Finalize
        # with detection on — NOT failures), and revoked communicator cids.
        # All empty in the default fault-free configuration; check_fault is
        # then two truth tests per wait iteration.
        self.failed_ranks: set[int] = set()
        self.departed_ranks: set[int] = set()
        self.revoked_cids: set = set()
        # Multi-tenant serve tier (docs/serving.md): tenant -> leased cid
        # namespace. Empty outside a broker — the cross-tenant channel guard
        # is then a single truth test (pay-for-use, like the fault path).
        self.cid_namespaces: dict[str, CidNamespace] = {}
        self._ns_lock = locksmith.make_lock("ctx.ns")
        self._ns_next_base = 1 << 20   # far above itertools.count(2)'s reach
        # Per-rank lifecycle flags (src/environment.jl:267-287 queries).
        self.initialized = [False] * size
        self.finalized = [False] * size
        self.thread_level = [None] * size
        self.main_threads: list[Optional[int]] = [None] * size
        # Attribute store for windows/files keyed by (kind, id).
        self.objects: dict[Any, Any] = {}
        self.objects_lock = locksmith.make_lock("ctx.objects")
        # Dynamic process management (src/comm.jl:123-162): each world rank
        # belongs to a "job world" — its own COMM_WORLD group + context id.
        # Spawned groups get a fresh world (MPI gives spawned jobs their own
        # MPI_COMM_WORLD); the parent side sees them only via the intercomm.
        self.worlds: dict[int, tuple[tuple[int, ...], Any]] = {
            r: (tuple(range(size)), 0) for r in range(size)}
        self.parent_comm: dict[int, Any] = {}     # spawned rank -> intercomm
        self.spawn_argv: dict[int, list] = {}     # spawned rank -> its argv
        # debug sequence-check counters: (dest_world, cid, src_comm_rank)
        self._seq_counters: dict = {}
        self._seq_lock = locksmith.make_lock("ctx.seq")
        self.spawned_threads: list[threading.Thread] = []
        self._spawn_lock = locksmith.make_lock("ctx.spawn")

    @property
    def host_token(self) -> str:
        """Identity of the shared-memory domain this rank lives in
        (src/comm.jl:107-115 MPI_COMM_TYPE_SHARED semantics). All
        rank-threads of one controller process trivially share memory; the
        multi-process context overrides this with the rank's transport
        address host (or the TPU_MPI_HOST_ID override)."""
        return "local"

    # -- failure fate-sharing ------------------------------------------------
    def fail(self, exc: BaseException, rank: Optional[int] = None) -> None:
        with self._failure_lock:
            if self.failure is None:
                self.failure = exc
                self.failed_rank = rank
        for mb in self.mailboxes:
            mb.notify()
        with self._channels_lock:
            chans = list(self._channels.values())
        for ch in chans:
            with ch.cond:
                ch.cond.notify_all()

    def check_failure(self) -> None:
        if self.failure is not None:
            raise AbortError(
                f"job aborted ({type(self.failure).__name__}: {self.failure})"
                + (f" originating on rank {self.failed_rank}" if self.failed_rank is not None else ""))

    # -- ULFM fault surface (docs/fault-tolerance.md) -------------------------
    def _notify_waiters(self) -> None:
        """Wake every blocked wait loop so it re-runs its fault checks."""
        for mb in self.mailboxes:
            mb.notify()
        with self._channels_lock:
            chans = list(self._channels.values())
        for ch in chans:
            with ch.cond:
                ch.cond.notify_all()

    def peer_failed(self, rank: int) -> None:
        """Record a peer's death (failure-detector verdict: heartbeat
        silence past the timeout, or a closed/refused transport socket) and
        wake all waiters — they raise ProcFailedError instead of hanging."""
        if rank in self.failed_ranks:
            return
        with self._failure_lock:
            self.failed_ranks.add(rank)
        self._notify_waiters()

    def peer_departed(self, rank: int) -> None:
        """Record a peer's CLEAN exit (it announced Finalize before closing
        its sockets); the detector must not count it as a failure."""
        self.departed_ranks.add(rank)

    def revoke_comm(self, cid) -> None:
        """Mark a communicator revoked; every pending and future op on it
        raises RevokedError deterministically (Comm_revoke's local half)."""
        if cid in self.revoked_cids:
            return
        self.revoked_cids.add(cid)
        self._notify_waiters()

    def check_fault(self, cid=None) -> None:
        """Raise the typed ULFM error for the current fault state:
        RevokedError when the op's communicator was revoked, ProcFailedError
        when the failure detector has declared a peer of the op's
        communicator dead. When the communicator's group is known (its
        collective channel exists — Comm_shrink registers one eagerly), only
        deaths INSIDE the group raise, so a shrunk survivor communicator
        keeps operating after the failure; with no group to consult the
        check is pessimistic. The recovery protocol itself
        (Comm_agree/Comm_shrink) bypasses this check."""
        if self.revoked_cids and cid is not None and cid in self.revoked_cids:
            raise RevokedError(
                f"communicator (cid={cid}) was revoked after a failure; "
                f"only Comm_shrink/Comm_agree remain legal on it")
        if self.failed_ranks:
            if isinstance(cid, tuple) and cid and cid[0] == "ftagree":
                # the recovery protocol's own rendezvous: agreement must
                # complete DESPITE declared failures, or Comm_shrink could
                # never run. (The thread tier conscripts the declared-dead
                # rank's still-live thread through it; the process tier
                # replaces this channel with the coordinator protocol.)
                return
            dead = sorted(self.failed_ranks)
            if cid is not None:
                ch = self._channels.get(cid)
                group = getattr(ch, "group", None) if ch is not None else None
                if group:
                    dead = sorted(self.failed_ranks & set(group))
                    if not dead:
                        return      # every dead rank is outside this comm
            raise ProcFailedError(
                f"peer process(es) {dead} failed (heartbeat timeout or "
                f"closed transport socket); Comm_revoke + Comm_shrink to "
                f"continue on the survivors", ranks=dead)

    def ft_agree(self, me: int, group, cid, epoch: int,
                 flag: int) -> tuple[int, frozenset]:
        """Fault-tolerant agreement (Comm_agree/Comm_shrink substrate):
        bitwise-AND of every live member's ``flag`` plus the union of their
        failed-set views. Threads of one process cannot die independently,
        so here it is an ordinary rendezvous — on a DEDICATED cid, because
        agreement must still work on a revoked communicator (the channel of
        a revoked cid raises RevokedError from its wait loop). The
        multi-process backend overrides this with a coordinator protocol
        that survives concurrent failures."""
        group = tuple(group)
        ch = self.channel(("ftagree", cid), len(group), group)

        def combine(contribs):
            value = ~0
            dead: set = set()
            for f, d in contribs:
                value &= f
                dead |= set(d)
            return [(value, frozenset(dead & set(group)))] * len(contribs)

        # opname deliberately excludes ``epoch``: the world Comm object is
        # SHARED by rank threads, so its epoch counter can interleave — the
        # channel's round counter already sequences successive agreements
        return ch.run(group.index(me),
                      (int(flag), frozenset(self.failed_ranks & set(group))),
                      combine, f"Comm_agree@{cid}")

    # -- communicator context ids -------------------------------------------
    def alloc_cid(self) -> int:
        """Allocate a fresh communicator context id (call from combine only,
        so all members of the parent communicator agree on the value). A
        thread bound to a tenant (broker worker) allocates from that
        tenant's leased namespace so Comm_dup/Comm_split stay in-range."""
        tenant = current_tenant()
        if tenant is not None:
            ns = self.cid_namespaces.get(tenant)
            if ns is None:
                raise SessionError(
                    f"tenant {tenant!r} has no leased cid namespace on this "
                    f"world (lease revoked?)")
            return ns.alloc()
        return next(self._next_cid)

    # -- tenant cid namespaces (serve tier, docs/serving.md) ------------------
    def lease_cid_namespace(self, tenant: str, span: int = 256) -> CidNamespace:
        """Carve a disjoint cid range for a tenant. Ranges start far above
        the sequential allocator so the two can never collide."""
        if span < 1:
            raise MPIError(f"cid namespace span must be >= 1, got {span}")
        with self._ns_lock:
            if tenant in self.cid_namespaces:
                raise SessionError(f"tenant {tenant!r} already holds a lease "
                                   f"on this world")
            base = self._ns_next_base
            self._ns_next_base += span
            ns = CidNamespace(tenant, base, base + span)
            self.cid_namespaces[tenant] = ns
            return ns

    def namespace_of_cid(self, cid: Any) -> Optional[CidNamespace]:
        """The namespace owning a cid, or None for shared/pool cids. Tuple
        cids (internal channels like ftagree) are keyed by their embedded
        numeric cid."""
        if isinstance(cid, tuple):
            cid = next((c for c in cid if isinstance(c, int)), None)
        if not isinstance(cid, int) or cid < (1 << 20):
            return None
        for ns in self.cid_namespaces.values():
            if ns.owns(cid):
                return ns
        return None

    def release_cid_namespace(self, tenant: str) -> list:
        """Revoke a tenant's lease: drop its namespace and drain every
        collective channel in its range (lease reclamation — the cids are
        dead; a straggler op on one raises rather than rendezvousing with
        nobody). Returns the drained cids."""
        with self._ns_lock:
            ns = self.cid_namespaces.pop(tenant, None)
        if ns is None:
            return []
        drained = []
        with self._channels_lock:
            for key in list(self._channels):
                cid = key
                if isinstance(cid, tuple):
                    cid = next((c for c in cid if isinstance(c, int)), None)
                if isinstance(cid, int) and ns.owns(cid):
                    ch = self._channels.pop(key)
                    drained.append(key)
                    drop = getattr(ch, "drop_shm", None)
                    if drop is not None:
                        try:
                            drop()
                        except Exception:
                            pass
        # every cid the tenant ever allocated is dead, channel or not — a
        # straggler op on one must raise (RevokedError), not rendezvous
        # with nobody and hang
        self.revoked_cids.update(range(ns.base, ns._next))
        self._notify_waiters()
        return drained

    def check_tenant_cid(self, cid: Any) -> None:
        """Cross-tenant isolation guard (pay-for-use: callers skip it while
        ``cid_namespaces`` is empty). A cid inside some tenant's leased
        range may only be touched by threads bound to that tenant."""
        ns = self.namespace_of_cid(cid)
        if ns is None:
            return
        tenant = current_tenant()
        if tenant != ns.tenant:
            raise SessionError(
                f"cid {cid} belongs to tenant {ns.tenant!r}; "
                + (f"caller is tenant {tenant!r}" if tenant is not None
                   else "caller holds no lease")
                + " — cross-tenant communicator use is forbidden")

    def channel(self, cid: int, size: int,
                group: Optional[tuple[int, ...]] = None) -> CollectiveChannel:
        # `group` (world ranks, comm order) is unused here — threads share an
        # address space — but the multi-process backend needs it for routing.
        if self.cid_namespaces:          # serve tier only; else one truth test
            self.check_tenant_cid(cid)
        with self._channels_lock:
            ch = self._channels.get(cid)
            if ch is None:
                ch = CollectiveChannel(self, size)
                # identity for diagnostics (analyze.matcher reads the live
                # contribs to name missing ranks in the deadlock dump)
                ch.cid = cid
                ch.group = group
                self._channels[cid] = ch
            return ch

    # -- dynamic process management -----------------------------------------
    def world_of(self, rank: int) -> tuple[tuple[int, ...], Any]:
        """(group, cid) of the COMM_WORLD the given world rank belongs to."""
        return self.worlds[rank]

    def add_ranks(self, n: int, world_cid: Any) -> tuple[int, ...]:
        """Extend the job with ``n`` new ranks forming their own world.
        Called from a spawn rendezvous combiner (single thread)."""
        with self._spawn_lock:
            start = len(self.mailboxes)
            new = tuple(range(start, start + n))
            for r in new:
                self.mailboxes.append(Mailbox(self))
                self.initialized.append(False)
                self.finalized.append(False)
                self.thread_level.append(None)
                self.main_threads.append(None)
                self.worlds[r] = (new, world_cid)
            return new

    def start_rank_thread(self, rank: int, body: Callable[[], Any]) -> None:
        """Run ``body`` as a new rank thread with fate-sharing."""
        def runner() -> None:
            set_env((self, rank))
            try:
                body()
            except BaseException as e:
                self.fail(e, rank)
            finally:
                set_env(None)

        t = threading.Thread(target=runner, name=f"tpu-mpi-spawned-{rank}",
                             daemon=True)
        self.spawned_threads.append(t)
        t.start()

    # -- device binding ------------------------------------------------------
    def device_for(self, rank: int):
        """The JAX device owned by a rank (rank i ↔ device i, wrapping)."""
        import jax
        devs = jax.devices()
        return devs[rank % len(devs)]


class FailureDetector:
    """Python half of the failure detector (docs/fault-tolerance.md).

    The native transport emits heartbeat frames from its poll loop and
    tracks per-peer last-heard stamps (``tm_hb_enable``/``tm_peer_age_ms``);
    this class turns those raw ages into verdicts: a peer silent past the
    failure timeout — or whose socket closed / refused a heartbeat — is
    declared dead via ``ctx.peer_failed``. Instantiated by the multi-process
    backend only when ``TPU_MPI_HEARTBEAT_MS`` > 0; :meth:`poll` is
    rate-limited to one sweep per heartbeat period and is driven from the
    backend's drainer loop (and from direct-pump waiters), so detection
    works no matter which thread owns the transport lease."""

    def __init__(self, ctx, transport, heartbeat_ms: int,
                 failure_timeout_ms: int = 0):
        self.ctx = ctx
        self.transport = transport
        self.heartbeat_ms = int(heartbeat_ms)
        # 0 derives a conservative default: 10 beats of silence, >= 1 s
        self.timeout_ms = int(failure_timeout_ms) or max(
            10 * self.heartbeat_ms, 1000)
        self._interval = max(self.heartbeat_ms / 1000.0, 0.01)
        self._last_poll = 0.0
        transport.hb_enable(self.heartbeat_ms)

    def poll(self) -> None:
        """One rate-limited liveness sweep; cheap no-op between periods."""
        now = time.monotonic()
        if now - self._last_poll < self._interval:
            return
        self._last_poll = now
        ctx, tr = self.ctx, self.transport
        for peer in range(tr.size):
            if (peer == tr.rank or peer in ctx.failed_ranks
                    or peer in ctx.departed_ranks):
                continue
            age = tr.peer_age_ms(peer)
            if age == -2 or age > self.timeout_ms:
                # flight recorder: the verdict itself is the crash-grade
                # event — record it (and dump) before the declaration
                # cascades into ProcFailedError raises on blocked waiters
                from . import flight
                flight.note("peer_declared_dead", peer=peer,
                            age_ms=int(age), timeout_ms=self.timeout_ms)
                flight.auto_dump("peer-failed")
                ctx.peer_failed(peer)


_jax_warmed = False


def _warm_jax_backend() -> None:
    """Initialize the JAX backend once, serially, before rank threads start.

    PJRT client creation is not safe under concurrent first-initialization
    from many threads (observed hang in make_c_api_client); the launcher owns
    backend bring-up, like mpiexec owns process bring-up in the reference.
    """
    global _jax_warmed
    if _jax_warmed:
        return
    try:
        import jax
        jax.devices()
        import jax.numpy as jnp
        jnp.zeros(1).block_until_ready()
    except Exception:
        pass
    _jax_warmed = True


def spmd_run(fn: Callable[[], Any], size: int, *, args: tuple = (),
             universe_size: Optional[int] = None,
             timeout: Optional[float] = None) -> list[Any]:
    """Run ``fn()`` as an SPMD program on ``size`` ranks (threads).

    The TPU-native mpiexec: where the reference forks N OS processes
    (/root/reference/bin/mpiexecjl:55-64, test/runtests.jl:28-45), we run N rank
    threads in one controller process sharing the JAX runtime. Returns the list
    of per-rank return values; re-raises the first rank failure (so a failing
    rank fails the whole run, matching test/runtests.jl:37-39).
    """
    _warm_jax_backend()
    ctx = SpmdContext(size, universe_size=universe_size)
    results: list[Any] = [None] * size
    first_error: list[Optional[BaseException]] = [None]
    error_lock = threading.Lock()

    def runner(rank: int) -> None:
        set_env((ctx, rank))
        try:
            results[rank] = fn(*args)
        except BaseException as e:
            with error_lock:
                if first_error[0] is None:
                    first_error[0] = e
            ctx.fail(e, rank)
        finally:
            set_env(None)

    threads = [threading.Thread(target=runner, args=(r,), name=f"tpu-mpi-rank-{r}",
                                daemon=True) for r in range(size)]
    for t in threads:
        t.start()
    deadline = None if timeout is None else time.monotonic() + timeout
    for t in threads:
        t.join(None if deadline is None else max(0.0, deadline - time.monotonic()))
        if t.is_alive():
            ctx.fail(DeadlockError("spmd_run timeout"), None)
    for t in threads:
        t.join(5.0)
    # Ranks added by Comm_spawn must finish before the job is done. Spawned
    # ranks may spawn further ranks, so re-snapshot until the list drains.
    joined: set = set()
    while True:
        pending = [t for t in list(ctx.spawned_threads) if t not in joined]
        if not pending:
            break
        for t in pending:
            t.join(None if deadline is None else max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                ctx.fail(DeadlockError("spawned rank did not finish"), None)
                t.join(5.0)
            joined.add(t)
    err = first_error[0]
    if err is None and ctx.failure is not None:
        # e.g. a rank stuck in pure compute past the timeout: the failure was
        # recorded on the context but no rank thread surfaced it.
        err = ctx.failure
    if err is not None:
        raise err
    return results
