"""Cross-process one-sided RMA over the native transport.

Reference: /root/reference/src/onesided.jl:24-219 — the reference's windows
span real OS processes (libmpi's RMA engine moves the bytes) and its suite
drives them under ``mpiexec -n N`` (test/test_onesided.jl:17-130). This module
is the multi-process analog for the ``tpurun --procs`` tier: every window rank
lives in its own process, and the OWNER of each window slice is its agent —
origins ship Put/Get/Accumulate/lock frames to the owner, whose drainer
thread applies them under the window's per-process atomic mutex (giving the
element-wise atomicity MPI guarantees for accumulates, src/onesided.jl:186-219).

Design rules:

- The drainer must NEVER block (it is the only thread that can process the
  frame that would unblock it). Passive-target lock grants are queued through
  a callback lock manager (:class:`LockManager`) instead of awaited.
- Completion (Win_flush / Win_fence / Win_unlock) rides the transport's
  per-peer FIFO ordering: a flush ack is generated only after the owner has
  applied every earlier frame from that origin, so one ack completes them all.
- Shared windows (Win_allocate_shared / Win_shared_query,
  src/onesided.jl:72-107) are real POSIX shared memory
  (``multiprocessing.shared_memory``): a peer's slab maps into this process
  and loads/stores hit it directly — the contract the reference gets from
  MPI_Win_allocate_shared.
"""

from __future__ import annotations

import itertools
import pickle
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

import numpy as np

from ._runtime import _POLL, deadlock_timeout, require_env
from .buffers import (extract_array, poison_fill, resolve_attached,
                      write_flat, write_range)
from . import error as _ec
from .error import DeadlockError, MPIError
from . import operators as _ops

# Predefined ops travel by name (pickling an Op loses singleton identity);
# custom ops travel through the extended wire codec (tpu_mpi.serialization
# via backend.send_frame), so closures/lambdas work cross-process too.
_PREDEFINED: dict[str, _ops.Op] = {
    v.name: v for v in vars(_ops).values() if isinstance(v, _ops.Op)
}


def _op_spec(op: _ops.Op) -> Any:
    return op.name if _PREDEFINED.get(op.name) is op else op


def _resolve_op(spec: Any) -> _ops.Op:
    return _PREDEFINED[spec] if isinstance(spec, str) else spec


_engine_init_lock = threading.Lock()


def _engine(ctx) -> "RmaEngine":
    eng = getattr(ctx, "_rma_engine", None)
    if eng is None:
        with _engine_init_lock:     # THREAD_MULTIPLE: one engine per ctx
            eng = getattr(ctx, "_rma_engine", None)
            if eng is None:
                eng = ctx._rma_engine = RmaEngine(ctx)
    return eng


class LockManager:
    """Owner-side passive-target lock queue (src/onesided.jl:138-148).

    Grant callbacks fire synchronously from request()/release() — never from
    a blocked wait — so the backend drainer can pump it safely. Origins are
    identified by world rank; EXCLUSIVE excludes all, SHARED excludes writers.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._readers: set[int] = set()
        self._writer: Optional[int] = None
        self._queue: deque[tuple[int, bool, Callable[[], None]]] = deque()

    def request(self, origin: int, exclusive: bool,
                grant: Callable[[], None]) -> None:
        with self._lock:
            self._queue.append((origin, exclusive, grant))
            ready = self._pump()
        for g in ready:
            g()

    def release(self, origin: int, exclusive: bool) -> None:
        with self._lock:
            if exclusive and self._writer == origin:
                self._writer = None
            else:
                self._readers.discard(origin)
            ready = self._pump()
        for g in ready:
            g()

    def _pump(self) -> list[Callable[[], None]]:
        ready: list[Callable[[], None]] = []
        while self._queue:
            origin, exclusive, grant = self._queue[0]
            if exclusive:
                if self._writer is not None or self._readers:
                    break
                self._writer = origin
            else:
                if self._writer is not None:
                    break
                self._readers.add(origin)
            self._queue.popleft()
            ready.append(grant)
        return ready


class ProcWinState:
    """This process's slice of a window spanning multiple processes.

    ``metas[r]`` is rank r's exposure: (disp_unit, nbytes, shm_meta) where
    shm_meta is (segment_name, length, dtype_str) for shared windows.
    """

    is_proc = True

    def __init__(self, win_id: Any, group: tuple[int, ...], my_rank: int,
                 dynamic: bool, metas: list):
        self.win_id = win_id
        self.group = tuple(group)           # comm rank -> world rank
        self.size = len(group)
        self.my_rank = my_rank              # this process's comm rank
        self.dynamic = dynamic
        self.metas = metas
        self.freed = False
        self.local: Optional[Any] = None    # locally exposed buffer
        self.attached: list[tuple[int, int, Any]] = []   # dynamic windows
        self.atomic_lock = threading.Lock()
        self.lockmgr = LockManager()
        self.lock = threading.Lock()        # origin-side bookkeeping
        # Lazy passive-target epochs (MPICH-style): Win_lock on a remote
        # target defers the wire lock; short write-only epochs ship as ONE
        # lock+ops+unlock frame at Win_unlock (1 round trip instead of 2+).
        # world rank -> {"excl": bool, "ops": [(kind, ...), ...]}
        self.deferred: dict[int, dict] = {}
        # THREAD_MULTIPLE: sibling threads sharing an origin epoch must see
        # buffer/materialize/ship as atomic steps — an append racing a
        # materialize pop would orphan (lose) the op, and a live send
        # racing the materialize's wire lock could reach the target before
        # the lock does. RLock: materialize replays ops that re-enter.
        self.epoch_lock = threading.RLock()
        self.dirty: set[int] = set()        # world ranks with unacked ops
        self._shm_own = None                # SharedMemory this rank created
        self._shm_peers: dict[int, tuple[Any, np.ndarray]] = {}

    # -- owner-side application (drainer thread or local fast path) ----------
    def _local_view(self, disp: int, count: int):
        """Resolve [disp, disp+count) of THIS process's exposed memory."""
        if self.dynamic:
            return resolve_attached(self.attached, disp, self.my_rank)
        if self.local is None:
            raise MPIError(f"rank {self.my_rank} exposes no memory in this "
                           "window")
        return self.local, extract_array(self.local), int(disp)

    def apply_put(self, disp: int, arr: np.ndarray) -> None:
        with self.atomic_lock:
            buf, tarr, off = self._local_view(disp, arr.size)
            write_range(buf, off, np.asarray(arr, tarr.dtype))

    def apply_acc(self, disp: int, arr: np.ndarray, op: _ops.Op,
                  fetch: bool) -> Optional[np.ndarray]:
        count = int(arr.size)
        with self.atomic_lock:
            buf, tarr, off = self._local_view(disp, count)
            flat = np.asarray(tarr).reshape(-1)
            old = flat[off:off + count].copy()
            # predefined ops unpickle to their singletons (Op.__reduce__),
            # so the shared identity-checked combine applies cross-process
            new = _ops.acc_combine(old, arr, op)
            if new is not None:
                write_range(buf, off, new)
        return old if fetch else None

    def read(self, disp: int, count: int) -> np.ndarray:
        with self.atomic_lock:
            buf, tarr, off = self._local_view(disp, count)
            return np.asarray(tarr).reshape(-1)[off:off + int(count)].copy()


class RmaEngine:
    """Per-process RMA hub: window registry + request/response matching."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.cond = threading.Condition()
        self.windows: dict[Any, ProcWinState] = {}
        # Frames can outrun window registration (the create-collective's
        # result reaches a fast origin before this process): stash + replay.
        self._pending: dict[Any, list[tuple[int, Any]]] = {}
        self._responses: dict[int, Any] = {}
        self._req_counter = itertools.count(1)
        self._req_lock = threading.Lock()

    # -- plumbing ------------------------------------------------------------
    def new_reqid(self) -> int:
        with self._req_lock:
            return self.ctx.local_rank + self.ctx.size * next(self._req_counter)

    def send(self, world_dst: int, item: tuple) -> None:
        try:
            self.ctx.send_frame(world_dst, ("rma",) + item)
        except MPIError:
            raise
        except (pickle.PicklingError, AttributeError, TypeError) as e:
            raise MPIError(
                f"RMA payload is not serializable: {e}") from None
        except Exception as e:
            # transport failure (peer died mid-epoch): fate-share like the
            # collective send path so siblings abort instead of timing out
            err = MPIError(f"RMA send to rank {world_dst} failed: "
                           f"{type(e).__name__}: {e}")
            self.ctx.fail(err)
            raise err from None

    def respond(self, origin: int, reqid: int, payload: Any) -> None:
        self.send(origin, ("resp", reqid, payload))

    def wait_resp(self, reqid: int, what: str) -> Any:
        done = lambda: reqid in self._responses
        if getattr(self.ctx, "_direct_pump", None) is not None:
            # blocked-origin direct drain (VERDICT r3 #4, extended to RMA):
            # the origin thread pumps its own transport while waiting for
            # the target's response (_runtime.pump_wait, the shared loop).
            from ._runtime import pump_wait
            with self.cond:
                pump_wait(self.ctx, self.cond, done, what)
                return self._responses.pop(reqid)
        limit = deadlock_timeout()
        deadline = time.monotonic() + limit
        with self.cond:
            while reqid not in self._responses:
                self.ctx.check_failure()
                if time.monotonic() > deadline:
                    raise DeadlockError(
                        f"deadlock suspected: {what} blocked >{limit}s")
                self.cond.wait(_POLL)
            return self._responses.pop(reqid)

    def deliver_resp(self, reqid: int, payload: Any) -> None:
        with self.cond:
            self._responses[reqid] = payload
            self.cond.notify_all()

    def register(self, win_id: Any, st: ProcWinState) -> None:
        """Publish a window and replay frames that beat the registration.
        Replay holds the registry lock so a frame arriving concurrently from
        the same origin cannot be applied out of FIFO order."""
        with self.cond:
            self.windows[win_id] = st
            for src, item in self._pending.pop(win_id, ()):
                self.apply(st, src, item)

    def unregister(self, win_id: Any) -> None:
        with self.cond:
            self.windows.pop(win_id, None)

    def window_or_stash(self, win_id: Any, src: int,
                        item: Any) -> Optional[ProcWinState]:
        with self.cond:
            st = self.windows.get(win_id)
            if st is None:
                self._pending.setdefault(win_id, []).append((src, item))
            return st

    # -- owner-side frame application ----------------------------------------
    def apply(self, st: ProcWinState, src: int, item: tuple) -> None:
        kind = item[1]
        if kind == "put":
            _, _, _, disp, arr = item
            st.apply_put(disp, np.asarray(arr))
        elif kind == "acc":
            _, _, _, disp, arr, opspec, reqid, origin = item
            old = st.apply_acc(disp, np.asarray(arr), _resolve_op(opspec),
                               fetch=reqid is not None)
            if reqid is not None:
                self.respond(origin, reqid, old)
        elif kind == "get":
            _, _, _, disp, count, reqid, origin = item
            self.respond(origin, reqid, st.read(disp, count))
        elif kind == "flush":
            _, _, _, reqid, origin = item
            self.respond(origin, reqid, None)   # FIFO: earlier frames applied
        elif kind == "lock":
            _, _, _, reqid, origin, excl = item
            st.lockmgr.request(
                origin, excl, lambda: self.respond(origin, reqid, None))
        elif kind == "unlock":
            _, _, _, reqid, origin, excl = item
            st.lockmgr.release(origin, excl)
            self.respond(origin, reqid, None)
        elif kind == "lepoch":
            # a whole deferred lock epoch in one frame: acquire the lock
            # (immediately or queued), apply every buffered op IN PROGRAM
            # ORDER — reads included — release, ack with the read results.
            # This is what makes an uncontended lock/get/unlock epoch ONE
            # round trip (VERDICT r4 next #6): Get / Fetch_and_op results
            # are only valid after the closing synchronization per MPI, so
            # they may legally travel in the unlock ack. The grant callback
            # runs wherever the lock manager fires it (this dispatch, or a
            # later release's pump) — always a frame-pumping thread, never
            # blocked.
            _, _, _, reqid, origin, excl, ops = item

            def run_epoch():
                reads: list = []
                for op in ops:
                    if op[0] == "put":
                        st.apply_put(op[1], np.asarray(op[2]))
                    elif op[0] == "acc":
                        st.apply_acc(op[1], np.asarray(op[2]),
                                     _resolve_op(op[3]), fetch=False)
                    elif op[0] == "get":
                        reads.append(st.read(op[1], op[2]))
                    else:               # ("facc", disp, arr, opspec)
                        reads.append(st.apply_acc(op[1], np.asarray(op[2]),
                                                  _resolve_op(op[3]),
                                                  fetch=True))
                st.lockmgr.release(origin, excl)
                self.respond(origin, reqid, reads or None)

            st.lockmgr.request(origin, excl, run_epoch)
        else:
            raise MPIError(f"unknown RMA frame kind {kind!r}")


def dispatch_rma(ctx, src_world: int, item: tuple) -> None:
    """Backend drainer entry point for ("rma", ...) frames."""
    eng = _engine(ctx)
    if item[1] == "resp":
        _, _, reqid, payload = item
        eng.deliver_resp(reqid, payload)
        return
    st = eng.window_or_stash(item[2], src_world, item)
    if st is not None:
        eng.apply(st, src_world, item)


# ---------------------------------------------------------------------------
# window creation (collective over the comm's ProcChannel)
# ---------------------------------------------------------------------------

def create_proc_window(comm, base: Optional[Any], disp_unit: Optional[int],
                       opname: str, *, dynamic: bool = False,
                       shm_meta: Optional[tuple] = None) -> ProcWinState:
    """Collectively create a multi-process window: share every rank's
    exposure metadata, mint a world-unique window id at the group's first
    process, register locally, replay any frames that arrived early."""
    ctx, _ = require_env()
    eng = _engine(ctx)
    my = comm.rank()
    nbytes = None if base is None else int(extract_array(base).nbytes)
    contrib = (disp_unit, nbytes, shm_meta)

    def combine(cs):
        # runs at the group's first process; its cid space is world-unique
        wid = ("win", ctx.alloc_cid())
        return [(wid, list(cs))] * len(cs)

    win_id, metas = comm.channel().run(my, contrib, combine, opname)
    st = ProcWinState(win_id, comm.group, my, dynamic, metas)
    st.local = base
    eng.register(win_id, st)
    return st


def create_proc_shared(comm, dtype: np.dtype, length: int,
                       opname: str) -> tuple[ProcWinState, np.ndarray]:
    """Win_allocate_shared across processes: each rank allocates a real POSIX
    shared-memory slab; peers map it on Win_shared_query."""
    from multiprocessing import shared_memory
    nbytes = max(1, int(length) * dtype.itemsize)
    shm = shared_memory.SharedMemory(create=True, size=nbytes)
    local = np.ndarray((int(length),), dtype=dtype, buffer=shm.buf)
    local[...] = 0
    st = create_proc_window(comm, local, dtype.itemsize, opname,
                            shm_meta=(shm.name, int(length), dtype.str))
    st._shm_own = shm
    return st, local


def proc_shared_query(st: ProcWinState, owner_rank: int):
    """(size_bytes, disp_unit, live array) of a peer's shared slab, mapped
    into this process via its POSIX segment name (src/onesided.jl:97-107)."""
    owner = int(owner_rank)
    disp_unit, nbytes, shm_meta = st.metas[owner]
    if owner == st.my_rank:
        arr = extract_array(st.local)
        return arr.size * arr.dtype.itemsize, disp_unit, st.local
    if shm_meta is None:
        raise MPIError(f"rank {owner} exposes no shared memory in this window")
    with st.lock:                    # THREAD_MULTIPLE: attach each peer once
        if owner not in st._shm_peers:
            from multiprocessing import shared_memory
            name, length, dtype_str = shm_meta
            seg = shared_memory.SharedMemory(name=name)
            arr = np.ndarray((length,), dtype=np.dtype(dtype_str),
                             buffer=seg.buf)
            st._shm_peers[owner] = (seg, arr)
        seg, arr = st._shm_peers[owner]
    return arr.size * arr.dtype.itemsize, disp_unit, arr


# ---------------------------------------------------------------------------
# origin-side data movement
# ---------------------------------------------------------------------------

def _target_world(st: ProcWinState, target_rank: int) -> int:
    r = int(target_rank)
    if not (0 <= r < st.size):       # no negative wrap: match the in-process
        raise MPIError(              # error contract, not IndexError
            f"rank {target_rank} exposes no memory in this window")
    return st.group[r]


def _origin_flat(origin: Any, count: int) -> np.ndarray:
    """Validated flat origin view — invalid operands fail at the origin with
    a clean MPIError, not in the owner's drainer (which would abort the job)."""
    arr = extract_array(origin)
    if arr is None:
        raise MPIError(f"not an RMA origin buffer: {type(origin).__name__}",
                       code=_ec.ERR_BUFFER)
    flat = np.asarray(arr).reshape(-1)
    if flat.size < int(count):
        raise MPIError(f"RMA origin has {flat.size} elements, count={count}",
                       code=_ec.ERR_COUNT)
    return np.ascontiguousarray(flat[:int(count)])


# A deferred epoch stays batched while it is small; past these bounds it
# materializes into a live wire lock. Reads batch too — their results
# travel back in the single unlock ack (MPI: Get / Fetch_and_op results
# are valid only after the closing synchronization).
_EPOCH_MAX_OPS = 16
_EPOCH_MAX_BYTES = 1 << 20

# deferred ops that carry an array payload to snapshot (reads carry a
# count + an origin REFERENCE to fill at completion instead)
_PAYLOAD_OPS = frozenset(("put", "acc", "facc"))


def _strict_poison(origin: Any, count: int) -> None:
    """Strict mode (``TPU_MPI_STRICT=1``): a batched read's origin holds no
    valid data until the closing synchronization (Win_unlock / Win_flush)
    fills it — MPI says consuming it earlier is erroneous. Poison it with a
    loud sentinel (NaN / 0xA5-pattern, buffers.poison_fill) so mid-epoch
    consumption fails visibly instead of reading plausible stale bytes.
    The completion write_flat overwrites the sentinel."""
    from . import config
    if config.load().strict:
        poison_fill(origin, count)


def _materialize_lock(st: ProcWinState, world: int) -> None:
    """Turn a deferred epoch into a live one: take the wire lock for real
    and replay the buffered ops as ordinary frames (FIFO keeps order);
    buffered reads complete HERE (their epoch is becoming live — e.g. a
    Win_flush demands completion). Caller holds st.epoch_lock."""
    ctx, _ = require_env()
    ep = st.deferred.pop(world, None)
    if ep is None:
        return
    eng = _engine(ctx)
    reqid = eng.new_reqid()
    eng.send(world, ("lock", st.win_id, reqid, ctx.local_rank, ep["excl"]))
    eng.wait_resp(reqid, "Win_lock")
    for op in ep["ops"]:
        if op[0] == "put":
            with st.lock:
                st.dirty.add(world)
            eng.send(world, ("put", st.win_id, op[1], op[2]))
        elif op[0] == "acc":
            with st.lock:
                st.dirty.add(world)
            eng.send(world, ("acc", st.win_id, op[1], op[2], op[3],
                             None, ctx.local_rank))
        elif op[0] == "get":
            _, disp, count, ref = op
            rid = eng.new_reqid()
            eng.send(world, ("get", st.win_id, disp, count, rid,
                             ctx.local_rank))
            write_flat(ref, np.asarray(eng.wait_resp(rid, "Get")), count)
        else:                            # ("facc", disp, arr, opspec, ref)
            _, disp, arr, opspec, ref = op
            with st.lock:
                st.dirty.add(world)
            rid = eng.new_reqid()
            eng.send(world, ("acc", st.win_id, disp, arr, opspec, rid,
                             ctx.local_rank))
            write_flat(ref, np.asarray(eng.wait_resp(rid, "Get_accumulate")),
                       int(np.asarray(arr).size))


def _op_bytes(op: tuple, shm_min: int = 0) -> int:
    """TCP-frame footprint of a deferred op: payload bytes for writes, the
    RESULT size for reads (a batched Get's data rides the unlock ack — it
    must count against the epoch bound too, or 16 huge reads would pickle
    gigabytes into one response frame). Buffers at or above the shm
    threshold never join a TCP frame — ``backend.dumps_oob_parts`` spills
    them to the one-copy shm lane in BOTH directions (lepoch out, ack
    back) — so they cost the frame bound nothing: a 4 MiB Put stays
    deferred and ships as ONE lepoch frame instead of materializing a live
    two-round-trip lock (ISSUE-1 bulk-path unification). Element size is
    conservatively 8 (the origin dtype is unknown here)."""
    def frame_cost(nb: int) -> int:
        return 0 if (shm_min and nb >= shm_min) else nb
    if op[0] == "get":
        return frame_cost(int(op[2]) * 8)
    nb = int(getattr(op[2], "nbytes", 0))
    if op[0] == "facc":
        return frame_cost(nb) * 2        # payload out + fetched value back
    return frame_cost(nb)


def _epoch_buffer(st: ProcWinState, world: int, op: tuple) -> bool:
    """Try to buffer an op into a deferred epoch; False = caller sends
    live (materializing first if the epoch just overflowed). Caller holds
    st.epoch_lock."""
    ep = st.deferred.get(world)
    if ep is None:
        return False
    ctx, _ = require_env()
    from .backend import _shm_min_bytes  # deferred: backend imports us
    shm_ok = getattr(ctx, "shm_ok", None)
    shm_min = _shm_min_bytes() if (shm_ok is not None and shm_ok(world)) else 0
    nbytes = sum(_op_bytes(o, shm_min) for o in ep["ops"])
    if (len(ep["ops"]) >= _EPOCH_MAX_OPS
            or nbytes + _op_bytes(op, shm_min) > _EPOCH_MAX_BYTES):
        _materialize_lock(st, world)
        return False
    if op[0] in _PAYLOAD_OPS:
        nb = int(getattr(op[2], "nbytes", 0))
        if not (shm_min and nb >= shm_min):
            # snapshot small payloads: _origin_flat returns a VIEW for
            # contiguous origins, and a deferred op ships at Win_unlock —
            # without the copy, mutating the origin between Put/Accumulate
            # and unlock would silently ship the mutated data (the eager
            # path snapshots at call time; both paths should observe the
            # same values when the user plays by MPI's rules)
            op = op[:2] + (np.array(op[2], copy=True),) + op[3:]
        # shm-lane payloads stay REFERENCED: MPI forbids modifying the
        # origin until the epoch's closing synchronization, and the shm
        # spill at unlock copies straight from the origin into the
        # segment — the lane's single copy, not copy + pickle + socket
    ep["ops"].append(op)
    return True


def rma_put(st: ProcWinState, origin: Any, count: int, target_rank: int,
            disp: int) -> None:
    ctx, _ = require_env()
    src = _origin_flat(origin, count)
    world = _target_world(st, target_rank)
    if world == ctx.local_rank:
        st.apply_put(disp, src)
        return
    with st.epoch_lock:
        if _epoch_buffer(st, world, ("put", int(disp), src)):
            return
        with st.lock:
            st.dirty.add(world)
        _engine(ctx).send(world, ("put", st.win_id, int(disp), src))


def rma_get(st: ProcWinState, origin: Any, count: int, target_rank: int,
            disp: int) -> None:
    ctx, _ = require_env()
    world = _target_world(st, target_rank)
    if world == ctx.local_rank:
        write_flat(origin, np.asarray(st.read(disp, int(count))), int(count))
        return
    with st.epoch_lock:
        # inside a deferred lock epoch the read BATCHES (VERDICT r4 #6):
        # it executes at the owner in program order within the single
        # unlock frame, and the result — valid only after the closing
        # synchronization per MPI — fills ``origin`` at Win_unlock (or at
        # Win_flush / epoch overflow, which materialize and complete it)
        if _epoch_buffer(st, world, ("get", int(disp), int(count), origin)):
            _strict_poison(origin, int(count))
            return
    eng = _engine(ctx)
    reqid = eng.new_reqid()
    eng.send(world, ("get", st.win_id, int(disp), int(count), reqid,
                     ctx.local_rank))
    write_flat(origin, np.asarray(eng.wait_resp(reqid, "Get")), int(count))


def rma_accumulate(st: ProcWinState, origin_flat: np.ndarray, target_rank: int,
                   disp: int, op: _ops.Op,
                   fetch_into: Optional[Any] = None) -> None:
    ctx, _ = require_env()
    src = np.ascontiguousarray(np.asarray(origin_flat).reshape(-1))
    count = int(src.size)
    world = _target_world(st, target_rank)
    if world == ctx.local_rank:
        old = st.apply_acc(disp, src, op, fetch=fetch_into is not None)
        if fetch_into is not None:
            write_flat(fetch_into, old, count)
        return
    eng = _engine(ctx)
    if fetch_into is None:
        with st.epoch_lock:
            if _epoch_buffer(st, world, ("acc", int(disp), src,
                                         _op_spec(op))):
                return
            with st.lock:
                st.dirty.add(world)
            eng.send(world, ("acc", st.win_id, int(disp), src, _op_spec(op),
                             None, ctx.local_rank))
    else:
        with st.epoch_lock:
            # fetching ops batch like plain reads: the fetched value fills
            # at Win_unlock (one frame, one round trip)
            if _epoch_buffer(st, world, ("facc", int(disp), src,
                                         _op_spec(op), fetch_into)):
                _strict_poison(fetch_into, count)
                return
        reqid = eng.new_reqid()
        eng.send(world, ("acc", st.win_id, int(disp), src, _op_spec(op),
                         reqid, ctx.local_rank))
        old = eng.wait_resp(reqid, "Get_accumulate")
        write_flat(fetch_into, np.asarray(old), count)


# ---------------------------------------------------------------------------
# origin-side epochs
# ---------------------------------------------------------------------------

def _flush_targets(st: ProcWinState, worlds) -> None:
    ctx, _ = require_env()
    eng = _engine(ctx)
    reqids = [eng.new_reqid() for _ in worlds]
    for world, rid in zip(worlds, reqids):
        eng.send(world, ("flush", st.win_id, rid, ctx.local_rank))
    for rid in reqids:
        eng.wait_resp(rid, "Win_flush")


def proc_flush(st: ProcWinState, target_rank: int) -> None:
    world = _target_world(st, target_rank)
    with st.epoch_lock:
        if world in st.deferred:
            # Win_flush inside a deferred epoch: the ops must complete at
            # the target NOW — take the real lock and flush the replay
            _materialize_lock(st, world)
    with st.lock:
        pending = world in st.dirty
        st.dirty.discard(world)
    if pending:
        _flush_targets(st, [world])


def proc_fence(win) -> None:
    """All RMA issued before the fence completes everywhere: flush every
    dirty target (FIFO ack ⇒ applied), then a dissemination barrier."""
    st = win._state
    with st.lock:
        dirty = sorted(st.dirty)
        st.dirty.clear()
    if dirty:
        _flush_targets(st, dirty)
    comm = win.comm
    comm.channel().run(comm.rank(), None, lambda cs: [None] * len(cs),
                       f"Win_fence@{comm.cid}", plan=("barrier",))


def proc_lock(st: ProcWinState, target_rank: int, exclusive: bool) -> None:
    ctx, _ = require_env()
    world = _target_world(st, target_rank)
    if world == ctx.local_rank:
        ev = threading.Event()
        st.lockmgr.request(ctx.local_rank, exclusive, ev.set)
        limit = deadlock_timeout()
        deadline = time.monotonic() + limit
        while not ev.wait(_POLL):
            ctx.check_failure()
            if time.monotonic() > deadline:
                raise DeadlockError(
                    f"deadlock suspected: Win_lock blocked >{limit}s")
        return
    # Lazy lock (MPICH-style): defer the wire lock — a short write-only
    # epoch ships as one lock+ops+unlock frame at Win_unlock (1 round trip
    # instead of 2+). Reads, flushes and big epochs materialize it.
    with st.epoch_lock:
        _proc_lock_deferred(st, world, target_rank, exclusive)


def _proc_lock_deferred(st: ProcWinState, world: int, target_rank: int,
                        exclusive: bool) -> None:
    if world in st.deferred:
        # double lock on the same target from this origin: the eager
        # protocol self-deadlocked loudly here; keep the failure loud
        # instead of silently dropping the first epoch's buffered ops
        raise MPIError(
            f"Win_lock on target {target_rank}: this origin already holds "
            f"a lock epoch on that target", code=_ec.ERR_RMA_SYNC)
    st.deferred[world] = {"excl": bool(exclusive), "ops": []}


def proc_unlock(st: ProcWinState, target_rank: int, exclusive: bool) -> None:
    """Win_unlock returns only once the epoch's ops completed at the target
    (src/onesided.jl:145-148): the ack answers after all earlier frames."""
    ctx, _ = require_env()
    world = _target_world(st, target_rank)
    if world == ctx.local_rank:
        st.lockmgr.release(ctx.local_rank, exclusive)
        return
    eng = _engine(ctx)
    with st.epoch_lock:
        # pop AND ship under the epoch lock: a sibling thread's op racing
        # this unlock must either land in the batch or observe the epoch
        # gone — never send a live frame that could beat the batch's lock
        ep = st.deferred.pop(world, None)
        if ep is not None:
            # whole deferred epoch in one frame; the ack means lock
            # acquired, every op applied (reads included), lock released.
            # Read ops keep their origin-buffer REFERENCES local — only
            # (kind, disp, count/payload) travels; results return in the
            # ack, in op order, and fill the origins here.
            wire_ops = []
            read_sinks: list = []
            for op in ep["ops"]:
                if op[0] == "get":
                    wire_ops.append(op[:3])
                    read_sinks.append((op[3], op[2]))
                elif op[0] == "facc":
                    wire_ops.append(op[:4])
                    read_sinks.append((op[4], int(np.asarray(op[2]).size)))
                else:
                    wire_ops.append(op)
            reqid = eng.new_reqid()
            eng.send(world, ("lepoch", st.win_id, reqid, ctx.local_rank,
                             ep["excl"], wire_ops))
            results = eng.wait_resp(reqid, "Win_unlock")
            for (ref, count), data in zip(read_sinks, results or []):
                write_flat(ref, np.asarray(data), count)
            with st.lock:
                # the ack completed every earlier FIFO frame too — keep
                # fence-mode dirty bookkeeping consistent with live unlock
                st.dirty.discard(world)
            return
    reqid = eng.new_reqid()
    eng.send(world, ("unlock", st.win_id, reqid, ctx.local_rank, exclusive))
    eng.wait_resp(reqid, "Win_unlock")
    with st.lock:
        st.dirty.discard(world)


def proc_free(win) -> None:
    """Collective free: barrier (every rank stops issuing RMA), then tear
    down local registration and shared-memory mappings."""
    st = win._state
    comm = win.comm
    comm.channel().run(comm.rank(), None, lambda cs: [None] * len(cs),
                       f"Win_free@{comm.cid}", plan=("barrier",))
    ctx, _ = require_env()
    _engine(ctx).unregister(st.win_id)
    st.freed = True
    for seg, _ in st._shm_peers.values():
        try:
            seg.close()
        except Exception:
            pass          # numpy views may still be exported (BufferError)
    st._shm_peers.clear()
    if st._shm_own is not None:
        try:
            # unlink first, in its own try: it needs no view release, and a
            # BufferError from close() (live st.local export) must not leak
            # the /dev/shm segment for the life of the job
            st._shm_own.unlink()
        except Exception:
            pass
        try:
            st._shm_own.close()
        except Exception:
            pass
        st._shm_own = None
