"""Session protocol: the framed wire format of the serve tier.

One frame = fixed header + JSON metadata + zero or more raw array blobs:

    u8  kind        (frame type, table below)
    u32 json_len    (big-endian)
    u16 nblobs
    json_len bytes  UTF-8 JSON metadata
    nblobs x { u32 blob_len, blob_len raw bytes }

Arrays travel as raw little-endian bytes with dtype/shape carried in the
metadata (``meta["blobs"]``), reconstructed with ``np.frombuffer`` — the
round trip is bitwise exact, which the two-tenant correctness test in
tests/test_serve.py asserts end-to-end.

Frame types (docs/serving.md has the full table):

    HELLO   client/worker -> broker   token, tenant, nranks (or role=worker)
    LEASE   broker -> client          tenant id, rank map, cid range
    OP      either direction          a collective / comm-management op
    RESULT  broker/worker -> peer     op completion + result arrays
    ERROR   broker -> client          typed failure (code + message)
    STATS   both                      per-tenant usage report request/reply
    DETACH  client -> broker          clean lease release
    BYE     broker -> client          lease revoked / broker shutting down
    PING/PONG both                    liveness probe

The transport is any SOCK_STREAM socket — TCP or Unix-domain; framing and
byte order match the native transport's length-prefixed style
(tpu_mpi/_native/transport.cc) so a future C++ fast path can speak it.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, Optional, Sequence

import numpy as np

from .. import error as _ec
from ..error import (MPIError, PoolDegradedError, QuotaExceededError,
                     ServeBusyError, SessionError, SLOExpiredError)

# frame kinds
HELLO = 1
LEASE = 2
OP = 3
RESULT = 4
ERROR = 5
STATS = 6
DETACH = 7
BYE = 8
PING = 9
PONG = 10

KIND_NAMES = {HELLO: "HELLO", LEASE: "LEASE", OP: "OP", RESULT: "RESULT",
              ERROR: "ERROR", STATS: "STATS", DETACH: "DETACH", BYE: "BYE",
              PING: "PING", PONG: "PONG"}

_HDR = struct.Struct("!BIH")
_BLOB = struct.Struct("!I")

# Sanity bound for a single frame's JSON section; array blobs are bounded
# by the config max_frame_bytes knob at recv time.
_MAX_JSON = 1 << 24


class Disconnect(Exception):
    """Peer closed the connection at a frame boundary (clean EOF)."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        try:
            chunk = sock.recv(min(n - got, 1 << 20))
        except (ConnectionResetError, BrokenPipeError, OSError) as e:
            raise Disconnect(f"connection lost mid-frame: {e}") from None
        if not chunk:
            if got == 0 and not chunks:
                raise Disconnect("peer closed")
            raise Disconnect("peer closed mid-frame")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def send_frame(sock: socket.socket, kind: int,
               meta: Optional[dict] = None,
               arrays: Sequence[Any] = ()) -> None:
    """Serialize and send one frame (thread-safety is the caller's: wrap in
    a per-connection send lock when several threads share the socket)."""
    meta = dict(meta or {})
    blobs = []
    if arrays:
        meta["blobs"] = []
        for a in arrays:
            a = np.ascontiguousarray(np.asarray(a))
            meta["blobs"].append({"dtype": a.dtype.str, "shape": list(a.shape)})
            blobs.append(a.tobytes())
    payload = json.dumps(meta, separators=(",", ":")).encode()
    parts = [_HDR.pack(kind, len(payload), len(blobs)), payload]
    for b in blobs:
        parts.append(_BLOB.pack(len(b)))
        parts.append(b)
    try:
        sock.sendall(b"".join(parts))
    except (ConnectionResetError, BrokenPipeError, OSError) as e:
        raise Disconnect(f"send failed: {e}") from None


def recv_frame(sock: socket.socket) -> tuple[int, dict, list]:
    """Receive one frame: (kind, meta, arrays). Raises Disconnect on EOF,
    SessionError on a corrupt stream."""
    from .. import config
    kind, json_len, nblobs = _HDR.unpack(_recv_exact(sock, _HDR.size))
    if kind not in KIND_NAMES or json_len > _MAX_JSON:
        raise SessionError(f"corrupt session frame (kind={kind}, "
                           f"json_len={json_len})")
    meta = json.loads(_recv_exact(sock, json_len).decode()) if json_len else {}
    max_blob = config.load().max_frame_bytes
    arrays = []
    descs = meta.get("blobs") or []
    for i in range(nblobs):
        (blen,) = _BLOB.unpack(_recv_exact(sock, _BLOB.size))
        if blen > max_blob:
            raise SessionError(f"session frame blob of {blen} bytes exceeds "
                               f"max_frame_bytes={max_blob}")
        raw = _recv_exact(sock, blen)
        if i < len(descs):
            d = descs[i]
            arrays.append(np.frombuffer(raw, dtype=np.dtype(d["dtype"]))
                          .reshape(d["shape"]))
        else:
            arrays.append(np.frombuffer(raw, dtype=np.uint8))
    return kind, meta, arrays


def error_meta(exc: BaseException) -> dict:
    """ERROR-frame metadata for an exception (typed errors keep their code,
    retriability, and structured attributes across the wire)."""
    meta = {"code": int(getattr(exc, "code", _ec.ERR_OTHER)),
            "type": type(exc).__name__,
            "message": str(getattr(exc, "args", [exc])[0]) if exc.args
                       else str(exc),
            "retriable": bool(getattr(exc, "retriable", False))}
    for attr in ("tenant", "used", "quota", "depth", "rid", "slo_ms",
                 "dead", "headroom"):
        v = getattr(exc, attr, None)
        if v is not None:
            meta[attr] = v
    return meta


def raise_for_error(meta: dict) -> None:
    """Reconstruct the typed exception an ERROR frame carries and raise it."""
    code = int(meta.get("code", _ec.ERR_OTHER))
    msg = meta.get("message", "broker error")
    if code == _ec.ERR_QUOTA:
        raise QuotaExceededError(msg, tenant=meta.get("tenant"),
                                 used=int(meta.get("used", 0)),
                                 quota=int(meta.get("quota", 0)))
    if code == _ec.ERR_SERVE_BUSY:
        raise ServeBusyError(msg, tenant=meta.get("tenant"),
                             depth=int(meta.get("depth", 0)))
    if code == _ec.ERR_SLO_EXPIRED:
        raise SLOExpiredError(msg, tenant=meta.get("tenant"),
                              rid=meta.get("rid"),
                              slo_ms=int(meta.get("slo_ms", 0)))
    if code == _ec.ERR_POOL_DEGRADED:
        raise PoolDegradedError(msg, tenant=meta.get("tenant"),
                                dead=tuple(meta.get("dead") or ()),
                                headroom=int(meta.get("headroom", 0)))
    if code == _ec.ERR_SESSION:
        raise SessionError(msg)
    raise MPIError(msg, code=code)


def parse_socket_addr(spec: str) -> tuple[str, Any]:
    """Classify a serve-socket spec: a value containing "/" is a Unix-domain
    socket path, otherwise "host:port" TCP. Returns ("unix", path) or
    ("tcp", (host, port)). Malformed values fail loudly (config contract)."""
    if not spec:
        raise MPIError("empty serve socket spec", code=_ec.ERR_ARG)
    if "/" in spec:
        return "unix", spec
    host, sep, port = spec.rpartition(":")
    if not sep or not host:
        raise MPIError(f"serve socket {spec!r} is neither a Unix path "
                       f"(contains '/') nor host:port", code=_ec.ERR_ARG)
    try:
        return "tcp", (host, int(port))
    except ValueError:
        raise MPIError(f"serve socket {spec!r} has a non-integer port",
                       code=_ec.ERR_ARG) from None


def connect(spec: str, timeout: float = 10.0) -> socket.socket:
    """Dial a serve socket spec (client side)."""
    kind, addr = parse_socket_addr(spec)
    if kind == "unix":
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(timeout)
        s.connect(addr)
    else:
        s = socket.create_connection(addr, timeout=timeout)
    s.settimeout(None)
    # latency: a LEASE/RESULT reply is one small write; don't let Nagle
    # hold it hostage to the next frame
    try:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass                                    # AF_UNIX has no TCP options
    return s


def listen(spec: Optional[str]) -> tuple[socket.socket, str]:
    """Bind + listen on a serve socket spec (broker side). ``None``/"" picks
    a loopback TCP port. Returns (socket, canonical spec clients dial)."""
    if not spec:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        s.listen(64)
        return s, f"127.0.0.1:{s.getsockname()[1]}"
    kind, addr = parse_socket_addr(spec)
    if kind == "unix":
        import os
        try:
            os.unlink(addr)
        except FileNotFoundError:
            pass
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.bind(addr)
        s.listen(64)
        return s, addr
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(addr)
    s.listen(64)
    return s, f"{addr[0]}:{s.getsockname()[1]}"
