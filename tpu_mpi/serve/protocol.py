"""Session protocol: the framed wire format of the serve tier.

One frame = fixed header + JSON metadata + zero or more raw array blobs:

    u8  kind        (frame type, table below)
    u32 json_len    (big-endian)
    u16 nblobs
    json_len bytes  UTF-8 JSON metadata
    nblobs x { u32 blob_len, blob_len raw bytes }

Arrays travel as raw little-endian bytes with dtype/shape carried in the
metadata (``meta["blobs"]``), reconstructed with ``np.frombuffer`` — the
round trip is bitwise exact, which the two-tenant correctness test in
tests/test_serve.py asserts end-to-end.

Frame types (docs/serving.md has the full table):

    HELLO   client/worker -> broker   token, tenant, nranks (or role=worker)
    LEASE   broker -> client          tenant id, rank map, cid range
    OP      either direction          a collective / comm-management op
    RESULT  broker/worker -> peer     op completion + result arrays
    ERROR   broker -> client          typed failure (code + message)
    STATS   both                      per-tenant usage report request/reply
    DETACH  client -> broker          clean lease release
    BYE     broker -> client          lease revoked / broker shutting down
    PING/PONG both                    liveness probe

The transport is any SOCK_STREAM socket — TCP or Unix-domain; framing and
byte order match the native transport's length-prefixed style
(tpu_mpi/_native/transport.cc) so a future C++ fast path can speak it.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, Optional, Sequence

import numpy as np

from .. import error as _ec
from ..error import (MPIError, PoolDegradedError, QuotaExceededError,
                     ServeBusyError, SessionError, SLOExpiredError)

# frame kinds
HELLO = 1
LEASE = 2
OP = 3
RESULT = 4
ERROR = 5
STATS = 6
DETACH = 7
BYE = 8
PING = 9
PONG = 10
REDIRECT = 11      # router -> client: re-dial your home broker directly
METRICS = 12       # both: Prometheus-text exposition request/reply

KIND_NAMES = {HELLO: "HELLO", LEASE: "LEASE", OP: "OP", RESULT: "RESULT",
              ERROR: "ERROR", STATS: "STATS", DETACH: "DETACH", BYE: "BYE",
              PING: "PING", PONG: "PONG", REDIRECT: "REDIRECT",
              METRICS: "METRICS"}

_HDR = struct.Struct("!BIH")
_BLOB = struct.Struct("!I")

# Sanity bound for a single frame's JSON section; array blobs are bounded
# by the config max_frame_bytes knob at recv time.
_MAX_JSON = 1 << 24


class Disconnect(Exception):
    """Peer closed the connection at a frame boundary (clean EOF)."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes into ONE preallocated buffer (no chunk-list
    join copy — the receive side of the zero-copy frame path; the returned
    bytearray is what ``np.frombuffer`` views directly)."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        try:
            r = sock.recv_into(view[got:], n - got)
        except (ConnectionResetError, BrokenPipeError, OSError) as e:
            raise Disconnect(f"connection lost mid-frame: {e}") from None
        if r == 0:
            if got == 0:
                raise Disconnect("peer closed")
            raise Disconnect("peer closed mid-frame")
        got += r
    return bytes(buf) if n < 64 else buf  # headers: hashable bytes is fine


# Linux IOV_MAX is 1024; stay well under it per sendmsg call.
_IOV_MAX = 512


def _sendmsg_all(sock: socket.socket, parts: list) -> int:
    """Scatter-gather send of a list of bytes-like parts with partial-send
    resumption — the frame path's writev. Returns the number of sendmsg
    syscalls issued (the ``sg_writes`` pvar)."""
    bufs = [p if isinstance(p, memoryview) else memoryview(p)
            for p in parts]
    bufs = [b.cast("B") if b.ndim != 1 or b.format != "B" else b
            for b in bufs if b.nbytes]
    calls = 0
    while bufs:
        try:
            n = sock.sendmsg(bufs[:_IOV_MAX])
        except (ConnectionResetError, BrokenPipeError, OSError) as e:
            raise Disconnect(f"send failed: {e}") from None
        calls += 1
        while n:
            if n >= len(bufs[0]):
                n -= len(bufs.pop(0))
            else:
                bufs[0] = bufs[0][n:]
                n = 0
    return calls


def send_frame(sock: socket.socket, kind: int,
               meta: Optional[dict] = None,
               arrays: Sequence[Any] = ()) -> None:
    """Serialize and send one frame (thread-safety is the caller's: wrap in
    a per-connection send lock when several threads share the socket).

    Zero-copy path (``TPU_MPI_SERVE_ZEROCOPY``, default on): array payloads
    are scatter-gather written straight from their backing buffers via
    ``sendmsg`` — a C-contiguous array (including the ``np.frombuffer``
    views ``recv_frame`` hands the broker) crosses this hop with ZERO
    marshalling copies; only a non-contiguous input pays one
    ``ascontiguousarray`` materialization, counted in the ``serve_frame``
    pvar block (gate: copies/op <= 1)."""
    from .. import config, perfvars
    meta = dict(meta or {})
    views: list = []
    copies = 0
    zc_bytes = 0
    if arrays:
        meta["blobs"] = []
        for a in arrays:
            arr = np.asarray(a)
            if not arr.flags.c_contiguous:
                arr = np.ascontiguousarray(arr)
                copies += 1
            else:
                zc_bytes += arr.nbytes
            meta["blobs"].append({"dtype": arr.dtype.str,
                                  "shape": list(arr.shape)})
            views.append(arr)
    payload = json.dumps(meta, separators=(",", ":")).encode()
    if arrays and not config.load().serve_zerocopy:
        # legacy marshal path (the before/after benchmark lane): every
        # payload is flattened to bytes and joined into one send buffer
        parts = [_HDR.pack(kind, len(payload), len(views)), payload]
        for arr in views:
            b = arr.tobytes()
            parts.append(_BLOB.pack(len(b)))
            parts.append(b)
        try:
            sock.sendall(b"".join(parts))
        except (ConnectionResetError, BrokenPipeError, OSError) as e:
            raise Disconnect(f"send failed: {e}") from None
        perfvars.note_serve_frame(ops=1, copies=copies + len(views),
                                  sg_writes=0, zc_bytes=0)
        return
    parts = [_HDR.pack(kind, len(payload), len(views)), payload]
    for arr in views:
        parts.append(_BLOB.pack(arr.nbytes))
        parts.append(memoryview(arr).cast("B") if arr.ndim else
                     memoryview(arr.reshape(1)).cast("B"))
    calls = _sendmsg_all(sock, parts)
    if arrays:
        perfvars.note_serve_frame(ops=1, copies=copies, sg_writes=calls,
                                  zc_bytes=zc_bytes)


def decode_blob(raw, desc: Optional[dict]) -> np.ndarray:
    """One received blob as a ``np.frombuffer`` VIEW over its receive
    buffer (typed+shaped when the metadata describes it). Shared by the
    blocking :func:`recv_frame` below and the event-driven front door's
    incremental parser (serve.frontdoor) so both transports reconstruct
    payloads identically."""
    if desc is not None:
        return (np.frombuffer(raw, dtype=np.dtype(desc["dtype"]))
                .reshape(desc["shape"]))
    return np.frombuffer(raw, dtype=np.uint8)


def recv_frame(sock: socket.socket) -> tuple[int, dict, list]:
    """Receive one frame: (kind, meta, arrays). Raises Disconnect on EOF,
    SessionError on a corrupt stream. Each array is a ``np.frombuffer``
    VIEW over the single receive buffer (no join/marshal copy), so
    forwarding it through :func:`send_frame` keeps the whole
    session-socket -> rank-mailbox hop copy-free."""
    from .. import config
    kind, json_len, nblobs = _HDR.unpack(_recv_exact(sock, _HDR.size))
    if kind not in KIND_NAMES or json_len > _MAX_JSON:
        raise SessionError(f"corrupt session frame (kind={kind}, "
                           f"json_len={json_len})")
    meta = json.loads(bytes(_recv_exact(sock, json_len)).decode()) \
        if json_len else {}
    max_blob = config.load().max_frame_bytes
    arrays = []
    descs = meta.get("blobs") or []
    for i in range(nblobs):
        (blen,) = _BLOB.unpack(_recv_exact(sock, _BLOB.size))
        if blen > max_blob:
            raise SessionError(f"session frame blob of {blen} bytes exceeds "
                               f"max_frame_bytes={max_blob}")
        raw = _recv_exact(sock, blen)
        arrays.append(decode_blob(raw, descs[i] if i < len(descs) else None))
    return kind, meta, arrays


def error_meta(exc: BaseException) -> dict:
    """ERROR-frame metadata for an exception (typed errors keep their code,
    retriability, and structured attributes across the wire)."""
    meta = {"code": int(getattr(exc, "code", _ec.ERR_OTHER)),
            "type": type(exc).__name__,
            "message": str(getattr(exc, "args", [exc])[0]) if exc.args
                       else str(exc),
            "retriable": bool(getattr(exc, "retriable", False))}
    for attr in ("tenant", "used", "quota", "depth", "rid", "slo_ms",
                 "dead", "headroom"):
        v = getattr(exc, attr, None)
        if v is not None:
            meta[attr] = v
    return meta


def raise_for_error(meta: dict) -> None:
    """Reconstruct the typed exception an ERROR frame carries and raise it."""
    code = int(meta.get("code", _ec.ERR_OTHER))
    msg = meta.get("message", "broker error")
    if code == _ec.ERR_QUOTA:
        raise QuotaExceededError(msg, tenant=meta.get("tenant"),
                                 used=int(meta.get("used", 0)),
                                 quota=int(meta.get("quota", 0)))
    if code == _ec.ERR_SERVE_BUSY:
        raise ServeBusyError(msg, tenant=meta.get("tenant"),
                             depth=int(meta.get("depth", 0)))
    if code == _ec.ERR_SLO_EXPIRED:
        raise SLOExpiredError(msg, tenant=meta.get("tenant"),
                              rid=meta.get("rid"),
                              slo_ms=int(meta.get("slo_ms", 0)))
    if code == _ec.ERR_POOL_DEGRADED:
        raise PoolDegradedError(msg, tenant=meta.get("tenant"),
                                dead=tuple(meta.get("dead") or ()),
                                headroom=int(meta.get("headroom", 0)))
    if code == _ec.ERR_SESSION:
        raise SessionError(msg)
    raise MPIError(msg, code=code)


def parse_socket_addr(spec: str) -> tuple[str, Any]:
    """Classify a serve-socket spec: a value containing "/" is a Unix-domain
    socket path, otherwise "host:port" TCP. Returns ("unix", path) or
    ("tcp", (host, port)). Malformed values fail loudly (config contract)."""
    if not spec:
        raise MPIError("empty serve socket spec", code=_ec.ERR_ARG)
    if "/" in spec:
        return "unix", spec
    host, sep, port = spec.rpartition(":")
    if not sep or not host:
        raise MPIError(f"serve socket {spec!r} is neither a Unix path "
                       f"(contains '/') nor host:port", code=_ec.ERR_ARG)
    try:
        return "tcp", (host, int(port))
    except ValueError:
        raise MPIError(f"serve socket {spec!r} has a non-integer port",
                       code=_ec.ERR_ARG) from None


def connect(spec: str, timeout: float = 10.0) -> socket.socket:
    """Dial a serve socket spec (client side)."""
    kind, addr = parse_socket_addr(spec)
    if kind == "unix":
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(timeout)
        s.connect(addr)
    else:
        s = socket.create_connection(addr, timeout=timeout)
    s.settimeout(None)
    # latency: a LEASE/RESULT reply is one small write; don't let Nagle
    # hold it hostage to the next frame
    try:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass                                    # AF_UNIX has no TCP options
    return s


# Listen backlog: attach herds arrive in bursts (the front-door scale
# lane dials thousands of sockets per second); a 64-entry backlog drops
# SYNs under that load and the herd sees connection resets, not queueing.
_BACKLOG = 1024


def listen(spec: Optional[str]) -> tuple[socket.socket, str]:
    """Bind + listen on a serve socket spec (broker side). ``None``/"" picks
    a loopback TCP port. Returns (socket, canonical spec clients dial)."""
    if not spec:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        s.listen(_BACKLOG)
        return s, f"127.0.0.1:{s.getsockname()[1]}"
    kind, addr = parse_socket_addr(spec)
    if kind == "unix":
        import os
        try:
            os.unlink(addr)
        except FileNotFoundError:
            pass
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.bind(addr)
        s.listen(_BACKLOG)
        return s, addr
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(addr)
    s.listen(_BACKLOG)
    return s, f"{addr[0]}:{s.getsockname()[1]}"
