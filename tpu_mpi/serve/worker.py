"""Pool worker process for the procs-backend broker (``TPU_MPI_SERVE_BACKEND=procs``).

One OS process per pool rank, the serve-tier analog of a ``tpurun --procs``
rank: it joins the broker's rendezvous (``launcher.Rendezvous`` — the same
coordinator the classic launcher uses), runs ``MPI.Init`` onto the native
framed transport, then dials the broker's pool-control socket and serves
``wop`` frames serially:

    broker ──OP {wop: coll, cid, ...} + part──▶ worker (this process)
    broker ◀──RESULT {oid} + result───────────  worker

The broker sends every worker's frames under ONE dispatch lock, and this
loop executes them in arrival order, so all pool ranks initiate collectives
in the same global order — the exact invariant the thread backend gets from
its per-rank queues. Collectives themselves run on the native transport
between the worker processes; the broker never touches payload bytes beyond
forwarding the client's frame views (the zero-copy path, ``serve_frame``
pvars).

Failure semantics: workers run with the heartbeat failure detector ON
(the broker's spawn env sets ``TPU_MPI_HEARTBEAT_MS`` unless the operator
chose a value), so a SIGKILL'd sibling surfaces as a typed
``ProcFailedError`` from the in-flight collective instead of a hang; the
broker additionally detects the death via control-socket EOF.

Elastic grow on this tier spawns REAL processes: survivors ``Comm_spawn``
:func:`_pool_child_entry` (a module-level function, so it serializes by
reference), and each child Inits, merges with the parent intercomm, then
dials the broker exactly like a first-generation worker — the pool-control
address rides the inherited spawn environment.
"""

from __future__ import annotations

import os
import sys
from typing import Any, Dict

import numpy as np

from .. import error as _ec
from ..error import MPIError, SessionError
from . import protocol


def _cidify(cid: Any) -> Any:
    """Canonicalize a JSON-decoded cid: the wire turns tuple cids (the
    procs tier's ``("shrink", ...)``/``("c", r, n)`` forms) into lists;
    comms and channels key on the tuple form."""
    if isinstance(cid, list):
        return tuple(_cidify(c) for c in cid)
    return cid


def _reduce_op(name: str):
    from .broker import _reduce_op as _ro
    return _ro(name)


class _PoolWorker:
    """The per-process frame loop: comm registry + wop dispatch."""

    def __init__(self, sock, ctx, rank: int):
        self.sock = sock
        self.ctx = ctx
        self.rank = rank                       # world rank
        self.comms: Dict[Any, Any] = {}        # cid -> Comm

    # -- comm registry -------------------------------------------------------
    def _comm(self, cid):
        comm = self.comms.get(cid)
        if comm is None:
            raise SessionError(f"pool worker {self.rank}: no comm for cid "
                               f"{cid!r} (register/warm never arrived?)")
        return comm

    def _register(self, cid, group) -> None:
        from ..comm import Comm
        group = tuple(group)
        comm = Comm(group, cid, name=f"serve-pool:{cid}")
        # eager channel registration, same reason as the thread backend:
        # check_fault scopes failures by the channel's group
        self.ctx.channel(cid, len(group), group)
        self.comms[cid] = comm

    def _rebind(self, cid, group) -> None:
        """Elastic rebind: drop the stale channel (its group spans a retired
        rank), re-register the SAME cid on the remapped group."""
        with self.ctx._channels_lock:
            self.ctx._channels.pop(cid, None)
        self._register(cid, group)
        from ..overlap import plans
        plans.invalidate(cid)

    # -- wop handlers --------------------------------------------------------
    def _wop_warm(self, meta: dict) -> tuple:
        from .. import collective
        self._register(_cidify(meta["cid"]), meta["group"])
        comm = self.comms[_cidify(meta["cid"])]
        collective.Barrier(comm)
        collective.Allreduce(np.ones(8, np.float32), _reduce_op("sum"), comm)
        return {}, []

    def _wop_coll(self, meta: dict, arrays: list) -> tuple:
        from .. import collective
        comm = self._comm(_cidify(meta["cid"]))
        kind = meta["kind"]
        if kind == "allreduce":
            res = collective.Allreduce(arrays[0],
                                       _reduce_op(meta.get("reduce", "sum")),
                                       comm)
        elif kind == "bcast":
            root = int(meta.get("root", 0))
            if int(meta["i"]) == root:
                buf = np.array(arrays[0], copy=True)
            else:
                d = meta["desc"]
                buf = np.empty(tuple(d["shape"]), np.dtype(d["dtype"]))
            res = collective.Bcast(buf, root, comm)
        elif kind == "barrier":
            collective.Barrier(comm)
            res = None
        else:
            raise MPIError(f"unknown pool coll kind {kind!r}",
                           code=_ec.ERR_ARG)
        if meta.get("ret") and res is not None:
            return {}, [np.asarray(res)]
        return {}, []

    def _wop_free(self, meta: dict) -> tuple:
        cid = _cidify(meta["cid"])
        from ..collective import nb_shutdown
        nb_shutdown(self.ctx, cid, self.rank)
        self.comms.pop(cid, None)
        with self.ctx._channels_lock:
            self.ctx._channels.pop(cid, None)
        from ..overlap import plans
        plans.invalidate(cid)
        return {}, []

    def _wop_revoke_ns(self, meta: dict) -> None:
        """Lease reclamation for one tenant's cid range: channels dropped,
        cids revoked so a straggler raises rather than hangs."""
        base, limit = int(meta["base"]), int(meta["limit"])
        from ..overlap import plans
        with self.ctx._channels_lock:
            stale = [k for k in self.ctx._channels
                     if isinstance(k, int) and base <= k < limit]
            for k in stale:
                del self.ctx._channels[k]
        for cid in [c for c in self.comms
                    if isinstance(c, int) and base <= c < limit]:
            del self.comms[cid]
            plans.invalidate(cid)
        self.ctx.revoked_cids.update(range(base, limit))

    def _wop_round(self, meta: dict) -> tuple:
        from ..elastic.protocol import rebind_round
        comm = self._comm(_cidify(meta["cid"]))
        rebind_round(comm, meta["op"], epoch=meta.get("epoch"),
                     declared=tuple(meta.get("declared") or comm.group))
        return {}, []

    def _wop_shrink(self, meta: dict) -> tuple:
        """Collapse the base comm to its survivors. The broker is the
        failure authority on the serve tier: it ships the declared-dead
        set explicitly, so a drain-and-retire shrink (rank alive, just
        idle) takes the same path as a SIGKILL shrink."""
        from ..comm import Comm_shrink
        for r in meta.get("dead") or ():
            self.ctx.peer_failed(int(r))
        comm = self._comm(_cidify(meta["cid"]))
        shrunk = Comm_shrink(comm)
        self.comms[shrunk.cid] = shrunk
        return {"group": list(shrunk.group), "cid": shrunk.cid}, []

    def _wop_grow(self, meta: dict) -> tuple:
        """Spawn n replacement worker PROCESSES and merge them in: the
        procs-tier realization of ElasticController.grow_base. Children
        inherit this worker's environment (spawn copies os.environ), so
        TPU_MPI_SERVE_POOL_ADDR/TOKEN reach them and they dial the broker
        themselves from :func:`_pool_child_entry`."""
        from ..comm import Comm_spawn, Intercomm_merge
        comm = self._comm(_cidify(meta["cid"]))
        inter = Comm_spawn(_pool_child_entry, None, int(meta["n"]), comm)
        merged = Intercomm_merge(inter, False)
        self.comms[merged.cid] = merged
        return {"group": list(merged.group), "cid": merged.cid}, []

    def _wop_pvars(self, meta: dict) -> tuple:
        from .. import perfvars
        return {"snapshot": perfvars.snapshot()}, []

    # -- the loop ------------------------------------------------------------
    def serve(self) -> None:
        while True:
            try:
                kind, meta, arrays = protocol.recv_frame(self.sock)
            except protocol.Disconnect:
                return                       # broker went away: exit quietly
            if kind != protocol.OP:
                continue
            wop = meta.get("wop")
            oid = meta.get("oid")
            if wop == "shutdown":
                return
            # fire-and-forget control frames (no oid, no reply): ordering
            # with later ops is the socket's FIFO
            if wop == "register":
                self._register(_cidify(meta["cid"]), meta["group"])
                continue
            if wop == "rebind":
                self._rebind(_cidify(meta["cid"]), meta["group"])
                continue
            if wop == "revoke_ns":
                self._wop_revoke_ns(meta)
                continue
            try:
                if wop == "coll":
                    rmeta, rarrays = self._wop_coll(meta, arrays)
                elif wop == "warm":
                    rmeta, rarrays = self._wop_warm(meta)
                elif wop == "free":
                    rmeta, rarrays = self._wop_free(meta)
                elif wop == "round":
                    rmeta, rarrays = self._wop_round(meta)
                elif wop == "shrink":
                    rmeta, rarrays = self._wop_shrink(meta)
                elif wop == "grow":
                    rmeta, rarrays = self._wop_grow(meta)
                elif wop == "pvars":
                    rmeta, rarrays = self._wop_pvars(meta)
                elif wop == "ping":
                    rmeta, rarrays = {}, []
                else:
                    raise MPIError(f"unknown pool wop {wop!r}",
                                   code=_ec.ERR_ARG)
            except BaseException as e:       # noqa: BLE001 - typed to broker
                em = protocol.error_meta(e)
                em["oid"] = oid
                try:
                    protocol.send_frame(self.sock, protocol.ERROR, em)
                except protocol.Disconnect:
                    return
                continue
            rmeta["oid"] = oid
            try:
                protocol.send_frame(self.sock, protocol.RESULT, rmeta,
                                    rarrays)
            except protocol.Disconnect:
                return


def _attach_to_broker(base_comm=None) -> _PoolWorker:
    """HELLO onto the broker's pool-control socket and build the loop
    state. ``base_comm`` (elastic children only) pre-seeds the registry
    with the merged pool-wide comm, whose cid the broker adopted from the
    survivors' grow replies."""
    from .._runtime import require_env
    ctx, rank = require_env()
    addr = os.environ["TPU_MPI_SERVE_POOL_ADDR"]
    sock = protocol.connect(addr)
    protocol.send_frame(sock, protocol.HELLO, {
        "role": "worker", "rank": rank, "pid": os.getpid(),
        "token": os.environ.get("TPU_MPI_SERVE_POOL_TOKEN", "")})
    w = _PoolWorker(sock, ctx, rank)
    if base_comm is not None:
        w.comms[base_comm.cid] = base_comm
    return w


def _pool_child_entry() -> None:
    """Comm_spawn entry for elastic growth (module-level: serializes by
    reference). Mirrors the thread backend's child_entry: Init, merge with
    the parent intercomm (high side — survivors keep their comm-relative
    ranks), then enter the ordinary worker loop."""
    from .. import environment
    from ..comm import Comm_get_parent, Intercomm_merge
    environment.Init()
    merged = Intercomm_merge(Comm_get_parent(), True)
    _attach_to_broker(merged).serve()


def main() -> int:
    """``python -m tpu_mpi.serve.worker``: first-generation pool worker,
    launched by the broker with the rendezvous triple + pool-control env."""
    from .. import environment
    environment.Init()
    worker = _attach_to_broker()
    worker.serve()
    try:
        environment.Finalize()               # clean "bye", not a failure
    except BaseException:                    # noqa: BLE001 - exiting anyway
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
