"""tpu_mpi.serve — the multi-tenant communicator service (docs/serving.md).

A :class:`Broker` (``tpurun --serve``) owns a warm Init'd world and leases
slices of it to clients; :func:`attach` (or ``MPI.Init(session=...)``)
joins as a tenant in one sub-millisecond round trip. Per-tenant cid
namespaces isolate communicators, a deficit-round-robin
:class:`~tpu_mpi.serve.queueing.FairQueue` shares the pool, and a
:class:`~tpu_mpi.serve.ledger.Ledger` enforces byte quotas and attributes
pvar counters per tenant.
"""

from __future__ import annotations

from typing import Optional

from .broker import Broker
from .ledger import Ledger, POOL_TENANT
from .protocol import Disconnect
from .queueing import FairQueue
from .session import ClientSession, SessionComm, attach, attach_many

__all__ = ["Broker", "ClientSession", "SessionComm", "FairQueue", "Ledger",
           "POOL_TENANT", "Disconnect", "attach", "attach_many",
           "current_session"]

# The session MPI.Init(session=...) attached on this process (one per
# process, matching Init's once-per-rank contract). Finalize detaches it.
_current: Optional[ClientSession] = None


def current_session() -> Optional[ClientSession]:
    """The session attached by ``MPI.Init(session=...)``, or None."""
    return _current


def _set_current(session: Optional[ClientSession]) -> None:
    global _current
    _current = session
