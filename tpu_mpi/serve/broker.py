"""The ``tpurun --serve`` broker: a warm world leased to many tenants.

One broker process owns one warm :class:`~tpu_mpi._runtime.SpmdContext` —
rank threads that already ran ``MPI.Init`` and a priming collective, so the
plan caches are hot — and leases slices of it to short-lived client
sessions over the framed session protocol (``serve.protocol``). The shape
(docs/serving.md):

    client ──HELLO──▶ handler thread ──▶ Ledger.charge ─▶ FairQueue
                                                             │ (DRR)
    client ◀─RESULT── handler thread ◀── PoolOp.done ◀── dispatcher
                                                             │
                                              rank worker threads (warm)

- one **handler thread** per connected client: authenticates, grants the
  lease (tenant id + rank map + cid-namespace range), then turns OP frames
  into :class:`PoolOp`\\ s and waits for their completion;
- one **dispatcher thread** pops the fair queue in deficit-round-robin
  order and fans each op out to the rank worker queues atomically, so
  every rank initiates collectives in the same global order (the same
  invariant the launcher tier gets from program order);
- N **rank worker threads**, each bound to one world rank of the warm
  context, executing closures serially. While executing for a tenant the
  thread carries the tenant in TLS (``set_current_tenant``), which routes
  ``alloc_cid`` into the tenant's namespace and arms the cross-tenant cid
  guard in ``SpmdContext.channel``.

Attach is <1 ms because nothing collective happens on the attach path: the
lease's root cid comes straight from the tenant's freshly carved namespace
(broker-side allocation, no rendezvous), and the world is already Init'd.

Fate-sharing note: a combine-step exception would poison the whole pool
via ``ctx.fail`` (thread-tier fate sharing), so the broker validates every
op — shapes, dtypes, cid ownership, quota — at admission, before anything
touches a rank queue. A malformed op is a typed ERROR frame to one tenant,
never a pool-wide failure.
"""

from __future__ import annotations

import hmac
import itertools
import json
import os
import queue
import secrets
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import config
from .. import error as _ec
from .. import flight as _flight
from .. import locksmith
from .. import tracectx as _tc
from ..analyze import events as _ev
from ..error import MPIError, PoolDegradedError, ProcFailedError, SessionError
from .._runtime import CidNamespace, SpmdContext, set_current_tenant, set_env
from . import protocol
from .ledger import CidShard, Ledger
from .queueing import FairQueue
from .worker import _cidify

_OPS = None                       # lazy operator table (imports jax)


def _reduce_op(name: str):
    global _OPS
    if _OPS is None:
        from .. import operators
        _OPS = {"sum": operators.SUM, "prod": operators.PROD,
                "min": operators.MIN, "max": operators.MAX}
    op = _OPS.get(name)
    if op is None:
        raise MPIError(f"unknown reduce op {name!r}; serve supports "
                       f"{sorted(_OPS)}", code=_ec.ERR_OP)
    return op


class PoolOp:
    """One admitted client op on its way through the fair queue to the
    rank workers. ``done`` fires once every member rank finished."""

    __slots__ = ("oid", "tenant", "kind", "cid", "parts", "reduce",
                 "root", "nbytes", "done", "results", "error",
                 "trace", "t_submit")

    def __init__(self, oid: int, tenant: str, kind: str, cid: int,
                 parts: List[np.ndarray], reduce: str, root: int):
        self.oid = oid
        self.tenant = tenant
        self.kind = kind
        self.cid = cid
        self.parts = parts
        self.reduce = reduce
        self.root = root
        self.nbytes = sum(int(p.nbytes) for p in parts)
        self.done = threading.Event()
        self.results: Optional[list] = None
        self.error: Optional[BaseException] = None
        # request tracing (tpu_mpi.tracectx): the sampled request's context,
        # bound to the rank-worker TLS while the op executes so pvar
        # op-scopes emit their phase spans under it; t_submit brackets the
        # fair-queue wait span reconstructed at pop time.
        self.trace: Optional[_tc.TraceCtx] = None
        self.t_submit: Optional[float] = None


class _ThreadPool:
    """The warm world: one SpmdContext, one worker thread per rank, each
    Init'd once at broker start and reused by every tenant."""

    kind = "threads"

    def __init__(self, nranks: int, shard: Optional[CidShard] = None):
        self.nranks = int(nranks)              # configured (restore-target) size
        self.ctx = SpmdContext(self.nranks)
        # multi-broker scale-out: this broker carves tenant namespaces from
        # its own disjoint cid shard (serve.ledger.CidShard)
        self.shard = shard or CidShard()
        self.ctx._ns_next_base = self.shard.base
        # elastic membership (tpu_mpi.elastic): `active` is the pool-wide
        # comm's group in merge order (survivors first, replacements after);
        # `failed` holds declared-dead world ranks; `retired` the subset
        # already shrunk out of the base comm.
        self.active: List[int] = list(range(self.nranks))
        self.failed: set = set()
        self.retired: set = set()
        self.base_comm: Any = None             # warm -> shrunk -> merged comm
        self._queues: List[queue.Queue] = [queue.Queue()
                                           for _ in range(self.nranks)]
        self._queues_lock = locksmith.make_lock("pool.queues")
        self._threads: List[threading.Thread] = []
        self._dispatch_lock = locksmith.make_lock("pool.dispatch")
        self._comms: Dict[int, Any] = {}          # cid -> Comm (shared)
        self._comms_lock = locksmith.make_lock("pool.comms")

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        for r in range(self.nranks):
            t = threading.Thread(target=self._worker, args=(r,),
                                 name=f"serve-rank{r}", daemon=True)
            t.start()
            self._threads.append(t)
        self._warm()

    def _worker(self, rank: int) -> None:
        set_env((self.ctx, rank))
        from .. import environment
        environment.Init()
        self._worker_loop(rank)

    def _worker_loop(self, rank: int) -> None:
        """Consume this rank's work queue until the None sentinel. Split
        from :meth:`_worker` so a rank spawned mid-life by an elastic grow
        (already Init'd by its spawn entry) can join the same loop."""
        q = self.ensure_queue(rank)
        while True:
            item = q.get()
            if item is None:
                return
            tenant, fn = item
            set_current_tenant(tenant)
            try:
                fn(rank)
            finally:
                set_current_tenant(None)
                # drop the task closure BEFORE blocking on the next get():
                # a loop local that outlives its op pins the op's payload
                # arrays — and with recv leases those alias registered
                # buffers the front door wants to recycle (an idle pool
                # would otherwise pin its last payload forever)
                del item, fn

    # -- elastic membership --------------------------------------------------
    def healthy(self) -> List[int]:
        """World ranks currently able to serve, in comm order."""
        return [r for r in self.active if r not in self.failed]

    def dead_in(self, group) -> tuple:
        return tuple(sorted(set(group) & self.failed))

    def mark_failed(self, rank: int) -> bool:
        """Failure-detector verdict: declare a pool rank dead. Waiters on
        comms spanning it raise ProcFailedError instead of hanging; the
        rank stays in ``active`` (degraded) until a resize shrinks it out."""
        if rank in self.failed or rank not in self.active:
            return False
        self.failed.add(rank)
        self.ctx.peer_failed(rank)
        return True

    def ensure_queue(self, rank: int) -> queue.Queue:
        with self._queues_lock:
            while len(self._queues) <= rank:
                self._queues.append(queue.Queue())
            return self._queues[rank]

    def _warm(self) -> None:
        """Prime the pool before the first lease: a Barrier plus a tiny
        Allreduce on a pool-internal comm walks the whole collective path
        (channels, plan cache, jit warm-up) so the first tenant op pays
        none of it."""
        from ..comm import Comm
        cid = self.ctx.alloc_cid()            # pool allocator (no tenant TLS)
        comm = Comm(tuple(range(self.nranks)), cid, ctx=self.ctx,
                    name="serve-warm")
        with self._comms_lock:
            self._comms[cid] = comm
        self.base_comm = comm
        self._run_on_all(None, lambda rank: self._warm_body(comm))

    @staticmethod
    def _warm_body(comm) -> None:
        from .. import collective
        collective.Barrier(comm)
        collective.Allreduce(np.ones(8, np.float32), _reduce_op("sum"), comm)

    def _run_on_all(self, tenant: Optional[str], fn) -> None:
        """Run ``fn(rank)`` on every healthy rank worker and wait."""
        self.run_on(self.healthy(), tenant, fn, timeout=None)

    def run_on(self, ranks, tenant: Optional[str], fn,
               timeout: Optional[float] = 120.0) -> list:
        """Run ``fn(rank)`` on the given rank workers and wait; returns the
        per-rank results in ``ranks`` order. The first exception propagates
        (after every rank finished, so no closure is left running)."""
        ranks = list(ranks)
        done = threading.Event()
        errs: list = []
        results: list = [None] * len(ranks)
        remaining = [len(ranks)]
        lock = threading.Lock()

        def make(i):
            def wrapped(rank):
                try:
                    results[i] = fn(rank)
                except BaseException as e:      # noqa: BLE001 - reported below
                    errs.append(e)
                finally:
                    with lock:
                        remaining[0] -= 1
                        if remaining[0] == 0:
                            done.set()
            return wrapped

        with self._dispatch_lock:
            for i, r in enumerate(ranks):
                self.ensure_queue(r).put((tenant, make(i)))
        if not done.wait(timeout):
            raise SessionError(f"pool closure timed out on ranks {ranks}")
        if errs:
            raise errs[0]
        return results

    def shutdown(self) -> None:
        with self._queues_lock:
            queues = list(self._queues)
        for q in queues:
            q.put(None)
        for t in self._threads:
            t.join(timeout=5)

    # -- comm registry -------------------------------------------------------
    def register_comm(self, group, cid: int, tenant: str):
        from ..comm import Comm
        comm = Comm(tuple(group), cid, ctx=self.ctx,
                    name=f"serve:{tenant}")
        # eager channel registration: check_fault scopes a failure by the
        # channel's GROUP, so a comm registered while the pool is degraded
        # must not inherit the pessimistic no-group check on its first op
        set_current_tenant(tenant)
        try:
            self.ctx.channel(cid, len(comm.group), comm.group)
        finally:
            set_current_tenant(None)
        with self._comms_lock:
            self._comms[cid] = comm
        return comm

    def comm_for(self, cid: int):
        with self._comms_lock:
            return self._comms.get(cid)

    def drop_comm(self, cid: int) -> None:
        with self._comms_lock:
            self._comms.pop(cid, None)

    def rebind_comm(self, cid, group, tenant: Optional[str]):
        """Point an existing cid at a remapped group (elastic rebind): drop
        the stale channel — its group spans a retired rank and would fault-
        check forever — then register a fresh Comm and its channel. The cid
        is UNCHANGED, so the tenant's lease, ledger books, and cid-range
        ownership all survive the resize untouched."""
        from ..comm import Comm
        group = tuple(group)
        with self.ctx._channels_lock:
            self.ctx._channels.pop(cid, None)
        set_current_tenant(tenant)
        try:
            comm = Comm(group, cid, ctx=self.ctx,
                        name=f"serve:{tenant or 'pool'}")
            self.ctx.channel(cid, len(group), group)
        finally:
            set_current_tenant(None)
        with self._comms_lock:
            self._comms[cid] = comm
        from ..overlap import plans
        plans.invalidate(cid)
        return comm

    # -- elastic resize primitives (driven by tpu_mpi.elastic) ----------------
    def adopt_base(self, comm) -> None:
        with self._comms_lock:
            self._comms[comm.cid] = comm
        self.base_comm = comm
        self.active = list(comm.group)

    def shrink_base(self) -> tuple:
        """Collapse the pool-wide comm to its survivors via Comm_shrink.
        EVERY member thread of the old base comm participates — including
        threads whose world rank was declared dead. That conscription is a
        thread-tier substrate honesty note: rank "death" here is a
        declaration (the sidecar process died; the rank thread shares our
        address space and cannot die independently), so the dead rank's
        thread stands in for it one last time in the ftagree rendezvous,
        exactly as ULFM's agreement excludes it from the outcome. The
        conscripted workers are then permanently retired. Returns
        ``(survivor_comm, dead_ranks)``."""
        from ..comm import Comm_shrink
        base = self.base_comm
        group = list(base.group)
        res = self.run_on(group, None, lambda rank: Comm_shrink(base))
        shrunk = next(c for r, c in zip(group, res) if r not in self.failed)
        dead = tuple(r for r in group if r in self.failed)
        for r in dead:
            self.retired.add(r)
            self.ensure_queue(r).put(None)     # retire the conscripted worker
        self.adopt_base(shrunk)
        return shrunk, dead

    def grow_base(self, n: int) -> tuple:
        """Spawn ``n`` replacement rank threads and merge them into the
        pool-wide comm (the GROW half of the elastic protocol): survivors
        collectively Comm_spawn the children, both sides Intercomm_merge,
        and merge ordering puts survivors first — so every pre-existing
        comm-relative rank is preserved. The children Init, adopt the
        merged world's epoch space (Intercomm_merge's epoch contribution),
        and enter the ordinary worker loop. Returns ``(merged_comm,
        new_world_ranks)``."""
        from ..comm import Comm_spawn, Intercomm_merge
        base = self.base_comm
        pool = self

        def child_entry():
            from .. import environment
            from ..comm import Comm_get_parent
            from ..comm import Intercomm_merge as _merge
            from .._runtime import require_env
            environment.Init()
            _, me = require_env()
            _merge(Comm_get_parent(), True)
            pool._worker_loop(me)

        def body(rank):
            inter = Comm_spawn(child_entry, None, n, base)
            return Intercomm_merge(inter, False)

        res = self.run_on(list(base.group), None, body)
        merged = res[0]
        new_ranks = tuple(r for r in merged.group if r not in base.group)
        for r in new_ranks:
            self.ensure_queue(r)
        self.adopt_base(merged)
        return merged, new_ranks

    # -- op execution --------------------------------------------------------
    def run_op(self, op: PoolOp, on_done) -> None:
        """Fan ``op`` out to every member rank's queue atomically (one
        dispatch lock → every rank sees the same initiation order) and
        return immediately; ``on_done(op)`` fires from the last rank."""
        comm = self.comm_for(op.cid)
        if comm is None:
            op.error = SessionError(f"cid {op.cid} has no live communicator")
            on_done(op)
            return
        group = comm.group
        results: list = [None] * len(group)
        remaining = [len(group)]
        lock = threading.Lock()

        def make(i):
            def run(rank):
                try:
                    if op.trace is None:
                        results[i] = self._execute(op, comm, i, rank)
                    else:
                        # bind the request's trace to this rank worker so
                        # the pvar op-scope emits its phase spans under it
                        with _tc.bind(op.trace):
                            results[i] = self._execute(op, comm, i, rank)
                except BaseException as e:      # noqa: BLE001 - sent as ERROR
                    op.error = e
                finally:
                    with lock:
                        remaining[0] -= 1
                        last = remaining[0] == 0
                    if last:
                        op.results = results
                        on_done(op)
            return run

        with self._dispatch_lock:
            for i, world_rank in enumerate(group):
                self._queues[world_rank].put((op.tenant, make(i)))

    def _execute(self, op: PoolOp, comm, i: int, rank: int):
        from .. import collective
        if op.kind == "allreduce":
            part = op.parts[i] if len(op.parts) > 1 else op.parts[0]
            return collective.Allreduce(part, _reduce_op(op.reduce), comm)
        if op.kind == "bcast":
            buf = (np.array(op.parts[0], copy=True) if i == op.root
                   else np.empty_like(op.parts[0]))
            return collective.Bcast(buf, op.root, comm)
        if op.kind == "barrier":
            collective.Barrier(comm)
            return None
        if op.kind == "dup":
            from ..comm import Comm_dup
            return Comm_dup(comm)
        if op.kind == "free":
            from ..collective import nb_shutdown
            nb_shutdown(self.ctx, op.cid, rank)
            if i == 0:
                from ..overlap import plans
                plans.invalidate(op.cid)
            return None
        raise MPIError(f"unknown serve op kind {op.kind!r}", code=_ec.ERR_ARG)

    # -- elastic rounds (driven by ElasticController._round) ------------------
    def elastic_round(self, op: str, epoch: int) -> None:
        """One rebind round on every rank of the pool-wide comm: the rank
        workers themselves rendezvous — a REAL Barrier, so explore models
        it and T214 audits the participant set."""
        from ..elastic.protocol import rebind_round
        comm = self.base_comm
        declared = tuple(comm.group)
        self.run_on(list(declared), None,
                    lambda rank: rebind_round(comm, op, epoch=epoch,
                                              declared=declared))

    # -- namespace plumbing (delegates to the warm context) -------------------
    def lease_ns(self, tenant: str, span: int):
        if self.ctx._ns_next_base + span > self.shard.limit:
            raise SessionError(
                f"broker cid shard {self.shard!r} exhausted — no room for a "
                f"{span}-cid namespace (shard the fleet wider or raise the "
                f"span)")
        return self.ctx.lease_cid_namespace(tenant, span=span)

    def release_ns(self, tenant: str) -> list:
        return self.ctx.release_cid_namespace(tenant)

    def snapshot_pvars(self) -> dict:
        from .. import perfvars
        return perfvars.snapshot()

    def info(self) -> dict:
        return {"kind": self.kind, "nranks": self.nranks,
                "active": list(self.active), "failed": sorted(self.failed),
                "capacity": len(self.healthy()),
                "comms": len(self._comms),
                "shard": [self.shard.base, self.shard.limit]}


class _PoolComm:
    """Broker-side stand-in for a procs-pool communicator. The broker only
    tracks (group, cid) — the real Comm objects, channels, and payloads
    live in the worker processes; everything the Broker/elastic layers read
    off a comm (``.group``, ``.cid``) is here."""

    __slots__ = ("group", "cid", "name")

    def __init__(self, group, cid, name: str = "pool-comm"):
        self.group = tuple(group)
        self.cid = cid
        self.name = name


class _BrokerCtx:
    """Context shim for the procs backend: the broker process holds no warm
    SpmdContext, but the serve layers still need a tracer anchor
    (``events.tracer_for``) and the tenant cid-namespace books — which on
    this tier are pure broker-side bookkeeping (workers learn cids from
    explicit register/rebind frames, so no shared allocator is needed)."""

    def __init__(self, size: int, shard: CidShard):
        self.size = size
        self.cid_namespaces: Dict[str, CidNamespace] = {}
        self._ns_lock = locksmith.make_lock("brokerctx.ns")
        self._ns_next_base = shard.base
        self._ns_limit = shard.limit
        self.revoked_cids: set = set()


class _WorkerLink:
    """One pool worker process as the broker sees it: its control socket
    plus liveness state. ``closing`` marks a deliberate broker-side close
    (shutdown, retire) so the reader's EOF isn't booked as a failure."""

    __slots__ = ("rank", "sock", "pid", "closing")

    def __init__(self, rank: int, sock, pid: int):
        self.rank = rank
        self.sock = sock
        self.pid = pid
        self.closing = False


class _Pending:
    """An in-flight pool request fanned out to a set of worker ranks; fires
    (event + optional callback) once every rank replied or died."""

    __slots__ = ("oid", "want", "replies", "error", "event", "cb")

    def __init__(self, oid: int, ranks, cb=None):
        self.oid = oid
        self.want = set(ranks)
        self.replies: Dict[int, tuple] = {}
        self.error: Optional[BaseException] = None
        self.event = threading.Event()
        self.cb = cb


class _ProcsPool:
    """The procs-backend warm world: one OS process per pool rank on the
    native framed transport (serve/worker.py), driven over per-worker
    control sockets. The broker process never joins the world — it owns the
    rendezvous (launcher.Rendezvous, shared with classic ``tpurun --procs``)
    and speaks the session frame protocol to each worker.

    Ordering invariant: every frame to every worker is sent under ONE
    dispatch lock and each worker executes its frames serially, so all
    ranks initiate collectives in the same global order — the same
    invariant the thread backend's atomic queue fan-out provides.

    Failure detection is two-plane: the broker sees a worker's control-
    socket EOF immediately (→ ``on_failure``), and the workers run the
    transport heartbeat detector so in-flight collectives spanning the dead
    rank raise typed ``ProcFailedError`` instead of hanging."""

    kind = "procs"

    #: seconds to wait for first-generation workers (cold jax import + Init)
    START_TIMEOUT = 300.0

    def __init__(self, nranks: int, shard: Optional[CidShard] = None,
                 on_failure=None, sim: Optional[int] = 1):
        self.nranks = int(nranks)
        self.shard = shard or CidShard()
        self.ctx = _BrokerCtx(self.nranks, self.shard)
        self.active: List[int] = list(range(self.nranks))
        self.failed: set = set()
        self.retired: set = set()
        self.base_comm: Any = None
        self.sim = sim                       # CPU-sim chips per worker; None = real
        self._on_failure = on_failure
        self._dispatch_lock = locksmith.make_lock("procs.dispatch")
        self._comms: Dict[Any, Any] = {}
        self._comms_lock = locksmith.make_lock("procs.comms")
        self._links: Dict[int, _WorkerLink] = {}
        self._links_lock = locksmith.make_lock("procs.links")
        self._link_cond = locksmith.make_condition("procs.links",
                                                   self._links_lock)
        self._pending: Dict[int, _Pending] = {}
        self._pending_lock = locksmith.make_lock("procs.pending")
        self._wire_oid = itertools.count(1)
        self._pool_cid = itertools.count(101)  # pool-internal cids < NS_FLOOR
        self._token = secrets.token_hex(16)
        self._rdv = None
        self._listener = None
        self.pool_addr: Optional[str] = None
        self._procs: List[subprocess.Popen] = []
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        from ..launcher import Rendezvous
        self._listener, self.pool_addr = protocol.listen(None)
        self._listener.settimeout(0.2)
        t = threading.Thread(target=self._accept_loop,
                             name="serve-pool-accept", daemon=True)
        t.start()
        self._threads.append(t)
        self._rdv = Rendezvous(self.nranks)
        extra = {"TPU_MPI_SERVE_POOL_ADDR": self.pool_addr,
                 "TPU_MPI_SERVE_POOL_TOKEN": self._token}
        # failure detection must be ON in the workers: a SIGKILL'd sibling
        # has to surface as a typed ProcFailedError from the in-flight
        # collective, not a hang (operator-set values win)
        if "TPU_MPI_HEARTBEAT_MS" not in os.environ:
            extra["TPU_MPI_HEARTBEAT_MS"] = "500"
        if "TPU_MPI_FAILURE_TIMEOUT_MS" not in os.environ:
            extra["TPU_MPI_FAILURE_TIMEOUT_MS"] = "2000"
        for r in range(self.nranks):
            env = self._rdv.child_env(r, sim=self.sim, extra=extra)
            # -c (not -m): serve/__init__ imports the worker module, so
            # runpy would warn about re-executing it as __main__
            self._procs.append(subprocess.Popen(
                [sys.executable, "-c",
                 "import tpu_mpi.serve.worker as w; raise SystemExit(w.main())"],
                env=env))
        self._wait_links(range(self.nranks), self.START_TIMEOUT)
        self._warm()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                kind, meta, _ = protocol.recv_frame(conn)
            except (protocol.Disconnect, SessionError):
                conn.close()
                continue
            if (kind != protocol.HELLO or meta.get("role") != "worker"
                    or not hmac.compare_digest(str(meta.get("token") or ""),
                                               self._token)):
                conn.close()
                continue
            link = _WorkerLink(int(meta["rank"]), conn,
                               int(meta.get("pid") or 0))
            with self._links_lock:
                self._links[link.rank] = link
                self._link_cond.notify_all()
            t = threading.Thread(target=self._reader, args=(link,),
                                 name=f"serve-pool-r{link.rank}", daemon=True)
            t.start()
            self._threads.append(t)

    def _wait_links(self, ranks, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        ranks = list(ranks)
        with self._links_lock:
            while not all(r in self._links for r in ranks):
                left = deadline - time.monotonic()
                if left <= 0:
                    missing = [r for r in ranks if r not in self._links]
                    raise SessionError(
                        f"pool worker(s) {missing} never dialed the broker "
                        f"within {timeout:.0f}s")
                self._link_cond.wait(left)

    def _reader(self, link: _WorkerLink) -> None:
        while True:
            try:
                kind, meta, arrays = protocol.recv_frame(link.sock)
            except (protocol.Disconnect, SessionError, OSError):
                break
            oid = meta.get("oid")
            if oid is None:
                continue
            err = None
            if kind == protocol.ERROR:
                try:
                    protocol.raise_for_error(meta)
                except MPIError as e:
                    err = e
            self._resolve(oid, link.rank, meta, arrays, err)
        self._link_down(link)

    def _link_down(self, link: _WorkerLink) -> None:
        if self._stop.is_set() or link.closing:
            return
        with self._links_lock:
            if self._links.get(link.rank) is link:
                del self._links[link.rank]
        err = ProcFailedError(f"pool worker rank {link.rank} died "
                              f"(control socket EOF)")
        fire = []
        with self._pending_lock:
            for oid, p in list(self._pending.items()):
                if link.rank in p.want:
                    p.want.discard(link.rank)
                    if p.error is None:
                        p.error = err
                    if not p.want:
                        del self._pending[oid]
                        fire.append(p)
        for p in fire:
            p.event.set()
            if p.cb is not None:
                p.cb(p)
        if self._on_failure is not None:
            self._on_failure(link.rank)

    def _resolve(self, oid: int, rank: int, meta: dict, arrays: list,
                 err: Optional[BaseException]) -> None:
        with self._pending_lock:
            p = self._pending.get(oid)
            if p is None or rank not in p.want:
                return
            p.want.discard(rank)
            p.replies[rank] = (meta, arrays)
            if err is not None and p.error is None:
                p.error = err
            done = not p.want
            if done:
                del self._pending[oid]
        if done:
            p.event.set()
            if p.cb is not None:
                p.cb(p)

    # -- frame plumbing ------------------------------------------------------
    def _request(self, ranks, metas, arrays=None, cb=None) -> _Pending:
        """Fan one OP frame per rank out under the dispatch lock (the
        global-initiation-order invariant) and register the pending entry
        BEFORE sending. ``metas`` is one dict for all ranks or a per-rank
        list; a missing/dead link resolves that rank as a failure."""
        ranks = list(ranks)
        oid = next(self._wire_oid)
        p = _Pending(oid, ranks, cb)
        with self._pending_lock:
            self._pending[oid] = p
        dead = []
        with self._dispatch_lock:
            for i, r in enumerate(ranks):
                with self._links_lock:
                    link = self._links.get(r)
                if link is None:
                    dead.append(r)
                    continue
                m = dict(metas[i] if isinstance(metas, list) else metas)
                m["oid"] = oid
                try:
                    protocol.send_frame(link.sock, protocol.OP, m,
                                        arrays[i] if arrays else ())
                except protocol.Disconnect:
                    dead.append(r)
        for r in dead:
            self._resolve(oid, r, {}, [],
                          ProcFailedError(f"pool worker rank {r} is gone"))
        return p

    def _cast(self, ranks, meta: dict) -> None:
        """Fire-and-forget control frame (register/rebind/revoke_ns):
        ordering with later ops on the same worker is the socket's FIFO."""
        with self._dispatch_lock:
            for r in ranks:
                with self._links_lock:
                    link = self._links.get(r)
                if link is None:
                    continue
                try:
                    protocol.send_frame(link.sock, protocol.OP, dict(meta))
                except protocol.Disconnect:
                    pass

    @staticmethod
    def _await(p: _Pending, timeout: float, what: str):
        if not p.event.wait(timeout):
            raise SessionError(f"{what} timed out on the procs pool "
                               f"after {timeout:.0f}s")
        if p.error is not None:
            raise p.error
        return p

    def _warm(self) -> None:
        cid = next(self._pool_cid)
        group = tuple(range(self.nranks))
        comm = _PoolComm(group, cid, name="serve-warm")
        with self._comms_lock:
            self._comms[cid] = comm
        self.base_comm = comm
        p = self._request(list(group), {"wop": "warm", "cid": cid,
                                        "group": list(group)})
        self._await(p, self.START_TIMEOUT, "pool warm-up")

    def shutdown(self) -> None:
        self._stop.set()
        with self._links_lock:
            links = list(self._links.values())
            self._links.clear()
        for link in links:
            link.closing = True
            try:
                protocol.send_frame(link.sock, protocol.OP,
                                    {"wop": "shutdown"})
            except (protocol.Disconnect, OSError):
                pass
        for link in links:
            try:
                link.sock.close()
            except OSError:
                pass
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        deadline = time.monotonic() + 20
        for pr in self._procs:
            try:
                pr.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                pr.kill()
                try:
                    pr.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass
        if self._rdv is not None:
            try:
                self._rdv.close(sweep=True)
            except Exception:      # noqa: BLE001 - teardown best-effort
                pass

    # -- elastic membership --------------------------------------------------
    def healthy(self) -> List[int]:
        return [r for r in self.active if r not in self.failed]

    def dead_in(self, group) -> tuple:
        return tuple(sorted(set(group) & self.failed))

    def mark_failed(self, rank: int) -> bool:
        """Failure verdict (control-socket EOF, or an idle retire): the
        workers' own heartbeat plane unblocks their in-flight collectives;
        broker-side there is nothing to poke — just the membership books."""
        if rank in self.failed or rank not in self.active:
            return False
        self.failed.add(rank)
        return True

    # -- comm registry -------------------------------------------------------
    def register_comm(self, group, cid, tenant: str):
        group = tuple(group)
        comm = _PoolComm(group, cid, name=f"serve:{tenant}")
        with self._comms_lock:
            self._comms[cid] = comm
        self._cast(group, {"wop": "register", "cid": cid,
                           "group": list(group)})
        return comm

    def comm_for(self, cid):
        with self._comms_lock:
            return self._comms.get(cid)

    def drop_comm(self, cid) -> None:
        with self._comms_lock:
            self._comms.pop(cid, None)

    def rebind_comm(self, cid, group, tenant: Optional[str]):
        """Elastic rebind, procs flavor: the broker-side (group, cid) pair
        is swapped and every member worker re-registers the SAME cid on the
        remapped group (stale channel dropped worker-side)."""
        group = tuple(group)
        comm = _PoolComm(group, cid, name=f"serve:{tenant or 'pool'}")
        with self._comms_lock:
            self._comms[cid] = comm
        self._cast(group, {"wop": "rebind", "cid": cid,
                           "group": list(group)})
        return comm

    # -- elastic resize primitives (driven by tpu_mpi.elastic) ----------------
    def adopt_base(self, comm) -> None:
        with self._comms_lock:
            self._comms[comm.cid] = comm
        self.base_comm = comm
        self.active = list(comm.group)

    def shrink_base(self) -> tuple:
        """Collapse the pool-wide comm to its survivors. The broker is the
        failure authority here: it ships the declared-dead set with the
        shrink frame, so a drain-and-retire (worker alive, just idle) walks
        the same ULFM path a SIGKILL does; the retiree is then told to shut
        down instead of being conscripted (it is a real process — unlike
        the thread tier, it CAN die independently)."""
        base = self.base_comm
        group = list(base.group)
        survivors = [r for r in group if r not in self.failed]
        dead = tuple(r for r in group if r in self.failed)
        p = self._request(survivors, {"wop": "shrink", "cid": base.cid,
                                      "dead": list(dead)})
        self._await(p, 120.0, "pool shrink")
        meta, _ = p.replies[survivors[0]]
        shrunk = _PoolComm(tuple(meta["group"]), _cidify(meta["cid"]),
                           name=f"{base.name}.shrink")
        for r in dead:
            self.retired.add(r)
            self._close_link(r)
        self.adopt_base(shrunk)
        return shrunk, dead

    def _close_link(self, rank: int) -> None:
        with self._links_lock:
            link = self._links.pop(rank, None)
        if link is None:
            return
        link.closing = True
        try:
            protocol.send_frame(link.sock, protocol.OP, {"wop": "shutdown"})
        except (protocol.Disconnect, OSError):
            pass
        try:
            link.sock.close()
        except OSError:
            pass

    def grow_base(self, n: int) -> tuple:
        """GROW on real processes: survivors Comm_spawn n replacement
        worker processes (serve.worker._pool_child_entry) and merge; each
        child dials the broker's pool socket itself — the address rides the
        spawn environment. Completion = survivor replies AND every new
        rank's HELLO."""
        base = self.base_comm
        survivors = [r for r in base.group if r not in self.failed]
        p = self._request(survivors, {"wop": "grow", "cid": base.cid,
                                      "n": int(n)})
        self._await(p, self.START_TIMEOUT, "pool grow")
        meta, _ = p.replies[survivors[0]]
        merged = _PoolComm(tuple(meta["group"]), _cidify(meta["cid"]),
                           name=f"{base.name}.merge")
        new_ranks = tuple(r for r in merged.group if r not in base.group)
        self._wait_links(new_ranks, self.START_TIMEOUT)
        self.adopt_base(merged)
        return merged, new_ranks

    def elastic_round(self, op: str, epoch: int) -> None:
        comm = self.base_comm
        declared = tuple(comm.group)
        p = self._request(list(declared),
                          {"wop": "round", "cid": comm.cid, "op": op,
                           "epoch": epoch, "declared": list(declared)})
        self._await(p, 120.0, f"elastic {op} round")

    # -- op execution --------------------------------------------------------
    def run_op(self, op: PoolOp, on_done) -> None:
        comm = self.comm_for(op.cid)
        if comm is None:
            op.error = SessionError(f"cid {op.cid} has no live communicator")
            on_done(op)
            return
        group = comm.group
        if op.kind == "dup":
            # broker-side on this tier: cid allocation is pure broker
            # bookkeeping, workers just register the fresh cid (FIFO keeps
            # it ahead of any op the tenant issues on it)
            try:
                ns = self.ctx.cid_namespaces.get(op.tenant)
                if ns is None:
                    raise SessionError(f"tenant {op.tenant!r} has no leased "
                                       f"cid namespace on this broker")
                new_cid = ns.alloc()
            except MPIError as e:
                op.error = e
                on_done(op)
                return
            self._cast(group, {"wop": "register", "cid": new_cid,
                               "group": list(group)})
            op.results = [_PoolComm(group, new_cid,
                                    name=f"serve:{op.tenant}.dup")]
            on_done(op)
            return
        metas: list = []
        arrays: list = []
        if op.kind in ("allreduce", "bcast", "barrier"):
            for i in range(len(group)):
                m = {"wop": "coll", "cid": op.cid, "kind": op.kind, "i": i,
                     "reduce": op.reduce, "root": op.root, "ret": i == 0}
                if op.kind == "allreduce":
                    # per-rank scatter: each worker receives only ITS part,
                    # forwarded as a view of the client's frame (zero-copy)
                    a = [op.parts[i] if len(op.parts) > 1 else op.parts[0]]
                elif op.kind == "bcast" and i == op.root:
                    a = [op.parts[0]]
                else:
                    if op.kind == "bcast":
                        m["desc"] = {"dtype": op.parts[0].dtype.str,
                                     "shape": list(op.parts[0].shape)}
                    a = []
                metas.append(m)
                arrays.append(a)
        elif op.kind == "free":
            metas = [{"wop": "free", "cid": op.cid}] * len(group)
            arrays = [()] * len(group)
        else:
            op.error = MPIError(f"unknown serve op kind {op.kind!r}",
                                code=_ec.ERR_ARG)
            on_done(op)
            return

        def cb(p: _Pending) -> None:
            if p.error is not None:
                op.error = p.error
            else:
                _, arr0 = p.replies.get(group[0], ({}, []))
                op.results = [np.asarray(arr0[0]) if arr0 else None]
            on_done(op)

        self._request(list(group), metas, arrays, cb=cb)

    # -- namespace plumbing (broker-local books on this tier) -----------------
    def lease_ns(self, tenant: str, span: int):
        with self.ctx._ns_lock:
            if tenant in self.ctx.cid_namespaces:
                raise SessionError(f"tenant {tenant!r} already holds a lease "
                                   f"on this broker")
            base = self.ctx._ns_next_base
            if base + span > self.ctx._ns_limit:
                raise SessionError(
                    f"broker cid shard {self.shard!r} exhausted — no room "
                    f"for a {span}-cid namespace")
            self.ctx._ns_next_base += span
            ns = CidNamespace(tenant, base, base + span)
            self.ctx.cid_namespaces[tenant] = ns
            return ns

    def release_ns(self, tenant: str) -> list:
        with self.ctx._ns_lock:
            ns = self.ctx.cid_namespaces.pop(tenant, None)
        if ns is None:
            return []
        self.ctx.revoked_cids.update(range(ns.base, ns._next))
        self._cast(tuple(self.healthy()),
                   {"wop": "revoke_ns", "base": ns.base, "limit": ns._next})
        return []

    def snapshot_pvars(self) -> dict:
        """Fleet pvar snapshot: the broker-local blocks (serve_frame lives
        here) merged with every healthy worker's — comm records concatenate
        (attribution folds them by cid), serve_frame counters sum."""
        from .. import perfvars
        snap = perfvars.snapshot()
        comms = list(snap.get("comms") or [])
        frame = dict(snap.get("serve_frame") or {})
        ranks = self.healthy()
        if ranks:
            p = self._request(list(ranks), {"wop": "pvars"})
            try:
                self._await(p, 30.0, "pool pvar snapshot")
            except MPIError:
                pass                       # degrade: report what arrived
            for r in ranks:
                rep = p.replies.get(r)
                if rep is None:
                    continue
                ws = rep[0].get("snapshot") or {}
                comms.extend(ws.get("comms") or [])
                for k, v in (ws.get("serve_frame") or {}).items():
                    frame[k] = frame.get(k, 0) + int(v)
        snap["comms"] = comms
        snap["serve_frame"] = frame
        return snap

    def info(self) -> dict:
        with self._links_lock:
            workers = {r: link.pid for r, link in sorted(self._links.items())}
        return {"kind": self.kind, "nranks": self.nranks,
                "active": list(self.active), "failed": sorted(self.failed),
                "capacity": len(self.healthy()),
                "comms": len(self._comms),
                "shard": [self.shard.base, self.shard.limit],
                "pool_addr": self.pool_addr, "workers": workers}


class Lease:
    """A tenant's live attachment: its namespace, its communicators, and
    the socket the handler serves it on."""

    __slots__ = ("tenant", "ns", "group", "root_cid", "comms", "conn",
                 "send_lock", "attached_at", "revoked")

    def __init__(self, tenant: str, ns, group, root_cid: int, conn):
        self.tenant = tenant
        self.ns = ns
        self.group = tuple(group)
        self.root_cid = root_cid
        self.comms = {root_cid}           # cids this lease may touch
        self.conn = conn
        self.send_lock = locksmith.make_lock(f"lease[{tenant}].send")
        self.attached_at = time.time()
        self.revoked = False


class Broker:
    """The serve daemon: listener + dispatcher + per-client handlers over
    one warm pool. Construct, :meth:`start`, then :meth:`serve_forever`
    (or drive :meth:`handle_connection` from tests)."""

    def __init__(self, nranks: int = 4, socket_spec: Optional[str] = None,
                 *, token: Optional[str] = None,
                 max_tenants: Optional[int] = None,
                 quota_bytes: Optional[int] = None,
                 quantum: int = 1 << 16, max_depth: int = 64,
                 max_inflight: int = 2, ns_span: int = 256,
                 infer=None, elastic=None,
                 backend: Optional[str] = None,
                 transport: Optional[str] = None,
                 shard=None):
        cfg = config.load()
        self.token = cfg.session_token if token is None else token
        self.max_tenants = (cfg.serve_max_tenants if max_tenants is None
                            else int(max_tenants))
        backend = (cfg.serve_backend if backend is None else backend) \
            or "threads"
        self.backend = backend
        transport = (cfg.serve_transport if transport is None
                     else transport) or "events"
        if transport not in ("events", "threads"):
            raise MPIError(
                f"unknown serve transport {transport!r} "
                f"(TPU_MPI_SERVE_TRANSPORT: 'events' or 'threads')",
                code=_ec.ERR_ARG)
        self.transport = transport
        self.front_door = None         # FrontDoor when transport == "events"
        if not isinstance(shard, CidShard):
            shard = CidShard.parse(cfg.serve_shard if shard is None
                                   else shard)
        self.shard = shard
        if backend == "procs":
            self.pool = _ProcsPool(nranks, shard=shard,
                                   on_failure=self.on_rank_failure)
        elif backend == "threads":
            self.pool = _ThreadPool(nranks, shard=shard)
        else:
            raise MPIError(
                f"unknown serve backend {backend!r} "
                f"(TPU_MPI_SERVE_BACKEND: 'threads' or 'procs')",
                code=_ec.ERR_ARG)
        self.fq = FairQueue(quantum=quantum, max_depth=max_depth,
                            max_inflight=max_inflight)
        self.ledger = Ledger(cfg.serve_quota_bytes if quota_bytes is None
                             else int(quota_bytes))
        self.ns_span = int(ns_span)
        self._socket_spec = (cfg.serve_socket if socket_spec is None
                             else socket_spec)
        self._listener: Optional[socket.socket] = None
        self.address: Optional[str] = None
        self._leases: Dict[str, Lease] = {}
        self._lease_lock = locksmith.make_lock("broker.leases")
        # cid-range ownership outlives the lease so pvar attribution in the
        # ledger stays correct after revocation
        self._cid_ranges: List[tuple] = []    # (base, limit, tenant)
        self._oid = itertools.count(1)
        self._tenant_seq = itertools.count(1)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self.started = threading.Event()
        # inference engine (tpu_mpi.infer): None = off; True or a kwarg
        # dict for InferEngine = build it at start()
        self._infer_spec = infer
        self.infer_engine = None
        self._infer_sched = None
        # elastic capacity (tpu_mpi.elastic): None = TPU_MPI_ELASTIC config
        self._elastic_spec = cfg.elastic if elastic is None else bool(elastic)
        self._resize_gate = threading.Event()  # set = attaches may proceed
        self._resize_gate.set()
        self.elastic = None                    # ElasticController when on
        self.sidecars = None
        self._elastic_lock = locksmith.make_lock("broker.elastic")
        self.elastic_state = {"enabled": bool(self._elastic_spec),
                              "resizes": 0, "rebinds": 0, "failures": 0,
                              "last_resize": None}

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        """Warm the pool, bind the socket, start dispatcher + acceptor."""
        if self._infer_spec and self.pool.kind != "threads":
            raise MPIError(
                "tpu_mpi.infer runs on the thread backend only — start the "
                "broker with TPU_MPI_SERVE_BACKEND=threads (or shard infer "
                "tenants onto a threads broker behind the router)",
                code=_ec.ERR_UNSUPPORTED_OPERATION)
        if locksmith.enabled():
            # dispatch-named lock transitions land in the event IR so
            # `analyze verify` can audit dispatch serialization (T215)
            locksmith.bind_context(self.pool.ctx)
        self.pool.start()
        if self._infer_spec:
            from ..infer import InferEngine, InferScheduler
            spec = (dict(self._infer_spec)
                    if isinstance(self._infer_spec, dict) else {})
            self.infer_engine = InferEngine(self.pool, **spec)
            self.infer_engine.start()
            self._infer_sched = InferScheduler(self.infer_engine)
            self._infer_sched.start()
        if self._elastic_spec:
            from ..elastic import ElasticController
            self.elastic = ElasticController(self)
            # sidecars model per-rank process death for THREAD ranks; procs
            # workers are real processes — control-socket EOF is the detector
            if config.load().elastic_sidecars and self.pool.kind == "threads":
                from ..elastic.sidecar import RankSidecars
                self.sidecars = RankSidecars(self.pool.active,
                                             on_death=self.on_rank_failure)
                self.sidecars.start()
            self.elastic.start()
        self._listener, self.address = protocol.listen(self._socket_spec)
        self._listener.settimeout(0.2)
        if self.transport == "events":
            from .frontdoor import FrontDoor
            self.front_door = FrontDoor(self, self._listener)
            self.front_door.start()
        d = threading.Thread(target=self._dispatch_loop,
                             name="serve-dispatch", daemon=True)
        d.start()
        self._threads.append(d)
        self.started.set()

    def serve_forever(self) -> None:
        if self.front_door is not None:
            # events transport: this thread becomes the readiness loop;
            # no per-connection threads are ever spawned
            self.front_door.serve_forever()
            return
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self.handle_connection, args=(conn,),
                                 name="serve-client", daemon=True)
            t.start()
            self._threads.append(t)

    def run_in_thread(self) -> threading.Thread:
        """start() + serve_forever() on a daemon thread (tests, examples)."""
        self.start()
        t = threading.Thread(target=self.serve_forever, name="serve-accept",
                             daemon=True)
        t.start()
        self._threads.append(t)
        return t

    def close(self) -> None:
        self._stop.set()
        if self.front_door is not None:
            self.front_door.close()
        if self.elastic is not None:
            self.elastic.close()
        if self.sidecars is not None:
            self.sidecars.close()
        with self._lease_lock:
            leases = list(self._leases.values())
        for lease in leases:
            self.revoke_lease(lease, "broker shutting down")
        if self._infer_sched is not None:
            self._infer_sched.close()
        self.fq.close()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        self.pool.shutdown()

    # -- dispatcher ----------------------------------------------------------
    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            op = self.fq.pop(timeout=0.2)
            if op is None:
                continue
            # trace the dispatcher's global initiation order: explore uses
            # these to label schedules, and their single-threaded origin is
            # the invariant that keeps cross-cid initiation orders aligned
            _ev.record_serve(self.pool.ctx, "dispatch", cid=op.cid,
                             tenant=op.tenant, kind=op.kind, oid=op.oid,
                             nbytes=op.nbytes)
            if _flight.enabled():
                # the crash dump must NAME the in-flight op: when a rank
                # dies mid-collective this is the last dispatch in the ring
                _flight.note("op_dispatch", tenant=op.tenant, op=op.kind,
                             oid=op.oid, cid=op.cid, nbytes=op.nbytes)
            if op.trace is not None and op.t_submit is not None:
                # the fair-queue wait, reconstructed at pop time: DRR decided
                # when this op's tenant got its turn
                _tc.emit_span(op.trace, "queue", "broker", op.t_submit,
                              time.monotonic(), tenant=op.tenant,
                              kind=op.kind, oid=op.oid)
            if op.kind == "generate":
                # DRR decided its admission slot; the scheduler batches it
                # from here — the fq slot frees immediately so a streaming
                # generation never starves the tenant's collective lane
                self._op_done(op)
                continue
            self.pool.run_op(op, self._op_done)
            del op      # don't pin the payload across the next blocking pop

    def _op_done(self, op: PoolOp) -> None:
        self.fq.complete(op)
        op.done.set()

    # -- degraded-pool serving (tpu_mpi.elastic) ------------------------------
    def on_rank_failure(self, rank: int) -> None:
        """Failure-detector verdict (sidecar death, or a test's injection):
        declare the rank dead and KEEP SERVING — tenants whose comms avoid
        the dead rank stream on, ops that span it get the retriable
        :class:`PoolDegradedError`, and the elastic controller (when on)
        schedules the restore resize."""
        if not self.pool.mark_failed(rank):
            return
        with self._elastic_lock:
            self.elastic_state["failures"] += 1
        from .. import perfvars
        if perfvars.enabled():
            perfvars.note_elastic(failures=1)
            perfvars.set_elastic_gauges(degraded=1,
                                        pool_size=len(self.pool.healthy()))
        _ev.record_serve(self.pool.ctx, "rank_failed", rank=rank,
                         capacity=len(self.pool.healthy()))
        if self.elastic is not None:
            self.elastic.kick()

    def _degraded_error(self, tenant: Optional[str],
                        group=None) -> PoolDegradedError:
        dead = (self.pool.dead_in(group) if group is not None
                else tuple(sorted(self.pool.failed)))
        headroom = len(self.pool.healthy())
        return PoolDegradedError(
            f"serve pool degraded: rank(s) {list(dead)} failed and are not "
            f"yet replaced ({headroom} healthy ranks remain) — retry once "
            f"the autoscaler restores capacity and rebinds the lease",
            tenant=tenant, dead=dead, headroom=headroom)

    def _elastic_section(self) -> dict:
        with self._elastic_lock:
            st = dict(self.elastic_state)
        healthy = len(self.pool.healthy())
        st.update({
            "pool_size": healthy,
            "target_size": (self.elastic.target if self.elastic is not None
                            else self.pool.nranks),
            "degraded": bool(self.pool.failed - self.pool.retired),
            "failed": sorted(self.pool.failed),
            # re-advertised capacity: ranks a NEW lease can span right now
            "headroom": healthy})
        return st

    # -- attach / leases -----------------------------------------------------
    def _check_token(self, supplied: Optional[str]) -> None:
        if not self.token:
            return                            # open broker ("" accepts any)
        if not hmac.compare_digest(str(supplied or ""), self.token):
            raise SessionError("session token rejected "
                               "(TPU_MPI_SESSION_TOKEN mismatch)")

    def attach_tenant(self, conn, meta: dict) -> Lease:
        t0_span = time.monotonic()
        self._check_token(meta.get("token"))
        # a resize holds the gate while the rank map is in flux: attaches
        # queue here and land on the post-resize pool (tests drive this)
        if not self._resize_gate.wait(timeout=30.0):
            raise SessionError("attach timed out waiting for an elastic "
                               "resize to finish")
        with self._lease_lock:
            if len(self._leases) >= self.max_tenants:
                raise SessionError(
                    f"broker at max_tenants={self.max_tenants} "
                    f"(TPU_MPI_SERVE_MAX_TENANTS) — detach a tenant first")
            tenant = meta.get("tenant") or f"t{next(self._tenant_seq)}"
            if tenant in self._leases:
                raise SessionError(f"tenant id {tenant!r} already attached")
            healthy = self.pool.healthy()
            nranks = int(meta.get("nranks") or len(healthy))
            if not 1 <= nranks <= max(self.pool.nranks, len(healthy)):
                raise SessionError(
                    f"requested nranks={nranks} outside pool size "
                    f"{max(self.pool.nranks, len(healthy))}")
            if nranks > len(healthy):
                # the pool COULD host this lease, just not until the
                # autoscaler restores the dead ranks: typed + retriable
                raise self._degraded_error(tenant)
            ns = self.pool.lease_ns(tenant, self.ns_span)
            self._cid_ranges.append((ns.base, ns.limit, tenant))
            # nothing collective below: root cid is a broker-side alloc, so
            # attach stays on the <1 ms budget
            root_cid = ns.alloc()
            group = tuple(healthy[:nranks])
            self.pool.register_comm(group, root_cid, tenant)
            lease = Lease(tenant, ns, group, root_cid, conn)
            self._leases[tenant] = lease
        self.fq.add_tenant(tenant)
        self.ledger.open_tenant(tenant)
        _ev.record_serve(self.pool.ctx, "lease", cid=root_cid, tenant=tenant,
                         base=ns.base, limit=ns.limit)
        ctx = _tc.TraceCtx.from_meta(meta)
        if ctx is not None and ctx.sampled:
            _tc.emit_span(ctx, "broker:attach", "broker", t0_span,
                          time.monotonic(), tenant=tenant)
        return lease

    def revoke_lease(self, lease: Lease, reason: str, *,
                     close_conn: bool = True) -> None:
        """Reclaim everything a dead/departing tenant held: queued ops are
        failed, its cid range is drained + revoked on the warm context
        (stragglers raise, never hang), its comms and plan-cache entries
        dropped, its ledger books closed. The pool itself stays healthy."""
        with self._lease_lock:
            if self._leases.get(lease.tenant) is not lease:
                return                        # already revoked
            del self._leases[lease.tenant]
            lease.revoked = True
        for op in self.fq.remove_tenant(lease.tenant):
            op.error = SessionError(
                f"lease for tenant {lease.tenant!r} revoked ({reason}) "
                f"before the op dispatched")
            op.done.set()
        if self._infer_sched is not None:
            # in-flight generations leave the batch; their KV chains free
            # on the next step — survivors keep streaming
            self._infer_sched.cancel_tenant(lease.tenant)
        self.pool.release_ns(lease.tenant)
        from ..overlap import plans
        for cid in list(lease.comms):
            self.pool.drop_comm(cid)
            plans.invalidate(cid)
        self.ledger.close_tenant(lease.tenant,
                                 revoked=reason != "client detached")
        _ev.record_serve(self.pool.ctx, "lease_revoke", tenant=lease.tenant,
                         reason=reason, base=lease.ns.base,
                         limit=lease.ns.limit)
        if _flight.enabled():
            _flight.note("lease_revoke", tenant=lease.tenant, reason=reason)
            if reason != "client detached":
                # involuntary revocation: snapshot the ring so whoever
                # debugs the eviction sees the seconds leading up to it
                _flight.auto_dump("lease-revoke")
        if close_conn:
            try:
                lease.conn.close()
            except OSError:
                pass

    # -- per-connection protocol loop ----------------------------------------
    def handle_connection(self, conn: socket.socket) -> None:
        try:
            kind, meta, _ = protocol.recv_frame(conn)
        except (protocol.Disconnect, SessionError):
            conn.close()
            return
        if kind == protocol.STATS:
            # lease-less admin probe (tpurun --serve --stats)
            try:
                self._check_token(meta.get("token"))
                protocol.send_frame(conn, protocol.STATS, self.stats())
            except MPIError as e:
                protocol.send_frame(conn, protocol.ERROR,
                                    protocol.error_meta(e))
            finally:
                conn.close()
            return
        if kind == protocol.METRICS:
            # lease-less Prometheus scrape: the text exposition of the same
            # snapshot STATS returns (docs/observability.md "Live export")
            try:
                self._check_token(meta.get("token"))
                from .. import stats as _stats
                protocol.send_frame(conn, protocol.METRICS,
                                    {"text": _stats.to_prometheus(
                                        self.stats())})
            except MPIError as e:
                protocol.send_frame(conn, protocol.ERROR,
                                    protocol.error_meta(e))
            finally:
                conn.close()
            return
        if kind != protocol.HELLO:
            protocol.send_frame(conn, protocol.ERROR, protocol.error_meta(
                SessionError(f"expected HELLO, got "
                             f"{protocol.KIND_NAMES.get(kind, kind)}")))
            conn.close()
            return
        t0 = time.perf_counter()
        try:
            lease = self.attach_tenant(conn, meta)
        except MPIError as e:
            protocol.send_frame(conn, protocol.ERROR, protocol.error_meta(e))
            conn.close()
            return
        attach_us = (time.perf_counter() - t0) * 1e6
        protocol.send_frame(conn, protocol.LEASE, {
            "tenant": lease.tenant, "ranks": list(lease.group),
            "cid": lease.root_cid,
            "cid_base": lease.ns.base, "cid_limit": lease.ns.limit,
            "pool": self.pool.info(), "attach_us": attach_us})
        detached = False
        try:
            while True:
                kind, meta, arrays = protocol.recv_frame(conn)
                if kind == protocol.DETACH:
                    detached = True
                    # book the lease out BEFORE replying so a client that
                    # inspects broker state right after BYE sees it settled
                    self.revoke_lease(lease, "client detached",
                                      close_conn=False)
                    protocol.send_frame(conn, protocol.BYE,
                                        {"tenant": lease.tenant})
                    break
                if kind == protocol.PING:
                    with lease.send_lock:
                        protocol.send_frame(conn, protocol.PONG, {})
                    continue
                if kind == protocol.STATS:
                    with lease.send_lock:
                        protocol.send_frame(conn, protocol.STATS, self.stats())
                    continue
                if kind == protocol.METRICS:
                    from .. import stats as _stats
                    text = _stats.to_prometheus(self.stats())
                    with lease.send_lock:
                        protocol.send_frame(conn, protocol.METRICS,
                                            {"text": text})
                    continue
                if kind != protocol.OP:
                    raise SessionError(
                        f"unexpected {protocol.KIND_NAMES.get(kind, kind)} "
                        f"frame mid-session")
                self._serve_op(lease, meta, arrays)
        except (protocol.Disconnect, SessionError, OSError):
            pass
        finally:
            self.revoke_lease(lease, "client detached" if detached
                              else "connection lost")
            try:
                conn.close()
            except OSError:
                pass

    def _serve_op(self, lease: Lease, meta: dict, arrays: list) -> None:
        if meta.get("op") == "generate":
            self._serve_generate(lease, meta, arrays)
            return
        try:
            reply_meta, reply_arrays = self._admit_and_run(lease, meta,
                                                           arrays)
        except MPIError as e:
            # typed rejection (quota, busy, session, arg): one tenant's
            # ERROR frame, never a pool failure
            with lease.send_lock:
                protocol.send_frame(lease.conn, protocol.ERROR,
                                    protocol.error_meta(e))
            return
        with lease.send_lock:
            protocol.send_frame(lease.conn, protocol.RESULT, reply_meta,
                                reply_arrays)

    def _admit_and_run(self, lease: Lease, meta: dict, arrays: list):
        """Traced wrapper: open the broker's span for a sampled request
        (everything downstream — queue wait, per-rank phases — nests under
        it), run admission + execution, and close it ok/error. An untraced
        request pays one dict lookup."""
        ctx = _tc.TraceCtx.from_meta(meta)
        if ctx is None:
            return self._admitted(lease, meta, arrays, None)
        rec = _tc.start_span(ctx, f"broker:{meta.get('op')}", "broker",
                             tenant=lease.tenant)
        try:
            reply_meta, reply_arrays = self._admitted(
                lease, meta, arrays, _tc.child_for_span(rec, ctx))
        except BaseException as e:
            _tc.end_span(rec, status="error", error=type(e).__name__)
            raise
        _tc.end_span(rec)
        # RESULT frames echo the context so a client (or mid-path proxy)
        # can stitch replies to requests without a side table
        reply_meta["trace"] = ctx.to_meta()
        return reply_meta, reply_arrays

    def _admitted(self, lease: Lease, meta: dict, arrays: list,
                  tctx: Optional[_tc.TraceCtx]):
        opname = meta.get("op")
        cid = int(meta.get("cid", lease.root_cid))
        if cid not in lease.comms:
            raise SessionError(
                f"tenant {lease.tenant!r} used cid {cid} outside its lease "
                f"(owns {sorted(lease.comms)}; namespace "
                f"[{lease.ns.base}, {lease.ns.limit})) — cross-tenant "
                f"communicator use is forbidden")
        # management ops that never touch the rank workers
        if opname == "pcontrol":
            level = int(meta.get("level", 1))
            totals = self.flush_ledger() if level >= 2 else None
            return {"op": opname, "level": level, "totals": totals}, []
        # degraded-pool guard: an op whose communicator spans a declared-
        # dead rank is rejected typed-and-retriable at admission — it would
        # only raise ProcFailedError from the rank workers (reject, don't
        # burn a pool slot). Comms on surviving ranks pass untouched.
        comm = self.pool.comm_for(cid)
        if comm is not None and self.pool.dead_in(comm.group):
            raise self._degraded_error(lease.tenant, comm.group)
        if opname in ("allreduce", "bcast"):
            self._validate_arrays(lease, opname, arrays, meta)
            if opname == "allreduce":
                _reduce_op(str(meta.get("reduce", "sum")))
        elif opname in ("barrier", "dup", "free"):
            if opname == "free" and cid == lease.root_cid:
                raise SessionError("the lease's root communicator is freed "
                                   "by DETACH, not by an explicit free")
            arrays = []
        else:
            raise MPIError(f"unknown serve op {opname!r}", code=_ec.ERR_ARG)
        op = PoolOp(next(self._oid), lease.tenant, opname, cid,
                    [np.asarray(a) for a in arrays],
                    str(meta.get("reduce", "sum")),
                    int(meta.get("root", 0)))
        op.trace = tctx
        if opname in ("allreduce", "bcast"):
            # admission book is the quota authority; breach = typed reject
            self.ledger.charge(lease.tenant, op.nbytes)
        try:
            op.t_submit = time.monotonic()
            self.fq.submit(op)
        except MPIError as e:
            if getattr(e, "retriable", False):
                self.ledger.note_busy(lease.tenant)
            raise
        if not op.done.wait(timeout=120.0):
            raise SessionError(f"op {opname} (oid={op.oid}) timed out on "
                               f"the pool")
        if op.error is not None:
            err = op.error
            if isinstance(err, ProcFailedError):
                # a rank died while the op was in flight: same contract as
                # the admission guard — typed, retriable, lease intact
                raise self._degraded_error(lease.tenant) from err
            if isinstance(err, MPIError):
                raise err
            raise MPIError(f"pool execution failed: {err}",
                           code=_ec.ERR_OTHER)
        return self._reply_for(lease, op)

    # -- streaming generation (tpu_mpi.infer) --------------------------------
    def _serve_generate(self, lease: Lease, meta: dict,
                        arrays: list) -> None:
        """One generation request, streamed: admission (quota + fair
        queue) then repeated RESULT frames ``{"stream": True, "tokens":
        [...], "done": bool}`` as the scheduler emits tokens. Typed errors
        (SLO eviction, revocation) arrive as a terminal ERROR frame."""
        ctx = _tc.TraceCtx.from_meta(meta)
        rec = _tc.start_span(ctx, "broker:generate", "broker",
                             tenant=lease.tenant)
        try:
            req = self._admit_generate(lease, meta, arrays,
                                       tctx=_tc.child_for_span(rec, ctx))
        except MPIError as e:
            _tc.end_span(rec, status="error", error=type(e).__name__)
            with lease.send_lock:
                protocol.send_frame(lease.conn, protocol.ERROR,
                                    protocol.error_meta(e))
            return
        while True:
            try:
                kind, payload = req.out.get(timeout=300.0)
            except queue.Empty:
                kind, payload = "err", SessionError(
                    f"generation rid={req.rid} stalled on the engine")
            if kind == "tok":
                with lease.send_lock:
                    protocol.send_frame(
                        lease.conn, protocol.RESULT,
                        {"op": "generate", "rid": req.rid, "stream": True,
                         "done": False,
                         "tokens": [int(t) for t in payload]})
            elif kind == "done":
                _tc.end_span(rec, rid=req.rid)
                done_meta = {"op": "generate", "rid": req.rid,
                             "stream": True, "done": True, "tokens": [],
                             **payload}
                if ctx is not None and ctx.sampled:
                    done_meta["trace"] = ctx.to_meta()
                with lease.send_lock:
                    protocol.send_frame(lease.conn, protocol.RESULT,
                                        done_meta)
                return
            else:
                _tc.end_span(rec, status="error",
                             error=type(payload).__name__)
                with lease.send_lock:
                    protocol.send_frame(lease.conn, protocol.ERROR,
                                        protocol.error_meta(payload))
                return

    def _admit_generate(self, lease: Lease, meta: dict, arrays: list,
                        tctx: Optional[_tc.TraceCtx] = None):
        if self._infer_sched is None:
            raise MPIError(
                "this broker has no inference engine (start it with "
                "tpurun --serve --infer, or Broker(infer=True))",
                code=_ec.ERR_UNSUPPORTED_OPERATION)
        if self.infer_engine is not None \
                and self.pool.dead_in(self.infer_engine.ranks):
            # the engine's pipeline spans the dead rank; generation resumes
            # once the resize rebinds the engine onto the replacements
            raise self._degraded_error(lease.tenant, self.infer_engine.ranks)
        if len(arrays) != 1:
            raise MPIError("generate takes exactly one prompt token array",
                           code=_ec.ERR_ARG)
        prompt = np.asarray(arrays[0])
        if prompt.ndim != 1 or prompt.dtype.kind not in "iu" \
                or prompt.size == 0:
            raise MPIError("generate prompt must be a non-empty 1-D integer "
                           "token array", code=_ec.ERR_ARG)
        cfg = self.infer_engine.cfg
        max_new = int(meta.get("max_new", 16))
        if max_new < 1:
            raise MPIError(f"max_new must be >= 1, got {max_new}",
                           code=_ec.ERR_ARG)
        lo, hi = int(prompt.min()), int(prompt.max())
        if lo < 0 or hi >= cfg.vocab:
            raise MPIError(f"prompt token {lo if lo < 0 else hi} outside "
                           f"vocab [0, {cfg.vocab})", code=_ec.ERR_ARG)
        if int(prompt.size) + max_new > cfg.max_seq:
            raise MPIError(
                f"prompt ({prompt.size}) + max_new ({max_new}) exceeds the "
                f"model's max_seq ({cfg.max_seq})", code=_ec.ERR_ARG)
        # admission charge: prompt bytes in + generated ids out
        nbytes = int(prompt.nbytes) + 8 * max_new
        self.ledger.charge(lease.tenant, nbytes)
        op = PoolOp(next(self._oid), lease.tenant, "generate",
                    lease.root_cid, [], "sum", 0)
        op.nbytes = nbytes
        op.trace = tctx
        try:
            op.t_submit = time.monotonic()
            self.fq.submit(op)
        except MPIError as e:
            if getattr(e, "retriable", False):
                self.ledger.note_busy(lease.tenant)
            raise
        if not op.done.wait(timeout=120.0):
            raise SessionError(f"generate (oid={op.oid}) timed out in the "
                               f"fair queue")
        if op.error is not None:
            raise op.error
        return self._infer_sched.submit(lease.tenant,
                                        [int(t) for t in prompt], max_new,
                                        tctx=tctx)

    def _validate_arrays(self, lease: Lease, opname: str, arrays: list,
                         meta: dict) -> None:
        """Admission-time shape/dtype agreement: the pool's combine step
        fate-shares on error, so anything that could throw there is
        rejected here instead."""
        if not arrays:
            raise MPIError(f"{opname} needs at least one array",
                           code=_ec.ERR_ARG)
        if opname == "allreduce" and len(arrays) not in (1, len(lease.group)):
            raise MPIError(
                f"allreduce takes 1 replicated part or exactly "
                f"{len(lease.group)} per-rank parts, got {len(arrays)}",
                code=_ec.ERR_ARG)
        if opname == "bcast":
            root = int(meta.get("root", 0))
            if not 0 <= root < len(lease.group):
                raise MPIError(f"bcast root {root} outside comm of size "
                               f"{len(lease.group)}", code=_ec.ERR_ARG)
            if len(arrays) != 1:
                raise MPIError("bcast takes exactly the root's array",
                               code=_ec.ERR_ARG)
        first = arrays[0]
        for a in arrays[1:]:
            if a.shape != first.shape or a.dtype != first.dtype:
                raise MPIError(
                    f"{opname} parts disagree: {a.dtype}{a.shape} vs "
                    f"{first.dtype}{first.shape}", code=_ec.ERR_ARG)

    def _reply_for(self, lease: Lease, op: PoolOp):
        if op.kind == "allreduce":
            # deterministic rank-ordered reduction: every rank's result is
            # bitwise identical; return rank 0's
            return {"op": op.kind, "oid": op.oid}, [np.asarray(op.results[0])]
        if op.kind == "bcast":
            return {"op": op.kind, "oid": op.oid}, [np.asarray(op.results[0])]
        if op.kind == "barrier":
            return {"op": op.kind, "oid": op.oid}, []
        if op.kind == "dup":
            new_comm = op.results[0]
            lease.comms.add(new_comm.cid)
            with self.pool._comms_lock:
                self.pool._comms[new_comm.cid] = new_comm
            return {"op": op.kind, "oid": op.oid, "cid": new_comm.cid}, []
        if op.kind == "free":
            lease.comms.discard(op.cid)
            self.pool.drop_comm(op.cid)
            return {"op": op.kind, "oid": op.oid}, []
        raise MPIError(f"unknown kind {op.kind!r}", code=_ec.ERR_ARG)

    # -- accounting ----------------------------------------------------------
    def _owner_of_cid(self, cid) -> Optional[str]:
        if isinstance(cid, (tuple, list)):   # wire-decoded tuple cids: list
            cid = next((c for c in cid if isinstance(c, int)), None)
        if not isinstance(cid, int):
            return None
        for base, limit, tenant in self._cid_ranges:
            if base <= cid < limit:
                return tenant
        return None

    def _flush_and_report(self) -> tuple:
        """Measured-book flush + report in ONE ledger-lock acquisition
        (Ledger.flush_and_report); the attribution pass runs lock-free."""
        totals, rep = self.ledger.flush_and_report(self.pool.snapshot_pvars(),
                                                   self._owner_of_cid)
        if _ev.enabled():
            # T208 front end: the flushed per-tenant measured rows plus the
            # pool totals and the live cid-ownership map, in one event the
            # trace verifier can re-add and cross-check
            measured = {t: dict(e.get("measured") or {})
                        for t, e in rep["tenants"].items()}
            _ev.record_serve(self.pool.ctx, "book", totals=dict(totals),
                             measured=measured,
                             ranges=[list(r) for r in self._cid_ranges])
        return totals, rep

    def flush_ledger(self) -> dict:
        """Rebuild the measured books from a fresh pvar snapshot; the
        returned pool totals equal the sum over tenants by construction."""
        totals, _ = self._flush_and_report()
        return totals

    def stats(self) -> dict:
        """One STATS snapshot, batched: one ledger-lock acquisition (flush
        + report fused), one queue-stats call, one lease-lock grab — a
        1k-tenant fleet polling stats must not serialize the op path on
        observability (ISSUE 15 satellite)."""
        totals, report = self._flush_and_report()
        with self._lease_lock:
            live = sorted(self._leases)
        from ..overlap import plans
        return {"address": self.address, "pool": self.pool.info(),
                "backend": self.pool.kind,
                "transport": self.transport,
                "front_door": (self.front_door.stats()
                               if self.front_door is not None else None),
                "shard": {"index": self.shard.index,
                          "count": self.shard.count,
                          "base": self.shard.base, "limit": self.shard.limit},
                "tenants_attached": live, "totals": totals,
                "ledger": report, "queue": self.fq.stats(),
                "plan_cache": plans.stats(),
                "serve_frame": self._serve_frame_block(),
                "infer": (self._infer_sched.stats()
                          if self._infer_sched is not None else None),
                "elastic": self._elastic_section()}

    def _serve_frame_block(self) -> dict:
        """The zero-copy frame pvars + the derived copies/op ratio the CI
        gate reads (ISSUE 15: copies per op <= 1 on the zero-copy path)."""
        from .. import perfvars
        frame = dict(perfvars.serve_frame_snapshot())
        ops = int(frame.get("ops", 0))
        frame["copies_per_op"] = (frame.get("copies", 0) / ops) if ops else 0.0
        return frame


# -- tpurun --serve CLI -------------------------------------------------------

def _stats_client(address: str, token: str) -> dict:
    sock = protocol.connect(address)
    try:
        protocol.send_frame(sock, protocol.STATS, {"token": token})
        kind, meta, _ = protocol.recv_frame(sock)
        if kind == protocol.ERROR:
            protocol.raise_for_error(meta)
        return meta
    finally:
        sock.close()


def _metrics_client(address: str, token: str) -> str:
    """One Prometheus scrape: the broker's METRICS frame text."""
    sock = protocol.connect(address)
    try:
        protocol.send_frame(sock, protocol.METRICS, {"token": token})
        kind, meta, _ = protocol.recv_frame(sock)
        if kind == protocol.ERROR:
            protocol.raise_for_error(meta)
        return str(meta.get("text", ""))
    finally:
        sock.close()


def main(argv: Optional[list] = None) -> int:
    """``tpurun --serve [--socket SPEC] [--nranks N] [--stats]``."""
    import argparse
    p = argparse.ArgumentParser(
        prog="tpurun --serve",
        description="run the multi-tenant broker daemon (docs/serving.md), "
                    "or query a running one with --stats")
    p.add_argument("--socket", default=None,
                   help="serve socket: unix path (contains '/') or host:port "
                        "(default: TPU_MPI_SERVE_SOCKET, else a loopback "
                        "port printed at startup)")
    p.add_argument("--nranks", type=int, default=4,
                   help="warm pool size (default 4)")
    p.add_argument("--token", default=None,
                   help="session token (default: TPU_MPI_SESSION_TOKEN)")
    p.add_argument("--max-tenants", type=int, default=None)
    p.add_argument("--quota-bytes", type=int, default=None)
    p.add_argument("--backend", default=None, choices=["threads", "procs"],
                   help="pool backend (default: TPU_MPI_SERVE_BACKEND, else "
                        "threads): 'procs' runs one OS process per rank on "
                        "the native framed transport")
    p.add_argument("--shard", default=None,
                   help="cid shard 'index/count' for multi-broker scale-out "
                        "(default: TPU_MPI_SERVE_SHARD, else the whole "
                        "range) — brokers of one fleet MUST use distinct "
                        "indices of the same count")
    p.add_argument("--router", action="store_true",
                   help="run the tenant router instead of a broker: shards "
                        "sessions across --brokers by tenant key "
                        "(docs/serving.md 'Scale-out')")
    p.add_argument("--brokers", default=None,
                   help="comma-separated broker sockets (router upstreams, "
                        "or multi-broker --stats; default: "
                        "TPU_MPI_SERVE_BROKERS)")
    p.add_argument("--router-mode", default=None,
                   choices=("splice", "redirect"),
                   help="router session handling: proxy every byte "
                        "(splice) or answer HELLO with the home broker "
                        "(redirect; default: TPU_MPI_SERVE_ROUTER_MODE)")
    p.add_argument("--infer", action="store_true",
                   help="serve token generation (tpu_mpi.infer): a "
                        "2-stage x N-expert MoE engine on the warm pool")
    p.add_argument("--elastic", action="store_true",
                   help="run the elastic autoscaler (tpu_mpi.elastic): "
                        "dead ranks are respawned and merged back, tenant "
                        "leases rebound, and the pool serves degraded in "
                        "between (docs/fault-tolerance.md)")
    p.add_argument("--stats", action="store_true",
                   help="report per-tenant usage of a running broker and "
                        "exit")
    p.add_argument("--watch", action="store_true",
                   help="with --stats: keep polling and stream interval "
                        "deltas/rates (unreachable brokers render an "
                        "error row, the stream continues)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="--watch poll interval in seconds (default 2)")
    p.add_argument("--metrics", action="store_true",
                   help="with --stats: print the Prometheus text "
                        "exposition (the METRICS frame) instead of JSON")
    args = p.parse_args(argv)

    cfg = config.load()
    if args.stats:
        # fleet view: --stats accepts one socket, a comma list, --brokers,
        # or TPU_MPI_SERVE_BROKERS; multiple reports merge into one
        # (per-tenant measured books still partition the summed totals)
        spec = (args.brokers or args.socket or cfg.serve_brokers
                or cfg.serve_socket)
        sockets = [s.strip() for s in (spec or "").split(",") if s.strip()]
        if not sockets:
            p.error("--stats needs --socket/--brokers or "
                    "TPU_MPI_SERVE_SOCKET/TPU_MPI_SERVE_BROKERS")
        token = cfg.session_token if args.token is None else args.token
        if args.metrics:
            for s in sockets:
                sys.stdout.write(_metrics_client(s, token))
            return 0
        if args.watch:
            from .. import stats as _stats

            def poll() -> list:
                out = []
                for s in sockets:
                    try:
                        out.append(_stats_client(s, token))
                    except Exception as e:  # noqa: BLE001 - rendered as row
                        out.append({"address": s, "error": str(e)})
                return out

            return _stats.watch_fleet(poll, interval=args.interval)
        reports = [_stats_client(s, token) for s in sockets]
        if len(reports) == 1:
            print(json.dumps(reports[0], indent=2, default=str))
        else:
            from .router import merge_stats
            print(json.dumps(merge_stats(reports), indent=2, default=str))
        return 0

    if args.router:
        from .router import Router
        spec = args.brokers or cfg.serve_brokers
        brokers = [s.strip() for s in (spec or "").split(",") if s.strip()]
        if not brokers:
            p.error("--router needs --brokers or TPU_MPI_SERVE_BROKERS")
        router = Router(brokers,
                        socket_spec=(args.socket or cfg.serve_router_socket
                                     or None),
                        token=args.token, mode=args.router_mode)
        router.start()
        print(f"tpu_mpi serve: router up — {len(brokers)} broker(s), "
              f"mode={router.mode}, socket={router.address} "
              f"(pid {os.getpid()})", flush=True)
        try:
            router.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            router.close()
        return 0

    broker = Broker(nranks=args.nranks, socket_spec=args.socket,
                    token=args.token, max_tenants=args.max_tenants,
                    quota_bytes=args.quota_bytes,
                    infer=True if args.infer else None,
                    elastic=True if args.elastic else None,
                    backend=args.backend, shard=args.shard)
    _flight.install_signal_hook()         # SIGTERM dumps the flight ring
    broker.start()
    print(f"tpu_mpi serve: broker up — pool={args.nranks} ranks "
          f"({broker.pool.kind}), socket={broker.address}, "
          f"shard={broker.shard.index}/{broker.shard.count}"
          + (", inference engine on" if args.infer else "")
          + (", elastic autoscaler on" if args.elastic else "")
          + f" (pid {os.getpid()})", flush=True)
    try:
        broker.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        broker.close()
    return 0
