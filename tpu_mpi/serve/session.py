"""Client side of the serve tier: attach to a broker, run collectives.

A :class:`ClientSession` is what ``MPI.Init(session=...)`` hands back (via
:func:`tpu_mpi.serve.current_session`): one socket to the broker, one
lease (tenant id + rank map + cid-namespace range), and synchronous RPC
collectives on it. Attach is a single HELLO/LEASE round trip — no Init
cold start, which the attach-latency benchmark
(benchmarks/serve_attach.py) quantifies.

Typed broker errors cross the wire: quota breach raises
:class:`~tpu_mpi.error.QuotaExceededError`, backpressure raises the
retriable :class:`~tpu_mpi.error.ServeBusyError`, lease violations raise
:class:`~tpu_mpi.error.SessionError` — the session stays usable after any
of them (reject, don't hang; see docs/serving.md's failure matrix).
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional

import numpy as np

from .. import config
from .. import locksmith
from .. import tracectx as _tc
from ..error import SessionError
from . import protocol


class SessionComm:
    """A communicator handle inside a session lease: just a cid the broker
    agreed to — all state lives broker-side."""

    __slots__ = ("session", "cid", "nranks")

    def __init__(self, session: "ClientSession", cid: int, nranks: int):
        self.session = session
        self.cid = cid
        self.nranks = nranks

    def __repr__(self) -> str:
        return f"<SessionComm cid={self.cid} nranks={self.nranks}>"


class ClientSession:
    """One tenant's attachment to a broker (use :func:`attach`)."""

    def __init__(self, sock, lease_meta: dict, address: str,
                 attach_trace: Optional[str] = None):
        self._sock = sock
        self._lock = locksmith.make_lock("session.rpc")   # one RPC in flight
        self.address = address
        # the attach handshake's trace id: op root spans link to it so a
        # viewer can hop from any request to the session's route (the
        # router splice/redirect span lives in the ATTACH trace — a
        # splicing router never parses op frames, so per-op router spans
        # cannot exist by design)
        self.attach_trace = attach_trace
        self.tenant: str = lease_meta["tenant"]
        self.ranks: List[int] = list(lease_meta["ranks"])
        self.cid_base: int = int(lease_meta["cid_base"])
        self.cid_limit: int = int(lease_meta["cid_limit"])
        self.attach_us: float = float(lease_meta.get("attach_us", 0.0))
        self.pool: dict = dict(lease_meta.get("pool", {}))
        self.comm = SessionComm(self, int(lease_meta["cid"]),
                                len(self.ranks))
        self._closed = False

    # -- plumbing ------------------------------------------------------------
    def _rpc(self, kind: int, meta: dict, arrays=()) -> tuple:
        with self._lock:
            if self._closed:
                raise SessionError("session is detached")
            try:
                protocol.send_frame(self._sock, kind, meta, arrays)
                rkind, rmeta, rarrays = protocol.recv_frame(self._sock)
            except protocol.Disconnect as e:
                # a vanished broker surfaces as the TYPED session error at
                # the API boundary, and the session knows it is dead — the
                # next call fails fast instead of writing to a corpse
                self._closed = True
                raise SessionError(
                    f"broker at {self.address} hung up mid-session: "
                    f"{e}") from None
        if rkind == protocol.ERROR:
            protocol.raise_for_error(rmeta)
        return rkind, rmeta, rarrays

    def _op(self, meta: dict, arrays=()) -> tuple:
        # trace birth (docs/observability.md "Request traces"): a sampled
        # op mints the trace here and the root span brackets the whole
        # client-observed RPC; every downstream hop parents under it
        ctx, rec = _tc.start_root(f"client:{meta.get('op')}", "client",
                                  tenant=self.tenant,
                                  link=self.attach_trace)
        if ctx is not None:
            meta = dict(meta)
            meta["trace"] = ctx.to_meta()
        try:
            _, rmeta, rarrays = self._rpc(protocol.OP, meta, arrays)
        except BaseException as e:
            _tc.end_span(rec, status="error", error=type(e).__name__)
            raise
        _tc.end_span(rec)
        return rmeta, rarrays

    def _cid(self, comm: Optional[SessionComm]) -> int:
        return (self.comm if comm is None else comm).cid

    # -- collectives ---------------------------------------------------------
    def allreduce(self, parts: Any, op: str = "sum",
                  comm: Optional[SessionComm] = None) -> np.ndarray:
        """Allreduce over the lease's ranks. ``parts`` is either one array
        (every rank contributes it) or a list of one array per rank; the
        reduced array comes back bitwise identical to an in-process
        deterministic rank-ordered reduction."""
        if isinstance(parts, (list, tuple)):
            arrays = [np.asarray(p) for p in parts]
        else:
            arrays = [np.asarray(parts)]
        _, out = self._op({"op": "allreduce", "cid": self._cid(comm),
                           "reduce": op}, arrays)
        return out[0]

    def bcast(self, buf: Any, root: int = 0,
              comm: Optional[SessionComm] = None) -> np.ndarray:
        _, out = self._op({"op": "bcast", "cid": self._cid(comm),
                           "root": int(root)}, [np.asarray(buf)])
        return out[0]

    def barrier(self, comm: Optional[SessionComm] = None) -> None:
        self._op({"op": "barrier", "cid": self._cid(comm)})

    # -- token generation (tpu_mpi.infer) ------------------------------------
    def generate(self, prompt: Any, max_new: int = 16,
                 on_token=None) -> List[int]:
        """Generate ``max_new`` tokens from an integer ``prompt`` on the
        broker's inference engine, streaming: RESULT frames arrive as the
        engine emits tokens (``on_token(id)`` per token, when given) and
        the full greedy sequence is returned. Typed errors pass through —
        an SLO eviction raises the retriable
        :class:`~tpu_mpi.error.SLOExpiredError`."""
        arr = np.ascontiguousarray(np.asarray(prompt, dtype=np.int32))
        ctx, rec = _tc.start_root("client:generate", "client",
                                  tenant=self.tenant,
                                  link=self.attach_trace)
        op_meta = {"op": "generate", "cid": self.comm.cid,
                   "max_new": int(max_new)}
        if ctx is not None:
            op_meta["trace"] = ctx.to_meta()
        try:
            with self._lock:
                if self._closed:
                    raise SessionError("session is detached")
                protocol.send_frame(self._sock, protocol.OP, op_meta, [arr])
                tokens: List[int] = []
                while True:
                    try:
                        rkind, rmeta, _ = protocol.recv_frame(self._sock)
                    except protocol.Disconnect as e:
                        self._closed = True
                        raise SessionError(
                            f"broker at {self.address} hung up mid-stream: "
                            f"{e}") from None
                    if rkind == protocol.ERROR:
                        protocol.raise_for_error(rmeta)
                    if rkind != protocol.RESULT:
                        raise SessionError(
                            f"expected streamed RESULT, got "
                            f"{protocol.KIND_NAMES.get(rkind, rkind)}")
                    new = [int(t) for t in rmeta.get("tokens", ())]
                    tokens.extend(new)
                    if on_token is not None:
                        for t in new:
                            on_token(t)
                    if rmeta.get("done"):
                        _tc.end_span(rec, tokens=len(tokens))
                        return tokens
        except BaseException as e:
            _tc.end_span(rec, status="error", error=type(e).__name__)
            raise

    # -- communicator management ---------------------------------------------
    def comm_dup(self, comm: Optional[SessionComm] = None) -> SessionComm:
        """Duplicate a communicator; the new cid is allocated inside this
        tenant's leased namespace on the broker."""
        meta, _ = self._op({"op": "dup", "cid": self._cid(comm)})
        return SessionComm(self, int(meta["cid"]), self.comm.nranks)

    def comm_free(self, comm: SessionComm) -> None:
        self._op({"op": "free", "cid": comm.cid})

    # -- accounting / liveness ------------------------------------------------
    def pcontrol(self, level: int = 2) -> dict:
        """MPI_Pcontrol over the wire: level >= 2 flushes the broker's
        per-tenant ledger from a fresh pvar snapshot."""
        meta, _ = self._op({"op": "pcontrol", "cid": self.comm.cid,
                            "level": int(level)})
        return meta

    def stats(self) -> dict:
        _, meta, _ = self._rpc(protocol.STATS, {})
        return meta

    def ping(self) -> None:
        self._rpc(protocol.PING, {})

    # -- lifecycle -----------------------------------------------------------
    def detach(self) -> None:
        """Clean lease release (the broker reclaims cids and closes the
        tenant's books as detached, not revoked)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                protocol.send_frame(self._sock, protocol.DETACH, {})
                protocol.recv_frame(self._sock)       # BYE
            except (protocol.Disconnect, OSError):
                pass
            finally:
                try:
                    self._sock.close()
                except OSError:
                    pass

    close = detach

    def __enter__(self) -> "ClientSession":
        return self

    def __exit__(self, *exc) -> bool:
        self.detach()
        return False

    def __repr__(self) -> str:
        state = "detached" if self._closed else "attached"
        return (f"<ClientSession {self.tenant!r} {state} "
                f"ranks={self.ranks} cids=[{self.cid_base},"
                f"{self.cid_limit})>")


def attach(address: Optional[str] = None, *, token: Optional[str] = None,
           tenant: Optional[str] = None, nranks: Optional[int] = None,
           timeout: float = 10.0) -> ClientSession:
    """Attach to a running broker and return a live :class:`ClientSession`.

    ``address`` defaults to the ``serve_socket`` knob (TPU_MPI_SERVE_SOCKET)
    and ``token`` to ``session_token`` (TPU_MPI_SESSION_TOKEN). The broker
    answers HELLO with either a LEASE (success) or a typed ERROR frame
    (bad token / max_tenants reached / duplicate tenant id), which is
    re-raised here as the matching exception."""
    cfg = config.load()
    address = address or cfg.serve_socket
    if not address:
        raise SessionError("no broker address: pass attach(address=...) or "
                           "set TPU_MPI_SERVE_SOCKET")
    token = cfg.session_token if token is None else token
    hello: dict = {"token": token}
    if tenant is not None:
        hello["tenant"] = tenant
    if nranks is not None:
        hello["nranks"] = int(nranks)
    # a sampled attach is traced too: ONE context for the whole handshake,
    # kept across the REDIRECT hop so the redirected HELLO carries the
    # same trace_id (the propagation edge tests pin this)
    ctx, rec = _tc.start_root("client:attach", "client")
    if ctx is not None:
        hello["trace"] = ctx.to_meta()
    # one REDIRECT hop allowed: a router in redirect mode answers HELLO
    # with the tenant's home broker and the data path goes direct
    for _hop in range(2):
        sock = protocol.connect(address, timeout=timeout)
        try:
            protocol.send_frame(sock, protocol.HELLO, hello)
            kind, meta, _ = protocol.recv_frame(sock)
        except protocol.Disconnect as e:
            sock.close()
            _tc.end_span(rec, status="error", error="Disconnect")
            raise SessionError(f"broker at {address} hung up during attach: "
                               f"{e}") from None
        if kind == protocol.REDIRECT:
            sock.close()
            address = meta["home"]
            if meta.get("tenant"):       # router-minted id: keep the HRW pin
                hello["tenant"] = meta["tenant"]
            continue
        if kind == protocol.ERROR:
            sock.close()
            _tc.end_span(rec, status="error", error="broker-error")
            protocol.raise_for_error(meta)
        if kind != protocol.LEASE:
            sock.close()
            _tc.end_span(rec, status="error", error="bad-frame")
            raise SessionError(f"expected LEASE, got "
                               f"{protocol.KIND_NAMES.get(kind, kind)}")
        _tc.end_span(rec, hops=_hop + 1)
        return ClientSession(sock, meta, address,
                             attach_trace=ctx.trace_id if ctx else None)
    _tc.end_span(rec, status="error", error="redirect-loop")
    raise SessionError(f"attach followed a REDIRECT to {address} and was "
                       f"redirected again — router loop?")


def attach_many(address: str, tenants: int, *, token: Optional[str] = None,
                nranks: Optional[int] = None, timeout: float = 120.0,
                window: int = 512) -> List[ClientSession]:
    """Attach ``tenants`` sessions to one broker with a pipelined handshake.

    :func:`attach` is one serial HELLO/LEASE round trip per call, so the
    attach rate of a herd is capped by latency.  Here up to ``window``
    handshakes are in flight at once: connect + HELLO are fired ahead and
    LEASE replies are drained FIFO, which is what the connection-count
    scaling lane (benchmarks/serve_scale_sweep.py) uses to storm a broker.
    The address must be the broker itself — REDIRECT answers (a router in
    redirect mode) are a :class:`~tpu_mpi.error.SessionError` here."""
    from collections import deque

    cfg = config.load()
    token = cfg.session_token if token is None else token
    hello: dict = {"token": token}
    if nranks is not None:
        hello["nranks"] = int(nranks)

    sessions: List[ClientSession] = []
    pending: "deque" = deque()               # sockets with HELLO sent

    def _drain_one() -> None:
        sock = pending.popleft()
        try:
            kind, meta, _ = protocol.recv_frame(sock)
        except protocol.Disconnect as e:
            sock.close()
            raise SessionError(f"broker at {address} hung up during "
                               f"pipelined attach: {e}") from None
        if kind == protocol.ERROR:
            sock.close()
            protocol.raise_for_error(meta)
        if kind != protocol.LEASE:
            sock.close()
            raise SessionError(f"pipelined attach expected LEASE, got "
                               f"{protocol.KIND_NAMES.get(kind, kind)}")
        sessions.append(ClientSession(sock, meta, address))

    try:
        for _ in range(int(tenants)):
            sock = protocol.connect(address, timeout=timeout)
            try:
                protocol.send_frame(sock, protocol.HELLO, hello)
            except (protocol.Disconnect, OSError) as e:
                sock.close()
                raise SessionError(f"broker at {address} refused a "
                                   f"pipelined HELLO: {e}") from None
            pending.append(sock)
            while len(pending) >= max(1, int(window)):
                _drain_one()
        while pending:
            _drain_one()
    except BaseException:
        for sock in pending:
            try:
                sock.close()
            except OSError:
                pass
        for s in sessions:
            try:
                s._sock.close()
                s._closed = True
            except OSError:
                pass
        raise
    return sessions
