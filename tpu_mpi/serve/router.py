"""Multi-broker scale-out: the tenant router + fleet stats merge.

``tpurun --serve --router --brokers a,b,...`` runs a thin session-level
proxy in front of N brokers (docs/serving.md "Scale-out"):

    client ──HELLO──▶ router ──HELLO──▶ home broker (HRW by tenant key)
    client ◀═════════ raw byte splice ═════════▶ home broker

- **Assignment** is rendezvous (highest-random-weight) hashing over the
  tenant key: deterministic, and STABLE under broker-list changes — removing
  a broker remaps only the tenants it hosted; every other tenant keeps its
  home (tests/test_serve_scale.py asserts both properties).
- After forwarding the (possibly tenant-injected) HELLO, the router splices
  raw bytes both ways until either side closes — no reframing, no payload
  copies beyond the kernel's, and ``generate`` streams pin to the home
  broker by construction (the whole connection lives there, so infer
  engines shard across brokers with their tenants).
- A STATS probe to the router fans out to every broker and merges the
  reports with :func:`merge_stats`.

Each broker behind a router MUST own a distinct cid shard
(``--shard i/N`` / ``TPU_MPI_SERVE_SHARD``): the shards' cid ranges are
disjoint by construction (serve.ledger.CidShard), which is what lets N
brokers' measured books be summed without a cid ever landing in two
tenants' rows — the cross-broker T208 invariant.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import select
import socket
import threading
import time
from typing import Dict, List, Optional

from .. import config
from .. import locksmith
from .. import tracectx as _tc
from ..error import MPIError, SessionError
from . import protocol


def assign_broker(tenant: str, brokers: List[str]) -> str:
    """Rendezvous (HRW) hash: the broker maximizing sha1(tenant|broker).
    Deterministic for a fixed list; removing a broker remaps ONLY its own
    tenants (the defining HRW property); ties break on the broker string."""
    if not brokers:
        raise MPIError("assign_broker needs at least one broker")
    return max(brokers,
               key=lambda b: (hashlib.sha1(f"{tenant}|{b}".encode())
                              .digest(), b))


def _sum_into(dst: dict, src: dict) -> None:
    """Recursively add numeric leaves of ``src`` into ``dst`` (fleet-total
    merge for counter blocks)."""
    for k, v in (src or {}).items():
        if isinstance(v, bool):
            dst[k] = bool(dst.get(k)) or v
        elif isinstance(v, (int, float)):
            dst[k] = dst.get(k, 0) + v
        elif isinstance(v, dict):
            _sum_into(dst.setdefault(k, {}), v)


def merge_stats(reports: List[dict]) -> dict:
    """Merge N per-broker STATS reports into one fleet view. Counter blocks
    (totals, queue, serve_frame) sum; ledger tenants union — their measured
    books still partition the summed pool totals because each broker
    attributes only cids in its OWN disjoint shard (T208 across brokers).
    A tenant name reused on two brokers keeps both rows, disambiguated as
    ``name@b<i>``."""
    merged: dict = {"brokers": [], "totals": {}, "queue": {},
                    "serve_frame": {},
                    "ledger": {"quota_bytes": 0, "tenants": {},
                               "flushes": 0, "last_flush": None},
                    "tenants_attached": []}
    for i, rep in enumerate(reports):
        if rep.get("error"):
            # an unreachable broker mid-poll: keep its {address, error} row
            # in the fleet view instead of failing the whole merge
            merged["brokers"].append({"address": rep.get("address"),
                                      "error": str(rep.get("error"))})
            continue
        merged["brokers"].append({
            "address": rep.get("address"), "backend": rep.get("backend"),
            "shard": rep.get("shard"), "pool": rep.get("pool"),
            "infer": rep.get("infer"), "elastic": rep.get("elastic"),
            "plan_cache": rep.get("plan_cache")})
        _sum_into(merged["totals"], rep.get("totals") or {})
        _sum_into(merged["serve_frame"], rep.get("serve_frame") or {})
        led = rep.get("ledger") or {}
        merged["ledger"]["quota_bytes"] += int(led.get("quota_bytes") or 0)
        merged["ledger"]["flushes"] += int(led.get("flushes") or 0)
        lf = led.get("last_flush")
        if lf is not None and (merged["ledger"]["last_flush"] is None
                               or lf > merged["ledger"]["last_flush"]):
            merged["ledger"]["last_flush"] = lf
        for t, row in (led.get("tenants") or {}).items():
            key = t if t not in merged["ledger"]["tenants"] else f"{t}@b{i}"
            merged["ledger"]["tenants"][key] = row
        merged["tenants_attached"].extend(rep.get("tenants_attached") or [])
        q = rep.get("queue") or {}
        for k, v in q.items():
            if k == "tenants":
                tq = merged["queue"].setdefault("tenants", {})
                for t, row in (v or {}).items():
                    key = t if t not in tq else f"{t}@b{i}"
                    tq[key] = row
            elif isinstance(v, (int, float)) and not isinstance(v, bool):
                merged["queue"][k] = merged["queue"].get(k, 0) + v
    merged["tenants_attached"].sort()
    merged["broker_count"] = len(reports)
    return merged


class Router:
    """The session router daemon. Construct with the broker list, then
    :meth:`start` + :meth:`serve_forever` (or drive :meth:`handle` from
    tests)."""

    def __init__(self, brokers: List[str], socket_spec: Optional[str] = None,
                 *, token: Optional[str] = None, mode: Optional[str] = None):
        if not brokers:
            raise MPIError("Router needs at least one broker socket")
        cfg = config.load()
        mode = mode or cfg.serve_router_mode
        if mode not in ("splice", "redirect"):
            raise MPIError(f"router mode {mode!r} is not 'splice' or "
                           f"'redirect' (TPU_MPI_SERVE_ROUTER_MODE)")
        # splice: transparent byte proxy (clients only ever see the router).
        # redirect: answer HELLO with the home broker and let the client
        # re-dial it — the data path skips the router entirely (the
        # serve_scale_sweep headline lane).
        self.mode = mode
        self.brokers = list(brokers)
        self.token = cfg.session_token if token is None else token
        self._socket_spec = socket_spec
        self._listener: Optional[socket.socket] = None
        self.address: Optional[str] = None
        self._tenant_seq = itertools.count(1)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # observability: tenant -> home broker of every live splice
        self.routes: Dict[str, str] = {}
        self._routes_lock = locksmith.make_lock("router.routes")

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self._listener, self.address = protocol.listen(self._socket_spec)
        self._listener.settimeout(0.2)

    def serve_forever(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self.handle, args=(conn,),
                                 name="serve-route", daemon=True)
            t.start()
            self._threads.append(t)

    def run_in_thread(self) -> threading.Thread:
        self.start()
        t = threading.Thread(target=self.serve_forever, name="serve-router",
                             daemon=True)
        t.start()
        self._threads.append(t)
        return t

    def close(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    # -- per-connection ------------------------------------------------------
    def handle(self, conn: socket.socket) -> None:
        try:
            kind, meta, arrays = protocol.recv_frame(conn)
        except (protocol.Disconnect, SessionError):
            conn.close()
            return
        try:
            if kind == protocol.STATS:
                self._handle_stats(conn, meta)
                return
            if kind == protocol.METRICS:
                self._handle_metrics(conn, meta)
                return
            if kind != protocol.HELLO:
                protocol.send_frame(conn, protocol.ERROR, protocol.error_meta(
                    SessionError(f"router expects HELLO or STATS, got "
                                 f"{protocol.KIND_NAMES.get(kind, kind)}")))
                return
            self._handle_hello(conn, meta, arrays)
        except (protocol.Disconnect, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle_stats(self, conn, meta: dict) -> None:
        from .broker import _stats_client
        token = meta.get("token")
        reports = []
        for b in self.brokers:
            try:
                reports.append(_stats_client(b, token))
            except (MPIError, OSError) as e:
                reports.append({"address": b, "error": str(e)})
        protocol.send_frame(conn, protocol.STATS, merge_stats(reports))

    def _handle_metrics(self, conn, meta: dict) -> None:
        """Fleet Prometheus scrape: every broker's METRICS text, joined
        (an unreachable broker becomes a comment line, not a failure)."""
        from .broker import _metrics_client
        token = meta.get("token")
        parts = []
        for b in self.brokers:
            try:
                parts.append(_metrics_client(b, token))
            except (MPIError, OSError) as e:
                parts.append(f"# {b} unreachable: {e}\n")
        protocol.send_frame(conn, protocol.METRICS, {"text": "".join(parts)})

    def _handle_hello(self, conn, meta: dict, arrays: list) -> None:
        # the session key IS the tenant id; a keyless HELLO gets a router-
        # generated one so its home is stable for the connection's lifetime
        meta = dict(meta)
        tenant = meta.get("tenant") or f"rt{next(self._tenant_seq)}"
        meta["tenant"] = tenant
        # request tracing: the HELLO's trace context passes through the hop
        # untouched (redirect echoes it back, splice forwards it verbatim);
        # the router contributes its own span for the routing decision
        tctx = _tc.TraceCtx.from_meta(meta)
        t0_span = time.monotonic()
        home = assign_broker(tenant, self.brokers)
        if self.mode == "redirect":
            protocol.send_frame(conn, protocol.REDIRECT,
                                {"home": home, "tenant": tenant})
            if tctx is not None and tctx.sampled:
                _tc.emit_span(tctx, "router:redirect", "router", t0_span,
                              time.monotonic(), tenant=tenant, home=home)
            return
        try:
            upstream = protocol.connect(home)
        except (OSError, MPIError) as e:
            protocol.send_frame(conn, protocol.ERROR, protocol.error_meta(
                SessionError(f"home broker {home} for tenant {tenant!r} "
                             f"unreachable: {e}")))
            return
        if tctx is not None and tctx.sampled:
            _tc.emit_span(tctx, "router:splice", "router", t0_span,
                          time.monotonic(), tenant=tenant, home=home)
        with self._routes_lock:
            self.routes[tenant] = home
        try:
            protocol.send_frame(upstream, protocol.HELLO, meta, arrays)
            self._splice(conn, upstream)
        finally:
            with self._routes_lock:
                self.routes.pop(tenant, None)
            try:
                upstream.close()
            except OSError:
                pass

    # idle grace for the surviving direction once one side has sent EOF:
    # the deadline re-arms every time that direction moves bytes, so a
    # long in-flight drain is never cut off — this only bounds a peer
    # that has gone silent while half-open
    _HALF_CLOSE_GRACE = 30.0

    @staticmethod
    def _splice(a: socket.socket, b: socket.socket) -> None:
        """Pump raw bytes both ways until BOTH sides finish: past the
        HELLO the router adds no framing and — on the native path — no
        userspace copies at all: each direction is a splice(2) byte pump
        through its own kernel pipe (socket → pipe → socket, transport.cc
        ``tmfd_splice``), with a plain recv/send pump as the portable
        fallback. Half-close is honored: one peer's EOF shuts down only
        the write side it feeds (``shutdown(SHUT_WR)`` on the opposite
        socket) and the reverse direction keeps flowing until its own
        EOF — a client done sending can still drain in-flight replies.
        Runs entirely on the calling handler thread: no pump threads to
        leak, one select loop owns both directions."""
        try:
            from .._native import splice_fd, load as _load_native
            _load_native()             # probe now: no native lib, no splice
        except Exception:
            splice_fd = None

        class _Dir:
            __slots__ = ("src", "dst", "pipe", "native", "open")

            def __init__(self, src, dst):
                self.src, self.dst = src, dst
                self.pipe = None
                self.native = splice_fd is not None
                if self.native:
                    try:
                        self.pipe = os.pipe()
                    except OSError:
                        self.native = False
                self.open = True

        def _py_pump(d) -> int:
            """One fallback pump slice; bytes moved, 0 on EAGAIN, -1 when
            this direction is done."""
            try:
                chunk = d.src.recv(1 << 16)
            except (BlockingIOError, InterruptedError):
                return 0
            except OSError:
                return -1
            if not chunk:
                return -1
            view = memoryview(chunk)
            deadline = time.monotonic() + Router._HALF_CLOSE_GRACE
            while view.nbytes:
                try:
                    view = view[d.dst.send(view):]
                except (BlockingIOError, InterruptedError):
                    if not select.select([], [d.dst], [], 1.0)[1] \
                            and time.monotonic() > deadline:
                        return -1       # peer stopped draining: give up
                except OSError:
                    return -1
            return len(chunk)

        def _pump(d) -> int:
            """Bytes moved this slice, 0 on EAGAIN, -1 on EOF/error."""
            if d.native:
                try:
                    moved = splice_fd(d.src.fileno(), d.dst.fileno(),
                                      d.pipe[0], d.pipe[1], 1 << 20)
                except OSError:
                    # EINVAL and friends: this fd pair can't splice —
                    # demote the direction to the userspace pump
                    d.native = False
                    return _py_pump(d)
                if moved == 0:
                    return -1           # 0 = EOF
                return max(moved, 0)    # >0 moved; -1 = EAGAIN
            return _py_pump(d)

        dirs = [_Dir(a, b), _Dir(b, a)]
        for s in (a, b):
            s.setblocking(False)
        first_eof = None
        try:
            while any(d.open for d in dirs):
                rds = [d.src for d in dirs if d.open]
                try:
                    ready = select.select(rds, [], [], 1.0)[0]
                except (OSError, ValueError):
                    break               # a socket died out from under us
                for d in dirs:
                    if not (d.open and d.src in ready):
                        continue
                    moved = _pump(d)
                    if moved < 0:
                        d.open = False
                        try:            # propagate EOF, read side stays up
                            d.dst.shutdown(socket.SHUT_WR)
                        except OSError:
                            pass
                    elif moved and first_eof is not None:
                        # the surviving direction is still draining: re-arm
                        # the grace so it bounds idleness, not total
                        # half-open lifetime
                        first_eof = time.monotonic()
                if any(d.open for d in dirs) != all(d.open for d in dirs):
                    if first_eof is None:
                        first_eof = time.monotonic()
                    elif time.monotonic() - first_eof > \
                            Router._HALF_CLOSE_GRACE:
                        break           # idle lame-duck half: bounded wait
        finally:
            for d in dirs:
                if d.pipe is not None:
                    for fd in d.pipe:
                        try:
                            os.close(fd)
                        except OSError:
                            pass
            for s in (a, b):
                try:
                    s.close()
                except OSError:
                    pass
