"""Per-tenant accounting ledger + byte/op quota enforcement.

Two books per tenant (docs/serving.md "Accounting and quotas"):

- **admitted**: bytes/ops charged at admission time, BEFORE the collective
  runs. This is the authoritative book for quota enforcement — a quota
  breach rejects with the typed :class:`~tpu_mpi.error.QuotaExceededError`
  and the op never touches the pool (reject, don't hang).
- **measured**: bytes/ops attributed from pvar snapshots
  (``tpu_mpi.perfvars``) by cid-range ownership — every ``(rank, cid)``
  counter whose cid falls inside a tenant's leased namespace is that
  tenant's; counters on shared/pool cids land under the ``_pool``
  pseudo-tenant. By construction the per-tenant measured books sum to the
  pool totals, which tests/test_serve.py asserts.

``Pcontrol(level >= 2)`` from a session client — or a STATS request —
drives a flush of the measured book (the broker calls
:meth:`Ledger.flush_from_pvars` with a fresh snapshot).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from .. import error as _ec
from .. import locksmith
from ..error import MPIError, QuotaExceededError

POOL_TENANT = "_pool"     # pseudo-tenant for pre-lease / shared-cid traffic

# Tenant cid namespaces start above this floor (the thread tier's
# ``SpmdContext._ns_next_base``); shard bases are carved above it.
NS_FLOOR = 1 << 20


class CidShard:
    """One broker's disjoint slice of the tenant cid space.

    Multi-broker scale-out (docs/serving.md "Scale-out") gives broker
    ``index`` of ``count`` the half-open cid range
    ``[NS_FLOOR + index*span, NS_FLOOR + (index+1)*span)`` to carve tenant
    namespaces from — ranges of distinct shards are disjoint BY
    CONSTRUCTION (property-tested in tests/test_serve_scale.py), which is
    what lets the measured books of N brokers be summed without a cid ever
    landing in two tenants' rows (the cross-broker T208 invariant)."""

    SPAN = 1 << 24          # cids per broker: room for ~16k default leases

    def __init__(self, index: int = 0, count: int = 1,
                 span: Optional[int] = None):
        index, count = int(index), int(count)
        if count < 1 or not (0 <= index < count):
            raise MPIError(f"cid shard index {index}/{count} out of range",
                           code=_ec.ERR_ARG)
        self.index, self.count = index, count
        self.span = int(span or self.SPAN)
        self.base = NS_FLOOR + self.index * self.span
        self.limit = self.base + self.span

    @classmethod
    def parse(cls, spec: str) -> "CidShard":
        """``"index/count"`` (the TPU_MPI_SERVE_SHARD / --shard grammar);
        ""/None means the single-broker whole-range shard."""
        if not spec:
            return cls()
        idx, sep, cnt = str(spec).partition("/")
        try:
            if not sep:
                raise ValueError(spec)
            return cls(int(idx), int(cnt))
        except ValueError:
            raise MPIError(f"cid shard spec {spec!r} is not 'index/count'",
                           code=_ec.ERR_ARG) from None

    def owns(self, cid: Any) -> bool:
        return isinstance(cid, int) and self.base <= cid < self.limit

    def __repr__(self) -> str:
        return (f"CidShard({self.index}/{self.count}, "
                f"[{self.base}, {self.limit}))")


class Ledger:
    def __init__(self, quota_bytes: int = 0):
        self.quota_bytes = int(quota_bytes)
        self._lock = locksmith.make_lock("ledger")
        self._tenants: Dict[str, dict] = {}
        self._flushes = 0
        self._last_flush: Optional[float] = None
        # per-tenant latency objectives (docs/observability.md "SLO burn"):
        # {"target_us": ..., "budget": tolerated miss fraction}
        self._objectives: Dict[str, dict] = {}

    # -- SLO objectives (latency burn-rate) -----------------------------------
    def set_objective(self, tenant: str, target_us: int,
                      budget: float = 0.01) -> None:
        """Give ``tenant`` a latency objective: at most ``budget`` of its
        ops may take ``target_us`` or longer. The burn rate reported per
        flush is observed-miss-fraction / budget — above 1.0 the tenant is
        spending error budget faster than the objective allows, and the
        elastic controller treats it as grow pressure."""
        target_us, budget = int(target_us), float(budget)
        if target_us <= 0 or not 0.0 < budget <= 1.0:
            raise MPIError(
                f"SLO objective target_us={target_us} budget={budget} "
                f"invalid (need target_us > 0 and 0 < budget <= 1)",
                code=_ec.ERR_ARG)
        with self._lock:
            self._objectives[tenant] = {"target_us": target_us,
                                        "budget": budget}

    @staticmethod
    def _default_objective() -> Optional[dict]:
        """The fleet-wide objective TPU_MPI_SERVE_SLO_US applies to every
        tenant without an explicit one (0 = no objective)."""
        from .. import config as _cfg
        us = int(getattr(_cfg.load(), "serve_slo_us", 0))
        return {"target_us": us, "budget": 0.01} if us > 0 else None

    @staticmethod
    def _slo_row(hist, obj: dict) -> dict:
        """Fold one tenant's merged log2-µs latency histogram against its
        objective. Bucket ``i`` covers [2^(i-1), 2^i) µs (bucket 0 is
        [0, 1)); a bucket whose lower edge clears the target counts as
        missed in full — the conservative reading of a histogram."""
        total = sum(hist)
        miss = sum(c for i, c in enumerate(hist)
                   if (0 if i == 0 else 1 << (i - 1)) >= obj["target_us"])
        frac = (miss / total) if total else 0.0
        return {"target_us": obj["target_us"], "budget": obj["budget"],
                "ops": int(total), "misses": int(miss),
                "miss_frac": round(frac, 6),
                "burn": round(frac / obj["budget"], 4)}

    def max_burn_rate(self) -> Optional[float]:
        """The worst per-tenant SLO burn over the last measured flush —
        the elastic controller's latency-derived grow signal. None when no
        tenant has an objective (or none has measured latency yet)."""
        default = self._default_objective()
        worst: Optional[float] = None
        with self._lock:
            for t, e in self._tenants.items():
                obj = self._objectives.get(t) or default
                hist = e.get("lat_hist")
                if obj is None or not hist:
                    continue
                burn = self._slo_row(hist, obj)["burn"]
                if worst is None or burn > worst:
                    worst = burn
        return worst

    def _entry(self, tenant: str) -> dict:
        e = self._tenants.get(tenant)
        if e is None:
            e = self._tenants[tenant] = {
                "admitted_bytes": 0, "admitted_ops": 0,
                "rejected_quota": 0, "rejected_busy": 0,
                "rebinds": 0,
                "measured": {}, "attached_at": time.time(),
                "revoked": False, "detached": False,
            }
        return e

    # -- lease lifecycle -----------------------------------------------------
    def open_tenant(self, tenant: str) -> None:
        with self._lock:
            self._entry(tenant)

    def close_tenant(self, tenant: str, revoked: bool = False) -> None:
        """Keep the books (usage survives the lease for --stats); just mark
        how the lease ended."""
        with self._lock:
            e = self._tenants.get(tenant)
            if e is not None:
                e["revoked"] = revoked
                e["detached"] = True

    # -- admission book (quota authority) -------------------------------------
    def charge(self, tenant: str, nbytes: int, ops: int = 1) -> None:
        """Charge an op at admission; quota breach is a typed rejection and
        nothing is charged (the op will not run)."""
        with self._lock:
            e = self._entry(tenant)
            if self.quota_bytes and e["admitted_bytes"] + nbytes > self.quota_bytes:
                e["rejected_quota"] += 1
                raise QuotaExceededError(
                    f"tenant {tenant!r} quota exhausted: "
                    f"{e['admitted_bytes']} + {nbytes} > "
                    f"{self.quota_bytes} quota bytes "
                    f"(TPU_MPI_SERVE_QUOTA_BYTES)", tenant=tenant,
                    used=e["admitted_bytes"], quota=self.quota_bytes)
            e["admitted_bytes"] += int(nbytes)
            e["admitted_ops"] += int(ops)

    def note_busy(self, tenant: str) -> None:
        with self._lock:
            self._entry(tenant)["rejected_busy"] += 1

    def note_rebind(self, tenant: str) -> None:
        """An elastic resize moved this tenant's lease onto replacement
        ranks (tpu_mpi.elastic): same cids, same books, new group. Counted
        so --stats can show how often a tenant rode through a resize."""
        with self._lock:
            self._entry(tenant)["rebinds"] += 1

    # -- measured book (pvar attribution) -------------------------------------
    def flush_from_pvars(self, snapshot: dict,
                         owner_of_cid: Callable[[Any], Optional[str]]) -> dict:
        """Rebuild the measured book from a pvar snapshot (the stable
        schema of ``perfvars.snapshot()``). ``owner_of_cid`` maps a cid to
        the owning tenant (None -> pool). Returns the pool-total row; the
        invariant ``sum(tenant rows) == pool totals`` holds by
        construction because every comm record lands in exactly one row."""
        books, hists, totals = self._attribute(snapshot, owner_of_cid)
        with self._lock:
            for t in self._tenants:
                self._tenants[t]["measured"] = books.pop(t, {})
                self._tenants[t]["lat_hist"] = hists.get(t) or []
            for t, row in books.items():
                e = self._entry(t)
                e["measured"] = row
                e["lat_hist"] = hists.get(t) or []
            self._flushes += 1
            self._last_flush = time.time()
        return totals

    # -- reporting -----------------------------------------------------------
    def report(self) -> dict:
        default_obj = self._default_objective()   # config read OUTSIDE the lock
        with self._lock:
            return self._report_locked(default_obj)

    def _report_locked(self, default_obj: Optional[dict] = None) -> dict:
        tenants = {}
        for t, e in self._tenants.items():
            row = {k: v for k, v in e.items()}
            obj = self._objectives.get(t) or default_obj
            hist = e.get("lat_hist")
            if obj is not None and hist:
                row["slo"] = self._slo_row(hist, obj)
            tenants[t] = row
        return {"quota_bytes": self.quota_bytes, "tenants": tenants,
                "flushes": self._flushes, "last_flush": self._last_flush}

    def flush_and_report(self, snapshot: dict,
                         owner_of_cid: Callable[[Any], Optional[str]]
                         ) -> tuple[dict, dict]:
        """Measured-book flush + report under ONE lock acquisition — the
        STATS fast path (a 1k-tenant fleet polling stats must not take the
        ledger lock three times per request; ISSUE 15 satellite).
        Returns ``(pool_totals, report)``."""
        books, hists, totals = self._attribute(snapshot, owner_of_cid)
        default_obj = self._default_objective()   # config read OUTSIDE the lock
        with self._lock:
            for t in self._tenants:
                self._tenants[t]["measured"] = books.pop(t, {})
                self._tenants[t]["lat_hist"] = hists.get(t) or []
            for t, row in books.items():
                e = self._entry(t)
                e["measured"] = row
                e["lat_hist"] = hists.get(t) or []
            self._flushes += 1
            self._last_flush = time.time()
            return totals, self._report_locked(default_obj)

    @staticmethod
    def _attribute(snapshot: dict,
                   owner_of_cid: Callable[[Any], Optional[str]]
                   ) -> tuple[Dict[str, dict], dict]:
        """Lock-free attribution pass shared by :meth:`flush_from_pvars`
        and :meth:`flush_and_report`: fold the snapshot's comm records into
        per-tenant measured rows + the pool-total row."""
        fields = ("bytes_sent", "bytes_recv", "sends", "recvs")
        totals = {f: 0 for f in fields}
        totals["coll_ops"] = 0
        books: Dict[str, dict] = {}
        hists: Dict[str, list] = {}
        for rec in snapshot.get("comms", ()):
            tenant = owner_of_cid(rec.get("cid")) or POOL_TENANT
            row = books.setdefault(tenant, {f: 0 for f in fields}
                                   | {"coll_ops": 0})
            for f in fields:
                v = int(rec.get(f, 0) or 0)
                row[f] += v
                totals[f] += v
            nops = sum(int(v) for v in (rec.get("ops") or {}).values())
            row["coll_ops"] += nops
            totals["coll_ops"] += nops
            # merged log2-µs latency histogram (all collectives of this
            # tenant's comms) — the SLO burn-rate input. Kept OUT of the
            # measured row: that book is scalar counters whose tenant rows
            # sum to the pool totals, and a list would break every
            # consumer that folds it.
            for buckets in (rec.get("hist") or {}).values():
                h = hists.setdefault(tenant, [])
                if len(h) < len(buckets):
                    h.extend([0] * (len(buckets) - len(h)))
                for i, c in enumerate(buckets):
                    h[i] += int(c)
        return books, hists, totals
