"""Admission control + deficit-round-robin fair queueing for collective slots.

The broker owns one warm device pool; tenants submit collectives that all
contend for it. Three mechanisms keep one tenant from starving the rest
(docs/serving.md "Fair queueing"):

- **bounded queue depth** per tenant: a submit past ``max_depth`` is
  rejected with the retriable :class:`~tpu_mpi.error.ServeBusyError`
  (backpressure surfaces as a status, never as an unbounded buffer);
- **bounded concurrency** per tenant: at most ``max_inflight`` of a
  tenant's collectives occupy pool slots at once, however deep its queue;
- **deficit round-robin** across tenants: each visit of the ring grants a
  tenant ``quantum`` bytes of credit; an op dispatches only when the
  tenant's accumulated deficit covers its byte cost, so many small ops and
  few big ops get proportionate shares of pool bandwidth (the classic DRR
  schedule of Shreedhar & Varghese, applied to collective payload bytes).

Everything is deterministic given a submission order — tests assert pop
order directly instead of racing timers.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional

from .. import locksmith
from ..error import ServeBusyError, SessionError


class FairQueue:
    """DRR scheduler over per-tenant FIFO queues (one broker dispatcher
    pops; any number of handler threads submit)."""

    def __init__(self, quantum: int = 1 << 16, max_depth: int = 64,
                 max_inflight: int = 2):
        if quantum < 1 or max_depth < 1 or max_inflight < 1:
            raise ValueError("quantum, max_depth and max_inflight must be >= 1")
        self.quantum = int(quantum)
        self.max_depth = int(max_depth)
        self.max_inflight = int(max_inflight)
        self._lock = locksmith.make_lock("fairqueue")
        self._cond = locksmith.make_condition("fairqueue", self._lock)
        self._queues: Dict[str, deque] = {}        # tenant -> ops
        self._deficit: Dict[str, int] = {}
        self._inflight: Dict[str, int] = {}
        self._ring: List[str] = []                 # visit order
        self._cursor = 0
        self._closed = False
        self._paused = False
        # counters for --stats
        self.submitted = 0
        self.rejected_busy = 0
        self.dispatched = 0

    # -- tenant lifecycle ----------------------------------------------------
    def add_tenant(self, tenant: str) -> None:
        with self._lock:
            if tenant in self._queues:
                raise SessionError(f"tenant {tenant!r} already queued")
            self._queues[tenant] = deque()
            self._deficit[tenant] = 0
            self._inflight[tenant] = 0
            self._ring.append(tenant)

    def remove_tenant(self, tenant: str) -> list:
        """Drop a tenant (lease revoked): its queued-but-undispatched ops
        are returned so the caller can fail them; in-flight ops finish on
        the pool (they no longer involve the client)."""
        with self._lock:
            dropped = list(self._queues.pop(tenant, ()))
            self._deficit.pop(tenant, None)
            self._inflight.pop(tenant, None)
            if tenant in self._ring:
                idx = self._ring.index(tenant)
                self._ring.remove(tenant)
                if idx < self._cursor:
                    self._cursor -= 1
                if self._ring:
                    self._cursor %= len(self._ring)
                else:
                    self._cursor = 0
            self._cond.notify_all()
            return dropped

    # -- producer side -------------------------------------------------------
    def submit(self, op: Any) -> None:
        """Enqueue one op (needs ``.tenant`` and ``.nbytes``). Raises the
        retriable ServeBusyError when the tenant's queue is at depth."""
        with self._lock:
            q = self._queues.get(op.tenant)
            if q is None:
                raise SessionError(f"tenant {op.tenant!r} holds no lease")
            if len(q) >= self.max_depth:
                self.rejected_busy += 1
                raise ServeBusyError(
                    f"tenant {op.tenant!r} admission queue is full "
                    f"({len(q)}/{self.max_depth} queued) — retry after a "
                    f"backoff", tenant=op.tenant, depth=len(q))
            q.append(op)
            self.submitted += 1
            self._cond.notify_all()

    # -- consumer side (single dispatcher) ------------------------------------
    def _eligible(self, tenant: str) -> bool:
        q = self._queues.get(tenant)
        return bool(q) and self._inflight[tenant] < self.max_inflight

    def _try_pop(self) -> tuple[Optional[Any], bool]:
        """One full DRR sweep: (op, deficit_blocked). ``deficit_blocked``
        means some eligible tenant was held back only by credit — another
        sweep (which grants another quantum per visit) will dispatch it, so
        the caller must resweep rather than wait for a notify."""
        n = len(self._ring)
        blocked = False
        if self._paused:
            return None, False
        for _ in range(n):
            tenant = self._ring[self._cursor]
            self._cursor = (self._cursor + 1) % n
            if not self._eligible(tenant):
                continue
            q = self._queues[tenant]
            cost = max(1, int(getattr(q[0], "nbytes", 0)))
            # grant this visit's quantum, bounded so an idle tenant can't
            # bank unbounded credit and later monopolize the pool
            self._deficit[tenant] = min(self._deficit[tenant] + self.quantum,
                                        cost + self.quantum)
            if self._deficit[tenant] >= cost:
                self._deficit[tenant] -= cost
                op = q.popleft()
                self._inflight[tenant] += 1
                self.dispatched += 1
                return op, blocked
            blocked = True
        return None, blocked

    def pop(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Next op in DRR order; blocks until one is dispatchable, the
        timeout expires (returns None), or the queue is closed (None)."""
        with self._lock:
            while True:
                if self._closed:
                    return None
                op, blocked = self._try_pop()
                if op is not None:
                    return op
                if blocked:
                    continue        # credit accrues per sweep, not per event
                if not self._cond.wait(timeout):
                    return None

    def complete(self, op: Any) -> None:
        """An op released its pool slot; its tenant may dispatch again."""
        with self._lock:
            if op.tenant in self._inflight:
                self._inflight[op.tenant] -= 1
            self._cond.notify_all()

    def pause(self) -> None:
        """Stop dispatching (submits still queue; nothing pops) — the
        quiesce half of the elastic rebind protocol. In-flight ops are the
        caller's to drain via :meth:`inflight_total`."""
        with self._lock:
            self._paused = True

    def resume(self) -> None:
        with self._lock:
            self._paused = False
            self._cond.notify_all()

    def inflight_total(self) -> int:
        """Ops dispatched to the pool and not yet completed, across all
        tenants (0 = the pool is drained and safe to remap)."""
        with self._lock:
            return sum(self._inflight.values())

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._cond.notify_all()

    def stats(self) -> dict:
        with self._lock:
            return {
                "tenants": {t: {"queued": len(q),
                                "inflight": self._inflight.get(t, 0),
                                "deficit": self._deficit.get(t, 0)}
                            for t, q in self._queues.items()},
                "submitted": self.submitted,
                "rejected_busy": self.rejected_busy,
                "dispatched": self.dispatched,
                "quantum": self.quantum,
                "max_depth": self.max_depth,
                "max_inflight": self.max_inflight,
                "paused": self._paused,
            }


class ReadyRing:
    """The front door's ready-queue: connections with parsed frames
    waiting for a worker, FIFO with membership dedup (an item is in the
    ring at most once however many readiness events fire while it waits).
    FIFO across connections is round-robin service at the connection
    level — per-tenant byte fairness stays :class:`FairQueue`'s job at
    admission, this ring only keeps one chatty socket from being enqueued
    a thousand times ahead of everyone else.

    Items need a writable ``queued`` attribute (the dedup bit, owned by
    the ring). Any number of producers (the event loop, workers
    re-enqueueing) and consumers (the worker pool) may call concurrently.
    """

    def __init__(self, name: str = "frontdoor.ready"):
        self._lock = locksmith.make_lock(name)
        self._cond = locksmith.make_condition(name, self._lock)
        self._ring: deque = deque()
        self._closed = False

    def push(self, item: Any) -> bool:
        """Enqueue unless already queued; returns True when enqueued."""
        with self._lock:
            if self._closed or item.queued:
                return False
            item.queued = True
            self._ring.append(item)
            self._cond.notify()
            return True

    def pop(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Next connection, blocking up to ``timeout``; None on timeout or
        close. The popped item's ``queued`` bit is cleared — a readiness
        event landing while a worker holds it re-enqueues it afresh."""
        with self._lock:
            while not self._ring:
                if self._closed:
                    return None
                if not self._cond.wait(timeout):
                    return None
            item = self._ring.popleft()
            item.queued = False
            return item

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._cond.notify_all()
